"""Self-driving ops tests — remediation engine, action catalog audit,
chaos-driven heals, and multi-tenant admission quotas.

The heal tests drive the REAL pipeline end to end: monkeypatched health
probes (the same seams tests/test_health.py uses) trip a rule, the
incident rising edge fires the engine, the engine records exactly one
bounded action against a stub live target, and the next clean sweep
resolves the incident. Stubs stand in for the live targets (scoring
tier, Cleaner, elastic groups) via the actions module's probe seams.
"""

import threading
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.ops_plane import actions as oa
from h2o3_tpu.ops_plane import remediate as orm
from h2o3_tpu.ops_plane import tenancy as ot
from h2o3_tpu.ops_plane.actions import ActionLog
from h2o3_tpu.ops_plane.remediate import RemediationEngine
from h2o3_tpu.ops_plane.tenancy import (QuotaExceeded, QuotaManager,
                                        sanitize_tenant, tenant_scope)
from h2o3_tpu.utils import health as hm
from h2o3_tpu.utils.health import HealthEvaluator
from h2o3_tpu.utils.incidents import IncidentLog


# -- stub live targets --------------------------------------------------------

class _StubPool:
    def __init__(self, n):
        self.replicas = [object()] * n


class _StubScoring:
    """Looks like ScoringService to act_serving_relief/act_pin_bucket."""

    def __init__(self, widens=True, replicas=1, cache=None):
        self._widens = widens
        self.pool = _StubPool(replicas)
        self.cache = cache
        self.widen_calls = 0
        self.restore_calls = 0
        self.replica_history = []

    def widen_admission(self):
        self.widen_calls += 1
        return [{"model": "glm_1", "target_ms": 75.0}] if self._widens else []

    def restore_admission(self):
        self.restore_calls += 1
        return [{"model": "glm_1", "target_ms": 50.0}]

    def configure_replicas(self, n):
        self.replica_history.append(n)
        self.pool = _StubPool(n)


class _StubCache:
    def __init__(self, buckets=(64, 256)):
        self._buckets = sorted(buckets)
        self._pin = None

    def pinned_bucket(self):
        return self._pin

    def compiled_buckets(self):
        return list(self._buckets)

    def pin_bucket(self, bucket):
        self._pin = bucket
        return bucket

    def unpin_bucket(self):
        self._pin = None


class _StubCleaner:
    def __init__(self, budget):
        self.budget = budget
        self.spilled = []

    def last_touched(self, key):
        return 0.0

    def force_spill(self, keys, limit=2):
        done = list(keys)[:limit]
        self.spilled.extend(done)
        return done


class _StubGroup:
    group_id = "grp_test"

    def __init__(self, rows):
        self._rows = rows
        self.reassigned = []
        self.joins = []

    def rows(self):
        return self._rows

    def preempt_reassign(self, wid, reason="ops_preempt"):
        self.reassigned.append(wid)
        return [0, 2]

    def request_join(self, wid):
        self.joins.append(wid)


def _engine(monkeypatch, mode="act", cooldown="0"):
    monkeypatch.setenv("H2O3TPU_REMEDIATE", mode)
    monkeypatch.setenv("H2O3TPU_OPS_COOLDOWN_SECS", cooldown)
    return RemediationEngine(actions=ActionLog())


# -- the action catalog -------------------------------------------------------

def test_observe_mode_records_without_executing(monkeypatch):
    svc = _StubScoring()
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    log = ActionLog()
    rec = log.record("serving_relief", "serving_shed_rate", "inc_1",
                     "observe")
    assert rec["outcome"] == "observed"
    assert rec["rollback_token"] is None
    assert svc.widen_calls == 0 and svc.replica_history == []
    assert log.recorded_total() == 1       # the decision IS in the trail


def test_unknown_action_is_a_failed_record():
    log = ActionLog()
    rec = log.record("reboot_the_moon", "some_rule", None, "act")
    assert rec["outcome"] == "failed"
    assert "unknown action" in rec["params"]["error"]
    assert log.recorded_total() == 1


def test_serving_relief_widens_admission_first(monkeypatch):
    svc = _StubScoring(widens=True)
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    log = ActionLog()
    rec = log.record("serving_relief", "serving_shed_rate", "inc_1", "act")
    assert rec["outcome"] == "applied"
    assert rec["params"]["widened"][0]["model"] == "glm_1"
    assert rec["rollback_token"] == rec["id"]
    assert svc.replica_history == []       # widening sufficed
    assert log.rollback(rec["id"]) is True
    assert svc.restore_calls == 1
    assert log.rollback(rec["id"]) is False   # token is single-use
    # the rollback itself is audited
    assert [r["action"] for r in log.list()][0] == "rollback"


def test_serving_relief_adds_one_replica_when_nothing_to_widen(monkeypatch):
    svc = _StubScoring(widens=False, replicas=1)
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    monkeypatch.setenv("H2O3TPU_OPS_MAX_REPLICAS", "2")
    log = ActionLog()
    rec = log.record("serving_relief", "serving_p99_slo", "inc_2", "act")
    assert rec["outcome"] == "applied" and rec["params"]["replicas"] == 2
    assert svc.replica_history == [2]
    # bounded: at the cap the action SKIPS instead of scaling forever
    rec2 = log.record("serving_relief", "serving_p99_slo", "inc_3", "act")
    assert rec2["outcome"] == "skipped"
    assert rec2["params"]["replica_cap"] == 2
    # rollback removes the replica it added
    assert log.rollback(rec["id"]) is True
    assert svc.replica_history == [2, 1]


def test_raise_cleaner_budget_bounded_at_cap(monkeypatch):
    cleaner = _StubCleaner(budget=1000)
    monkeypatch.setattr(oa, "_cleaner", lambda: cleaner)
    monkeypatch.setenv("H2O3TPU_OPS_CLEANER_CAP_FACTOR", "2.0")
    oa._CLEANER_BASE.pop(id(cleaner), None)
    log = ActionLog()
    rec = log.record("raise_cleaner_budget", "memory_spill_thrash", "i", "act")
    assert rec["outcome"] == "applied" and cleaner.budget == 1500
    rec = log.record("raise_cleaner_budget", "memory_spill_thrash", "i", "act")
    assert rec["outcome"] == "applied" and cleaner.budget == 2000  # cap 2x
    # at the ceiling with no cold tenant: skipped, never unbounded
    rec = log.record("raise_cleaner_budget", "memory_spill_thrash", "i", "act")
    assert rec["outcome"] == "skipped" and cleaner.budget == 2000
    # rollback restores the prior budget
    applied = [r for r in log.list() if r["outcome"] == "applied"]
    assert log.rollback(applied[0]["id"]) is True   # newest applied: 1500->2000
    assert cleaner.budget == 1500


def test_raise_cleaner_budget_evicts_coldest_tenant_at_ceiling(monkeypatch):
    cleaner = _StubCleaner(budget=1000)
    monkeypatch.setattr(oa, "_cleaner", lambda: cleaner)
    monkeypatch.setenv("H2O3TPU_OPS_CLEANER_CAP_FACTOR", "1.0")  # at ceiling

    class _StubQuotas:
        def coldest_tenant(self):
            return "hoarder"

        def keys_of(self, tenant):
            return ["k3", "k1", "k2"]

    monkeypatch.setattr(oa, "_quotas", lambda: _StubQuotas())
    oa._CLEANER_BASE.pop(id(cleaner), None)
    log = ActionLog()
    rec = log.record("raise_cleaner_budget", "memory_spill_thrash", "i", "act")
    assert rec["outcome"] == "applied"
    assert rec["params"]["evicted_tenant"] == "hoarder"
    assert len(rec["params"]["spilled_keys"]) == 2      # bounded to 2 keys
    assert cleaner.budget == 1000                       # budget untouched


def test_reassign_shards_picks_single_worst_worker(monkeypatch):
    g = _StubGroup([
        {"worker": 0, "state": "ACTIVE", "last_heartbeat_ago_ms": 10.0},
        {"worker": 1, "state": "SUSPECT", "last_heartbeat_ago_ms": 9000.0},
        {"worker": 2, "state": "EJECTED", "last_heartbeat_ago_ms": 99999.0},
    ])
    monkeypatch.setattr(oa, "_live_groups", lambda: [g])
    log = ActionLog()
    rec = log.record("reassign_shards", "elastic_heartbeat_gap", "i", "act")
    assert rec["outcome"] == "applied"
    assert rec["params"]["worker"] == 1            # worst LIVE, not EJECTED
    assert rec["params"]["moved_shards"] == [0, 2]
    assert g.reassigned == [1]                     # exactly one worker
    assert log.rollback(rec["id"]) is True
    assert g.joins == [1]


def test_pin_bucket_pins_largest_compiled_and_unpins(monkeypatch):
    cache = _StubCache(buckets=(64, 256))
    monkeypatch.setattr(oa, "_scorer_cache", lambda: cache)
    log = ActionLog()
    rec = log.record("pin_bucket", "compute_recompile_storm", "i", "act")
    assert rec["outcome"] == "applied"
    assert rec["params"]["pinned_bucket"] == 256
    assert cache.pinned_bucket() == 256
    # idempotence bound: an already-pinned cache is a skip, not a re-pin
    rec2 = log.record("pin_bucket", "compute_recompile_storm", "i", "act")
    assert rec2["outcome"] == "skipped"
    assert log.rollback(rec["id"]) is True
    assert cache.pinned_bucket() is None


def test_failed_action_is_audited_not_raised(monkeypatch):
    def boom():
        raise RuntimeError("live target sick")
    monkeypatch.setattr(oa, "_cleaner", boom)
    log = ActionLog()
    rec = log.record("raise_cleaner_budget", "memory_spill_thrash", "i", "act")
    assert rec["outcome"] == "failed"
    assert "RuntimeError" in rec["params"]["error"]
    assert log.recorded_total() == 1


def test_action_log_capacity_bounds_the_trail():
    log = ActionLog(capacity=5)
    for i in range(9):
        log.record("nope", "r", f"i{i}", "observe")
    assert log.recorded_total() == 5
    assert log.list()[0]["incident_id"] == "i8"    # newest first


# -- the engine: kill switch, cooldown, rising edges --------------------------

def test_kill_switch_off_records_nothing(monkeypatch):
    eng = _engine(monkeypatch, mode="off")
    assert eng.on_incident({"id": "i", "rule": "serving_shed_rate"},
                           None) is None
    assert eng.actions.recorded_total() == 0


def test_default_and_unknown_modes_read_observe(monkeypatch):
    monkeypatch.delenv("H2O3TPU_REMEDIATE", raising=False)
    assert orm.remediate_mode() == "observe"
    monkeypatch.setenv("H2O3TPU_REMEDIATE", "yolo")
    assert orm.remediate_mode() == "observe"       # typos fail safe
    monkeypatch.setenv("H2O3TPU_REMEDIATE", " ACT ")
    assert orm.remediate_mode() == "act"


def test_unmapped_rule_pages_a_human(monkeypatch):
    eng = _engine(monkeypatch, mode="act")
    assert eng.on_incident({"id": "i", "rule": "memory_leak_growth"},
                           None) is None
    assert eng.actions.recorded_total() == 0


def test_cooldown_rate_limits_per_rule(monkeypatch):
    eng = _engine(monkeypatch, mode="observe", cooldown="3600")
    assert eng.on_incident({"id": "i1", "rule": "serving_shed_rate"},
                           None) is not None
    # same rule inside the cooldown: suppressed, NOT appended
    assert eng.on_incident({"id": "i2", "rule": "serving_shed_rate"},
                           None) is None
    # a different rule has its own cooldown clock
    assert eng.on_incident({"id": "i3", "rule": "memory_spill_thrash"},
                           None) is not None
    assert eng.actions.recorded_total() == 2


def test_rising_edge_fires_once_per_episode(monkeypatch):
    eng = _engine(monkeypatch, mode="observe")
    log = IncidentLog(capacity=8)
    eng.install(log)
    try:
        log.open("serving_shed_rate", "serving", "degraded", "m", 0.4, 0.05)
        log.open("serving_shed_rate", "serving", "degraded", "m", 0.5, 0.05)
        assert eng.actions.recorded_total() == 1   # repeat folded, no refire
        log.resolve("serving_shed_rate")
        log.open("serving_shed_rate", "serving", "degraded", "m", 0.6, 0.05)
        assert eng.actions.recorded_total() == 2   # new episode, new edge
    finally:
        eng.uninstall()


def test_act_mode_stamps_action_id_into_incident(monkeypatch):
    svc = _StubScoring()
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    eng = _engine(monkeypatch, mode="act")
    log = IncidentLog(capacity=8)
    eng.install(log)
    try:
        log.open("serving_shed_rate", "serving", "degraded", "m", 0.4, 0.05)
        [inc] = log.list()
        rec = eng.actions.list()[0]
        assert inc["action_id"] == rec["id"]
        assert rec["incident_id"] == inc["id"]
        full = log.get(inc["id"])
        assert full["context"]["remediation_action"] == rec["id"]
    finally:
        eng.uninstall()


def test_policy_view_names_mode_map_and_bounds(monkeypatch):
    monkeypatch.setenv("H2O3TPU_REMEDIATE", "observe")
    view = RemediationEngine(actions=ActionLog()).policy_view()
    assert view["mode"] == "observe"
    assert view["policy"]["memory_spill_thrash"] == "raise_cleaner_budget"
    assert view["bounds"]["reassign_workers_per_action"] == 1
    assert view["bounds"]["spill_keys_per_action"] == 2


# -- chaos-driven heals (the acceptance demo, one per failure class) ----------

def _healing_rig(monkeypatch, mode="act"):
    """A private evaluator + engine pair wired rising-edge to each other."""
    ev = HealthEvaluator(interval_s=9.0, incidents=IncidentLog(capacity=16))
    eng = _engine(monkeypatch, mode=mode)
    eng.install(ev.incidents)
    return ev, eng


def test_spill_thrash_heals_with_one_budget_raise(monkeypatch):
    cleaner = _StubCleaner(budget=1 << 20)
    monkeypatch.setattr(oa, "_cleaner", lambda: cleaner)
    oa._CLEANER_BASE.pop(id(cleaner), None)
    stats = {"spill_count": 0, "restore_count": 0}
    monkeypatch.setattr(hm, "_cleaner_stats", lambda: dict(stats))
    ev, eng = _healing_rig(monkeypatch)
    try:
        ev.evaluate()                              # window baseline
        stats.update(spill_count=6, restore_count=6)   # ping-pong chaos
        ev.evaluate()                              # trips -> edge -> action
        assert cleaner.budget == int((1 << 20) * 1.5)
        applied = [r for r in eng.actions.list() if r["outcome"] == "applied"]
        assert [r["action"] for r in applied] == ["raise_cleaner_budget"]
        # counters quiet next sweep (working set fits) -> incident resolves
        ev.evaluate()
        [inc] = ev.incidents.list(state="resolved")
        assert inc["rule"] == "memory_spill_thrash"
        assert inc["resolved_at"] is not None
        assert inc["action_id"] == applied[0]["id"]
        assert ev.incidents.list(state="open") == []
    finally:
        eng.uninstall()


def test_serving_overload_heals_with_one_admission_widen(monkeypatch):
    svc = _StubScoring(widens=True)
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    stats = {"shed_total": 0,
             "resident": [{"model": "glm_1",
                           "slo": {"target_ms": 50.0, "p99_ms": 20.0}}]}
    total = [100.0]
    monkeypatch.setattr(hm, "_serving_stats", lambda: dict(stats))
    monkeypatch.setattr(hm, "_score_requests_total", lambda: total[0])
    ev, eng = _healing_rig(monkeypatch)
    try:
        ev.evaluate()                              # baseline
        stats["shed_total"], total[0] = 40, 200.0  # 40/100 shed this window
        ev.evaluate()
        assert svc.widen_calls == 1                # exactly one action
        applied = [r for r in eng.actions.list() if r["outcome"] == "applied"]
        assert [r["action"] for r in applied] == ["serving_relief"]
        assert applied[0]["rule"] == "serving_shed_rate"
        ev.evaluate()                              # traffic drained: quiet
        [inc] = ev.incidents.list(state="resolved")
        assert inc["rule"] == "serving_shed_rate"
        assert inc["action_id"] == applied[0]["id"]
    finally:
        eng.uninstall()


def test_stalled_worker_heals_with_one_preemptive_reassign(monkeypatch):
    rows = [{"worker": 0, "state": "ACTIVE", "last_heartbeat_ago_ms": 10.0},
            {"worker": 1, "state": "ACTIVE",
             "last_heartbeat_ago_ms": 120_000.0}]
    g = _StubGroup(rows)
    monkeypatch.setattr(oa, "_live_groups", lambda: [g])
    monkeypatch.setattr(hm, "_elastic_rows", lambda: list(rows))
    monkeypatch.setenv("H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS", "30")
    ev, eng = _healing_rig(monkeypatch)
    try:
        ev.evaluate()                              # gap rule: no window
        assert g.reassigned == [1]                 # one bounded reassignment
        applied = [r for r in eng.actions.list() if r["outcome"] == "applied"]
        assert [r["action"] for r in applied] == ["reassign_shards"]
        rows[1] = {"worker": 1, "state": "EJECTED",
                   "last_heartbeat_ago_ms": 120_000.0}
        ev.evaluate()                              # silence now accounted
        [inc] = ev.incidents.list(state="resolved")
        assert inc["rule"] == "elastic_heartbeat_gap"
        assert inc["action_id"] == applied[0]["id"]
    finally:
        eng.uninstall()


def test_observe_mode_heals_nothing_but_logs_the_decision(monkeypatch):
    cleaner = _StubCleaner(budget=1 << 20)
    monkeypatch.setattr(oa, "_cleaner", lambda: cleaner)
    stats = {"spill_count": 0, "restore_count": 0}
    monkeypatch.setattr(hm, "_cleaner_stats", lambda: dict(stats))
    ev, eng = _healing_rig(monkeypatch, mode="observe")
    try:
        ev.evaluate()
        stats.update(spill_count=6, restore_count=6)
        ev.evaluate()
        assert cleaner.budget == 1 << 20           # UNTOUCHED
        recs = eng.actions.list()
        assert [r["outcome"] for r in recs] == ["observed"]
        [inc] = ev.incidents.list(state="open")
        assert inc["action_id"] is None            # nothing to stamp
    finally:
        eng.uninstall()


# -- incident API satellites --------------------------------------------------

def test_incident_state_filter_and_resolution_stamps():
    log = IncidentLog(capacity=8)
    log.open("rule_a", "serving", "degraded", "m", 1, 0)
    log.open("rule_b", "memory", "degraded", "m", 1, 0)
    log.resolve("rule_a")
    opens = log.list(state="open")
    resolved = log.list(state="resolved")
    assert [r["rule"] for r in opens] == ["rule_b"]
    assert [r["rule"] for r in resolved] == ["rule_a"]
    assert resolved[0]["resolved_at"] is not None
    assert opens[0]["resolved_at"] is None
    assert {r["rule"] for r in log.list()} == {"rule_a", "rule_b"}
    with pytest.raises(ValueError):
        log.list(state="everything")


def test_listener_faults_are_isolated():
    log = IncidentLog(capacity=8)
    calls = []

    def bad_listener(record, src):
        raise RuntimeError("listener bug")

    def good_listener(record, src):
        calls.append(record["rule"])

    log.add_listener(bad_listener)
    log.add_listener(good_listener)
    log.open("rule_x", "serving", "degraded", "m", 1, 0)
    assert calls == ["rule_x"]          # the bad one didn't block the good
    [inc] = log.list()                  # ...or the open itself
    assert inc["rule"] == "rule_x"
    log.remove_listener(bad_listener)
    log.remove_listener(good_listener)


# -- multi-tenant admission ---------------------------------------------------

def test_sanitize_tenant_contract():
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant("") == "default"
    assert sanitize_tenant("team-a.prod_1") == "team-a.prod_1"
    with pytest.raises(ValueError):
        sanitize_tenant("bad tenant!")
    with pytest.raises(ValueError):
        sanitize_tenant("x" * 65)


def test_tenant_scope_binds_context():
    assert ot.current_tenant() == "default"
    with tenant_scope("team-a"):
        assert ot.current_tenant() == "team-a"
        with tenant_scope(None):
            assert ot.current_tenant() == "default"
        assert ot.current_tenant() == "team-a"
    assert ot.current_tenant() == "default"


def test_qps_quota_sheds_with_retry_after():
    qm = QuotaManager()
    qm.set_quota("team-a", qps=2)
    assert qm.admit("team-a") == "team-a"
    qm.admit("team-a")
    with pytest.raises(QuotaExceeded) as ei:
        qm.admit("team-a")
    assert ei.value.dimension == "qps"
    assert ei.value.retry_after_s > 0
    assert "429" not in str(ei.value)   # the REST layer owns the status
    # the shed is visible in usage, never silent
    assert qm.usage("team-a")["shed"] == {"qps": 1}
    # an unquota'd tenant is admitted freely
    for _ in range(5):
        qm.admit("team-b")


def test_device_seconds_quota_windows_out(monkeypatch):
    monkeypatch.setenv("H2O3TPU_TENANT_WINDOW_SECS", "1")
    qm = QuotaManager()
    qm.set_quota("team-a", device_seconds=0.5)
    qm.charge_device_seconds("team-a", 0.6)
    with pytest.raises(QuotaExceeded) as ei:
        qm.admit("team-a")
    assert ei.value.dimension == "device_seconds"
    u = qm.usage("team-a")
    assert u["device_seconds_window"] == 0.6
    assert u["device_seconds_total"] == 0.6
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:   # the charge ages out of the window
        try:
            qm.admit("team-a")
            break
        except QuotaExceeded:
            time.sleep(0.05)
    else:
        pytest.fail("device-seconds charge never aged out of the window")
    assert qm.usage("team-a")["device_seconds_total"] == 0.6  # lifetime stays


def test_bytes_quota_prices_owned_keys(monkeypatch):
    qm = QuotaManager()
    qm.set_quota("team-a", bytes=1000)
    with tenant_scope("team-a"):
        qm.tag_key("frame_a")
    monkeypatch.setattr(QuotaManager, "_bytes_locked",
                        lambda self, tenant: 2048 if tenant == "team-a"
                        else 0)
    with pytest.raises(QuotaExceeded) as ei:
        qm.admit("team-a")
    assert ei.value.dimension == "bytes" and ei.value.observed == 2048
    assert qm.owner_of("frame_a") == "team-a"
    qm.untag_key("frame_a")
    assert qm.owner_of("frame_a") is None


def test_coldest_tenant_never_the_default(monkeypatch):
    qm = QuotaManager()
    qm.set_quota("default", bytes=10)
    qm.set_quota("hoarder", bytes=10)
    monkeypatch.setattr(QuotaManager, "_bytes_locked",
                        lambda self, tenant: 4096)
    assert qm.coldest_tenant() == "hoarder"
    qm.remove_quota("hoarder")
    assert qm.coldest_tenant() is None   # only default left: nobody


def test_usage_all_covers_every_known_tenant():
    qm = QuotaManager()
    qm.set_quota("team-a", qps=100)
    qm.charge_device_seconds("team-b", 0.1)
    tenants = {u["tenant"] for u in qm.usage_all()}
    assert {"default", "team-a", "team-b"} <= tenants


# -- REST surface -------------------------------------------------------------

@pytest.fixture
def server():
    from h2o3_tpu.api.server import H2OServer
    s = H2OServer(port=0).start()
    yield s
    s.stop()
    ot.QUOTAS.reset()
    from h2o3_tpu.ops_plane import ACTIONS, ENGINE
    ACTIONS.reset()
    ENGINE.reset()


@pytest.fixture
def client(server):
    from h2o3_tpu.api.client import H2OClient
    return H2OClient(server.url)


def test_ops_endpoint_serves_policy_actions_tenants(client):
    out = client.ops()
    assert out["__meta"]["schema_type"] == "OpsV3"
    assert out["remediation"]["mode"] in ("off", "observe", "act")
    assert "policy" in out["remediation"]
    assert isinstance(out["actions"], list)
    assert any(u["tenant"] == "default" for u in out["tenants"])


def test_quota_crud_via_rest(client):
    q = client.set_quota("team-a", qps=10, bytes=1 << 20)
    assert q == {"tenant": "team-a", "qps": 10.0,
                 "device_seconds": None, "bytes": 1 << 20}
    out = client.ops()
    assert any(r["tenant"] == "team-a" and r["qps"] == 10.0
               for r in out["quotas"])
    assert client.remove_quota("team-a") is True
    assert client.remove_quota("team-a") is False
    with pytest.raises(RuntimeError, match="400"):
        client.set_quota("bad tenant!", qps=1)


def test_two_tenant_overload_sheds_only_the_over_quota_tenant(server):
    """ISSUE acceptance: tenant A blows its budget and gets 429 +
    Retry-After; tenant B's requests keep landing untouched."""
    from h2o3_tpu.api.client import H2OClient
    a = H2OClient(server.url, tenant="team-a")
    b = H2OClient(server.url, tenant="team-b")
    a.set_quota("team-a", qps=2)

    def post_file(cli):
        req = urllib.request.Request(
            server.url + "/3/PostFile", data=b"x,y\n1,2\n",
            headers={"X-H2O3-Tenant": cli.tenant}, method="POST")
        return urllib.request.urlopen(req, timeout=30)

    assert post_file(a).status == 200
    assert post_file(a).status == 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_file(a)
    assert ei.value.code == 429
    assert int(ei.value.headers["Retry-After"]) >= 1
    # tenant B rides through the same instant, same route
    for _ in range(3):
        assert post_file(b).status == 200
    # the shed is ledgered, not silent
    usage = {u["tenant"]: u for u in a.ops()["tenants"]}
    assert usage["team-a"]["shed"].get("qps", 0) >= 1
    assert usage["team-b"]["shed"] == {}


def test_tenant_query_param_and_bad_tenant_400(server):
    req = urllib.request.Request(
        server.url + "/3/PostFile?tenant=team-q", data=b"x\n1\n",
        method="POST")
    assert urllib.request.urlopen(req, timeout=30).status == 200
    usage = {u["tenant"] for u in ot.QUOTAS.usage_all()}
    assert "team-q" in usage
    bad = urllib.request.Request(
        server.url + "/3/Ping", headers={"X-H2O3-Tenant": "no spaces"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(bad, timeout=30)
    assert ei.value.code == 400


def test_get_routes_are_never_quota_metered(server, client):
    client.set_quota("team-a", qps=0)     # zero budget: every POST sheds
    t = __import__("h2o3_tpu.api.client", fromlist=["H2OClient"]) \
        .H2OClient(server.url, tenant="team-a")
    assert t.ops()["__meta"]["schema_type"] == "OpsV3"   # GET still lands
    assert t.request("GET", "/3/Cloud")["cloud_healthy"] in (True, False)


def test_incidents_rest_state_filter_and_action_stamp(server, client,
                                                      monkeypatch):
    from h2o3_tpu.utils.incidents import INCIDENTS
    svc = _StubScoring()
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    monkeypatch.setenv("H2O3TPU_REMEDIATE", "act")
    monkeypatch.setenv("H2O3TPU_OPS_COOLDOWN_SECS", "0")
    try:
        INCIDENTS.open("serving_shed_rate", "serving", "degraded",
                       "m", 0.4, 0.05)
        opens = client.incidents(state="open")
        rule_rows = [r for r in opens if r["rule"] == "serving_shed_rate"]
        assert rule_rows and rule_rows[0]["action_id"] is not None
        # the stamped action is fetchable from the ops log
        acts = {r["id"] for r in client.ops()["actions"]}
        assert rule_rows[0]["action_id"] in acts
        INCIDENTS.resolve("serving_shed_rate")
        resolved = client.incidents(state="resolved")
        row = [r for r in resolved if r["rule"] == "serving_shed_rate"][0]
        assert row["resolved_at"] is not None
        with pytest.raises(RuntimeError, match="400"):
            client.incidents(state="everything")
    finally:
        INCIDENTS.reset()


def test_rollback_via_rest(server, client, monkeypatch):
    svc = _StubScoring()
    monkeypatch.setattr(oa, "_scoring", lambda: svc)
    from h2o3_tpu.ops_plane import ACTIONS
    rec = ACTIONS.record("serving_relief", "serving_shed_rate", None, "act")
    assert rec["outcome"] == "applied"
    assert client.rollback_action(rec["id"]) is True
    assert svc.restore_calls == 1
    assert client.rollback_action(rec["id"]) is False


# -- scoring charges device-seconds to the bound tenant -----------------------

def test_scoring_charges_device_seconds(rng):
    import numpy as np

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.serving import service as svc_mod
    X = rng.normal(size=(200, 3)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.where(X[:, 0] > 0, "yes", "no")
    fr = Frame.from_arrays(cols, key="ops_glm_train")
    glm = GLM(family="binomial", lambda_=1e-4,
              model_id="ops_glm").train(y="y", training_frame=fr)
    rows = [{f"x{i}": float(X[r, i]) for i in range(3)} for r in range(8)]
    svc_mod.SCORING.reset()
    before = ot.QUOTAS.usage("team-score")["device_seconds_total"]
    try:
        with tenant_scope("team-score"):
            out = svc_mod.SCORING.score(glm.key, rows)
        assert len(out["predictions"]["predict"]) == 8
        after = ot.QUOTAS.usage("team-score")["device_seconds_total"]
        assert after > before      # the batch share landed on the tenant
    finally:
        svc_mod.SCORING.reset()
