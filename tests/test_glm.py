"""GLM tests — golden-metric parity against sklearn/statsmodels-style closed
forms (reference test model: h2o-py ``pyunit_*`` GLM suites under
``h2o-py/tests/testdir_algos/glm/``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import GLM


def _regression_data(rng, n=2000):
    X = rng.normal(size=(n, 4))
    beta = np.array([1.5, -2.0, 0.5, 0.0])
    y = X @ beta + 3.0 + rng.normal(scale=0.1, size=n)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    return Frame.from_arrays(cols), beta


def test_glm_gaussian_recovers_coefficients(rng):
    f, beta = _regression_data(rng)
    m = GLM(family="gaussian").train(y="y", training_frame=f)
    coef = m.coef()
    for i, b in enumerate(beta):
        assert abs(coef[f"x{i}"] - b) < 0.02, coef
    assert abs(coef["Intercept"] - 3.0) < 0.02
    assert m.training_metrics.rmse < 0.12
    assert m.training_metrics.r2 > 0.99


def test_glm_gaussian_matches_lstsq(rng):
    f, _ = _regression_data(rng)
    m = GLM(family="gaussian", standardize=False).train(y="y", training_frame=f)
    X = np.column_stack([f.vec(c).to_numpy() for c in ["x0", "x1", "x2", "x3"]])
    A = np.column_stack([X, np.ones(len(X))])
    ref = np.linalg.lstsq(A, f.vec("y").to_numpy(), rcond=None)[0]
    got = [m.coef()[c] for c in ["x0", "x1", "x2", "x3", "Intercept"]]
    np.testing.assert_allclose(got, ref, atol=5e-3)


def test_glm_binomial_vs_sklearn(rng):
    n = 4000
    X = rng.normal(size=(n, 3))
    logits = 0.8 * X[:, 0] - 1.2 * X[:, 1] + 0.3
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(int)
    f = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.array(["yes" if v else "no" for v in y], dtype=object),
    })
    m = GLM(family="binomial").train(y="y", training_frame=f)

    from sklearn.linear_model import LogisticRegression
    sk = LogisticRegression(penalty=None, tol=1e-8, max_iter=200).fit(X, y)
    coef = m.coef()
    np.testing.assert_allclose(
        [coef["a"], coef["b"], coef["c"]], sk.coef_[0], atol=2e-3)
    np.testing.assert_allclose(coef["Intercept"], sk.intercept_[0], atol=2e-3)

    from sklearn.metrics import roc_auc_score, log_loss
    p = sk.predict_proba(X)[:, 1]
    assert abs(m.training_metrics.auc - roc_auc_score(y, p)) < 0.005
    assert abs(m.training_metrics.logloss - log_loss(y, p)) < 1e-3


def test_glm_categorical_features(rng):
    n = 3000
    g = rng.choice(["u", "v", "w"], size=n)
    eff = {"u": 0.0, "v": 1.0, "w": -2.0}
    y = np.array([eff[c] for c in g]) + rng.normal(scale=0.05, size=n)
    f = Frame.from_arrays({"g": g.astype(object), "y": y})
    m = GLM(family="gaussian").train(y="y", training_frame=f)
    coef = m.coef()
    # reference layout: first level is the base when use_all_factor_levels=False
    assert abs(coef["Intercept"] - 0.0) < 0.01
    assert abs(coef["g.v"] - 1.0) < 0.02
    assert abs(coef["g.w"] - (-2.0)) < 0.02


def test_glm_poisson(rng):
    n = 5000
    x = rng.normal(size=n)
    lam = np.exp(0.5 * x + 1.0)
    y = rng.poisson(lam).astype(float)
    f = Frame.from_arrays({"x": x, "y": y})
    m = GLM(family="poisson", standardize=False).train(y="y", training_frame=f)
    coef = m.coef()
    assert abs(coef["x"] - 0.5) < 0.05
    assert abs(coef["Intercept"] - 1.0) < 0.05


def test_glm_ridge_shrinks(rng):
    f, _ = _regression_data(rng)
    m0 = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=f)
    m1 = GLM(family="gaussian", lambda_=10.0).train(y="y", training_frame=f)
    b0 = np.array([m0.coef_norm()[f"x{i}"] for i in range(4)])
    b1 = np.array([m1.coef_norm()[f"x{i}"] for i in range(4)])
    assert np.linalg.norm(b1) < 0.5 * np.linalg.norm(b0)  # strong shrinkage at lambda=10


def test_glm_predict_and_valid(rng):
    f, _ = _regression_data(rng, n=1000)
    f2, _ = _regression_data(rng, n=500)
    m = GLM().train(y="y", training_frame=f, validation_frame=f2)
    assert m.validation_metrics.r2 > 0.98
    pred = m.predict(f2)
    assert pred.names == ["predict"]
    assert pred.nrows == 500


def test_glm_binomial_predict_frame(rng):
    n = 800
    x = rng.normal(size=n)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "pos", "neg")
    f = Frame.from_arrays({"x": x, "y": y.astype(object)})
    m = GLM(family="binomial").train(y="y", training_frame=f)
    pred = m.predict(f)
    assert pred.names == ["predict", "pneg", "ppos"]
    df = pred.to_pandas()
    assert set(df["predict"].unique()) <= {"neg", "pos"}
    np.testing.assert_allclose(df["pneg"] + df["ppos"], 1.0, atol=1e-5)


def test_glm_cv(rng):
    f, _ = _regression_data(rng)
    m = GLM(nfolds=3).train(y="y", training_frame=f)
    assert m.cross_validation_metrics is not None
    assert m.cross_validation_metrics.r2 > 0.98


def test_glm_na_handling(rng):
    x = rng.normal(size=500)
    y = 2 * x + 1
    x_na = x.copy()
    x_na[::7] = np.nan
    f = Frame.from_arrays({"x": x_na, "y": y})
    m = GLM().train(y="y", training_frame=f)
    assert np.isfinite(m.training_metrics.rmse)


def test_glm_unknown_param():
    with pytest.raises(ValueError, match="unknown parameters"):
        GLM(bogus=1)


def test_glm_missing_response(rng):
    f, _ = _regression_data(rng, n=100)
    with pytest.raises(ValueError, match="supervised"):
        GLM().train(training_frame=f)


def test_glm_auto_family(rng):
    f, _ = _regression_data(rng, n=300)
    m = GLM(family="AUTO").train(y="y", training_frame=f)
    assert m.params["family"] == "gaussian"


def test_glm_max_iterations_validated(rng):
    f, _ = _regression_data(rng, n=100)
    with pytest.raises(ValueError, match="max_iterations"):
        GLM(max_iterations=0).train(y="y", training_frame=f)


def test_glm_impute_without_standardize(rng):
    """NaNs must impute to the column mean even with standardize=False (review regression)."""
    x = np.array([1.0, 2.0, 3.0, np.nan, 4.0] * 20)
    y = np.nan_to_num(x, nan=2.5) * 2.0
    f = Frame.from_arrays({"x": x, "y": y})
    m = GLM(standardize=False).train(y="y", training_frame=f)
    assert m.training_metrics.rmse < 1e-3  # exact fit only if NaN->mean(2.5)


def test_glm_tweedie_power_passthrough(rng):
    n = 2000
    x = rng.normal(size=n)
    mu = np.exp(0.4 * x + 0.5)
    y = rng.poisson(mu) * rng.gamma(2.0, 0.5, size=n)
    f = Frame.from_arrays({"x": x, "y": y})
    m11 = GLM(family="tweedie", tweedie_variance_power=1.1, standardize=False).train(y="y", training_frame=f)
    m19 = GLM(family="tweedie", tweedie_variance_power=1.9, standardize=False).train(y="y", training_frame=f)
    # different variance powers must give different fits (was silently ignored)
    assert abs(m11.coef()["x"] - m19.coef()["x"]) > 1e-4


def test_glm_builder_reusable_after_auto(rng):
    """Builder params must not be mutated by training (review regression)."""
    f, _ = _regression_data(rng, n=200)
    b = GLM(family="AUTO")
    n2 = 200
    x = rng.normal(size=n2)
    yb = np.where(x > 0, "p", "n").astype(object)
    fb = Frame.from_arrays({"x": x, "y": yb})
    m1 = b.train(y="y", training_frame=fb)
    assert m1.params["family"] == "binomial"
    assert b.params["family"] == "AUTO"
    m2 = b.train(y="y", training_frame=f)  # numeric response: AUTO -> gaussian
    assert m2.params["family"] == "gaussian"


def test_glm_lasso_sparsifies(rng):
    """Elastic-net L1 with proper units: moderate lambda zeroes the null coef
    but keeps real signals."""
    f, beta = _regression_data(rng)  # true beta [1.5, -2, 0.5, 0]
    m = GLM(alpha=1.0, lambda_=0.05).train(y="y", training_frame=f)
    bn = m.coef_norm()
    assert abs(bn["x3"]) < 1e-6, bn          # pure-noise coef zeroed
    assert abs(bn["x0"]) > 0.5 and abs(bn["x1"]) > 0.5


def test_glm_p_values(rng):
    """Wald inference (reference: GLM.java computePValues): strong predictor
    gets p ~ 0, pure-noise predictor p > 0.05."""
    n = 500
    X = rng.normal(size=(n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + rng.normal(scale=1.0, size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x0": X[:, 0], "noise": X[:, 1], "y": y})
    m = GLM(family="gaussian", lambda_=0.0, compute_p_values=True).train(
        y="y", training_frame=fr)
    tbl = {r["name"]: r for r in m.coef_table()}
    assert tbl["x0"]["p_value"] < 1e-6
    assert tbl["noise"]["p_value"] > 0.01
    # SE sanity: sigma/sqrt(n) scale for a standardized design
    assert 0.0 < tbl["x0"]["std_error"] < 1.0
    with pytest.raises(ValueError, match="regularization"):
        GLM(family="gaussian", lambda_=0.5, compute_p_values=True).train(
            y="y", training_frame=fr)


def test_glm_lambda_search(rng):
    n = 400
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (1.5 * X[:, 0] - X[:, 1] + rng.normal(scale=0.5, size=n)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = y
    fr = Frame.from_arrays(cols)
    m = GLM(family="gaussian", alpha=1.0, lambda_search=True, nlambdas=20).train(
        y="y", training_frame=fr)
    path = m.get_regularization_path()
    assert len(path) >= 2
    lams = [p["lambda_"] for p in path]
    assert all(a > b for a, b in zip(lams, lams[1:]))   # decreasing
    devs = [p["deviance"] for p in path]
    assert devs[-1] <= devs[0] + 1e-6                    # deviance improves
    assert m.output["lambda_best"] in lams
    # the selected fit actually learned the signal
    assert m.training_metrics.r2 > 0.8


def test_glm_negativebinomial_and_quasibinomial(rng):
    """New families (reference: GLM negativebinomial w/ theta,
    quasibinomial/fractionalbinomial on continuous [0,1] response)."""
    n = 600
    X = rng.normal(size=(n, 2)).astype(np.float32)
    mu = np.exp(0.7 * X[:, 0] + 1.0)
    y_nb = rng.negative_binomial(n=2, p=2 / (2 + mu)).astype(np.float32)
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "y": y_nb})
    m = GLM(family="negativebinomial", theta=0.5, lambda_=0.0).train(
        y="y", training_frame=fr)
    c = m.coef()
    assert abs(c["x0"] - 0.7) < 0.15          # recovers the log-link slope
    pred = np.asarray(m.predict(fr).vec("predict").to_numpy())
    assert (pred > 0).all()

    p_frac = 1 / (1 + np.exp(-(1.5 * X[:, 0])))
    y_frac = np.clip(p_frac + rng.normal(scale=0.05, size=n), 0, 1).astype(np.float32)
    fr2 = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "y": y_frac})
    m2 = GLM(family="fractionalbinomial", lambda_=0.0).train(y="y", training_frame=fr2)
    pred2 = np.asarray(m2.predict(fr2).vec("predict").to_numpy())
    assert (pred2 >= 0).all() and (pred2 <= 1).all()
    assert np.corrcoef(pred2, p_frac)[0, 1] > 0.95
