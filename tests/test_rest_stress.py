"""Concurrent-REST stress: multiple clients racing on shared keys
(VERDICT r4 weak #7 / next #5).

Reference contract: ``water/Lockable.java:1-299`` — a model build
write-locks its destination and read-locks its input frames, so two
clients hammering train/predict/delete on the same keys never corrupt
state or crash the cloud; a delete of an in-use key waits for the lock.
Here the threaded REST server (api/server.py) + ``utils/registry.LOCKS``
must provide the same guarantee.  Every server-side error that is NOT a
client-visible 4xx-style KeyError (key already deleted — an accepted
outcome of racing deletes) fails the test.
"""

import threading
import time

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OClient, H2OServer
from h2o3_tpu.utils.registry import DKV, LOCKS


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


@pytest.fixture
def shared_frame(rng):
    n = 300
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0)
    f = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.array(["yes" if t else "no" for t in y], dtype=object)},
        key="stress_frame")
    DKV.put("stress_frame", f)
    return f


class TestKeyLocks:
    """Unit semantics of the Lockable analog itself."""

    def test_readers_shared_writer_exclusive(self):
        order = []
        locks = LOCKS.__class__()
        with locks.read("k"):
            with locks.read("k"):       # shared + same-thread re-read
                order.append("r2")

        t_done = threading.Event()

        def writer():
            with locks.write("k"):
                order.append("w")
            t_done.set()

        with locks.read("k"):
            t = threading.Thread(target=writer)
            t.start()
            time.sleep(0.1)
            assert not t_done.is_set()   # writer waits for the reader
            order.append("r-release")
        t.join(5)
        assert t_done.is_set()
        assert order == ["r2", "r-release", "w"]

    def test_write_reentrant_same_thread(self):
        locks = LOCKS.__class__()
        with locks.write("k"), locks.write("k"):
            with locks.read("k"):        # write -> read downgrade is fine
                pass
        # fully released: another thread can take it immediately
        acquired = threading.Event()

        def w():
            with locks.write("k"):
                acquired.set()

        t = threading.Thread(target=w)
        t.start()
        t.join(5)
        assert acquired.is_set()

    def test_multi_key_total_order_no_deadlock(self):
        locks = LOCKS.__class__()
        stop = time.time() + 2.0
        errs = []

        def worker(keys):
            try:
                while time.time() < stop:
                    with locks.write(*keys):
                        pass
            except Exception as e:          # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(ks,))
              for ks in (("a", "b"), ("b", "c"), ("c", "a"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
            assert not t.is_alive(), "deadlock between multi-key writers"
        assert not errs

    def test_mixed_write_read_sets_no_abba(self):
        """Two builds with swapped model/frame roles: write(F)+read(M) vs
        write(M)+read(F) must never wedge (the single-locked()-call global
        order is what prevents it)."""
        locks = LOCKS.__class__()
        stop = time.time() + 2.0
        errs = []

        def worker(w, r):
            try:
                while time.time() < stop:
                    with locks.locked(write=(w,), read=(r,)):
                        pass
            except Exception as e:          # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=("F", "M")),
              threading.Thread(target=worker, args=("M", "F"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
            assert not t.is_alive(), "ABBA deadlock across write+read sets"
        assert not errs


def test_delete_waits_for_training(server, shared_frame):
    """DELETE of the in-training model key must not corrupt the build:
    either it waits for the write lock (reference semantics) and removes
    the finished model, or the build re-puts after — in both orders the
    final state is consistent and nothing 500s."""
    client = H2OClient(server.url)
    model = client.train("gbm", "stress_frame", y="y", ntrees=20,
                         max_depth=3, model_id="stress_gbm")
    assert model["output"]["training_metrics"]["auc"] > 0.5
    # delete while a fresh training into the SAME key is in flight
    r = client.request("POST", "/3/ModelBuilders/gbm", dict(
        training_frame="stress_frame", response_column="y", ntrees=30,
        model_id="stress_gbm"))
    client.rm("stress_gbm")               # waits on the write lock
    client._poll(r["job"]["key"]["name"])
    # consistent end state: key either gone or a complete trained model
    try:
        m = client.model("stress_gbm")
        assert m["output"]["training_metrics"]["auc"] > 0.5
    except RuntimeError as e:
        assert "404" in str(e)


def test_concurrent_clients_stress(server, shared_frame):
    """2 trainer threads + predictor + deleter + frame-churner, all live
    against one server; no unexpected server error may surface."""
    url = server.url
    stop = time.time() + 12.0
    unexpected: list[str] = []

    def note(e: Exception, who: str):
        msg = str(e)
        # accepted raced outcomes: 404 after a concurrent delete, or the
        # registry reporting a mid-request vanished key
        if "404" in msg or "KeyError" in msg or "not found" in msg.lower():
            return
        unexpected.append(f"{who}: {type(e).__name__}: {msg}")

    def trainer(tid: int):
        c = H2OClient(url)
        i = 0
        while time.time() < stop:
            i += 1
            try:
                m = c.train("gbm" if tid else "glm", "stress_frame", y="y",
                            ntrees=5, max_depth=3,
                            model_id=f"stress_t{tid}_{i}")
                auc = m["output"]["training_metrics"].get("auc")
                if auc is not None:
                    assert 0.0 <= auc <= 1.0
                c.rm(f"stress_t{tid}_{i}")
            except Exception as e:        # noqa: BLE001
                note(e, f"trainer{tid}")

    def predictor():
        c = H2OClient(url)
        m = c.train("gbm", "stress_frame", y="y", ntrees=3, max_depth=2,
                    model_id="stress_scorer")
        del m
        while time.time() < stop:
            try:
                dest = c.predict("stress_scorer", "stress_frame")
                c.rm(dest)
            except Exception as e:        # noqa: BLE001
                note(e, "predictor")

    def churner():
        """Creates and deletes its OWN frames — registry churn under the
        readers' feet."""
        c = H2OClient(url)
        i = 0
        while time.time() < stop:
            i += 1
            key = f"churn_{i}"
            try:
                c.rapids(f'(assign {key} (rep_len 1.5 50))', id=key)
                c.rm(key)
            except Exception as e:        # noqa: BLE001
                note(e, "churner")

    threads = [threading.Thread(target=trainer, args=(0,)),
               threading.Thread(target=trainer, args=(1,)),
               threading.Thread(target=predictor),
               threading.Thread(target=churner)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(90)
        assert not t.is_alive(), "stress thread wedged (lock deadlock?)"
    assert not unexpected, "\n".join(unexpected[:10])
    # the server survived and still answers
    assert H2OClient(url).ping()
