"""Infogram tests (reference: h2o-admissibleml hex/Infogram)."""

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.infogram import Infogram


def _frame(rng, n=1500):
    x0 = rng.normal(size=n).astype(np.float32)          # strong signal
    # redundant copy of x0: tight noise so its unique information is ~zero
    x1 = (x0 + rng.normal(scale=0.05, size=n)).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)          # pure noise
    x3 = rng.normal(size=n).astype(np.float32)          # independent signal
    logit = 2.0 * x0 + 1.5 * x3
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    return Frame.from_arrays({"x0": x0, "x1": x1, "x2": x2, "x3": x3, "y": y})


def test_core_infogram(rng):
    fr = _frame(rng)
    m = Infogram(seed=7).train(y="y", training_frame=fr)
    data = {d["column"]: d for d in m.infogram_data()}
    assert set(data) == {"x0", "x1", "x2", "x3"}
    # independent signal x3 must be admissible: relevant AND irreplaceable
    assert "x3" in m.get_admissible_features()
    # pure noise must not be admissible
    assert "x2" not in m.get_admissible_features()
    # redundant copy: x1's CMI must be far below the max (its info is in x0)
    assert data["x1"]["cmi"] < 0.6
    assert data["x3"]["cmi"] > 0.5
    # normalizations land in [0, 1]
    for d in data.values():
        assert -1e-9 <= d["cmi"] <= 1 + 1e-9
        assert -1e-9 <= d["relevance"] <= 1 + 1e-9


def test_fair_infogram(rng):
    n = 500
    prot = rng.choice(["g1", "g2"], size=n)
    leak = (prot == "g1").astype(np.float32) + rng.normal(scale=0.05, size=n).astype(np.float32)
    safe = rng.normal(size=n).astype(np.float32)
    logit = 2.0 * (prot == "g1") + 1.5 * safe
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"prot": prot, "leak": leak, "safe": safe, "y": y})
    m = Infogram(protected_columns=["prot"], seed=7).train(y="y", training_frame=fr)
    data = {d["column"]: d for d in m.infogram_data()}
    assert set(data) == {"leak", "safe"}
    # 'safe' carries info beyond the protected attribute; 'leak' mostly doesn't
    assert data["safe"]["cmi"] > data["leak"]["cmi"]
    assert "safe" in m.get_admissible_features()


def test_infogram_glm_surrogate(rng):
    fr = _frame(rng, n=300)
    m = Infogram(algorithm="glm", seed=3).train(y="y", training_frame=fr)
    assert len(m.infogram_data()) == 4
