"""Ops plane — the layer that turns observability into behavior.

Reference: H2O-3's L1 substrate (PAPER.md) arbitrates resources with a
priority scheduler and a Cleaner; TensorFlow (PAPERS.md) is the template
for a runtime that self-manages placement and memory under load. Four
PRs of observability (metrics, traces, memory, compute, health) end at a
human; this package closes the loop:

- :mod:`h2o3_tpu.ops_plane.remediate` — a policy engine subscribed to
  :class:`~h2o3_tpu.utils.incidents.IncidentLog` rising edges, mapping
  each health-rule class to one bounded, cooldown-limited action.
- :mod:`h2o3_tpu.ops_plane.actions` — the action catalog + the
  append-only :class:`~h2o3_tpu.ops_plane.actions.ActionLog` every
  mutation of a live policy target flows through (graftlint ACT001).
- :mod:`h2o3_tpu.ops_plane.tenancy` — per-tenant admission quotas
  (device-seconds, bytes, QPS) so no one caller can starve the rest.

Everything is opt-in: nothing here imports at server start beyond the
subscription, the kill switch ``H2O3TPU_REMEDIATE=off|observe|act``
defaults to ``observe`` (log-what-I-would-do, touch nothing), and the
serving/DKV hot paths only consult tenancy when this package is already
loaded. docs/OPERATIONS.md is the operator-facing catalog.
"""

from h2o3_tpu.ops_plane.actions import ACTIONS, ActionLog
from h2o3_tpu.ops_plane.remediate import ENGINE, RemediationEngine, install
from h2o3_tpu.ops_plane.tenancy import QUOTAS, QuotaExceeded, QuotaManager

__all__ = ["ACTIONS", "ActionLog", "ENGINE", "RemediationEngine",
           "install", "QUOTAS", "QuotaExceeded", "QuotaManager"]
