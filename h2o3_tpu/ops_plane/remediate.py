"""RemediationEngine — incident rising edges → bounded audited actions.

Reference: the "self-managing runtime" half of the TensorFlow design
(PAPERS.md) scoped by H2O-3 conservatism — the engine may only take
actions from the fixed catalog (:mod:`h2o3_tpu.ops_plane.actions`), one
per incident episode, cooldown-limited per rule, and only when the
operator turned the key:

``H2O3TPU_REMEDIATE`` (resolved at each incident, never at import —
the ENV001 discipline):

- ``off``      — the listener does nothing at all;
- ``observe``  — DEFAULT: every decision is recorded in the ActionLog
  with outcome ``observed``; no state is touched (log-what-I-would-do);
- ``act``      — the action executes; outcome/rollback are recorded and
  the ``action_id`` is stamped back into the trigger incident.

The policy map is deliberately small and static — four of the ten health
rules have a safe automatic response; the rest (leak growth, MFU
collapse, retry exhaustion…) page a human, on purpose. The subscription
uses :meth:`IncidentLog.add_listener` rising edges, so a repeating trip
(folded into the open incident) can never re-fire the action — one
episode, one action, until the incident resolves and re-opens.
"""

from __future__ import annotations

import os
import time

from h2o3_tpu.ops_plane.actions import ACTIONS, ACTIONS_TOTAL
from h2o3_tpu.utils import lockwitness

#: health rule -> action class (actions.CATALOG names the functions)
POLICY: dict = {
    "serving_shed_rate": "serving_relief",
    "serving_p99_slo": "serving_relief",
    "memory_spill_thrash": "raise_cleaner_budget",
    "elastic_heartbeat_gap": "reassign_shards",
    "compute_recompile_storm": "pin_bucket",
}

MODES = ("off", "observe", "act")


def remediate_mode() -> str:
    """The kill switch, resolved at call time. Unknown values read as
    ``observe`` — a typo in the knob must fail safe (log, touch
    nothing), not silently arm the engine."""
    mode = os.environ.get("H2O3TPU_REMEDIATE", "observe").strip().lower()
    return mode if mode in MODES else "observe"


def cooldown_secs_from_env(default: float = 60.0) -> float:
    """Per-rule action cooldown (``H2O3TPU_OPS_COOLDOWN_SECS``) — the
    rate limit between actions for the SAME rule."""
    try:
        return max(float(os.environ.get("H2O3TPU_OPS_COOLDOWN_SECS", "")
                         or default), 0.0)
    except ValueError:
        return default


class RemediationEngine:
    """The incident listener (singleton :data:`ENGINE`; tests build their
    own with a private ActionLog)."""

    def __init__(self, actions=None):
        self.actions = actions if actions is not None else ACTIONS
        self._lock = lockwitness.lock("ops_plane.remediate.RemediationEngine._lock")
        self._last_action: dict[str, float] = {}    # rule -> monotonic
        self._installed_on: list = []

    # -- subscription --------------------------------------------------------

    def install(self, incident_log=None) -> None:
        """Subscribe to ``incident_log`` rising edges (default: the
        process-wide ring). Idempotent — add_listener dedupes."""
        if incident_log is None:
            from h2o3_tpu.utils.incidents import INCIDENTS
            incident_log = INCIDENTS
        incident_log.add_listener(self.on_incident)
        with self._lock:
            if incident_log not in self._installed_on:
                self._installed_on.append(incident_log)

    def uninstall(self) -> None:
        with self._lock:
            logs, self._installed_on = self._installed_on, []
        for log in logs:
            log.remove_listener(self.on_incident)

    # -- the decision --------------------------------------------------------

    def on_incident(self, record: dict, log) -> "dict | None":
        """One incident OPEN → at most one audited action. Returns the
        action record (or None: mode off, unmapped rule, or cooldown)."""
        mode = remediate_mode()
        if mode == "off":
            return None
        rule = record.get("rule")
        action = POLICY.get(rule)
        if action is None:
            return None       # this rule pages a human, by design
        now = time.monotonic()
        cooldown = cooldown_secs_from_env()
        with self._lock:
            last = self._last_action.get(rule)
            if last is not None and now - last < cooldown:
                # rate limit: metered but NOT appended — a storm of
                # re-opened incidents inside the cooldown must not fill
                # the audit ring with no-ops
                ACTIONS_TOTAL.labels(rule=rule, action=action,
                                     outcome="cooldown").inc()
                return None
            self._last_action[rule] = now
        rec = self.actions.record(action, rule, record.get("id"), mode)
        if mode == "act" and log is not None:
            log.annotate_action(record.get("id"), rec["id"])
        return rec

    # -- views ---------------------------------------------------------------

    def policy_view(self) -> dict:
        """The ``GET /3/Ops`` policy block: mode, map, bounds."""
        from h2o3_tpu.ops_plane.actions import (cleaner_cap_factor_from_env,
                                                max_replicas_from_env)
        return {
            "mode": remediate_mode(),
            "cooldown_secs": cooldown_secs_from_env(),
            "policy": dict(POLICY),
            "bounds": {"max_replicas": max_replicas_from_env(),
                       "cleaner_cap_factor": cleaner_cap_factor_from_env(),
                       "reassign_workers_per_action": 1,
                       "spill_keys_per_action": 2},
        }

    def reset(self) -> None:
        """Forget cooldowns (tests/bench isolation only)."""
        with self._lock:
            self._last_action.clear()


#: the process-wide engine (installed by ``H2OServer.start``)
ENGINE = RemediationEngine()


def install(incident_log=None) -> None:
    """Module-level convenience: subscribe the process engine."""
    ENGINE.install(incident_log)
