"""The remediation action catalog + the append-only audit log.

Reference: H2O-3's Cleaner is the archetype — a runtime daemon allowed to
change system state (spill memory) only inside strict bounds (the
budget); this module holds every bounded mutation the remediation engine
(:mod:`h2o3_tpu.ops_plane.remediate`) may take, and the audit trail that
makes them operable:

- **actions are functions named ``act_*``** returning what they did, how
  to undo it, and whether they actually touched anything. Each action is
  *bounded* (replica cap, Cleaner-budget ceiling, one worker per
  reassignment, one pinned bucket) so a runaway policy cannot scale or
  spill without limit.
- **ActionLog.record is the ONLY entry point** — graftlint ACT001
  enforces that no ops-plane code calls a live policy setter (replica
  count, Cleaner budget, admission window, shard map) outside an
  ``act_*`` body, and no code calls an ``act_*`` function except the
  log. In ``observe`` mode the log records what it WOULD do and executes
  nothing; in ``act`` mode it executes, stamps the outcome
  (``applied`` / ``skipped`` / ``failed``), and keeps a rollback token.

Probe seams (``_scoring`` / ``_cleaner`` / ``_live_groups`` /
``_scorer_cache``) are module-level so tests monkeypatch them exactly
like the health evaluator's (utils/health.py).
"""

from __future__ import annotations

import os
import time
import uuid

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

#: every recorded action, by rule, action class, and outcome
ACTIONS_TOTAL = _tm.METRICS.counter(
    "h2o3_ops_actions", "remediation actions recorded",
    ("rule", "action", "outcome"))

#: audit ring capacity (append-only semantics within the bound)
LOG_CAPACITY = 256


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def max_replicas_from_env(default: int = 4) -> int:
    """Replica-count ceiling for serving relief
    (``H2O3TPU_OPS_MAX_REPLICAS``)."""
    return max(_env_int("H2O3TPU_OPS_MAX_REPLICAS", default), 1)


def cleaner_cap_factor_from_env(default: float = 4.0) -> float:
    """How far the Cleaner budget may be raised, as a multiple of its
    value when remediation first touched it
    (``H2O3TPU_OPS_CLEANER_CAP_FACTOR``)."""
    return max(_env_float("H2O3TPU_OPS_CLEANER_CAP_FACTOR", default), 1.0)


# -- live-target seams (tests monkeypatch these) -----------------------------

def _scoring():
    """The scoring tier ONLY if serving is already loaded — remediation
    must not be what imports the stack."""
    import sys
    m = sys.modules.get("h2o3_tpu.serving.service")
    return m.SCORING if m is not None else None


def _scorer_cache():
    svc = _scoring()
    return svc.cache if svc is not None else None


def _cleaner():
    from h2o3_tpu.utils.cleaner import CLEANER
    return CLEANER


def _live_groups():
    from h2o3_tpu.parallel import elastic
    return elastic.live_groups()


def _quotas():
    from h2o3_tpu.ops_plane.tenancy import QUOTAS
    return QUOTAS


#: Cleaner budget when remediation first raised it — the ceiling anchor.
#: Keyed by id(cleaner) so a test's private Cleaner gets its own anchor.
_CLEANER_BASE: dict[int, int] = {}
_CLEANER_BASE_LOCK = lockwitness.lock("ops_plane.actions._CLEANER_BASE_LOCK")


class _ActionResult:
    """What an ``act_*`` function did: parameters for the audit record, a
    rollback thunk (None = irreversible/nothing to undo), and whether it
    touched anything (``skipped`` actions changed no state)."""

    __slots__ = ("outcome", "params", "rollback")

    def __init__(self, outcome: str, params: dict, rollback=None):
        self.outcome = outcome      # "applied" | "skipped"
        self.params = params
        self.rollback = rollback


def _applied(params: dict, rollback=None) -> _ActionResult:
    return _ActionResult("applied", params, rollback)


def _skipped(reason: str, **params) -> _ActionResult:
    return _ActionResult("skipped", {"reason": reason, **params})


# -- the catalog (each bounded; docs/OPERATIONS.md is the operator table) ----

def act_serving_relief(incident: dict) -> _ActionResult:
    """Shed-rate / p99 trip: widen the admission window of every resident
    model with an SLO target (cumulative ×1.5, bounded at ×4 the original
    — ``ScoringService.widen_admission``); with nothing to widen, add ONE
    scoring replica up to ``H2O3TPU_OPS_MAX_REPLICAS``. Rollback restores
    the original targets / removes the added replica."""
    svc = _scoring()
    if svc is None:
        return _skipped("serving tier not loaded")
    widened = svc.widen_admission()
    if widened:
        return _applied({"widened": widened},
                        rollback=svc.restore_admission)
    pool = svc.pool
    cap = max_replicas_from_env()
    if pool is not None and len(pool.replicas) < cap:
        n = len(pool.replicas) + 1
        svc.configure_replicas(n)
        return _applied({"replicas": n},
                        rollback=lambda: svc.configure_replicas(n - 1))
    return _skipped("no SLO target to widen and no replica headroom",
                    replica_cap=cap)


def act_raise_cleaner_budget(incident: dict) -> _ActionResult:
    """Spill-thrash trip: raise the Cleaner budget ×1.5 so the working
    set fits, bounded at ``H2O3TPU_OPS_CLEANER_CAP_FACTOR`` × the budget
    remediation first saw. At the ceiling, fall back to parking the
    coldest quota'd tenant's two least-recently-touched keys on disk
    (``Cleaner.force_spill`` — spilled behind stubs, never deleted).
    Rollback restores the previous budget."""
    cleaner = _cleaner()
    budget = cleaner.budget
    if budget is None:
        return _skipped("cleaner disabled (no budget to raise)")
    with _CLEANER_BASE_LOCK:
        base = _CLEANER_BASE.setdefault(id(cleaner), int(budget))
    cap = int(base * cleaner_cap_factor_from_env())
    new_budget = min(int(budget * 1.5), cap)
    if new_budget > budget:
        def rollback(c=cleaner, prev=int(budget)):
            c.budget = prev
        cleaner.budget = new_budget
        return _applied({"budget_bytes": new_budget,
                         "previous_bytes": int(budget),
                         "cap_bytes": cap}, rollback=rollback)
    quotas = _quotas()
    tenant = quotas.coldest_tenant()
    if tenant is not None:
        keys = sorted(quotas.keys_of(tenant),
                      key=cleaner.last_touched)
        spilled = cleaner.force_spill(keys, limit=2)
        if spilled:
            return _applied({"budget_at_cap_bytes": cap,
                             "evicted_tenant": tenant,
                             "spilled_keys": spilled})
    return _skipped("budget at ceiling and no cold tenant keys to park",
                    cap_bytes=cap)


def act_reassign_shards(incident: dict) -> _ActionResult:
    """Heartbeat-gap trip: preemptively move the silent worker's data
    shards to live peers NOW (``ElasticGroup.preempt_reassign``) instead
    of waiting for the round-boundary sweep — bounded to the ONE worst
    worker per action. Rollback re-admits the worker at the next round
    boundary (``request_join``)."""
    worst = None     # (gap_ms, group, wid)
    for g in _live_groups():
        for row in g.rows():
            if row["state"] in ("ACTIVE", "SUSPECT"):
                gap = row["last_heartbeat_ago_ms"]
                if worst is None or gap > worst[0]:
                    worst = (gap, g, row["worker"])
    if worst is None:
        return _skipped("no live elastic workers to inspect")
    gap_ms, group, wid = worst
    moved = group.preempt_reassign(wid)

    def rollback(g=group, w=wid):
        g.request_join(w)
    return _applied({"group": group.group_id, "worker": wid,
                     "heartbeat_gap_ms": gap_ms, "moved_shards": moved},
                    rollback=rollback)


def act_pin_bucket(incident: dict) -> _ActionResult:
    """Recompile-storm trip: pin the scorer cache's bucket floor at the
    largest bucket already compiled, collapsing churning small signatures
    onto one warm executable (``ScorerCache.pin_bucket`` — padding waste
    bounded by the pin). Rollback unpins."""
    cache = _scorer_cache()
    if cache is None:
        return _skipped("serving tier not loaded")
    if cache.pinned_bucket() is not None:
        return _skipped("bucket already pinned",
                        pinned_bucket=cache.pinned_bucket())
    buckets = cache.compiled_buckets()
    if not buckets:
        return _skipped("no compiled serving signatures to pin")
    pinned = cache.pin_bucket(max(buckets))
    return _applied({"pinned_bucket": pinned,
                     "compiled_buckets": buckets},
                    rollback=cache.unpin_bucket)


#: rule-facing registry — the policy map (remediate.py) names these
CATALOG: dict = {
    "serving_relief": act_serving_relief,
    "raise_cleaner_budget": act_raise_cleaner_budget,
    "reassign_shards": act_reassign_shards,
    "pin_bucket": act_pin_bucket,
}


class ActionLog:
    """Append-only audit trail of remediation actions — THE gateway every
    policy mutation flows through (graftlint ACT001). One record per
    decision: action class, trigger rule + incident id, parameters,
    outcome, and a rollback token when the action is reversible."""

    def __init__(self, capacity: int = LOG_CAPACITY):
        self._lock = lockwitness.lock("ops_plane.actions.ActionLog._lock")
        self._capacity = capacity
        self._records: list[dict] = []
        self._rollbacks: dict[str, object] = {}   # action id -> thunk

    def record(self, action: str, rule: str, incident_id: str | None,
               mode: str) -> dict:
        """Decide-and-audit one action. ``observe`` mode appends the
        record with outcome ``observed`` and EXECUTES NOTHING; ``act``
        mode runs the catalog function and stamps what happened. The
        record is returned (and appended) in every case — including
        ``failed`` — because an audit trail with holes is not one."""
        fn = CATALOG.get(action)
        aid = f"act_{uuid.uuid4().hex[:10]}"
        rec = {"id": aid, "action": action, "rule": rule,
               "incident_id": incident_id, "mode": mode,
               "at_ms": int(time.time() * 1000),
               "params": {}, "outcome": None, "rollback_token": None}
        if fn is None:
            rec["outcome"] = "failed"
            rec["params"] = {"error": f"unknown action {action!r}"}
        elif mode != "act":
            rec["outcome"] = "observed"
        else:
            try:
                result = fn({"id": incident_id, "rule": rule})
                rec["outcome"] = result.outcome
                rec["params"] = result.params
                if result.rollback is not None:
                    rec["rollback_token"] = aid
            except Exception as e:   # noqa: BLE001 — a failed action is a
                # record, not a crash of the incident path that fired it
                rec["outcome"] = "failed"
                rec["params"] = {"error": f"{type(e).__name__}: {e}"}
                result = None
        with self._lock:
            self._records.append(rec)
            del self._records[:-self._capacity]
            if rec["rollback_token"] is not None:
                self._rollbacks[aid] = result.rollback
        ACTIONS_TOTAL.labels(rule=rule, action=action,
                             outcome=rec["outcome"]).inc()
        return dict(rec)

    def rollback(self, action_id: str) -> bool:
        """Undo a recorded action by its rollback token; the rollback is
        itself appended to the trail. False when the token is unknown or
        already consumed."""
        with self._lock:
            thunk = self._rollbacks.pop(action_id, None)
            src = next((r for r in self._records
                        if r["id"] == action_id), None)
        if thunk is None:
            return False
        rec = {"id": f"act_{uuid.uuid4().hex[:10]}", "action": "rollback",
               "rule": src["rule"] if src else None,
               "incident_id": src["incident_id"] if src else None,
               "mode": "act", "at_ms": int(time.time() * 1000),
               "params": {"rolls_back": action_id}, "outcome": None,
               "rollback_token": None}
        try:
            thunk()
            rec["outcome"] = "applied"
        except Exception as e:   # noqa: BLE001 — audit the failure too
            rec["outcome"] = "failed"
            rec["params"]["error"] = f"{type(e).__name__}: {e}"
        with self._lock:
            self._records.append(rec)
            del self._records[:-self._capacity]
        ACTIONS_TOTAL.labels(rule=rec["rule"] or "unknown",
                             action="rollback",
                             outcome=rec["outcome"]).inc()
        return rec["outcome"] == "applied"

    # -- views ---------------------------------------------------------------

    def list(self) -> list[dict]:
        """All records, newest first (the ``GET /3/Ops`` action log)."""
        with self._lock:
            return [dict(r) for r in reversed(self._records)]

    def applied_total(self) -> int:
        with self._lock:
            return sum(1 for r in self._records
                       if r["outcome"] == "applied")

    def recorded_total(self) -> int:
        with self._lock:
            return len(self._records)

    def reset(self) -> None:
        """Drop the trail (tests/bench isolation only)."""
        with self._lock:
            self._records.clear()
            self._rollbacks.clear()


#: the process-wide audit trail (``GET /3/Ops`` → ``actions``)
ACTIONS = ActionLog()
