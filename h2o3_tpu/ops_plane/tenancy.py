"""Multi-tenant admission — per-tenant quotas over the meters we already have.

Reference: H2O-3's F/J priority ladder keeps one user's giant parse from
starving another's interactive scoring; "millions of users" (PAPER.md)
needs the same property across *tenants*. This module prices each tenant
by the three meters earlier PRs built —

- **device-seconds**: the scoring tier charges each request its pro-rata
  share of batch device wall (``serving/service.py``, queue wait
  excluded) into a rolling window;
- **bytes**: DKV puts tag their key with the putting tenant
  (``utils/registry.py``) and the ledger prices keys with the same
  ``MemoryMeter`` measure ``/3/Memory`` reports;
- **QPS**: a one-second sliding admission window.

Requests carry a tenant id (REST ``X-H2O3-Tenant`` header or ``tenant``
param; untagged callers are the ``default`` tenant). ``QuotaManager.
admit`` enforces configured budgets; over-quota work is refused with
:class:`QuotaExceeded` — the REST layer maps it to ``429 + Retry-After``,
never a silent drop. Tenants without a configured quota are admitted
unmetered-by-budget but still metered (usage shows in ``GET /3/Ops``).

Metric labels are bounded: only the default tenant and tenants with a
configured quota get their own label; everyone else folds into
``other`` (an open tenant namespace must not explode the registry).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import time

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

DEFAULT_TENANT = "default"

_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "h2o3_tenant", default=DEFAULT_TENANT)

#: admissions by tenant and outcome (admitted / shed_qps /
#: shed_device_seconds / shed_bytes)
TENANT_REQUESTS = _tm.METRICS.counter(
    "h2o3_tenant_requests", "tenant admissions by outcome",
    ("tenant", "outcome"))

#: DKV bytes attributed to each tenant's tagged keys
TENANT_BYTES = _tm.METRICS.gauge(
    "h2o3_tenant_bytes", "DKV bytes owned by tenant", ("tenant",))

#: device-seconds charged to each tenant (scoring pro-rata batch wall)
TENANT_DEVICE_SECONDS = _tm.METRICS.counter(
    "h2o3_tenant_device_seconds", "device-seconds charged to tenant",
    ("tenant",))


def window_secs_from_env(default: float = 60.0) -> float:
    """Rolling window for the device-seconds budget
    (``H2O3TPU_TENANT_WINDOW_SECS``)."""
    try:
        return max(float(os.environ.get("H2O3TPU_TENANT_WINDOW_SECS", "")
                         or default), 1.0)
    except ValueError:
        return default


def sanitize_tenant(tenant) -> str:
    """Validate a caller-supplied tenant id (None/empty → the default
    tenant; anything outside ``[A-Za-z0-9._-]{1,64}`` raises — the REST
    layer maps that to 400, a hostile header must not mint labels)."""
    if tenant is None or tenant == "":
        return DEFAULT_TENANT
    tenant = str(tenant)
    if not _TENANT_RE.match(tenant):
        raise ValueError(f"invalid tenant id {tenant!r} "
                         "(allowed: [A-Za-z0-9._-]{1,64})")
    return tenant


def current_tenant() -> str:
    return _CURRENT.get()


@contextlib.contextmanager
def tenant_scope(tenant: str):
    """Bind the request's tenant for the current context (the REST
    dispatcher wraps each handler call; DKV puts and scoring charges made
    inside attribute to it)."""
    token = _CURRENT.set(sanitize_tenant(tenant))
    try:
        yield
    finally:
        _CURRENT.reset(token)


class QuotaExceeded(RuntimeError):
    """Admission refused under a tenant budget (HTTP 429 + Retry-After)."""

    def __init__(self, tenant: str, dimension: str, observed, budget,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"tenant {tenant!r} over {dimension} quota: "
            f"{observed} > {budget}; retry after {retry_after_s:.1f}s")
        self.tenant = tenant
        self.dimension = dimension
        self.observed = observed
        self.budget = budget
        self.retry_after_s = max(retry_after_s, 0.1)


class QuotaManager:
    """Per-tenant budgets + usage ledgers (singleton :data:`QUOTAS`)."""

    def __init__(self):
        self._lock = lockwitness.lock("ops_plane.tenancy.QuotaManager._lock")
        # tenant -> {"qps": float|None, "device_seconds": float|None,
        #            "bytes": int|None}
        self._quotas: dict[str, dict] = {}
        self._requests: dict[str, list] = {}        # admit timestamps (1s)
        self._device: dict[str, list] = {}          # (ts, secs) window
        self._device_total: dict[str, float] = {}   # lifetime
        self._key_owner: dict[str, str] = {}        # DKV key -> tenant
        self._shed: dict[str, dict] = {}            # tenant -> {dim: count}

    # -- label bounding ------------------------------------------------------

    def _label_locked(self, tenant: str) -> str:
        # graftlint: ok(_locked suffix: every caller holds self._lock)
        return tenant if tenant == DEFAULT_TENANT \
            or tenant in self._quotas else "other"

    # -- quota CRUD ----------------------------------------------------------

    def set_quota(self, tenant: str, qps=None, device_seconds=None,
                  bytes=None) -> dict:   # noqa: A002 — the REST param name
        """Install (replace) a tenant's budgets. ``None`` dimensions are
        unlimited. Returns the installed record."""
        tenant = sanitize_tenant(tenant)
        rec = {"qps": float(qps) if qps is not None else None,
               "device_seconds": (float(device_seconds)
                                  if device_seconds is not None else None),
               "bytes": int(bytes) if bytes is not None else None}
        with self._lock:
            self._quotas[tenant] = rec
        return {"tenant": tenant, **rec}

    def remove_quota(self, tenant: str) -> bool:
        with self._lock:
            return self._quotas.pop(sanitize_tenant(tenant), None) is not None

    def quotas(self) -> list[dict]:
        with self._lock:
            return [{"tenant": t, **q}
                    for t, q in sorted(self._quotas.items())]

    # -- admission -----------------------------------------------------------

    def admit(self, tenant: str | None = None) -> str:
        """Admit one request for ``tenant`` (default: the bound context
        tenant), charging the QPS window; raises :class:`QuotaExceeded`
        when any configured dimension is over budget. Returns the
        sanitized tenant id."""
        tenant = sanitize_tenant(tenant) if tenant is not None \
            else current_tenant()
        now = time.monotonic()
        window = window_secs_from_env()
        with self._lock:
            label = self._label_locked(tenant)
            quota = self._quotas.get(tenant) or {}
            reqs = self._requests.setdefault(tenant, [])
            del reqs[:self._expired(reqs, now - 1.0)]
            dev = self._device.setdefault(tenant, [])
            self._trim_device_locked(dev, now - window)
            try:
                budget = quota.get("qps")
                if budget is not None and len(reqs) >= budget:
                    retry = (reqs[0] + 1.0 - now) if reqs else 1.0
                    raise QuotaExceeded(tenant, "qps", len(reqs), budget,
                                        retry_after_s=retry)
                budget = quota.get("device_seconds")
                if budget is not None:
                    used = sum(s for _t, s in dev)
                    if used >= budget:
                        retry = (dev[0][0] + window - now) if dev else 1.0
                        raise QuotaExceeded(
                            tenant, "device_seconds", round(used, 4),
                            budget, retry_after_s=retry)
                budget = quota.get("bytes")
                if budget is not None:
                    used = self._bytes_locked(tenant)
                    if used >= budget:
                        raise QuotaExceeded(tenant, "bytes", used, budget,
                                            retry_after_s=5.0)
            except QuotaExceeded as e:
                shed = self._shed.setdefault(tenant, {})
                shed[e.dimension] = shed.get(e.dimension, 0) + 1
                TENANT_REQUESTS.labels(
                    tenant=label, outcome=f"shed_{e.dimension}").inc()
                raise
            reqs.append(now)
        TENANT_REQUESTS.labels(tenant=label, outcome="admitted").inc()
        return tenant

    @staticmethod
    def _expired(stamps: list, cutoff: float) -> int:
        i = 0
        while i < len(stamps) and stamps[i] < cutoff:
            i += 1
        return i

    @staticmethod
    def _trim_device_locked(dev: list, cutoff: float) -> None:
        i = 0
        while i < len(dev) and dev[i][0] < cutoff:
            i += 1
        del dev[:i]

    # -- charging ------------------------------------------------------------

    def charge_device_seconds(self, tenant: str, seconds: float) -> None:
        """Scoring charges each request's pro-rata device wall here
        (``serving/service.py`` after a successful score)."""
        if seconds <= 0:
            return
        now = time.monotonic()
        with self._lock:
            tenant = sanitize_tenant(tenant)
            self._device.setdefault(tenant, []).append((now, seconds))
            self._device_total[tenant] = \
                self._device_total.get(tenant, 0.0) + seconds
            label = self._label_locked(tenant)
        TENANT_DEVICE_SECONDS.labels(tenant=label).inc(seconds)

    # -- DKV tenant tagging (registry put/remove hooks) ----------------------

    def tag_key(self, key: str) -> None:
        with self._lock:
            self._key_owner[key] = current_tenant()

    def untag_key(self, key: str) -> None:
        with self._lock:
            self._key_owner.pop(key, None)

    def untag_all(self) -> None:
        with self._lock:
            self._key_owner.clear()

    def owner_of(self, key: str) -> str | None:
        with self._lock:
            return self._key_owner.get(key)

    def keys_of(self, tenant: str) -> list[str]:
        with self._lock:
            return [k for k, t in self._key_owner.items() if t == tenant]

    def _bytes_locked(self, tenant: str) -> int:
        from h2o3_tpu.utils.memory import MEMORY
        # graftlint: ok(MEMORY.key_bytes takes the meter lock; order
        # quotas→meter is one-way — the meter never calls back here)
        return sum(MEMORY.key_bytes(k)
                   for k, t in self._key_owner.items() if t == tenant)

    # -- views ---------------------------------------------------------------

    def usage(self, tenant: str) -> dict:
        now = time.monotonic()
        window = window_secs_from_env()
        with self._lock:
            tenant = sanitize_tenant(tenant)
            reqs = self._requests.get(tenant, [])
            dev = self._device.get(tenant, [])
            self._trim_device_locked(dev, now - window)
            nbytes = self._bytes_locked(tenant)
            keys = sum(1 for t in self._key_owner.values() if t == tenant)
            label = self._label_locked(tenant)
            out = {
                "tenant": tenant,
                "qps_1s": len(reqs) - self._expired(reqs, now - 1.0),
                "device_seconds_window": round(sum(s for _t, s in dev), 4),
                "device_seconds_total": round(
                    self._device_total.get(tenant, 0.0), 4),
                "bytes": nbytes, "keys": keys,
                "quota": dict(self._quotas.get(tenant) or {}) or None,
                "shed": dict(self._shed.get(tenant, {})),
            }
        TENANT_BYTES.labels(tenant=label).set(nbytes)
        return out

    def usage_all(self) -> list[dict]:
        with self._lock:
            tenants = ({DEFAULT_TENANT} | set(self._quotas)
                       | set(self._key_owner.values())
                       | set(self._device_total) | set(self._requests))
        return [self.usage(t) for t in sorted(tenants)]

    def coldest_tenant(self) -> str | None:
        """The quota'd tenant holding the most bytes — the spill-thrash
        remediation's eviction candidate when the Cleaner budget is
        already at its ceiling. Never the default tenant (evicting the
        anonymous pool would punish everyone)."""
        with self._lock:
            candidates = [t for t in self._quotas if t != DEFAULT_TENANT]
            if not candidates:
                return None
            sized = [(self._bytes_locked(t), t) for t in candidates]
        sized.sort(reverse=True)
        return sized[0][1] if sized and sized[0][0] > 0 else None

    def reset(self) -> None:
        """Drop all quotas/ledgers (tests/bench isolation only)."""
        with self._lock:
            self._quotas.clear()
            self._requests.clear()
            self._device.clear()
            self._device_total.clear()
            self._key_owner.clear()
            self._shed.clear()


#: the process-wide quota manager (``GET/POST /3/Ops``)
QUOTAS = QuotaManager()
