"""Streaming chunked parse — read → decompress → tokenize → device stages.

Reference: the 2-phase distributed parse (``water/parser/ParseDataset.java``:
a ParseSetup type/header guess pass, then an MRTask over raw file chunks with
per-chunk CSV state machines). The all-at-once path (``frame/parse.py``)
reads the whole file, materializes full host columns, and uploads once —
host peak is O(file). This module replaces that for large/compressed inputs
with the overlapped input-pipeline design (TensorFlow's prefetch/stage
decoupling, PAPERS.md): four stages connected by small bounded queues,

    read (raw byte blocks)
      → decompress (incremental gzip, line re-assembly, fixed-row batching)
      → tokenize/columnarize + encode (CSV → typed columns → CompressedChunk)
      → assemble/device_put (fuse chunks into Vecs; upload or stay lazy)

so host peak transient memory is O(chunk), not O(file) — the only O(file)
residency is the *compressed* column payloads the Frame keeps (and the
Cleaner can spill those; utils/cleaner.py). Every queue wait is bounded
with an abort-flag recheck (graftlint WTX001): a died stage can never park
its neighbours.

Type inference runs on the first chunk (the ParseSetup sample); a later
chunk that breaks a column's numeric guess raises a promote-and-reparse
restart with that column forced categorical — bounded by ncols restarts,
exactly the reference's setup-vs-parse split collapsed into a retry.
"""

from __future__ import annotations

import io
import os
import queue
import threading
import zlib

import numpy as np

from h2o3_tpu.frame.types import CAT_NA, VecType
from h2o3_tpu.ingest.encode import CompressedChunk, encode_codes, encode_numeric
from h2o3_tpu.utils import telemetry as _tm

#: raw-read block size (bytes) — the unit the read stage hands downstream
_READ_BLOCK = 1 << 20

#: bounded-queue poll period; every wait rechecks the abort flag at this
#: cadence so a dead neighbour stage can never park a thread forever
_POLL_S = 0.2

_EOF = object()


class ParsePromoted(Exception):
    """A chunk past the sample broke one or more columns' numeric guesses
    — reparse with those columns forced categorical (internal control
    flow). Carries EVERY failing column of the offending chunk so k
    simultaneous breaks cost one restart, not k."""

    def __init__(self, columns: list[str]):
        super().__init__(", ".join(columns))
        self.columns = list(columns)


class _Aborted(Exception):
    """A sibling stage failed; unwind quietly (its error is the real one)."""


class IngestStats:
    """One streaming parse's accounting — rides into ``extra.ingest`` and
    the ``h2o3_ingest_*`` metrics."""

    def __init__(self):
        self.rows = 0
        self.chunks = 0
        self.bytes_in = 0            # decompressed source bytes consumed
        self.bytes_raw = 0           # what eager float32/int32 columns would hold
        self.bytes_encoded = 0       # compressed host payload bytes
        self.restarts = 0
        self.inflight_peak = 0       # high-water of bytes queued between stages
        self._inflight = 0
        self._lock = threading.Lock()

    def grow(self, n: int) -> None:
        with self._lock:
            self._inflight += n
            if self._inflight > self.inflight_peak:
                self.inflight_peak = self._inflight

    def shrink(self, n: int) -> None:
        with self._lock:
            self._inflight -= n

    @property
    def compression_ratio(self) -> float:
        return (self.bytes_raw / self.bytes_encoded) if self.bytes_encoded \
            else 1.0

    def as_dict(self) -> dict:
        return {"rows": self.rows, "chunks": self.chunks,
                "bytes_in": self.bytes_in, "bytes_raw": self.bytes_raw,
                "bytes_encoded": self.bytes_encoded,
                "compression_ratio": round(self.compression_ratio, 3),
                "restarts": self.restarts,
                "inflight_peak_bytes": self.inflight_peak}


def chunk_rows_default() -> int:
    return int(os.environ.get("H2O3TPU_INGEST_CHUNK_ROWS", str(1 << 16)))


def queue_depth_default() -> int:
    return int(os.environ.get("H2O3TPU_INGEST_QUEUE", "4"))


# ---------------------------------------------------------------------------
# bounded-queue plumbing (WTX001-clean: every wait polls the abort flag)


def _q_put(q: "queue.Queue", item, abort: threading.Event) -> None:
    while True:
        if abort.is_set():
            raise _Aborted()
        try:
            q.put(item, timeout=_POLL_S)
            return
        except queue.Full:
            continue


def _q_get(q: "queue.Queue", abort: threading.Event):
    while True:
        if abort.is_set():
            raise _Aborted()
        try:
            return q.get(timeout=_POLL_S)
        except queue.Empty:
            continue


def _split_records(data: bytes, in_quote: bool):
    """Split ``data`` on newlines that are OUTSIDE double-quoted fields
    (RFC-4180: a quoted field may contain embedded newlines; `""` escapes
    toggle parity twice and fall out naturally). Vectorized over the block
    — a Python char loop on 1MB blocks would dominate the stage. Returns
    (records, remainder, in_quote) where ``in_quote`` is the state at the
    START of the remainder (the caller re-scans the remainder next round;
    a cut newline sits at quote depth 0, so any cut resets it)."""
    arr = np.frombuffer(data, np.uint8)
    parity = (np.cumsum(arr == ord('"')) & 1).astype(bool)
    if in_quote:
        parity = ~parity
    cuts = np.flatnonzero((arr == ord("\n")) & ~parity)
    records = []
    start = 0
    for c in cuts.tolist():
        records.append(data[start:c])
        start = c + 1
    return records, data[start:], in_quote if not len(cuts) else False


class _Stage(threading.Thread):
    """One pipeline stage: runs ``fn``, records its error, trips the shared
    abort flag so every sibling unwinds within one poll period."""

    def __init__(self, name: str, fn, abort: threading.Event):
        super().__init__(name=f"ingest-{name}", daemon=True)
        self._fn = fn
        self._abort = abort
        self.error: BaseException | None = None

    def run(self) -> None:
        try:
            self._fn()
        except _Aborted:
            pass
        except BaseException as e:   # noqa: BLE001 — carried to the driver
            self.error = e
            self._abort.set()


# ---------------------------------------------------------------------------
# stage bodies


def _read_stage(path: str, out_q, abort, progress) -> None:
    """Raw byte blocks off disk — never the whole file (graftlint ING001).
    ``progress`` is fed the raw (on-disk) byte offset for Job accounting."""
    with open(path, "rb") as fh:
        while True:
            block = fh.read(_READ_BLOCK)
            progress["raw_pos"] = fh.tell()
            if not block:
                break
            _q_put(out_q, block, abort)
    _q_put(out_q, _EOF, abort)


def _decompress_stage(in_q, out_q, abort, gzipped: bool, chunk_rows: int,
                      stats: IngestStats, has_header: bool) -> None:
    """Incremental gunzip + line re-assembly + fixed-row-count batching.

    Emits ``("header", line)`` once (when the file has one), then
    ``("lines", [line, ...])`` batches of exactly ``chunk_rows`` rows
    (except the tail). Holds at most one partial line + one open batch —
    O(chunk) regardless of file size."""
    dec = zlib.decompressobj(wbits=47) if gzipped else None   # gzip|zlib hdr
    tail = b""
    in_quote = False

    def gunzip(block: bytes) -> bytes:
        """Incremental decompress across MEMBER boundaries: concatenated
        gzip members (pigz, log rotation, `cat a.gz b.gz`) are one valid
        stream, but a decompressobj stops at its member's end — restart on
        ``unused_data`` or every member after the first silently drops."""
        nonlocal dec
        out = b""
        while block:
            out += dec.decompress(block)
            if not dec.eof:
                break
            block = dec.unused_data
            dec = zlib.decompressobj(wbits=47)
        return out
    batch: list[bytes] = []
    header_sent = not has_header

    def flush_batch():
        nonlocal batch
        if batch:
            nb = sum(len(ln) for ln in batch)
            stats.grow(nb)
            _q_put(out_q, ("lines", batch, nb), abort)
            batch = []

    while True:
        block = _q_get(in_q, abort)
        if block is _EOF:
            if dec is not None:
                tail += dec.flush()
            break
        if dec is not None:
            block = gunzip(block)
        stats.bytes_in += len(block)
        lines, tail, in_quote = _split_records(tail + block, in_quote)
        for ln in lines:
            if ln.endswith(b"\r"):
                ln = ln[:-1]
            if not ln:
                continue
            if not header_sent:
                header_sent = True
                _q_put(out_q, ("header", ln), abort)
                continue
            batch.append(ln)
            if len(batch) >= chunk_rows:
                flush_batch()
    if tail.strip():
        ln = tail[:-1] if tail.endswith(b"\r") else tail
        if not header_sent:
            _q_put(out_q, ("header", ln), abort)
        else:
            batch.append(ln)
    flush_batch()
    _q_put(out_q, _EOF, abort)


class _ColumnState:
    """One column's accumulated encoded chunks + (for categoricals) the
    insertion-order dictionary built across chunks."""

    def __init__(self, name: str, forced: "VecType | None"):
        self.name = name
        self.forced = forced
        self.kind: str | None = \
            "cat" if forced is VecType.CAT else \
            "num" if forced in (VecType.NUM, VecType.INT) else None
        self.chunks: list[CompressedChunk] = []
        self.lut: dict[str, int] = {}        # categorical level -> raw code
        # INT-vs-NUM typing mirrors the eager _guess_type contract (some
        # finite values, all integral) — NOT the achieved codec, which
        # falls back to f32 for integral spans wider than i16
        self.integral = True
        self.has_finite = False


def _tokenize_stage(in_q, out_q, abort, sep: str, na_strings, forced: dict,
                    columns: list[_ColumnState], stats: IngestStats) -> None:
    """CSV lines → typed per-column arrays → CompressedChunks.

    The first batch is the ParseSetup sample: undeclared columns guess
    numeric-vs-categorical from it. A later batch whose numeric column
    holds an unparseable token raises :class:`ParsePromoted` — the driver
    restarts the whole parse with that column forced categorical."""
    import pandas as pd

    def parse_batch(lines: list[bytes], na_filter: bool = True):
        # same dialect as the eager pd.read_csv path (no skipinitialspace):
        # a file must produce identical names/domains whichever path routes
        buf = io.BytesIO(b"\n".join(lines))
        if not na_filter:   # header parse: a column named "NA" stays "NA"
            return pd.read_csv(buf, header=None, sep=sep, dtype=str,
                               na_filter=False)
        return pd.read_csv(buf, header=None, sep=sep, dtype=str,
                           na_values=na_strings, keep_default_na=True)

    while True:
        item = _q_get(in_q, abort)
        if item is _EOF:
            break
        if item[0] == "header":
            # parse the header line with the SAME csv reader as the data
            # (quoted names containing the separator split correctly) but
            # WITHOUT NA filtering — a column literally named "NA" keeps
            # its name, matching the eager path
            hdr = parse_batch([item[1]], na_filter=False)
            names = [str(v) if v is not None and v == v else ""
                     for v in hdr.iloc[0].tolist()]
            seen: dict[str, int] = {}
            for i, n in enumerate(names):
                n = n or f"C{i + 1}"
                if n in seen:   # pandas-style dedup: x, x.1, x.2 ...
                    seen[n] += 1
                    n = f"{n}.{seen[n]}"
                seen.setdefault(n, 0)
                columns.append(_ColumnState(n, forced.get(n)))
            continue
        _tag, lines, nb = item
        df = parse_batch(lines)
        if not columns:            # headerless file: C1..Cn on first batch
            for i in range(df.shape[1]):
                columns.append(_ColumnState(f"C{i + 1}",
                                            forced.get(f"C{i + 1}")))
        if df.shape[1] != len(columns):
            raise ValueError(
                f"row has {df.shape[1]} fields, header declares "
                f"{len(columns)} (chunk of {len(lines)} rows)")
        enc_bytes = 0
        promote: list[str] = []
        for j, col in enumerate(columns):
            s = df.iloc[:, j]
            nums = pd.to_numeric(s, errors="coerce")
            if col.kind is None:
                # the sample decides: any token that is non-NA yet
                # non-numeric makes the column categorical
                bad = nums.isna() & s.notna()
                col.kind = "cat" if bool(bad.any()) else "num"
            if col.kind == "num":
                bad = nums.isna() & s.notna()
                if bool(bad.any()) and col.forced is None:
                    # only a GUESSED numeric promotes; a user-forced
                    # numeric column treats bad tokens as NA (h2o-py
                    # col_types semantics), which the coerce already did.
                    # Keep scanning: every column this chunk breaks rides
                    # ONE restart
                    promote.append(col.name)
                    continue
                host = nums.to_numpy(np.float32)
                finite = host[np.isfinite(host)]
                if finite.size:
                    col.has_finite = True
                    if not np.all(finite == np.round(finite)):
                        col.integral = False
                chunk = encode_numeric(host)
            else:
                # vectorized dictionary build: factorize the chunk (C
                # loop), then extend the cross-chunk dictionary only over
                # this chunk's O(cardinality) distinct levels
                local, uniques = pd.factorize(s)
                lut = col.lut
                if len(uniques):
                    mapping = np.array(
                        [lut.setdefault(str(u), len(lut)) for u in uniques],
                        dtype=np.int32)
                    codes = np.where(
                        local >= 0, mapping[np.clip(local, 0, None)],
                        np.int32(CAT_NA)).astype(np.int32)
                else:                      # all-NA chunk
                    codes = np.full(len(s), CAT_NA, dtype=np.int32)
                chunk = encode_codes(codes, len(lut))
            col.chunks.append(chunk)
            enc_bytes += chunk.nbytes
            stats.bytes_raw += chunk.raw_bytes
        if promote:
            raise ParsePromoted(promote)
        stats.bytes_encoded += enc_bytes
        stats.rows += df.shape[0]
        stats.chunks += 1
        stats.shrink(nb)
        _q_put(out_q, ("chunk", df.shape[0]), abort)
    _q_put(out_q, _EOF, abort)


# ---------------------------------------------------------------------------
# driver


def _assemble(columns: list[_ColumnState], nrows: int, lazy: bool):
    """Fuse each column's chunk list into one Vec. Categorical dictionaries
    are re-sorted to the parser contract (lexicographic domains) with a
    chunk-by-chunk code remap — never more than one decoded column live."""
    from h2o3_tpu.frame.vec import Vec
    from h2o3_tpu.ingest.encode import concat_chunks
    vecs = []
    for col in columns:
        if col.kind == "cat":
            domain = sorted(col.lut)
            rank = {lvl: i for i, lvl in enumerate(domain)}
            perm = np.full(max(len(col.lut), 1), CAT_NA, dtype=np.int32)
            for lvl, raw in col.lut.items():
                perm[raw] = rank[lvl]
            remapped = []
            for ch in col.chunks:
                codes = ch.decode()
                ok = codes >= 0
                codes[ok] = perm[codes[ok]]
                remapped.append(encode_codes(codes, len(domain)))
            fused = concat_chunks(remapped, is_categorical=True,
                                  cardinality=len(domain))
            vecs.append(Vec.from_compressed(fused, VecType.CAT, nrows,
                                            domain=tuple(domain)))
        else:
            fused = concat_chunks(col.chunks)
            # the eager _guess_type contract, not the achieved codec:
            # a wide integral span falls back to the f32 codec yet is
            # still an INT column
            vtype = VecType.INT if (col.has_finite and col.integral) \
                else VecType.NUM
            vecs.append(Vec.from_compressed(fused, vtype, nrows))
        col.chunks = []            # the fused chunk owns the payload now
    if not lazy:
        for v in vecs:
            _ = v.data      # materialize (per column — never O(file) host)
    return vecs


def stream_import(path: str, key: str | None = None, header: int | None = 0,
                  col_types: dict | None = None,
                  na_strings: list | None = None, sep: str | None = None,
                  chunk_rows: int | None = None, lazy: bool | None = None,
                  job=None):
    """Streaming chunked CSV parse → Frame with compressed host columns.

    ``job`` (a :class:`~h2o3_tpu.models.job.Job`) receives row/byte progress
    per chunk; cancelling it aborts every stage within one poll period."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.utils.registry import DKV

    sep = sep or ","
    chunk_rows = chunk_rows or chunk_rows_default()
    if lazy is None:
        lazy = os.environ.get("H2O3TPU_INGEST_EAGER", "0") != "1"
    from h2o3_tpu.frame.binfmt import is_gzipped
    gzipped = is_gzipped(path)       # magic bytes, never the extension
    total_bytes = os.path.getsize(path)
    na = list(na_strings) if na_strings else None
    # normalize h2o-py style col_types ("enum"/"numeric") to VecType
    forced: dict[str, VecType] = {}
    for cname, t in (col_types or {}).items():
        if isinstance(t, VecType):
            forced[cname] = t
        elif str(t).lower() in ("enum", "cat", "categorical", "factor",
                                "string"):
            forced[cname] = VecType.CAT
        else:
            forced[cname] = VecType.NUM
    stats = IngestStats()

    # promote-and-reparse is bounded by the column count (each restart
    # forces at least one NEW column categorical); the width is known only
    # after a pass has seen the header, so the bound is re-derived per
    # attempt with 64 as the pre-header floor
    restarts = 0
    while True:
        abort = threading.Event()
        depth = queue_depth_default()
        raw_q: queue.Queue = queue.Queue(maxsize=depth)
        line_q: queue.Queue = queue.Queue(maxsize=depth)
        done_q: queue.Queue = queue.Queue(maxsize=depth)
        columns: list[_ColumnState] = []
        progress = {"raw_pos": 0}
        stages = [
            _Stage("read", lambda: _read_stage(path, raw_q, abort, progress),
                   abort),
            _Stage("decompress",
                   lambda: _decompress_stage(raw_q, line_q, abort, gzipped,
                                             chunk_rows, stats,
                                             has_header=header is not None
                                             and header >= 0),
                   abort),
            _Stage("tokenize",
                   lambda: _tokenize_stage(line_q, done_q, abort, sep, na,
                                           forced, columns, stats),
                   abort),
        ]
        for s in stages:
            s.start()
        nrows = 0
        try:
            while True:
                item = _q_get(done_q, abort)
                if item is _EOF:
                    break
                nrows += item[1]
                if job is not None:
                    frac = min(progress["raw_pos"] / total_bytes, 1.0) \
                        if total_bytes else 1.0
                    job.update(0.95 * frac,
                               f"parsed {nrows} rows / "
                               f"{stats.bytes_in} bytes")
        except _Aborted:
            pass
        except BaseException:
            abort.set()
            raise
        finally:
            for s in stages:
                s.join(timeout=30.0)
        err = next((s.error for s in stages if s.error is not None), None)
        if isinstance(err, ParsePromoted):
            restarts += 1
            if restarts > max(64, len(columns)):
                raise ValueError(
                    f"parse of {path!r} exceeded {max(64, len(columns))} "
                    "type-promotion restarts")
            for cname in err.columns:
                forced[cname] = VecType.CAT
            stats.restarts += 1
            _tm.INGEST_RESTARTS.inc()
            # rewind the accounting the aborted pass accumulated (queued
            # items die with their stages, so in-flight resets too)
            stats.rows = stats.chunks = 0
            stats.bytes_in = stats.bytes_raw = stats.bytes_encoded = 0
            with stats._lock:
                stats._inflight = 0
            continue
        if err is not None:
            raise err
        break

    # throughput counters land ONCE per successful parse — per-chunk
    # increments would double-count every promote-and-reparse restart
    _tm.INGEST_CHUNKS.inc(stats.chunks)
    _tm.INGEST_ROWS.inc(stats.rows)
    _tm.INGEST_BYTES.inc(stats.bytes_in)
    _tm.INGEST_ENCODED_BYTES.inc(stats.bytes_encoded)
    vecs = _assemble(columns, nrows, lazy)
    fr = Frame([c.name for c in columns], vecs,
               key=key)
    fr._ingest_stats = stats.as_dict()
    if job is not None:
        job.update(1.0, f"parsed {nrows} rows / {stats.bytes_in} bytes")
    if key:
        DKV.put(key, fr)
    return fr
