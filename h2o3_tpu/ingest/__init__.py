"""Streaming ingest + out-of-core frames (docs/INGEST.md).

The data plane that survives datasets bigger than host RAM, mirroring the
reference substrate's three legs (PAPER.md L1/L2): a streaming chunked
parse whose host peak is O(chunk) (:mod:`h2o3_tpu.ingest.pipeline`),
compressed column encodings with lazy decompress-on-access
(:mod:`h2o3_tpu.ingest.encode` + the ``Vec`` seam), and Cleaner-driven
spill of cold DKV values to persist (:mod:`h2o3_tpu.utils.cleaner`).

``frame.parse.import_file`` routes large/compressed files here behind
``H2O3TPU_INGEST_STREAMING`` (``auto`` streams gzip and files over the
``H2O3TPU_INGEST_STREAM_MIN_BYTES`` floor; ``1`` forces, ``0`` disables).
"""

from h2o3_tpu.ingest.encode import CompressedChunk, encode_column
from h2o3_tpu.ingest.pipeline import IngestStats, ParsePromoted, stream_import

__all__ = ["CompressedChunk", "IngestStats", "ParsePromoted",
           "encode_column", "stream_import"]
