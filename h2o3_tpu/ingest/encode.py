"""Compressed chunk encodings — lossless narrow host payloads per column.

Reference: ``water/fvec/NewChunk.java:993-997`` — the reference parser picks
the cheapest of ~20 chunk codecs per 64KB fragment (``C1Chunk``/``C2SChunk``/
``C4Chunk`` narrow ints with bias, ``CXIChunk`` sparse, categorical domain
codes), and every read decompresses on access (``Chunk.atd``). That codec
zoo is why H2O-3's substrate survives datasets bigger than RAM (PAPER.md L2).

TPU-native subset: device compute wants dense float32/int32, so compression
lives HOST-side only. A :class:`CompressedChunk` is a column's resident host
payload in its cheapest **lossless** encoding:

- ``i8``/``i16`` — bias-shifted narrow ints for integral columns whose value
  range fits the width (the C1/C2-style codecs); NaN maps to the width's
  minimum as an NA sentinel, so round-trip is exact.
- ``dict8``/``dict16``/``dict32`` — dictionary codes for categoricals (the
  domain IS the dictionary; codes are narrowed to the cheapest width that
  holds the cardinality, with -1 = NA riding in every signed width).
- ``f32``/``i32`` — identity fallbacks when nothing narrower is lossless.

``decode()`` reproduces the exact float32 (or int32 code) array the eager
parse path would have produced — bit-identical model inputs are the
contract the ingest tests and ``bench_ingest`` hold.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.types import CAT_NA

#: widths tried for integral numeric columns, cheapest first; each reserves
#: its dtype's minimum as the NA sentinel so the usable range is one short
_INT_WIDTHS = ((np.int8, 1), (np.int16, 2))

#: widths tried for categorical code columns (codes are >= -1 = CAT_NA,
#: which every signed width represents natively)
_DICT_WIDTHS = ((np.int8, "dict8"), (np.int16, "dict16"))


class CompressedChunk:
    """One column's host payload in its cheapest lossless encoding.

    ``payload`` is the narrow numpy array; ``codec`` names the encoding;
    ``bias`` shifts narrow-int payloads back to the original values.
    """

    __slots__ = ("codec", "payload", "bias", "raw_bytes")

    def __init__(self, codec: str, payload: np.ndarray, bias: float = 0.0,
                 raw_bytes: int | None = None):
        self.codec = codec
        self.payload = payload
        self.bias = float(bias)
        # what the uncompressed (float32/int32) column would have occupied —
        # the numerator of the compression ratio the bench artifact reports
        self.raw_bytes = int(raw_bytes if raw_bytes is not None
                             else len(payload) * 4)

    @property
    def nbytes(self) -> int:
        return int(self.payload.nbytes)

    @property
    def nrows(self) -> int:
        return int(len(self.payload))

    def decode(self) -> np.ndarray:
        """The exact array the eager path would hold: float32 with NaN for
        numeric codecs, int32 codes (CAT_NA for missing) for dict codecs."""
        p = self.payload
        if self.codec == "f32":
            return p
        if self.codec == "i32":
            return p.astype(np.float32)
        if self.codec.startswith("dict"):
            return p.astype(np.int32)
        # narrow int with bias: the dtype minimum is the NA sentinel
        sentinel = np.iinfo(p.dtype).min
        out = p.astype(np.float32) + np.float32(self.bias)
        out[p == sentinel] = np.nan
        return out

    def __repr__(self) -> str:
        return (f"CompressedChunk({self.codec}, n={self.nrows}, "
                f"{self.nbytes}B/{self.raw_bytes}B)")


def encode_numeric(values: np.ndarray) -> CompressedChunk:
    """Encode a float32 numeric column (NaN = missing) losslessly.

    Narrow-int widths apply only when every finite value is integral AND
    exactly representable in float32 after the bias shift — otherwise the
    identity ``f32`` codec keeps the column as-is."""
    v = np.asarray(values, dtype=np.float32)
    finite = v[np.isfinite(v)]
    if finite.size and np.all(finite == np.round(finite)):
        lo = float(finite.min())
        hi = float(finite.max())
        for dtype, _width in _INT_WIDTHS:
            info = np.iinfo(dtype)
            # reserve info.min for NA; bias at the column minimum so the
            # span (not the magnitude) decides the width
            if hi - lo <= info.max - (info.min + 1):
                sentinel = info.min
                shifted = np.full(v.shape, sentinel, dtype=dtype)
                ok = np.isfinite(v)
                shifted[ok] = (v[ok] - np.float32(lo)).astype(np.int64) \
                    + (sentinel + 1)
                chunk = CompressedChunk(f"i{np.dtype(dtype).itemsize * 8}",
                                        shifted,
                                        bias=lo - (sentinel + 1),
                                        raw_bytes=v.nbytes)
                # paranoid losslessness check on the chunk boundary values:
                # float32 cannot represent every int past 2**24, in which
                # case the identity codec is the only exact one
                if np.array_equal(chunk.decode(), v, equal_nan=True):
                    return chunk
    return CompressedChunk("f32", v, raw_bytes=v.nbytes)


def encode_codes(codes: np.ndarray, cardinality: int) -> CompressedChunk:
    """Dictionary-code a categorical column: codes are already the
    dictionary indices (the Vec's domain is the dictionary); narrow them to
    the cheapest width holding ``cardinality`` (CAT_NA = -1 fits every
    signed width)."""
    c = np.asarray(codes, dtype=np.int32)
    for dtype, codec in _DICT_WIDTHS:
        if cardinality - 1 <= np.iinfo(dtype).max:
            return CompressedChunk(codec, c.astype(dtype), raw_bytes=c.nbytes)
    return CompressedChunk("dict32", c, raw_bytes=c.nbytes)


def encode_column(values: np.ndarray, is_categorical: bool = False,
                  cardinality: int = 0) -> CompressedChunk:
    """Encode one parsed column chunk (float32 numerics or int32 codes)."""
    if is_categorical:
        return encode_codes(values, cardinality)
    return encode_numeric(values)


def concat_chunks(chunks: list[CompressedChunk],
                  is_categorical: bool = False,
                  cardinality: int = 0) -> CompressedChunk:
    """Fuse per-chunk encodings of one column into a single column-spanning
    chunk, re-encoded so the fused payload is as narrow as the fused value
    range allows (two chunks may each fit i8 under different biases)."""
    if len(chunks) == 1 and not is_categorical:
        return chunks[0]
    decoded = np.concatenate([c.decode() for c in chunks]) if chunks \
        else np.empty(0, np.float32)
    return encode_column(decoded, is_categorical=is_categorical,
                         cardinality=cardinality)
