"""Reference-format MOJO importer, part 2: the non-tree long-tail families.

Extends ``mojo_ref`` (which handles GBM/DRF/IF/GLM/KMeans/SE) with readers
for the remaining reference artifact families (VERDICT r4 missing #1):
DeepLearning, PCA, GLRM, CoxPH, Word2Vec, RuleFit, TargetEncoder and
IsotonicRegression.  Format provenance (studied, not copied — these are
from-scratch Python readers of the documented container layout):

- kv store: scalars and numeric arrays live in ``model.ini`` ``[info]``
  as ``Arrays.toString`` text (``hex/genmodel/AbstractMojoWriter.java:61-80``);
  binary blobs are separate zip entries written through ``ByteBuffer``,
  which is **big-endian** regardless of the ``endianness`` info key
  (``ModelMojoReader.java:208-235`` readRectangularDoubleArray).
- DeepLearning: ``hex/deeplearning/DeepLearningMojoWriter.java:34-95``
  (weight_layer{i}/bias_layer{i} kv arrays, float-truncated weights) and
  the scoring stack ``DeeplearningMojoModel.java:62-130`` +
  ``NeuralNetwork.java:37-95`` + ``ActivationUtils.java`` +
  ``GenModel.setInput/setCats`` (``GenModel.java:707-770``).
- PCA: ``PCAMojoWriter.java:23-40`` / ``PCAMojoModel.java:25-52``
  (eigenvectors_raw big-endian double blob [size][k], permutation,
  level-skip rules for unseen/NA categoricals).
- GLRM: ``GlrmMojoReader.java:18-74`` / ``GlrmMojoModel.java:88-360``
  (per-row prox-prox X solve seeded ``seed + row``), with
  ``GlrmLoss.java`` / ``GlrmRegularizer.java`` reproduced exactly and
  ``java.util.Random`` re-implemented for init/tie-break parity.
- CoxPH: ``CoxPHMojoWriter.java:31-54`` / ``CoxPHMojoModel.java:75-170``
  (x_mean rectangular blobs, strata kv map, lpBase subtraction).
- Word2Vec: ``Word2VecMojoWriter.java:27-45`` (vocabulary text file +
  big-endian float32 ``vectors`` blob) / ``Word2VecMojoModel.java``.
- RuleFit: ``RuleFitMojoWriter.java:34-147`` kv-encoded rule ensemble over
  a nested GLM (MultiModelMojoReader layout shared with StackedEnsemble),
  scoring per ``RuleFitMojoModel.java:25-63`` + ``MojoRuleEnsemble.java``
  (note the writer's bug-compatible ``cat_treshold_length_{i}_{cond}``
  key carrying the i-th categorical threshold VALUE).
- TargetEncoder: ``ai/h2o/targetencoding/TargetEncoderMojoWriter.java``
  four ini-style files under ``feature_engineering/target_encoding/`` and
  blended-encoding math per ``TargetEncoderMojoModel.java:10-205`` /
  ``EncodingMap.java``.
- Isotonic: ``IsotonicRegressionMojoWriter`` → calibrator blobs
  (``AbstractMojoWriter.java:82-95``: int32 length + doubles) scored per
  ``IsotonicRegressionUtils.java:7-43``.

Like part 1, decoding happens once at import; scoring is vectorized numpy
over rows (GLRM's per-row iterative solve is the one reference-mandated
scalar loop).  This is a host-side path by design: imported artifacts are
one-shot batch scorers, not training loops — device residency comes from
``Generic._score_raw`` materializing the result like every other model.
"""

from __future__ import annotations

import math
import re
import struct

import numpy as np

from h2o3_tpu.genmodel.mojo_ref import (
    _RefModelBase, _kv, _kv_doubles, _unescape,
)

__all__ = ["load_ext_family", "EXT_ALGOS"]


# -- kv / blob helpers -------------------------------------------------------

def _kv_ints(info: dict, key: str, default=None):
    v = _kv_doubles(info, key)
    if v is None:
        return default
    return v.astype(np.int64)


def _kv_bool(info: dict, key: str, default: bool = False) -> bool:
    v = _kv(info, key)
    return default if v is None else v == "true"


def _be_doubles(blob: bytes, n: int) -> np.ndarray:
    """ByteBuffer.putDouble stream — big-endian, no length header."""
    return np.frombuffer(blob, ">f8", n).astype(np.float64)


def _be_len_doubles(blob: bytes) -> np.ndarray:
    """readblobDoubles layout: int32 count then doubles (big-endian)."""
    (n,) = struct.unpack_from(">i", blob, 0)
    return np.frombuffer(blob, ">f8", n, 4).astype(np.float64)


def _read_text(z, name: str, unescape: bool = False) -> list[str]:
    lines = z.read(name).decode().split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    return [_unescape(s) if unescape else s for s in lines]


def _rect(z, prefix: str, info: dict, title: str) -> np.ndarray:
    """writeRectangularDoubleArray: {title}_size1/_size2 kv + blob."""
    s1 = int(_kv(info, f"{title}_size1"))
    s2 = int(_kv(info, f"{title}_size2"))
    return _be_doubles(z.read(prefix + title), s1 * s2).reshape(s1, s2)


# -- java.util.Random (LCG) for GLRM init/tie-break parity -------------------

class _JavaRandom:
    """Bit-exact ``java.util.Random``: 48-bit LCG, Marsaglia-polar
    nextGaussian — GlrmMojoModel seeds one per row (seed + row index)."""

    __slots__ = ("_s", "_g")
    _M = (1 << 48) - 1

    def __init__(self, seed: int):
        self._s = (seed ^ 0x5DEECE66D) & self._M
        self._g = None

    def _next(self, bits: int) -> int:
        self._s = (self._s * 0x5DEECE66D + 0xB) & self._M
        return self._s >> (48 - bits)

    def next_int(self, n: int) -> int:
        if n <= 0:
            raise ValueError("n must be positive")
        if (n & -n) == n:                       # power of two
            return (n * self._next(31)) >> 31
        while True:
            bits = self._next(31)
            val = bits % n
            if bits - val + (n - 1) < (1 << 31):   # no int32 overflow
                return val

    def next_double(self) -> float:
        return ((self._next(26) << 27) + self._next(27)) * (2.0 ** -53)

    def next_gaussian(self) -> float:
        if self._g is not None:
            g, self._g = self._g, None
            return g
        while True:
            v1 = 2 * self.next_double() - 1
            v2 = 2 * self.next_double() - 1
            s = v1 * v1 + v2 * v2
            if 0 < s < 1:
                break
        mult = math.sqrt(-2 * math.log(s) / s)
        self._g = v2 * mult
        return v1 * mult


# -- DeepLearning ------------------------------------------------------------

def _dl_linkinv(family: str | None, f: np.ndarray) -> np.ndarray:
    """DeeplearningMojoModel.linkInv: exp capped at 1e19."""
    if family in ("bernoulli", "quasibinomial", "modified_huber", "ordinal"):
        return 1.0 / (1.0 + np.minimum(1e19, np.exp(-f)))
    if family in ("multinomial", "poisson", "gamma", "tweedie"):
        return np.minimum(1e19, np.exp(f))
    return f


class RefDeepLearningModel(_RefModelBase):
    """Imported DeepLearning MOJO: kv weights, exact fprop semantics."""

    algo = "deeplearning"

    def __init__(self, info, columns, domains):
        super().__init__(info, columns, domains)
        self.cats = int(_kv(info, "cats", 0))
        self.nums = int(_kv(info, "nums", 0))
        self.cat_offsets = _kv_ints(info, "cat_offsets", np.zeros(1, np.int64))
        self.norm_mul = _kv_doubles(info, "norm_mul")
        self.norm_sub = _kv_doubles(info, "norm_sub")
        self.norm_resp_mul = _kv_doubles(info, "norm_resp_mul")
        self.norm_resp_sub = _kv_doubles(info, "norm_resp_sub")
        self.use_all_levels = _kv_bool(info, "use_all_factor_levels")
        # mean_imputation / cat_modes are read by the reference reader but
        # NEVER used in its scoring path: DeeplearningMojoModel.score0
        # hardcodes replaceMissingWithZero=true (NaN num -> 0 AFTER
        # standardization, which IS the training mean; NA cat -> the
        # factor's extra last level).  Matching that exactly.
        self.activation = _kv(info, "activation")
        self.family = _kv(info, "distribution")
        if self.family == "modified_huber":
            raise ValueError(
                "modified_huber DeepLearning MOJOs score a constant in the "
                "reference (DeeplearningMojoModel.java:108 reads preds[0] "
                "right after zeroing it) — refusing to reproduce that")
        self.units = _kv_ints(info, "neural_network_sizes")
        self.dropout = _kv_doubles(info, "hidden_dropout_ratios")
        if self.dropout is None:
            self.dropout = np.zeros(len(self.units) - 1)
        self.balance_classes = _kv_bool(info, "balance_classes")
        self.prior_distrib = _kv_doubles(info, "prior_class_distrib")
        self.model_distrib = _kv_doubles(info, "model_class_distrib")
        n_layers = len(self.units) - 1
        self.weights: list[np.ndarray] = []
        self.biases: list[np.ndarray] = []
        self.maxk = 1
        if self.activation in ("Maxout", "MaxoutWithDropout"):
            b0 = _kv_doubles(info, "bias_layer0")
            self.maxk = len(b0) // int(self.units[1])
        for i in range(n_layers):
            w = _kv_doubles(info, f"weight_layer{i}")
            b = _kv_doubles(info, f"bias_layer{i}")
            # convertDouble2Float: weights round-trip through float32
            self.weights.append(w.astype(np.float32).astype(np.float64))
            self.biases.append(b)

    # layer activations: hidden layers use the parameter activation, the
    # output layer Softmax (classifier) / Linear (DeeplearningMojoModel.init)
    def _layer_activation(self, layer: int) -> str:
        if layer == len(self.units) - 2:
            return "Softmax" if self.is_classifier else "Linear"
        return self.activation

    def _net_input(self, X: np.ndarray) -> np.ndarray:
        """GenModel.setInput(DL variant): one-hot cats (NA -> the factor's
        last level), standardized nums with NaN->0."""
        n = X.shape[0]
        width = int(self.cat_offsets[self.cats]) + self.nums
        out = np.zeros((n, width))
        for i in range(self.cats):
            d = X[:, i]
            lo, hi = int(self.cat_offsets[i]), int(self.cat_offsets[i + 1])
            c = np.trunc(np.nan_to_num(d, nan=0.0)).astype(np.int64)
            if self.use_all_levels:
                idx = c + lo
            else:
                idx = np.where(c != 0, c - 1 + lo, -1)
            idx = np.where(np.isnan(d), hi - 1, np.minimum(idx, hi - 1))
            rows = np.arange(n)
            hit = idx >= 0
            out[rows[hit], idx[hit]] = 1.0
        for j in range(self.nums):
            d = X[:, self.cats + j]
            if self.norm_mul is not None and len(self.norm_mul) > 0:
                d = (d - self.norm_sub[j]) * self.norm_mul[j]
            out[:, int(self.cat_offsets[self.cats]) + j] = \
                np.nan_to_num(d, nan=0.0)
        return out

    def _fprop(self, h: np.ndarray, layer: int) -> np.ndarray:
        w, b = self.weights[layer], self.biases[layer]
        out_size = int(self.units[layer + 1])
        in_size = h.shape[1]
        act = self._layer_activation(layer)
        if act in ("Maxout", "MaxoutWithDropout"):
            # wValues[maxK*(row*inSize+col)+k] (NeuralNetwork.java:81-93)
            W = w.reshape(out_size, in_size, self.maxk)
            B = b.reshape(out_size, self.maxk)
            z = np.einsum("ni,oik->nok", h, W) + B[None, :, :]
            # MaxoutOut.eval walks countInd = index*maxK then += k — for
            # maxK<=2 that is a plain max over k (the supported case)
            v = z.max(axis=2)
        else:
            W = w.reshape(out_size, in_size)
            z = h @ W.T + b[None, :]
            v = z
        if act == "Linear":
            pass
        elif act == "Softmax":
            e = np.exp(v - v.max(axis=1, keepdims=True))
            v = e / e.sum(axis=1, keepdims=True)
        elif act.startswith("ExpRectifier"):
            v = np.where(v >= 0, v, np.exp(np.minimum(v, 0)) - 1)
        elif act.startswith("Rectifier"):
            v = 0.5 * (v + np.abs(v))
        elif act.startswith("Tanh"):
            v = 1.0 - 2.0 / (1.0 + np.exp(2.0 * v))
        elif act.startswith("Maxout"):
            pass
        else:
            raise ValueError(f"unsupported DL activation {act!r}")
        if act.endswith("WithDropout"):
            v = v * (1.0 - self.dropout[layer])
        return v

    def score(self, X: np.ndarray) -> np.ndarray:
        h = self._net_input(X)
        for layer in range(len(self.units) - 1):
            h = self._fprop(h, layer)
        if self.is_classifier:
            if self.balance_classes and self.model_distrib is not None:
                # GenModel.correctProbabilities
                h = h * (self.prior_distrib / self.model_distrib)[None, :]
                s = h.sum(axis=1, keepdims=True)
                h = np.where(s > 0, h / s, h)
            return h
        out = h[:, 0]
        if self.norm_resp_mul is not None and len(self.norm_resp_mul) > 0:
            out = out / self.norm_resp_mul[0] + self.norm_resp_sub[0]
        return _dl_linkinv(self.family, out)


# -- PCA ---------------------------------------------------------------------

class RefPCAModel(_RefModelBase):
    """Imported PCA MOJO: project rows onto k eigenvectors."""

    algo = "pca"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.k = int(_kv(info, "k"))
        self.permutation = _kv_ints(info, "permutation")
        self.ncats = int(_kv(info, "ncats", 0))
        self.nnums = int(_kv(info, "nnums", 0))
        self.norm_sub = _kv_doubles(info, "normSub")
        self.norm_mul = _kv_doubles(info, "normMul")
        self.cat_offsets = _kv_ints(info, "catOffsets", np.zeros(1, np.int64))
        self.use_all_levels = _kv_bool(info, "use_all_factor_levels")
        size = int(_kv(info, "eigenvector_size"))
        self.eig = _be_doubles(z.read(prefix + "eigenvectors_raw"),
                               size * self.k).reshape(size, self.k)

    @property
    def is_classifier(self) -> bool:
        return False

    def score(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        out = np.zeros((n, self.k))
        num_start = int(self.cat_offsets[self.ncats])
        for j in range(self.ncats):
            d = X[:, self.permutation[j]]
            last = int(self.cat_offsets[j + 1] - self.cat_offsets[j]) - 1
            lvl = np.trunc(np.nan_to_num(d, nan=0.0)).astype(np.int64) \
                - (0 if self.use_all_levels else 1)
            ok = ~np.isnan(d) & (lvl >= 0) & (lvl <= last)
            idx = np.clip(lvl, 0, last) + int(self.cat_offsets[j])
            out += np.where(ok[:, None], self.eig[idx, :], 0.0)
        for j in range(self.nnums):
            d = (X[:, self.permutation[self.ncats + j]]
                 - self.norm_sub[j]) * self.norm_mul[j]
            out += d[:, None] * self.eig[num_start + j, :][None, :]
        return out

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        raw = self.score(self._design(frame))
        return Frame([f"PC{i + 1}" for i in range(self.k)],
                     [Vec.from_numpy(raw[:, i].astype(np.float32))
                      for i in range(self.k)])


# -- GLRM --------------------------------------------------------------------

_GLRM_NUM_ALPHAS = 10
_GLRM_ITERS = 100
_GLRM_EPS = 1e-10


class RefGlrmModel(_RefModelBase):
    """Imported GLRM MOJO: per-row prox-prox solve for the X factors."""

    algo = "glrm"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.ncolA = int(_kv(info, "ncolA"))
        self.ncolX = int(_kv(info, "ncolX"))
        self.ncolY = int(_kv(info, "ncolY"))
        self.nrowY = int(_kv(info, "nrowY"))
        self.gammax = float(_kv(info, "gammaX", 0.0) or 0.0)
        self.regx = _kv(info, "regularizationX", "None")
        self.ncats = int(_kv(info, "num_categories", 0))
        self.nnums = int(_kv(info, "num_numeric", 0))
        self.norm_sub = _kv_doubles(info, "norm_sub")
        if self.norm_sub is None:
            self.norm_sub = np.zeros(self.nnums)
        self.norm_mul = _kv_doubles(info, "norm_mul")
        if self.norm_mul is None:
            self.norm_mul = np.ones(self.nnums)
        self.permutation = _kv_ints(info, "cols_permutation")
        self.num_levels = _kv_ints(info, "num_levels_per_category",
                                   np.zeros(0, np.int64))
        self.seed = int(_kv(info, "seed", 0) or 0)
        losses = _read_text(z, prefix + "losses")
        for name in losses:
            if name.startswith("Periodic"):
                # GlrmLoss.valueOf("Periodic(p)") throws in the reference
                # reader too (GlrmMojoReader.java:36) — these MOJOs never
                # loaded anywhere
                raise ValueError("Periodic GLRM loss is unreadable in the "
                                 "reference MOJO format")
        self.losses = losses
        # archetypes blob is [nrowY=rank][ncolY] (GlrmMojoWriter.java:63-70)
        self.arch = _be_doubles(z.read(prefix + "archetypes"),
                                self.nrowY * self.ncolY
                                ).reshape(self.nrowY, self.ncolY)

    @property
    def is_classifier(self) -> bool:
        return False

    # loss primitives (GlrmLoss.java) — u is xY, a the (standardized) datum
    def _loss(self, kind: str, u: float, a: float) -> float:
        if kind == "Quadratic":
            return (u - a) * (u - a)
        if kind == "Absolute":
            return abs(u - a)
        if kind == "Huber":
            x = u - a
            return x - 0.5 if x > 1 else (-x - 0.5 if x < -1 else 0.5 * x * x)
        if kind == "Poisson":
            return math.exp(u) + (0.0 if a == 0
                                  else -a * u + a * math.log(a) - a)
        if kind == "Logistic":
            return math.log1p(math.exp((1 - 2 * a) * u))
        if kind == "Hinge":
            return max(1 + (1 - 2 * a) * u, 0.0)
        raise ValueError(f"unsupported GLRM numeric loss {kind!r}")

    def _lgrad(self, kind: str, u: float, a: float) -> float:
        if kind == "Quadratic":
            return 2 * (u - a)
        if kind == "Absolute":
            return float(np.sign(u - a))
        if kind == "Huber":
            x = u - a
            return 1.0 if x > 1 else (-1.0 if x < -1 else x)
        if kind == "Poisson":
            return math.exp(u) - a
        if kind == "Logistic":
            s = 1 - 2 * a
            return s / (1 + math.exp(-s * u))
        if kind == "Hinge":
            s = 1 - 2 * a
            return s if 1 + s * u > 0 else 0.0
        raise ValueError(f"unsupported GLRM numeric loss {kind!r}")

    def _mloss(self, kind: str, u: np.ndarray, a: int) -> float:
        if kind == "Categorical":
            s = float(np.maximum(1 + u, 0).sum())
            return s + max(1 - u[a], 0) - max(1 + u[a], 0)
        if kind == "Ordinal":
            idx = np.arange(len(u) - 1)
            return float(np.where(a > idx, np.maximum(1 - u[:-1], 0), 1.0
                                  ).sum())
        raise ValueError(f"unsupported GLRM categorical loss {kind!r}")

    def _mlgrad(self, kind: str, u: np.ndarray, a: int) -> np.ndarray:
        if kind == "Categorical":
            g = (1 + u > 0).astype(np.float64)
            g[a] = -1.0 if 1 - u[a] > 0 else 0.0
            return g
        if kind == "Ordinal":
            g = np.zeros_like(u)
            idx = np.arange(len(u) - 1)
            g[:-1] = np.where((a > idx) & (1 - u[:-1] > 0), -1.0, 0.0)
            return g
        raise ValueError(f"unsupported GLRM categorical loss {kind!r}")

    # regularizer (GlrmRegularizer.java)
    def _regularize(self, u: np.ndarray) -> float:
        r = self.regx
        if r == "None":
            return 0.0
        if r == "Quadratic":
            return float((u * u).sum())
        if r == "L2":
            return float(np.sqrt((u * u).sum()))
        if r == "L1":
            return float(np.abs(u).sum())
        if r == "NonNegative":
            return math.inf if (u < 0).any() else 0.0
        if r == "OneSparse":
            if (u < 0).any():
                return math.inf
            return 0.0 if (u > 0).sum() == 1 else math.inf
        if r == "UnitOneSparse":
            ones = (u == 1).sum()
            zeros = (u == 0).sum()
            return 0.0 if ones == 1 and zeros == len(u) - 1 else math.inf
        if r == "Simplex":
            if (u < 0).any():
                return math.inf
            return 0.0 if abs(u.sum() - 1.0) <= 1e-8 * max(len(u), 1) \
                else math.inf
        raise ValueError(f"unsupported GLRM regularizer {r!r}")

    def _max_index(self, u: np.ndarray, rng: _JavaRandom) -> int:
        """ArrayUtils.maxIndex(u, rand): reservoir tie-break."""
        result, max_count = 0, 0
        for i in range(1, len(u)):
            if u[i] > u[result]:
                result, max_count = i, 1
            elif u[i] == u[result]:
                max_count += 1
                if rng.next_int(max_count) == 0:
                    result = i
        return result

    def _rproxgrad(self, u: np.ndarray, delta: float, rng: _JavaRandom
                   ) -> np.ndarray:
        r = self.regx
        if r == "None" or delta == 0:
            return u
        if r == "Quadratic":
            return u / (1 + 2 * delta)
        if r == "L2":
            w = 1 - delta / np.sqrt((u * u).sum())
            return np.zeros_like(u) if w < 0 else w * u
        if r == "L1":
            return np.maximum(u - delta, 0) + np.minimum(u + delta, 0)
        if r == "NonNegative":
            return np.maximum(u, 0)
        if r == "OneSparse":
            v = np.zeros_like(u)
            i = self._max_index(u, rng)
            v[i] = u[i] if u[i] > 0 else 1e-6
            return v
        if r == "UnitOneSparse":
            v = np.zeros_like(u)
            v[self._max_index(u, rng)] = 1.0
            return v
        if r == "Simplex":
            n = len(u)
            order = np.argsort(u, kind="stable")
            us = u[order]
            ucsum = np.cumsum(us[::-1])[::-1]
            t = (ucsum[0] - 1) / n
            for i in range(n - 1, 0, -1):
                tmp = (ucsum[i] - 1) / (n - i)
                if tmp >= us[i - 1]:
                    t = tmp
                    break
            return np.maximum(u - t, 0)
        raise ValueError(f"unsupported GLRM regularizer {r!r}")

    def _project(self, u: np.ndarray, rng: _JavaRandom) -> np.ndarray:
        if self.regx in ("None", "Quadratic", "L2", "L1"):
            return u
        if self.regx == "Simplex" and self._regularize(u) == 0:
            return u
        return self._rproxgrad(u, 1.0, rng)

    def _adapt_row(self, row: np.ndarray) -> np.ndarray:
        """GlrmMojoModel.getRowData: permute, unseen cat level -> NaN."""
        a = np.empty(self.ncolA)
        for i in range(self.ncats):
            t = row[self.permutation[i]]
            a[i] = np.nan if (not np.isnan(t) and t >= self.num_levels[i]) \
                else t
        for i in range(self.ncats, self.ncolA):
            a[i] = row[self.permutation[i]]
        return a

    def _xy_cat(self, x: np.ndarray, j: int, cat_offset: int) -> np.ndarray:
        nl = int(self.num_levels[j])
        return x @ self.arch[:, cat_offset:cat_offset + nl]

    def _objective(self, x: np.ndarray, a: np.ndarray) -> float:
        res = 0.0
        cat_offset = 0
        for j in range(self.ncats):
            nl = int(self.num_levels[j])
            if not np.isnan(a[j]):
                res += self._mloss(self.losses[j],
                                   self._xy_cat(x, j, cat_offset), int(a[j]))
            cat_offset += nl
        for j in range(self.ncats, self.ncolA):
            js = j - self.ncats
            if np.isnan(a[j]):
                continue
            xy = float(x @ self.arch[:, js + cat_offset])
            res += self._loss(self.losses[j], xy,
                              (a[j] - self.norm_sub[js]) * self.norm_mul[js])
        res += self.gammax * self._regularize(x)
        return res

    def _gradientL(self, x: np.ndarray, a: np.ndarray) -> np.ndarray:
        grad = np.zeros(self.ncolX)
        cat_offset = 0
        for j in range(self.ncats):
            nl = int(self.num_levels[j])
            if not np.isnan(a[j]):
                xy = self._xy_cat(x, j, cat_offset)
                gl = self._mlgrad(self.losses[j], xy, int(a[j]))
                grad += self.arch[:, cat_offset:cat_offset + nl] @ gl
            cat_offset += nl
        for j in range(self.ncats, self.ncolA):
            js = j - self.ncats
            if np.isnan(a[j]):
                continue
            y = self.arch[:, js + cat_offset]
            xy = float(x @ y)
            gl = self._lgrad(self.losses[j], xy,
                             (a[j] - self.norm_sub[js]) * self.norm_mul[js])
            grad += gl * y
        return grad

    def _score_row(self, row: np.ndarray, seed: int) -> np.ndarray:
        a = self._adapt_row(row)
        rng = _JavaRandom(seed)
        x = np.array([rng.next_gaussian() for _ in range(self.ncolX)])
        x = self._project(x, rng)
        old_obj = self._objective(x, a)
        alphas = 0.5 ** np.arange(1, _GLRM_NUM_ALPHAS + 1)
        iters = 0
        while iters < _GLRM_ITERS:
            iters += 1
            grad = self._gradientL(x, a)
            # applyBestAlpha (GlrmMojoModel.java:152-189)
            if old_obj == 0:
                break
            scale = 1.0 / old_obj if old_obj > 10 else 1.0
            lowest, best_x = math.inf, None
            for al in alphas * scale:
                xnew = self._rproxgrad(x - al * grad, al * self.gammax, rng)
                nobj = self._objective(xnew, a)
                if nobj < lowest:
                    lowest, best_x = nobj, xnew
                if nobj == 0:
                    break
            if lowest < old_obj:
                x = best_x
            obj = lowest
            improvement = 1 - obj / old_obj
            old_obj = obj
            if improvement < _GLRM_EPS:
                break
        return x

    def score(self, X: np.ndarray) -> np.ndarray:
        # seed + rcnt: row i of a fresh scoring pass uses seed + i
        return np.stack([self._score_row(X[i], self.seed + i)
                         for i in range(X.shape[0])])

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        raw = self.score(self._design(frame))
        return Frame([f"Arch{i + 1}" for i in range(self.ncolX)],
                     [Vec.from_numpy(raw[:, i].astype(np.float32))
                      for i in range(self.ncolX)])


# -- CoxPH -------------------------------------------------------------------

class RefCoxPHModel(_RefModelBase):
    """Imported CoxPH MOJO: linear predictor relative to the per-stratum
    training mean (lp - lpBase)."""

    algo = "coxph"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        if _kv(info, "interaction_targets") is not None:
            raise ValueError("CoxPH MOJOs with interaction terms are not "
                             "supported by this importer yet")
        self.coef = _kv_doubles(info, "coef")
        self.cats = int(_kv(info, "cats", 0))
        self.nums = int(_kv(info, "num_numerical_columns", 0))
        self.cat_offsets = _kv_ints(info, "cat_offsets", np.zeros(1, np.int64))
        self.num_offsets = _kv_ints(info, "num_offsets", np.zeros(0, np.int64))
        self.use_all_levels = _kv_bool(info, "use_all_factor_levels")
        self.x_mean_cat = _rect(z, prefix, info, "x_mean_cat")
        self.x_mean_num = _rect(z, prefix, info, "x_mean_num")
        n_strata = int(_kv(info, "strata_count", 0))
        self.strata: dict[tuple, int] = {}
        self.strata_len = 0
        for i in range(n_strata):
            s = _kv_doubles(info, f"strata_{i}")
            self.strata_len = len(s)
            self.strata[tuple(int(v) for v in s)] = i
        self.lp_base = self._compute_lp_base()

    @property
    def is_classifier(self) -> bool:
        return False

    def _compute_lp_base(self) -> np.ndarray:
        num_start = self.x_mean_cat.shape[1] if len(self.x_mean_cat) else 0
        size = max(len(self.strata), 1)
        lp = np.zeros(size)
        for s in range(size):
            lp[s] += self.x_mean_cat[s] @ self.coef[:num_start]
            lp[s] += self.x_mean_num[s] @ \
                self.coef[num_start:num_start + self.x_mean_num.shape[1]]
        return lp

    def score(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        sl = self.strata_len
        lp = np.zeros(n)
        # categorical contribution (CoxPHMojoModel.forCategories)
        n_cat_cols = self.cats if not self.use_all_levels \
            else len(self.cat_offsets) - 1
        lowest = 1 if not self.use_all_levels else 0
        for c in range(n_cat_cols):
            val = X[:, sl + c]
            v = np.trunc(np.nan_to_num(val, nan=0.0)).astype(np.int64) - lowest
            x = v + int(self.cat_offsets[c])
            ok = (v >= 0) & (x < int(self.cat_offsets[c + 1])) & ~np.isnan(val)
            contrib = np.where(ok, self.coef[np.clip(x, 0, len(self.coef) - 1)],
                               0.0)
            lp += np.where(np.isnan(val), np.nan, contrib)
        # numeric contribution (forOtherColumns)
        for i in range(self.nums):
            if int(self.num_offsets[i]) >= len(self.coef):
                break
            lp += self.coef[int(self.num_offsets[i])] * X[:, sl + self.cats + i]
        # per-row stratum base; an NA or training-unseen stratum yields an
        # NA prediction for THAT row (the reference NPEs the whole batch —
        # CoxPHMojoModel.strataForRow unboxes a null — which no batch
        # scorer should reproduce)
        base = np.zeros(n)
        if self.strata:
            for r in range(n):
                svals = X[r, :sl]
                if np.isnan(svals).any():
                    base[r] = np.nan
                    continue
                idx = self.strata.get(tuple(int(v) for v in svals))
                base[r] = np.nan if idx is None else self.lp_base[idx]
        else:
            base[:] = self.lp_base[0]
        return lp - base


# -- Word2Vec ----------------------------------------------------------------

class RefWord2VecModel(_RefModelBase):
    """Imported Word2Vec MOJO: word -> embedding lookup (no score0 in the
    reference either — Word2VecMojoModel.java:31 throws)."""

    algo = "word2vec"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.vec_size = int(_kv(info, "vec_size"))
        vocab_size = int(_kv(info, "vocab_size"))
        raw = z.read(prefix + "vectors")
        if len(raw) != vocab_size * self.vec_size * 4:
            raise ValueError("corrupted word2vec vectors blob: "
                             f"{len(raw)} bytes for {vocab_size} words")
        vecs = np.frombuffer(raw, ">f4").reshape(vocab_size, self.vec_size)
        words = _read_text(z, prefix + "vocabulary", unescape=True)
        if len(words) != vocab_size:
            raise ValueError(f"vocabulary has {len(words)} words, "
                             f"expected {vocab_size}")
        self.words = words
        self.vectors = vecs.astype(np.float32)
        self._index = {w: i for i, w in enumerate(words)}

    @property
    def is_classifier(self) -> bool:
        return False

    def transform0(self, word: str) -> np.ndarray | None:
        i = self._index.get(word)
        return None if i is None else self.vectors[i]

    def transform(self, words) -> np.ndarray:
        """Batch lookup; unknown words map to NaN rows (the h2o-py
        ``w2v.transform`` AGGREGATE/NONE surface builds on this)."""
        out = np.full((len(words), self.vec_size), np.nan, np.float32)
        for r, w in enumerate(words):
            i = self._index.get(w)
            if i is not None:
                out[r] = self.vectors[i]
        return out

    def find_synonyms(self, word: str, count: int = 20) -> dict[str, float]:
        v = self.transform0(word)
        if v is None:
            return {}
        norms = np.linalg.norm(self.vectors, axis=1) * np.linalg.norm(v)
        sims = np.where(norms > 0, self.vectors @ v / norms, 0.0)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            if self.words[i] == word:
                continue
            out[self.words[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def predict(self, frame):
        raise ValueError("Word2Vec MOJOs embed words (use .transform); "
                         "they do not predict rows")

    def _score_raw(self, frame):
        self.predict(frame)


# -- Isotonic regression -----------------------------------------------------

class RefIsotonicModel(_RefModelBase):
    """Imported IsotonicRegression MOJO: clip + linear interpolation."""

    algo = "isotonicregression"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.min_x = float(_kv(info, "calib_min_x", "nan"))
        self.max_x = float(_kv(info, "calib_max_x", "nan"))
        self.thresholds_x = _be_len_doubles(z.read(prefix + "calib/thresholds_x"))
        self.thresholds_y = _be_len_doubles(z.read(prefix + "calib/thresholds_y"))

    @property
    def is_classifier(self) -> bool:
        return False

    def score(self, X: np.ndarray) -> np.ndarray:
        x = np.clip(X[:, 0], self.min_x, self.max_x)
        y = np.interp(x, self.thresholds_x, self.thresholds_y)
        return np.where(np.isnan(X[:, 0]), np.nan, y)


# -- RuleFit -----------------------------------------------------------------

class _RefRule:
    __slots__ = ("conditions", "var_name")

    def __init__(self, conditions, var_name):
        self.conditions = conditions
        self.var_name = var_name


class RefRuleFitModel(_RefModelBase):
    """Imported RuleFit MOJO: kv rule ensemble over a nested GLM."""

    algo = "rulefit"

    MODEL_TYPES = {0: "linear", 1: "rules_and_linear", 2: "rules"}

    def __init__(self, info, columns, domains, linear):
        super().__init__(info, columns, domains)
        self.linear = linear
        self.model_type = self.MODEL_TYPES[int(_kv(info, "model_type", 1))]
        self.depth = int(_kv(info, "depth", 0) or 0)
        self.ntrees = int(_kv(info, "ntrees", 0) or 0)
        n = int(_kv(info, "linear_names_len", 0) or 0)
        self.linear_names = [_kv(info, f"linear_names_{i}") for i in range(n)]
        self.rules: dict[tuple, list[_RefRule]] = {}
        if self.model_type != "linear":
            for i in range(self.depth):
                for j in range(self.ntrees):
                    cnt = int(_kv(info, f"num_rules_M{i}T{j}", 0) or 0)
                    self.rules[(i, j)] = [
                        self._read_rule(info, f"{i}_{j}_{k}")
                        for k in range(cnt)]
        # response domain for multinomial class-rule grouping
        rd = self.response_domain
        self.classes = list(rd) if rd else None

    def _read_rule(self, info, rid: str) -> _RefRule:
        ncond = int(_kv(info, f"num_conditions_rule_id_{rid}", 0) or 0)
        conds = []
        for i in range(ncond):
            cid = f"{i}_{rid}"
            ctype = int(_kv(info, f"type_{cid}"))
            cond = {
                "feature_index": int(_kv(info, f"feature_index_{cid}")),
                "operator": int(_kv(info, f"operator_{cid}")),
                "nas_included": _kv_bool(info, f"nas_included_{cid}"),
            }
            if ctype == 0:        # categorical: In over threshold levels
                # bug-compatible key: the i-th threshold VALUE is stored
                # under cat_treshold_length_{i}_{cid}
                # (RuleFitMojoWriter.java:131)
                nth = int(_kv(info, f"cat_treshold_length_{cid}", 0) or 0)
                cond["cat_threshold"] = np.array(
                    [int(_kv(info, f"cat_treshold_length_{t}_{cid}"))
                     for t in range(nth)], np.int64)
            else:
                cond["num_threshold"] = float(_kv(info, f"num_treshold{cid}"))
            conds.append(cond)
        return _RefRule(conds, _kv(info, f"var_name_rule_id_{rid}"))

    def _eval_rules(self, X: np.ndarray, rules: list[_RefRule]) -> np.ndarray:
        """[n, n_rules] 0/1 firing matrix (MojoCondition.map vectorized)."""
        n = X.shape[0]
        out = np.ones((n, len(rules)), bool)
        for r, rule in enumerate(rules):
            for c in rule.conditions:
                col = X[:, c["feature_index"]]
                isna = np.isnan(col)
                if c["operator"] == 0:       # LessThan
                    test = col < c["num_threshold"]
                elif c["operator"] == 1:     # GreaterThanOrEqual
                    test = col >= c["num_threshold"]
                else:                        # In (categorical)
                    test = np.isin(np.nan_to_num(col, nan=-1).astype(np.int64),
                                   c["cat_threshold"])
                ok = np.where(isna, c["nas_included"], test & ~isna)
                out[:, r] &= ok
        return out

    def _decode(self, fired: np.ndarray, rules: list[_RefRule],
                class_id: int = -1) -> np.ndarray:
        """Last-fired rule's domain index in the linear model's matching
        column (MojoRuleEnsemble.decode/getValueByVarName)."""
        n = fired.shape[0]
        val = np.full(n, -1, np.int64)
        lin_names = list(self.linear.columns[: self.linear.n_features])
        for r, rule in enumerate(rules):
            vn = rule.var_name
            var = vn[: vn.index("N")]
            if class_id >= 0:
                var += f"C{class_id}"
            i = lin_names.index(var)
            dom = self.linear.domains[i]
            code = dom.index(vn)
            val = np.where(fired[:, r], code, val)
        return np.where(val >= 0, val.astype(np.float64), np.nan)

    def _transform(self, X: np.ndarray) -> np.ndarray:
        """[n, depth*ntrees(*nclasses)] rule-derived categorical codes."""
        n = X.shape[0]
        multinomial = self.classes is not None and len(self.classes) > 2
        cols = []
        for i in range(self.depth):
            for j in range(self.ntrees):
                rules = self.rules[(i, j)]
                if multinomial:
                    for k, cls in enumerate(self.classes):
                        # varName grammar: M{i}T{j}N{node}_{class}
                        # (RuleFitMojoWriter.java:70-77).  The reference
                        # groups by endsWith(class), which conflates
                        # suffix-overlapping labels ('low'/'verylow');
                        # match the full grammar instead.
                        pat = re.compile(
                            rf"M{i}T{j}N\d+_{re.escape(cls)}$")
                        class_rules = [r for r in rules
                                       if pat.match(r.var_name)]
                        fired = self._eval_rules(X, class_rules)
                        cols.append(self._decode(fired, class_rules, k))
                else:
                    fired = self._eval_rules(X, rules)
                    cols.append(self._decode(fired, rules))
        return np.stack(cols, 1) if cols else np.zeros((n, 0))

    def score(self, X: np.ndarray) -> np.ndarray:
        if self.model_type == "linear":
            test = X
        else:
            rules_part = self._transform(X)
            test = rules_part if self.model_type == "rules" \
                else np.concatenate([rules_part, X], 1)
        # RuleFitMojoModel.map: reorder by the linear model's column order
        lin_names = list(self.linear.columns[: self.linear.n_features])
        lin_X = np.zeros((X.shape[0], self.linear.n_features))
        for i, name in enumerate(self.linear_names):
            lin_X[:, lin_names.index(name)] = test[:, i]
        return self.linear.score(lin_X)


# -- TargetEncoder -----------------------------------------------------------

_TE_DIR = "feature_engineering/target_encoding/"


class RefTargetEncoderModel(_RefModelBase):
    """Imported TargetEncoder MOJO: per-level (blended) posterior means."""

    algo = "targetencoder"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.with_blending = _kv_bool(info, "with_blending")
        self.inflection_point = float(_kv(info, "inflection_point", 0.0) or 0.0)
        self.smoothing = float(_kv(info, "smoothing", 1.0) or 1.0)
        self.keep_original = _kv_bool(info, "keep_original_categorical_columns")
        self.non_predictors = [s for s in
                               (_kv(info, "non_predictors", "") or "").split(";")
                               if s]
        names = set(z.namelist())
        # encodings: {te_column: {category: {target_class: (num, den)}}}
        self.encodings: dict[str, dict[int, dict[int, tuple]]] = {}
        if prefix + _TE_DIR + "encoding_map.ini" in names:
            cur = None
            for line in _read_text(z, prefix + _TE_DIR + "encoding_map.ini"):
                line = line.strip()
                if not line:
                    continue
                if line.startswith("[") and line.endswith("]"):
                    cur = line[1:-1]
                    self.encodings[cur] = {}
                else:
                    k, _, v = line.partition("=")
                    parts = [float(p) for p in v.split()]
                    cat = int(k.strip())
                    tc = int(parts[2]) if len(parts) > 2 else -1
                    self.encodings[cur].setdefault(cat, {})[tc] = \
                        (parts[0], parts[1])
        self.has_nas: dict[str, bool] = {}
        if prefix + _TE_DIR + "te_column_name_to_missing_values_presence.ini" \
                in names:
            for line in _read_text(
                    z, prefix + _TE_DIR
                    + "te_column_name_to_missing_values_presence.ini"):
                k, _, v = line.partition("=")
                self.has_nas[k.strip()] = v.strip() == "1"
        self.inenc = self._parse_mapping(z, prefix + _TE_DIR
                                         + "input_encoding_columns_map.ini",
                                         names)
        self.inout = self._parse_mapping(z, prefix + _TE_DIR
                                         + "input_output_columns_map.ini",
                                         names)
        if not self.inenc:        # legacy MOJOs: identity mapping
            k = self.nclasses - 1 if self.nclasses > 2 else 1
            for col in self.encodings:
                self.inenc.append(([col], col, None))
                outs = [f"{col}_te"] if k == 1 else \
                    [f"{col}_{i + 1}_te" for i in range(k)]
                self.inout.append(([col], outs, None))
        self._priors: dict[tuple, float] = {}

    @staticmethod
    def _parse_mapping(z, name: str, names: set) -> list:
        """[( [from...], to|[to...], domain|None )] from the [from]/[to]
        ini groups (TargetEncoderMojoReader.parseColumnsMapping)."""
        out = []
        if name not in names:
            return out
        frm = to = dom = None
        for line in _read_text(z, name):
            if line == "[from]":
                if frm is not None and to is not None:
                    out.append((frm, to, dom))
                frm, to, dom = [], None, None
            elif line == "[to]":
                to = []
            elif line == "[to_domain]":
                dom = []
            elif dom is not None:
                dom.append(line)
            elif to is not None:
                to.append(line)
            else:
                frm.append(line)
        if frm is not None and to is not None:
            out.append((frm, to, dom))
        return out

    @property
    def is_classifier(self) -> bool:
        return False

    def _prior(self, te_col: str, target_class: int) -> float:
        key = (te_col, target_class)
        if key not in self._priors:
            num = den = 0.0
            for targets in self.encodings[te_col].values():
                nd = targets[target_class]
                num += nd[0]
                den += nd[1]
            self._priors[key] = num / den
        return self._priors[key]

    def _encode_value(self, nd: tuple, prior: float) -> float:
        post = nd[0] / nd[1]
        if self.with_blending:
            lam = 1.0 / (1.0 + math.exp(
                (self.inflection_point - int(nd[1])) / self.smoothing))
            return lam * post + (1 - lam) * prior
        return post

    def _encode_category(self, te_col: str, cat: int) -> list[float]:
        enc = self.encodings[te_col]
        if self.nclasses > 2:
            return [self._encode_value(enc[cat][t + 1], self._prior(te_col,
                                                                    t + 1))
                    for t in range(self.nclasses - 1)]
        return [self._encode_value(enc[cat][-1], self._prior(te_col, -1))]

    def _encode_na(self, te_col: str) -> list[float]:
        if self.has_nas.get(te_col, False):
            na_cat = len(self.encodings[te_col]) - 1
            return self._encode_category(te_col, na_cat)
        if self.nclasses > 2:
            return [self._prior(te_col, t + 1)
                    for t in range(self.nclasses - 1)]
        return [self._prior(te_col, -1)]

    def _interaction_value(self, X: np.ndarray, cols_idx: list[int],
                           domain: list[str]) -> np.ndarray:
        """TargetEncoderMojoModel.interactionValue vectorized."""
        inter = np.zeros(X.shape[0], np.int64)
        mult = 1
        for ci in cols_idx:
            card = len(self.domains[ci])
            v = X[:, ci]
            v = np.where(np.isnan(v) | (v >= card), card, v).astype(np.int64)
            inter += mult * v
            mult *= card + 1
        dom = np.array([int(d) for d in domain], np.int64)
        pos = np.searchsorted(dom, inter)
        pos_c = np.clip(pos, 0, len(dom) - 1)
        return np.where(dom[pos_c] == inter, pos_c, -1).astype(np.float64)

    def transform(self, frame):
        """Frame -> Frame with the encoded columns appended, in
        _inencMapping order (score0 parity)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        X = self._design_all(frame)
        out = Frame(list(frame.names), list(frame.vecs))
        col_index = {c: j for j, c in enumerate(self.columns)}
        for m_idx, (frm, te_col, dom) in enumerate(self.inenc):
            if isinstance(te_col, list):   # ColumnsToSingleMapping.toSingle
                te_col = te_col[0]
            if len(frm) == 1:
                cat = X[:, col_index[frm[0]]]
            else:
                cat = self._interaction_value(
                    X, [col_index[f] for f in frm], dom)
                cat = np.where(cat < 0, np.nan, cat)
            k = self.nclasses - 1 if self.nclasses > 2 else 1
            vals = np.empty((len(cat), k))
            na_enc = self._encode_na(te_col)
            enc = self.encodings[te_col]
            cache: dict[int, list[float]] = {}
            for r, c in enumerate(cat):
                if np.isnan(c) or int(c) not in enc:
                    vals[r] = na_enc
                else:
                    ci = int(c)
                    if ci not in cache:
                        cache[ci] = self._encode_category(te_col, ci)
                    vals[r] = cache[ci]
            names = self.inout[m_idx][1] if m_idx < len(self.inout) else \
                [f"{te_col}_te"]
            for col_i in range(k):
                out.add(names[col_i] if col_i < len(names)
                        else f"{te_col}_{col_i + 1}_te",
                        Vec.from_numpy(vals[:, col_i].astype(np.float32)))
            if not self.keep_original:
                # TE replaces the source categorical(s) unless the model
                # was built with keep_original_categorical_columns=true
                for src in frm:
                    if src in out and src not in self.non_predictors:
                        out.remove(src)
        return out

    def _design_all(self, frame) -> np.ndarray:
        """Like _design but over ALL model columns (TE encodes by column
        name, the response/non-predictors just stay NaN if absent)."""
        saved = self.n_features
        try:
            self.n_features = len(self.columns)
            return self._design(frame)
        finally:
            self.n_features = saved

    def predict(self, frame):
        raise ValueError("TargetEncoder MOJOs transform frames (use "
                         ".transform); they do not predict rows")

    def _score_raw(self, frame):
        self.predict(frame)


# -- ExtendedIsolationForest -------------------------------------------------

_EULER_MASCHERONI = 0.5772156649


def _eif_avg_path(n: float) -> float:
    """MathUtils.harmonicNumberEstimation-based c(n) (EIF paper eq. 2)."""
    if n < 2:
        return 0.0
    if n == 2:
        return 1.0
    return 2 * (math.log(n - 1) + _EULER_MASCHERONI) - 2.0 * (n - 1.0) / n


class RefExtendedIsoForModel(_RefModelBase):
    """Imported ExtendedIsolationForest MOJO: little-endian tree blobs of
    heap-indexed records — [i32 node, u8 'N'|'L'] + (NODE: k doubles n,
    k doubles p | LEAF: i32 num_rows); routing by dot(row - p, n) <= 0
    (``ExtendedIsolationForestMojoModel.java:59-122`` scoreTree0)."""

    algo = "extendedisolationforest"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.ntrees = int(_kv(info, "ntrees", 0))
        self.sample_size = int(_kv(info, "sample_size", 0))
        self.trees = [self._parse_tree(z.read(f"{prefix}trees/t{t:02d}.bin"))
                      for t in range(self.ntrees)]

    @staticmethod
    def _parse_tree(blob: bytes):
        """Dense heap-indexed arrays so scoring vectorizes across rows
        like the module's other tree importers: is_leaf mask, split
        normals/intercepts N/P, leaf row counts."""
        (k,) = struct.unpack_from("<i", blob, 0)
        pos = 4
        nodes = {}
        while pos < len(blob):
            num, typ = struct.unpack_from("<iB", blob, pos)
            pos += 5
            if typ == ord("N"):
                n = np.frombuffer(blob, "<f8", k, pos)
                p = np.frombuffer(blob, "<f8", k, pos + 8 * k)
                nodes[num] = ("N", n, p)
                pos += 16 * k
            elif typ == ord("L"):
                (rows,) = struct.unpack_from("<i", blob, pos)
                nodes[num] = ("L", rows)
                pos += 4
            else:
                raise ValueError(f"unknown EIF node type {typ}")
        size = max(nodes) + 1
        is_leaf = np.zeros(size, bool)
        N = np.zeros((size, k))
        P = np.zeros((size, k))
        leaf_rows = np.zeros(size)
        for num, nd in nodes.items():
            if nd[0] == "L":
                is_leaf[num] = True
                leaf_rows[num] = nd[1]
            else:
                N[num], P[num] = nd[1], nd[2]
        return dict(is_leaf=is_leaf, N=N, P=P, rows=leaf_rows, size=size)

    @property
    def is_classifier(self) -> bool:
        return False

    @staticmethod
    def _avg_path_vec(n: np.ndarray) -> np.ndarray:
        out = np.where(n < 2, 0.0, np.where(
            n == 2, 1.0,
            2 * (np.log(np.maximum(n - 1, 1)) + _EULER_MASCHERONI)
            - 2.0 * (n - 1.0) / np.maximum(n, 1)))
        return out

    def _tree_path_lengths(self, t: dict, X: np.ndarray) -> np.ndarray:
        """Vectorized level-by-level heap descent (the
        RefXGBoostModel._tree_scores pattern)."""
        n = X.shape[0]
        node = np.zeros(n, np.int64)
        height = np.zeros(n)
        for _ in range(t["size"] + 1):
            leaf = t["is_leaf"][node]
            if leaf.all():
                return height + self._avg_path_vec(t["rows"][node])
            mul = ((X - t["P"][node]) * t["N"][node]).sum(axis=1)
            nxt = np.where(mul <= 0, 2 * node + 1, 2 * node + 2)
            node = np.where(leaf, node, nxt)
            height = height + (~leaf)
        raise ValueError("cyclic EIF tree structure (corrupt blob)")

    def score(self, X: np.ndarray) -> np.ndarray:
        """[n, 2]: (anomaly_score, mean_length) — EIF paper eq. 1."""
        pl = np.zeros(X.shape[0])
        for t in self.trees:
            pl += self._tree_path_lengths(t, X)
        pl /= max(self.ntrees, 1)
        denom = _eif_avg_path(self.sample_size)
        score = 2.0 ** (-pl / denom) if denom > 0 else np.ones_like(pl)
        return np.stack([score, pl], 1)

    def _score_raw(self, frame):
        import jax.numpy as jnp
        raw = self.score(self._design(frame))[:, 0].astype(np.float32)
        pad = frame.vecs[0].plen - frame.nrows
        if pad > 0:
            raw = np.pad(raw, (0, pad))
        return jnp.asarray(raw)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        raw = self.score(self._design(frame))
        return Frame(["anomaly_score", "mean_length"],
                     [Vec.from_numpy(raw[:, 0].astype(np.float32)),
                      Vec.from_numpy(raw[:, 1].astype(np.float32))])


# -- XGBoost -----------------------------------------------------------------

class _XgbTree:
    __slots__ = ("cleft", "cright", "split_index", "default_left", "value")

    def __init__(self, cleft, cright, split_index, default_left, value):
        self.cleft, self.cright = cleft, cright
        self.split_index, self.default_left = split_index, default_left
        self.value = value                 # leaf value OR split condition


def _parse_booster(blob: bytes):
    """The pre-1.0 xgboost binary model format (the bytes H2O's
    ``XGBoostMojoWriter`` embeds as ``boosterBytes`` and scores through
    biz.k11i xgboost-predictor — ``XGBoostJavaMojoModel.java:63``):
    LearnerModelParam (136 B: f32 base_score, u32 num_feature, i32
    num_class, reserved), len-prefixed objective + booster names, then for
    gbtree/dart a GBTreeModelParam (160 B) and per tree a TreeParam
    (148 B) + nodes (20 B: parent, cleft, cright, sindex, value) + stats
    (16 B) + tree_info group ids.  Layout probed against the reference's
    own committed boosterBytes (offsets verified in tests)."""
    base_score, num_feature, num_class = struct.unpack_from("<fIi", blob, 0)
    pos = 136
    (ln,) = struct.unpack_from("<Q", blob, pos)
    obj = blob[pos + 8: pos + 8 + ln].decode()
    pos += 8 + ln
    (ln,) = struct.unpack_from("<Q", blob, pos)
    booster = blob[pos + 8: pos + 8 + ln].decode()
    pos += 8 + ln
    if booster not in ("gbtree", "dart"):
        raise ValueError(f"unsupported xgboost booster {booster!r}")
    num_trees, _roots, _nf, _pad = struct.unpack_from("<iiii", blob, pos)
    (num_output_group,) = struct.unpack_from("<i", blob, pos + 24)
    (size_leaf_vector,) = struct.unpack_from("<i", blob, pos + 28)
    pos += 160
    trees = []
    for _ in range(num_trees):
        _r, n_nodes, _d, _md, _nf2, slv = struct.unpack_from("<6i", blob, pos)
        pos += 148
        nodes = np.frombuffer(blob, "<i4", n_nodes * 5, pos).reshape(n_nodes, 5)
        vals = np.frombuffer(blob, "<f4", n_nodes * 5, pos).reshape(n_nodes, 5)
        pos += n_nodes * 20
        pos += n_nodes * 16                     # RTreeNodeStat
        if slv:
            # dmlc vector serialization: the u64 IS the total element
            # count (slv * num_nodes) — skip exactly that many f32s
            (lv,) = struct.unpack_from("<Q", blob, pos)
            pos += 8 + 4 * lv
        sindex = nodes[:, 3].astype(np.uint32)
        trees.append(_XgbTree(
            cleft=nodes[:, 1].copy(), cright=nodes[:, 2].copy(),
            split_index=(sindex & 0x7FFFFFFF).astype(np.int64),
            default_left=(sindex >> 31).astype(bool),
            value=vals[:, 4].copy().astype(np.float64)))
    tree_info = np.frombuffer(blob, "<i4", num_trees, pos)
    pos += 4 * num_trees
    weight_drop = None
    if booster == "dart":
        # std::vector<bst_float>: u64 count + f32 weights
        (lv,) = struct.unpack_from("<Q", blob, pos)
        weight_drop = np.frombuffer(blob, "<f4", lv, pos + 8
                                    ).astype(np.float64)
    return dict(base_score=float(base_score), num_feature=int(num_feature),
                num_class=int(num_class), objective=obj,
                trees=trees, tree_info=tree_info,
                num_output_group=max(1, int(num_output_group)),
                weight_drop=weight_drop)


class RefXGBoostModel(_RefModelBase):
    """Imported XGBoost MOJO: parse boosterBytes, score like the
    reference's xgboost-predictor path (``XGBoostJavaMojoModel.score0`` +
    ``OneHotEncoderFactory``: cats one-hot through GenModel.setCats, then
    nums; sparse mode maps 0/not-hot to NaN so they take default paths)."""

    algo = "xgboost"

    def __init__(self, z, prefix, info, columns, domains):
        super().__init__(info, columns, domains)
        self.cats = int(_kv(info, "cats", 0))
        self.nums = int(_kv(info, "nums", 0))
        self.cat_offsets = _kv_ints(info, "cat_offsets", np.zeros(1, np.int64))
        self.use_all_levels = _kv_bool(info, "use_all_factor_levels")
        self.sparse = _kv_bool(info, "sparse")
        self.booster = _parse_booster(z.read(prefix + "boosterBytes"))

    def _encode(self, X: np.ndarray) -> np.ndarray:
        """[n, catOffsets[cats] + nums] one-hot + raw nums; not-hot = 0
        (dense) or NaN (sparse), num 0 -> NaN under sparse."""
        n = X.shape[0]
        not_hot = np.nan if self.sparse else 0.0
        width = int(self.cat_offsets[self.cats]) + self.nums
        out = np.full((n, width), not_hot)
        for i in range(self.cats):
            d = X[:, i]
            lo, hi = int(self.cat_offsets[i]), int(self.cat_offsets[i + 1])
            c = np.trunc(np.nan_to_num(d, nan=0.0)).astype(np.int64)
            if self.use_all_levels:
                idx = c + lo
            else:
                idx = np.where(c != 0, c - 1 + lo, -1)
            idx = np.where(np.isnan(d), hi - 1, np.minimum(idx, hi - 1))
            rows = np.arange(n)
            hit = idx >= 0
            out[rows[hit], idx[hit]] = 1.0
        for j in range(self.nums):
            v = X[:, self.cats + j]
            if self.sparse:
                v = np.where(v == 0, np.nan, v)
            out[:, int(self.cat_offsets[self.cats]) + j] = v
        return out

    def _tree_scores(self, F: np.ndarray, t: _XgbTree) -> np.ndarray:
        n = F.shape[0]
        node = np.zeros(n, np.int64)
        # loop until every row reaches a leaf; each step strictly descends
        # the tree, so > num_nodes iterations means a cycle (corrupt blob)
        for _ in range(len(t.value) + 1):
            leaf = t.cleft[node] == -1
            if leaf.all():
                return t.value[node]
            f = F[np.arange(n), t.split_index[node]]
            is_na = np.isnan(f)
            go_left = np.where(is_na, t.default_left[node],
                               f < t.value[node])
            nxt = np.where(go_left, t.cleft[node], t.cright[node])
            node = np.where(leaf, node, nxt)
        raise ValueError("cyclic xgboost tree structure (corrupt booster)")

    def score(self, X: np.ndarray) -> np.ndarray:
        F = self._encode(X)
        b = self.booster
        k = b["num_output_group"]
        margins = np.full((X.shape[0], k), b["base_score"])
        for ti, t in enumerate(b["trees"]):
            w = 1.0 if b["weight_drop"] is None else b["weight_drop"][ti]
            margins[:, int(b["tree_info"][ti])] += w * self._tree_scores(F, t)
        obj = b["objective"]
        if obj.startswith(("binary:logistic", "reg:logistic")):
            p1 = 1.0 / (1.0 + np.exp(-margins[:, 0]))
            return np.stack([1 - p1, p1], 1)
        if obj.startswith("multi:"):
            z = margins - margins.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        if obj.startswith("count:") or obj.startswith("reg:gamma") \
                or obj.startswith("reg:tweedie"):
            return np.exp(margins[:, 0])
        return margins[:, 0]                    # reg:squarederror/linear


# -- dispatch ----------------------------------------------------------------

EXT_ALGOS = ("deeplearning", "pca", "glrm", "coxph", "word2vec",
             "isotonicregression", "rulefit", "targetencoder", "xgboost",
             "extendedisolationforest")


def load_ext_family(algo, z, prefix, info, columns, domains, load_sub):
    """Dispatch hook called from ``mojo_ref._load_from_zip`` for the
    part-2 families.  ``load_sub(prefix)`` loads a nested submodel from the
    same archive (MultiModelMojoReader layout)."""
    if algo == "deeplearning":
        return RefDeepLearningModel(info, columns, domains)
    if algo == "pca":
        return RefPCAModel(z, prefix, info, columns, domains)
    if algo == "glrm":
        return RefGlrmModel(z, prefix, info, columns, domains)
    if algo == "coxph":
        return RefCoxPHModel(z, prefix, info, columns, domains)
    if algo == "word2vec":
        return RefWord2VecModel(z, prefix, info, columns, domains)
    if algo == "isotonicregression":
        return RefIsotonicModel(z, prefix, info, columns, domains)
    if algo == "rulefit":
        # MultiModelMojoReader layout (same grammar as StackedEnsemble in
        # mojo_ref); only the named linear model is needed for scoring
        target = _kv(info, "linear_model")
        linear = None
        for i in range(int(_kv(info, "submodel_count", 0))):
            if _kv(info, f"submodel_key_{i}") == target:
                linear = load_sub(prefix + _kv(info, f"submodel_dir_{i}"))
                break
        if linear is None:
            raise ValueError("rulefit MOJO names a linear model that is "
                             "not among its submodels")
        return RefRuleFitModel(info, columns, domains, linear)
    if algo == "targetencoder":
        return RefTargetEncoderModel(z, prefix, info, columns, domains)
    if algo == "xgboost":
        return RefXGBoostModel(z, prefix, info, columns, domains)
    if algo == "extendedisolationforest":
        return RefExtendedIsoForModel(z, prefix, info, columns, domains)
    return None
