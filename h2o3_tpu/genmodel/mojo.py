"""MOJO-style portable scoring artifacts — versioned, pickle-free.

Reference: ``h2o-genmodel`` MOJO — a zip of ``model.ini`` metadata + binary
payload, written by ``hex/genmodel/AbstractMojoWriter.java`` and read back by
``hex/genmodel/ModelMojoReader.java`` into a standalone scorer with no
cluster required. The reference format is deliberately language-neutral:
ini text + named binary blobs, never Java serialization.

This framework's artifact keeps that contract with TPU-native content:

- ``model.ini``     — readable metadata (format/version, algorithm, model
  class, response info, key parameters)
- ``structure.json``— the model's object tree with every array replaced by a
  ``{"$a": name}`` placeholder
- ``arrays.npz``    — the named numeric arrays (tree heaps, GLM betas, DL
  weight matrices, …)

Loading reconstructs the model WITHOUT unpickling anything: ``json.loads`` +
``np.load(allow_pickle=False)`` only, so artifacts from untrusted sources
cannot execute code (the round-1 artifact shipped a pickle — flagged in
VERDICT r2 as unsafe; this is the fix). A ``format = h2o3_tpu_mojo`` v1
pickle artifact is refused with guidance unless ``allow_legacy=True``.
"""

from __future__ import annotations

import configparser
import dataclasses
import io
import json
import zipfile

import numpy as np

MOJO_FORMAT = "h2o3_tpu_mojo"
MOJO_VERSION = "2.0"

_JSON_SCALARS = (bool, int, float, str, type(None))


# ---------------------------------------------------------------------------
# encode


class _Encoder:
    def __init__(self):
        self.arrays: dict[str, np.ndarray] = {}
        self._n = 0

    def _store(self, arr: np.ndarray) -> dict:
        name = f"a{self._n}"
        self._n += 1
        self.arrays[name] = np.asarray(arr)
        return {"$a": name}

    def encode(self, obj):
        from h2o3_tpu.models.data_info import DataInfo
        from h2o3_tpu.models.model_base import Model
        from h2o3_tpu.models.tree import Tree

        if isinstance(obj, _JSON_SCALARS):
            if isinstance(obj, float) and not np.isfinite(obj):
                return {"$f": repr(obj)}
            return obj
        if isinstance(obj, (np.floating, np.integer, np.bool_)):
            return self.encode(obj.item())
        if isinstance(obj, np.ndarray):
            return self._store(obj)
        if isinstance(obj, Tree):
            return {"$tree": {f.name: self.encode(getattr(obj, f.name))
                              for f in dataclasses.fields(Tree)}}
        if isinstance(obj, DataInfo):
            return {"$di": {f.name: self.encode(getattr(obj, f.name))
                            for f in dataclasses.fields(DataInfo)}}
        if isinstance(obj, Model):
            return {"$model": _encode_model(obj, self)}
        if isinstance(obj, tuple):
            return {"$t": [self.encode(v) for v in obj]}
        if isinstance(obj, list):
            return [self.encode(v) for v in obj]
        if isinstance(obj, dict):
            return {"$d": {str(k): self.encode(v) for k, v in obj.items()}}
        # jax arrays reach here only if host_copy was skipped
        if hasattr(obj, "__array__"):
            return self._store(np.asarray(obj))
        raise TypeError(
            f"MOJO cannot serialize {type(obj).__name__}: the artifact is "
            "restricted to arrays + JSON so it loads without unpickling")


def _encode_model(model, enc: _Encoder) -> dict:
    """The scoring-relevant state of one model (metrics and CV artifacts are
    training-session state — the reference MOJO omits them too)."""
    params = {}
    for k, v in dict(model.params).items():
        try:
            params[k] = enc.encode(v)
        except TypeError:
            continue     # callables (custom metrics), frames: not portable
    return {
        "class": type(model).__name__,
        "algo": model.algo,
        "key": model.key,
        "response_column": enc.encode(model.response_column),
        "response_domain": enc.encode(model.response_domain),
        "params": params,
        "output": enc.encode(model.output),
        "data_info": enc.encode(model.data_info),
        "preprocessors": [_encode_model(p, enc)
                          for p in getattr(model, "preprocessors", [])],
        "scoring_history": enc.encode(model.scoring_history),
    }


# ---------------------------------------------------------------------------
# decode


def _model_classes() -> dict[str, type]:
    """Every concrete Model subclass by class name (the loader's registry —
    no class names are ever imported from the artifact itself)."""
    import h2o3_tpu.models  # noqa: F401 — populates the subclass tree
    import h2o3_tpu.orchestration.stacked_ensemble  # noqa: F401
    from h2o3_tpu.models.model_base import Model

    out: dict[str, type] = {}
    stack = [Model]
    while stack:
        cls = stack.pop()
        for sub in cls.__subclasses__():
            out[sub.__name__] = sub
            stack.append(sub)
    return out


class _Decoder:
    def __init__(self, arrays):
        self.arrays = arrays
        self.classes = _model_classes()

    def decode(self, obj):
        from h2o3_tpu.models.data_info import DataInfo
        from h2o3_tpu.models.tree import Tree

        if isinstance(obj, _JSON_SCALARS):
            return obj
        if isinstance(obj, list):
            return [self.decode(v) for v in obj]
        assert isinstance(obj, dict), f"corrupt structure node: {obj!r}"
        if "$a" in obj:
            return self.arrays[obj["$a"]]
        if "$f" in obj:
            return float(obj["$f"])
        if "$t" in obj:
            return tuple(self.decode(v) for v in obj["$t"])
        if "$d" in obj:
            return {k: self.decode(v) for k, v in obj["$d"].items()}
        if "$tree" in obj:
            return Tree(**{k: self.decode(v)
                           for k, v in obj["$tree"].items()})
        if "$di" in obj:
            return DataInfo(**{k: self.decode(v)
                               for k, v in obj["$di"].items()})
        if "$model" in obj:
            return self.decode_model(obj["$model"])
        raise ValueError(f"unknown structure marker in {list(obj)[:3]}")

    def decode_model(self, spec: dict):
        from h2o3_tpu.models.model_base import ModelParameters

        cls = self.classes.get(spec["class"])
        if cls is None:
            raise ValueError(f"artifact needs unknown model class "
                             f"{spec['class']!r}; upgrade h2o3_tpu")
        m = cls.__new__(cls)           # bypass __init__: state comes whole
        m.key = spec["key"]
        m.params = ModelParameters(
            {k: self.decode(v) for k, v in spec["params"].items()})
        m.response_column = self.decode(spec["response_column"])
        m.response_domain = self.decode(spec["response_domain"])
        m.output = self.decode(spec["output"])
        m.data_info = self.decode(spec["data_info"])
        m.training_metrics = None
        m.validation_metrics = None
        m.cross_validation_metrics = None
        m.cv_holdout_predictions = None
        m.cv_holdout_mask = None
        m.run_time_ms = 0
        m.scoring_history = self.decode(spec.get("scoring_history"))
        m.preprocessors = [self.decode_model(p)
                           for p in spec.get("preprocessors", [])]
        return m


# ---------------------------------------------------------------------------
# public surface


def write_mojo(model, path: str) -> str:
    """Export a model as a portable artifact (h2o-py: ``download_mojo``)."""
    from h2o3_tpu.persist.model_io import host_copy

    m = host_copy(model)
    enc = _Encoder()
    structure = _encode_model(m, enc)

    ini = configparser.ConfigParser()
    ini["info"] = {
        "format": MOJO_FORMAT,
        "version": MOJO_VERSION,
        "algorithm": model.algo,
        "model_class": type(model).__name__,
        "model_key": model.key,
        "response_column": str(model.response_column),
        "n_classes": str(model.nclasses),
        "n_arrays": str(len(enc.arrays)),
    }
    ini["columns"] = {"response_domain":
                      json.dumps(list(model.response_domain or []))}
    ini["parameters"] = {k: json.dumps(v, default=str)
                         for k, v in dict(model.params).items()
                         if isinstance(v, (int, float, str, bool, type(None),
                                           list, tuple))}
    buf = io.StringIO()
    ini.write(buf)
    npz = io.BytesIO()
    np.savez_compressed(npz, **enc.arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", buf.getvalue())
        z.writestr("structure.json", json.dumps(structure))
        z.writestr("arrays.npz", npz.getvalue())
    return path


class MojoModel:
    """Standalone scorer over an imported artifact (reference:
    ``hex.genmodel.MojoModel``); no DKV registration, no training state."""

    def __init__(self, inner, info: dict):
        self._inner = inner
        self.info = info
        self.algo = info.get("algorithm", inner.algo)

    @staticmethod
    def load(path: str, allow_legacy: bool = False) -> "MojoModel":
        with zipfile.ZipFile(path) as z:
            ini = configparser.ConfigParser()
            ini.read_string(z.read("model.ini").decode())
            if ini["info"].get("format") != MOJO_FORMAT:
                raise ValueError(f"{path} is not a {MOJO_FORMAT} artifact")
            if "payload.bin" in z.namelist():     # v1 pickle payload
                if not allow_legacy:
                    raise ValueError(
                        f"{path} is a v1 pickle-payload artifact; loading "
                        "executes arbitrary code. Re-export it with this "
                        "build, or pass allow_legacy=True if you trust the "
                        "source")
                import pickle
                inner = pickle.loads(z.read("payload.bin"))
                return MojoModel(inner, dict(ini["info"]))
            structure = json.loads(z.read("structure.json"))
            arrays = dict(np.load(io.BytesIO(z.read("arrays.npz")),
                                  allow_pickle=False))
        inner = _Decoder(arrays).decode_model(structure)
        return MojoModel(inner, dict(ini["info"]))

    def predict(self, frame):
        return self._inner.predict(frame)

    def _score_raw(self, frame):
        return self._inner._score_raw(frame)

    @property
    def nclasses(self) -> int:
        return int(self.info.get("n_classes", 0))

    def __repr__(self) -> str:
        return f"MojoModel(algo={self.algo!r}, key={self.info.get('model_key')!r})"
