"""MOJO-style portable scoring artifacts.

Reference: ``h2o-genmodel`` MOJO — a zip of ``model.ini`` metadata + binary
payload, written by ``hex/genmodel/AbstractMojoWriter.java`` and scored by a
standalone runtime (``MojoModel.java``) with no cluster required.

This framework's artifact keeps the contract (one self-describing zip,
loadable for scoring without the training process or the DKV) with a
TPU-native payload: ``model.ini`` carries readable metadata (algo, columns,
domains, key parameters) and ``payload.bin`` the pickled host-converted model
(every array numpy — see ``persist.model_io``). It is not byte-compatible
with the reference's Java MOJO (that format embeds a JVM scorer), which is
why the ini advertises ``format = h2o3_tpu_mojo``.
"""

from __future__ import annotations

import configparser
import io
import json
import pickle
import zipfile

MOJO_FORMAT = "h2o3_tpu_mojo"
MOJO_VERSION = "1.0"


def write_mojo(model, path: str) -> str:
    """Export a model as a portable artifact (h2o-py: ``download_mojo``)."""
    from h2o3_tpu.persist.model_io import host_copy

    m = host_copy(model)
    ini = configparser.ConfigParser()
    ini["info"] = {
        "format": MOJO_FORMAT,
        "version": MOJO_VERSION,
        "algorithm": model.algo,
        "model_key": model.key,
        "response_column": str(model.response_column),
        "n_classes": str(model.nclasses),
    }
    ini["columns"] = {"response_domain":
                      json.dumps(list(model.response_domain or []))}
    ini["parameters"] = {k: json.dumps(v, default=str)
                         for k, v in dict(model.params).items()
                         if isinstance(v, (int, float, str, bool, type(None),
                                           list, tuple))}
    buf = io.StringIO()
    ini.write(buf)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", buf.getvalue())
        z.writestr("payload.bin", pickle.dumps(m))
    return path


class MojoModel:
    """Standalone scorer over an imported artifact (reference:
    ``hex.genmodel.MojoModel``); no DKV registration, no training state."""

    def __init__(self, inner, info: dict):
        self._inner = inner
        self.info = info
        self.algo = info.get("algorithm", inner.algo)

    @staticmethod
    def load(path: str) -> "MojoModel":
        with zipfile.ZipFile(path) as z:
            ini = configparser.ConfigParser()
            ini.read_string(z.read("model.ini").decode())
            if ini["info"].get("format") != MOJO_FORMAT:
                raise ValueError(f"{path} is not a {MOJO_FORMAT} artifact")
            inner = pickle.loads(z.read("payload.bin"))
        return MojoModel(inner, dict(ini["info"]))

    def predict(self, frame):
        return self._inner.predict(frame)

    def _score_raw(self, frame):
        return self._inner._score_raw(frame)

    @property
    def nclasses(self) -> int:
        return int(self.info.get("n_classes", 0))

    def __repr__(self) -> str:
        return f"MojoModel(algo={self.algo!r}, key={self.info.get('model_key')!r})"
