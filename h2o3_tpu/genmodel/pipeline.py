"""MOJO pipeline transform runtime — feature engineering that ships WITH a
scoring artifact and runs before the model scores.

Reference: ``h2o-genmodel-extensions/mojo-pipeline/.../transformers/*.java``
(MathUnaryTransform, MathBinaryTransform, StringUnaryTransform,
StringGrepTransform, StringSplitTransform, StringPropertiesUnary/Binary,
TimeUnaryTransform, ToNumericConversion, ToStringConversion) and the
``MojoPipelineBuilder`` assembly (``hex/genmodel/MojoPipelineBuilder.java``).
The reference executes each transform as a per-row Java loop over MojoFrame
columns; here numeric transforms are vectorized device ops (XLA fuses the
whole transform chain into the scoring program's input processing) and
string transforms run on the host payloads (string columns are host-resident
by design, ``frame/vec.py:93``).

A ``MojoPipeline`` is an ordered list of ``Transform`` steps plus a final
model; ``save``/``load`` round-trips through a json spec inside the MOJO v2
zip so pipelines are portable artifacts like the reference's.
"""

from __future__ import annotations

import json
import re
import zipfile

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec

__all__ = ["Transform", "MojoPipeline", "MATH_UNARY", "MATH_BINARY",
           "STRING_UNARY"]

# -- op tables (names match the reference factories) -------------------------

MATH_UNARY = {
    "abs": jnp.abs, "acos": jnp.arccos, "acosh": jnp.arccosh,
    "asin": jnp.arcsin, "asinh": jnp.arcsinh, "atan": jnp.arctan,
    "atanh": jnp.arctanh, "ceiling": jnp.ceil, "cos": jnp.cos,
    "cosh": jnp.cosh, "cospi": lambda x: jnp.cos(jnp.pi * x),
    "digamma": lambda x: _scipy_host(x, "digamma"),
    "exp": jnp.exp, "expm1": jnp.expm1, "floor": jnp.floor,
    "gamma": lambda x: _scipy_host(x, "gamma"),
    "lgamma": lambda x: _scipy_host(x, "gammaln"),
    "log": jnp.log, "log10": jnp.log10, "log1p": jnp.log1p,
    "log2": jnp.log2, "round": jnp.round, "sign": jnp.sign,
    "signif": jnp.round,                      # signif(x, digits) via params
    "sin": jnp.sin, "sinh": jnp.sinh, "sinpi": lambda x: jnp.sin(jnp.pi * x),
    "sqrt": jnp.sqrt, "tan": jnp.tan, "tanh": jnp.tanh,
    "tanpi": lambda x: jnp.tan(jnp.pi * x),
    "trigamma": lambda x: _scipy_host(x, "polygamma1"),
    "trunc": jnp.trunc, "none": lambda x: x,
}

MATH_BINARY = {
    "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply, "/": jnp.divide,
    "%": jnp.mod, "^": jnp.power, "intDiv": lambda a, b: jnp.floor_divide(a, b),
    "==": lambda a, b: (a == b).astype(jnp.float32),
    "!=": lambda a, b: (a != b).astype(jnp.float32),
    "<": lambda a, b: (a < b).astype(jnp.float32),
    "<=": lambda a, b: (a <= b).astype(jnp.float32),
    ">": lambda a, b: (a > b).astype(jnp.float32),
    ">=": lambda a, b: (a >= b).astype(jnp.float32),
    "min": jnp.minimum, "max": jnp.maximum,
}

STRING_UNARY = {
    "toupper": lambda s: s.upper(), "tolower": lambda s: s.lower(),
    "trim": lambda s: s.strip(), "lstrip": lambda s: s.lstrip(),
    "rstrip": lambda s: s.rstrip(),
}

STRING_PROPS = {
    "length": lambda s: float(len(s)),
    "num_words": lambda s: float(len(s.split())),
    "entropy": lambda s: _entropy(s),
}


def _entropy(s: str) -> float:
    if not s:
        return 0.0
    from collections import Counter
    n = len(s)
    return float(-sum((c / n) * np.log2(c / n)
                      for c in Counter(s).values()))


def _scipy_host(x, fn: str):
    """Special functions absent from jnp: host round-trip via scipy (these
    are rare pipeline ops; the common ops stay fused on device)."""
    import scipy.special as sp
    import jax
    a = np.asarray(jax.device_get(x), np.float64)
    f = (lambda v: sp.polygamma(1, v)) if fn == "polygamma1" \
        else getattr(sp, fn)
    return jnp.asarray(f(a).astype(np.float32))


TIME_UNARY = ("year", "month", "day", "hour", "minute", "second",
              "dayOfWeek", "week")


class Transform:
    """One pipeline step: op over input column(s) into an output column.

    kinds: math_unary / math_binary / string_unary / string_prop /
    string_grep / string_split / time_unary / to_numeric / to_string.
    """

    def __init__(self, kind: str, op: str, inputs: list[str], output: str,
                 params: dict | None = None):
        self.kind = kind
        self.op = op
        self.inputs = list(inputs)
        self.output = output
        self.params = dict(params or {})
        self._check()

    def _check(self) -> None:
        tables = {"math_unary": MATH_UNARY, "math_binary": MATH_BINARY,
                  "string_unary": STRING_UNARY, "string_prop": STRING_PROPS}
        if self.kind in tables and self.op not in tables[self.kind]:
            raise ValueError(f"unsupported {self.kind} op {self.op!r}; "
                             f"have {sorted(tables[self.kind])}")
        if self.kind == "time_unary" and self.op not in TIME_UNARY:
            raise ValueError(f"unsupported time op {self.op!r}")

    # -- application ---------------------------------------------------------

    def apply(self, frame: Frame) -> Frame:
        out = Frame(list(frame.names), list(frame.vecs))
        if self.kind == "math_unary":
            v = frame.vec(self.inputs[0])
            if self.op == "signif":
                digits = int(self.params.get("digits", 6))
                x = v.as_float()
                # guard 0: log10(0) -> -inf -> mag inf -> 0*inf = NaN
                ax = jnp.where(x == 0, 1.0, jnp.abs(x))
                mag = jnp.power(10.0, digits - 1 - jnp.floor(jnp.log10(ax)))
                y = jnp.where(x == 0, 0.0, jnp.round(x * mag) / mag)
            else:
                y = MATH_UNARY[self.op](v.as_float())
            vec = Vec.from_device(y.astype(jnp.float32), frame.nrows,
                                  VecType.NUM)
        elif self.kind == "math_binary":
            a = frame.vec(self.inputs[0]).as_float()
            b = (frame.vec(self.inputs[1]).as_float()
                 if len(self.inputs) > 1 else
                 jnp.float32(self.params["constant"]))
            if self.params.get("reverse"):      # constant OP column
                a, b = b, a
            y = MATH_BINARY[self.op](a, b)
            vec = Vec.from_device(y.astype(jnp.float32), frame.nrows,
                                  VecType.NUM)
        elif self.kind in ("string_unary", "string_prop", "string_grep",
                           "to_numeric", "to_string", "string_split"):
            vec = self._apply_string(frame)
        elif self.kind == "time_unary":
            from h2o3_tpu.rapids import timeops
            fn = {"dayOfWeek": "day_of_week"}.get(self.op, self.op)
            vec = getattr(timeops, fn)(frame.vec(self.inputs[0]))
        else:
            raise ValueError(f"unknown transform kind {self.kind!r}")
        if self.kind == "string_split":
            # split emits N columns: output, output.1, ...
            for i, v in enumerate(vec):
                _set_col(out, self.output if i == 0 else
                         f"{self.output}.{i}", v)
        else:
            _set_col(out, self.output, vec)
        return out

    def _apply_string(self, frame: Frame):
        v = frame.vec(self.inputs[0])
        vals = (v.labels() if v.is_categorical else
                v.host_values[: frame.nrows] if v.type is VecType.STR else
                [None if np.isnan(x) else _numstr(x) for x in v.to_numpy()])
        if self.kind == "string_unary":
            f = STRING_UNARY[self.op]
            return Vec.from_numpy(np.array(
                [None if s is None else f(str(s)) for s in vals],
                dtype=object), type=VecType.STR)
        if self.kind == "string_prop":
            f = STRING_PROPS[self.op]
            return Vec.from_numpy(np.float32(
                [np.nan if s is None else f(str(s)) for s in vals]))
        if self.kind == "string_grep":
            pat = re.compile(self.params["regex"])
            inv = bool(self.params.get("invert"))
            return Vec.from_numpy(np.float32(
                [np.nan if s is None else
                 float(bool(pat.search(str(s))) != inv) for s in vals]))
        if self.kind == "string_split":
            pat = self.params.get("pattern", r"\s+")
            parts = [([] if s is None else re.split(pat, str(s)))
                     for s in vals]
            width = max((len(p) for p in parts), default=1)
            cols = []
            for i in range(width):
                cols.append(Vec.from_numpy(np.array(
                    [p[i] if i < len(p) else None for p in parts],
                    dtype=object), type=VecType.STR))
            return cols
        if self.kind == "to_numeric":
            def conv(s):
                try:
                    return float(s)
                except (TypeError, ValueError):
                    return np.nan
            return Vec.from_numpy(np.float32([conv(s) for s in vals]))
        # to_string
        return Vec.from_numpy(np.array(
            [None if s is None else str(s) for s in vals], dtype=object),
            type=VecType.STR)

    def spec(self) -> dict:
        return dict(kind=self.kind, op=self.op, inputs=self.inputs,
                    output=self.output, params=self.params)

    @staticmethod
    def from_spec(d: dict) -> "Transform":
        return Transform(d["kind"], d["op"], d["inputs"], d["output"],
                         d.get("params"))


def _numstr(x: float) -> str:
    return str(int(x)) if float(x).is_integer() else repr(float(x))


def _set_col(frame: Frame, name: str, vec) -> None:
    """Add-or-replace: in-place transforms (output == an existing column)
    are a normal reference-pipeline shape."""
    if name in frame.names:
        frame.replace_vec(name, vec)
    else:
        frame.add(name, vec)


class MojoPipeline:
    """Transforms + final model as ONE portable scoring artifact
    (reference: ``MojoPipelineBuilder`` assembling main + generated-column
    sub-mojos)."""

    def __init__(self, transforms: list[Transform], model=None):
        self.transforms = list(transforms)
        self.model = model

    def transform(self, frame: Frame) -> Frame:
        for t in self.transforms:
            frame = t.apply(frame)
        return frame

    def predict(self, frame: Frame) -> Frame:
        fr = self.transform(frame)
        if self.model is None:
            return fr
        return self.model.predict(fr)

    # -- artifact round-trip -------------------------------------------------

    def save(self, path: str) -> str:
        """Zip with pipeline.json (+ the model's own MOJO v2 when present)."""
        import io
        import os
        spec = dict(format="h2o3_tpu/mojo-pipeline", version=1,
                    transforms=[t.spec() for t in self.transforms])
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("pipeline.json", json.dumps(spec, indent=1))
            if self.model is not None:
                from h2o3_tpu.genmodel.mojo import write_mojo
                tmp = path + ".model.tmp"
                write_mojo(self.model, tmp)
                z.write(tmp, "model.mojo")
                os.unlink(tmp)
        return path

    @staticmethod
    def load(path: str) -> "MojoPipeline":
        import io
        with zipfile.ZipFile(path) as z:
            spec = json.loads(z.read("pipeline.json"))
            if spec.get("format") != "h2o3_tpu/mojo-pipeline":
                raise ValueError(f"{path} is not a mojo-pipeline artifact")
            model = None
            if "model.mojo" in z.namelist():
                import os
                import tempfile
                from h2o3_tpu.genmodel.mojo import MojoModel
                with tempfile.NamedTemporaryFile(suffix=".zip",
                                                 delete=False) as f:
                    f.write(z.read("model.mojo"))
                    tmp = f.name
                try:
                    model = MojoModel.load(tmp)
                finally:
                    os.unlink(tmp)
        return MojoPipeline([Transform.from_spec(t)
                             for t in spec["transforms"]], model)
