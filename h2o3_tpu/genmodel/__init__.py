"""Scoring artifacts + explainability (reference: ``h2o-genmodel``, 25 kLoC:
MOJO writers/readers, standalone scorers, TreeSHAP)."""

from h2o3_tpu.genmodel.codegen import download_pojo, generate_pojo
from h2o3_tpu.genmodel.generic import Generic, GenericModel, import_mojo
from h2o3_tpu.genmodel.mojo import MojoModel, write_mojo
from h2o3_tpu.genmodel.pipeline import MojoPipeline, Transform
from h2o3_tpu.genmodel.treeshap import ensemble_contributions, tree_shap

__all__ = ["Generic", "GenericModel", "MojoModel", "MojoPipeline",
           "Transform", "download_pojo", "ensemble_contributions",
           "generate_pojo", "import_mojo", "tree_shap", "write_mojo"]
