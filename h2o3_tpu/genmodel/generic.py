"""Generic — import an external scoring artifact as a first-class model.

Reference: ``hex/generic/GenericModel.java`` (1.3 kLoC): wraps an imported
MOJO so it predicts, computes metrics, and sits in grids/leaderboards like a
trained model.
"""

from __future__ import annotations

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


class GenericModel(Model):
    algo = "generic"

    def _score_raw(self, frame: Frame):
        return self.output["mojo"]._score_raw(frame)

    def predict(self, frame: Frame) -> Frame:
        # the artifact knows its own prediction-frame shape (e.g. an
        # imported IsolationForest emits [predict, mean_length]); the
        # generic Model.predict only understands classifier/regression
        inner = self.output["mojo"]
        if hasattr(inner, "predict"):
            return inner.predict(frame)
        return super().predict(frame)


class Generic(ModelBuilder):
    """h2o-py surface: ``H2OGenericEstimator(path=...)`` / ``h2o.import_mojo``."""

    algo = "generic"
    unsupervised = True   # response comes from the artifact, not train()

    @classmethod
    def defaults(cls) -> dict:
        return dict(super().defaults(), path=None)

    def train(self, x=None, y=None, training_frame=None, **kw):
        from h2o3_tpu.genmodel.mojo import MojoModel
        from h2o3_tpu.genmodel.mojo_ref import is_reference_mojo, load_ref_mojo
        path = self.params.get("path")
        if not path:
            raise ValueError("path to a mojo artifact is required")
        if is_reference_mojo(path):
            # a real H2O-3 MOJO zip (the migration path: users arrive with
            # artifacts from model.download_mojo()) — reference
            # hex/generic/GenericModel.java wraps them the same way
            inner = load_ref_mojo(path)
            mojo = inner
        else:
            mojo = MojoModel.load(path)
            inner = mojo._inner
        model = GenericModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None,
            response_column=inner.response_column,
            response_domain=inner.response_domain,
            output=dict(mojo=mojo, source_algo=mojo.algo),
        )
        # the artifact's decision threshold (max-F1 at training time) must
        # drive predict() labels, not argmax — EasyPredict parity
        thr = getattr(inner, "_default_threshold", None)
        if thr is not None:
            model._default_threshold = float(thr)
        if training_frame is not None and inner.response_column is not None \
                and inner.response_column in training_frame:
            model.training_metrics = model.model_performance(training_frame)
        from h2o3_tpu.utils.registry import DKV
        DKV.put(model.key, model)
        self.model = model
        return model


def import_mojo(path: str, model_id: str | None = None) -> GenericModel:
    """h2o-py: ``h2o.import_mojo`` — one-call artifact import."""
    return Generic(path=path, model_id=model_id).train()
