"""Genmodel tooling: PrintMojo + the row-oriented easy-predict wrapper.

Reference: ``h2o-genmodel``'s ``tools/PrintMojo.java`` (render a MOJO's
trees as Graphviz dot / a readable listing) and
``easy/EasyPredictModelWrapper.java`` (score one ``RowData`` dict at a time
with named columns and string categoricals, returning a typed prediction).

    python -m h2o3_tpu.genmodel.tools model.mojo --format dot > trees.dot
    python -m h2o3_tpu.genmodel.tools model.mojo --format list
"""
from __future__ import annotations

import numpy as np

__all__ = ["print_mojo", "EasyPredictModelWrapper"]


# ---------------------------------------------------------------------------
# PrintMojo


def _tree_iter(model):
    """(label, Tree) pairs across single-output and multinomial models."""
    out = model.output
    if out.get("trees_multi") is not None:
        dom = model.response_domain or []
        for k, trees in enumerate(out["trees_multi"]):
            for i, t in enumerate(trees):
                yield f"class {dom[k] if k < len(dom) else k} tree {i}", t
    else:
        for i, t in enumerate(out.get("trees") or []):
            yield f"tree {i}", t


def _node_label(t, i, x_cols, domains):
    feat = int(np.asarray(t.feat)[i])
    if feat < 0 or not bool(np.asarray(t.is_split)[i]):
        return f"leaf = {float(np.asarray(t.leaf)[i]):.5g}"
    name = x_cols[feat] if feat < len(x_cols) else f"f{feat}"
    if t.left_mask is not None and name in domains:
        mask = np.asarray(t.left_mask)[i]
        dom = domains[name]
        levels = [dom[b] for b in np.nonzero(mask)[0] if b < len(dom)]
        shown = ", ".join(levels[:4]) + ("…" if len(levels) > 4 else "")
        return f"{name} ∈ {{{shown}}}"
    return f"{name} < {float(np.asarray(t.thresh_val)[i]):.5g}"


def print_mojo(path_or_model, fmt: str = "dot", max_trees: int | None = None,
               out=None) -> str:
    """Render a MOJO's (or live model's) trees (reference PrintMojo).

    ``fmt``: ``"dot"`` (Graphviz digraphs, one per tree) or ``"list"``
    (indented text). Returns the rendering; also writes to ``out`` if given.
    """
    model = path_or_model
    if isinstance(path_or_model, str):
        from h2o3_tpu.genmodel.mojo import MojoModel
        model = MojoModel.load(path_or_model)._inner
    x_cols = model.output.get("x_cols", [])
    domains = model.output.get("feat_domains") or {}
    chunks: list[str] = []
    for n, (label, t) in enumerate(_tree_iter(model)):
        if max_trees is not None and n >= max_trees:
            break
        heap = len(np.asarray(t.feat))
        is_split = np.asarray(t.is_split)
        # nodes reachable from the root only
        reach = {0}
        for i in range(heap):
            if i in reach and bool(is_split[i]) and 2 * i + 2 < heap:
                reach.update((2 * i + 1, 2 * i + 2))
        if fmt == "dot":
            lines = [f'digraph "{label}" {{', "  node [shape=box];"]
            for i in sorted(reach):
                lines.append(f'  n{i} [label="{_node_label(t, i, x_cols, domains)}"];')
                if bool(is_split[i]) and 2 * i + 2 < heap:
                    na_l = bool(np.asarray(t.na_left)[i])
                    yes = "yes, NA" if na_l else "yes"
                    no = "no" if na_l else "no, NA"
                    lines.append(f'  n{i} -> n{2 * i + 1} [label="{yes}"];')
                    lines.append(f'  n{i} -> n{2 * i + 2} [label="{no}"];')
            lines.append("}")
            chunks.append("\n".join(lines))
        else:
            lines = [label]
            stack = [(0, 0)]
            while stack:
                i, depth = stack.pop()
                if i not in reach:
                    continue
                lines.append("  " * (depth + 1) + _node_label(t, i, x_cols,
                                                              domains))
                if bool(is_split[i]) and 2 * i + 2 < heap:
                    stack.append((2 * i + 2, depth + 1))
                    stack.append((2 * i + 1, depth + 1))
            chunks.append("\n".join(lines))
    text = "\n\n".join(chunks) + "\n"
    if out is not None:
        out.write(text)
    return text


# ---------------------------------------------------------------------------
# EasyPredictModelWrapper


class EasyPredictModelWrapper:
    """Row-oriented scoring over named columns (reference
    ``easy/EasyPredictModelWrapper.java``): feed one dict per row, strings
    for categoricals, missing keys = NA; get a typed prediction back."""

    def __init__(self, model):
        self.model = model

    def _row_frame(self, rows: list[dict]):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.frame.types import VecType
        cols, vecs = [], []
        domains = self.model.output.get("feat_domains") or {}
        for c in self.model.output.get("x_cols", []):
            cols.append(c)
            if c in domains:
                dom = tuple(domains[c])
                codes = np.array([dom.index(r[c]) if r.get(c) in dom else -1
                                  for r in rows], np.int32)
                vecs.append(Vec.from_numpy(codes, type=VecType.CAT,
                                           domain=dom))
            else:
                vals = np.array([np.nan if r.get(c) is None
                                 else float(r[c]) for r in rows], np.float32)
                vecs.append(Vec.from_numpy(vals))
        return Frame(cols, vecs)

    def predict(self, row: dict) -> dict:
        """One row in, one typed prediction out."""
        preds = self.model.predict(self._row_frame([row]))
        out: dict = {}
        if self.model.is_classifier:
            out["label"] = preds.vec("predict").labels()[0]
            out["class_probabilities"] = {
                d: float(preds.vec(f"p{d}").to_numpy()[0])
                for d in self.model.response_domain}
        else:
            out["value"] = float(preds.vec("predict").to_numpy()[0])
        return out

    def predict_batch(self, rows: list[dict]) -> list[dict]:
        preds = self.model.predict(self._row_frame(rows))
        n = len(rows)
        if self.model.is_classifier:
            labs = preds.vec("predict").labels()[:n]
            probs = {d: preds.vec(f"p{d}").to_numpy()[:n]
                     for d in self.model.response_domain}
            return [{"label": labs[i],
                     "class_probabilities": {d: float(p[i])
                                             for d, p in probs.items()}}
                    for i in range(n)]
        vals = preds.vec("predict").to_numpy()[:n]
        return [{"value": float(v)} for v in vals]


if __name__ == "__main__":
    import argparse
    import sys

    ap = argparse.ArgumentParser(description="PrintMojo")
    ap.add_argument("mojo")
    ap.add_argument("--format", choices=("dot", "list"), default="dot")
    ap.add_argument("--max-trees", type=int, default=None)
    a = ap.parse_args()
    print_mojo(a.mojo, fmt=a.format, max_trees=a.max_trees, out=sys.stdout)
