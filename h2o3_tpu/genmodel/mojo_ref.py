"""Reference-format MOJO importer: load real H2O-3 ``.zip`` MOJOs.

H2O-3 users arrive with MOJO zips produced by ``model.download_mojo()``; this
module reads that format directly so ``h2o.import_mojo`` / ``Generic`` work on
existing artifacts (VERDICT r3 missing #1). Families: GBM, DRF, IsolationForest
(tree bytecode >= 1.20), GLM, K-means, and StackedEnsemble (nested
submodels).  Format provenance (studied, not
copied — this is a from-scratch Python reader):

- ``model.ini`` grammar: ``hex/genmodel/ModelMojoReader.java:286-333``
  ([info] key=value, [columns] one per line, [domains] ``idx: card file``).
- tree bytecode: ``hex/genmodel/algos/tree/SharedTreeMojoModel.java:134-250``
  (the ScoreTree2 walker: nodeType/colId/naSplitDir headers, sized left
  subtree skips, inline leaf floats) with bitset splits per
  ``hex/genmodel/utils/GenmodelBitSet.java:57-69`` (fill2/fill3) and
  little-endian scalars per ``hex/genmodel/utils/ByteBufferWrapper.java``.
- NA routing codes: ``hex/genmodel/algos/tree/NaSplitDir.java``
  (NAvsREST=1, NALeft=2, NARight=3, Left=4, Right=5).
- tree file layout + per-class grouping: ``SharedTreeMojoReader.java:13-60``
  (``trees/t{class:02d}_{group:03d}.bin``), index =
  ``class * n_groups + group`` (``SharedTreeMojoModel.java:952``).
- GBM assembly: ``GbmMojoReader.java`` (distribution/init_f/link) and
  ``GbmMojoModel.java:37-66`` (unifyPreds: linkInv for bernoulli/regression,
  softmax rescale for multinomial).
- DRF assembly: ``DrfMojoModel.java:31-62`` (average over groups; binomial
  single-tree complement; multinomial vote normalization).
- GLM scoring: ``GlmMojoModel.java:26-78`` (mean imputation, catOffsets
  one-hot indexing, beta layout cats|nums|intercept, link inverse).

Only MOJO versions >= 1.20 use this tree bytecode (ScoreTree2); older
artifacts (2016-era) raise a clear error.  Decoding happens once at import:
each compressed tree is expanded into structure-of-arrays node tables and
scoring is vectorized numpy over rows (recursive partition descent), so a
frame scores in O(rows·depth) like the reference's per-row walker but
without the per-row interpreter loop.
"""

from __future__ import annotations

import io
import struct
import zipfile

import numpy as np

__all__ = ["is_reference_mojo", "load_ref_mojo"]

# NaSplitDir values (NaSplitDir.java)
_NA_VS_REST = 1
_NA_LEFT = 2
_LEFT = 4


# -- model.ini ---------------------------------------------------------------

def _parse_ini(text: str):
    """(info: dict[str,str], columns: list[str], domain_files: {col: fname})."""
    info: dict = {}
    columns: list[str] = []
    domain_files: dict[int, tuple[int, str]] = {}
    section = 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[info]":
            section = 1
        elif line == "[columns]":
            section = 2
        elif line == "[domains]":
            section = 3
        elif section == 1:
            k, _, v = line.partition("=")
            info[k.strip()] = v.strip()
        elif section == 2:
            columns.append(line)
        elif section == 3:
            # "7: 2 d000.txt"  (col index: cardinality filename)
            idx, _, rest = line.partition(":")
            card, _, fname = rest.strip().partition(" ")
            domain_files[int(idx)] = (int(card), fname.strip())
    return info, columns, domain_files


def _unescape(s: str) -> str:
    """StringEscapeUtils.unescapeNewlines analog for domain values."""
    return s.replace("\\n", "\n").replace("\\r", "\r").replace("\\\\", "\\") \
        if "\\" in s else s


def _kv(info: dict, key: str, default=None):
    v = info.get(key)
    if v is None or v == "null":
        return default
    return v


def _kv_doubles(info: dict, key: str):
    v = _kv(info, key)
    if v is None:
        return None
    v = v.strip()
    if v.startswith("["):
        v = v[1:-1]
    return np.array([float(x) for x in v.split(",") if x.strip()], np.float64)


# -- compressed tree decode --------------------------------------------------

class _Reader:
    """Little-endian cursor over a tree blob (ByteBufferWrapper.java)."""

    __slots__ = ("b", "p")

    def __init__(self, b: bytes):
        self.b, self.p = b, 0

    def u1(self):
        v = self.b[self.p]
        self.p += 1
        return v

    def u2(self):
        v = self.b[self.p] | (self.b[self.p + 1] << 8)
        self.p += 2
        return v

    def u3(self):
        v = self.b[self.p] | (self.b[self.p + 1] << 8) | (self.b[self.p + 2] << 16)
        self.p += 3
        return v

    def i4(self):
        (v,) = struct.unpack_from("<i", self.b, self.p)
        self.p += 4
        return v

    def f4(self):
        (v,) = struct.unpack_from("<f", self.b, self.p)
        self.p += 4
        return v


class _DecodedTree:
    """Structure-of-arrays decode of one compressed tree."""

    __slots__ = ("col", "split", "left", "right", "leaf", "na_vs_rest",
                 "leftward", "bitset")

    def __init__(self):
        self.col: list[int] = []          # -1 for leaves
        self.split: list[float] = []
        self.left: list[int] = []
        self.right: list[int] = []
        self.leaf: list[float] = []
        self.na_vs_rest: list[bool] = []
        self.leftward: list[bool] = []
        self.bitset: list[tuple | None] = []   # (bitoff, nbits, np.uint8 bytes)

    def _add(self, col, split, leaf, navr, lw, bs) -> int:
        i = len(self.col)
        self.col.append(col)
        self.split.append(split)
        self.left.append(-1)
        self.right.append(-1)
        self.leaf.append(leaf)
        self.na_vs_rest.append(navr)
        self.leftward.append(lw)
        self.bitset.append(bs)
        return i


def _decode_tree(blob: bytes) -> _DecodedTree:
    """Expand the ScoreTree2 bytecode (SharedTreeMojoModel.java:134) into
    node tables, once, at import time."""
    t = _DecodedTree()
    r = _Reader(blob)

    def node() -> int:
        node_type = r.u1()
        col = r.u2()
        if col == 65535:                       # whole tree is a single leaf
            return t._add(-1, np.nan, r.f4(), False, False, None)
        na_dir = r.u1()
        na_vs_rest = na_dir == _NA_VS_REST
        leftward = na_dir in (_NA_LEFT, _LEFT)
        lmask = node_type & 51
        equal = node_type & 12                 # 0 float split, 8/12 bitset
        split_val, bs = np.nan, None
        if not na_vs_rest:
            if equal == 0:
                split_val = r.f4()
            elif equal == 8:                   # fill2: inline 32-bit set
                bs = (0, 32, np.frombuffer(r.b, np.uint8, 4, r.p).copy())
                r.p += 4
            else:                              # fill3: offset + sized set
                bitoff = r.u2()
                nbits = r.i4()
                nbytes = ((nbits - 1) >> 3) + 1
                bs = (bitoff, nbits,
                      np.frombuffer(r.b, np.uint8, nbytes, r.p).copy())
                r.p += nbytes
        me = t._add(col, split_val, np.nan, na_vs_rest, leftward, bs)
        if lmask <= 3:
            r.p += lmask + 1                   # left-subtree byte size: unused
        if lmask & 16:
            t.left[me] = t._add(-1, np.nan, r.f4(), False, False, None)
        else:
            t.left[me] = node()
        rmask = (node_type & 0xC0) >> 2
        if rmask & 16:
            t.right[me] = t._add(-1, np.nan, r.f4(), False, False, None)
        else:
            t.right[me] = node()
        return me

    root = node()
    assert root == 0
    return t


def _score_tree(t: _DecodedTree, X: np.ndarray, domain_len: np.ndarray
                ) -> np.ndarray:
    """Vectorized walk: recursive row partitioning over the decoded nodes.
    Exactly the ScoreTree2 routing ternary (SharedTreeMojoModel.java:215)."""
    n = X.shape[0]
    out = np.zeros(n, np.float64)

    def walk(i: int, rows: np.ndarray) -> None:
        if rows.size == 0:
            return
        if t.col[i] < 0:
            out[rows] = t.leaf[i]
            return
        d = X[rows, t.col[i]]
        iv = np.trunc(d)                      # Java (int)d truncation
        nan_or_out = np.isnan(d)
        bs = t.bitset[i]
        if bs is not None:
            bitoff, nbits, bits = bs
            rel = iv - bitoff
            in_range = (rel >= 0) & (rel < nbits)
            nan_or_out |= ~in_range
        dl = domain_len[t.col[i]]
        if dl >= 0:                           # categorical col: unseen level
            nan_or_out |= ~np.isnan(d) & (iv >= dl)
        if bs is not None:
            rel_c = np.clip(np.nan_to_num(iv - bitoff, nan=0), 0, nbits - 1
                            ).astype(np.int64)
            contains = (bits[rel_c >> 3] >> (rel_c & 7)) & 1
            test = contains.astype(bool)
        elif t.na_vs_rest[i]:
            test = np.zeros(d.shape, bool)    # non-NA always goes left
        else:
            test = d >= t.split[i]
        go_right = np.where(nan_or_out, not t.leftward[i],
                            False if t.na_vs_rest[i] else test)
        walk(t.right[i], rows[go_right])
        walk(t.left[i], rows[~go_right])

    walk(0, np.arange(n))
    return out


# -- link inverses (GbmMojoModel.linkInv / GlmMojoModel link functions) ------

def _default_link(family: str | None) -> str:
    """ModelMojoReader.defaultLinkFunction (ModelMojoReader.java:387)."""
    if family in ("bernoulli", "fractionalbinomial", "quasibinomial",
                  "modified_huber", "ordinal"):
        return "logit"
    if family in ("poisson", "gamma", "tweedie", "negativebinomial"):
        return "log"
    return "identity"


def _link_inv(name: str, f: np.ndarray) -> np.ndarray:
    if name in ("identity", None):
        return f
    if name == "log":
        return np.exp(f)
    if name in ("logit", "ologit"):
        return 1.0 / (1.0 + np.exp(-f))
    if name == "ologlog":
        return 1.0 - np.exp(-np.exp(f))
    if name == "inverse":
        xx = np.where(np.abs(f) < 1e-5, np.where(f < 0, -1e-5, 1e-5), f)
        return 1.0 / xx
    raise ValueError(f"unsupported MOJO link function {name!r}")


# -- imported model wrappers -------------------------------------------------

class _RefModelBase:
    """Common surface the ``Generic`` wrapper consumes (mirrors this repo's
    own MOJO inner models): response_column/response_domain/_score_raw."""

    algo = "ref_mojo"

    def __init__(self, info, columns, domains):
        self.info = info
        self.columns = columns
        self.domains = domains                  # per-column list[str] | None
        self.n_features = int(_kv(info, "n_features"))
        self.nclasses = max(1, int(_kv(info, "n_classes", 1)))
        self.supervised = _kv(info, "supervised") == "true"
        self.response_column = columns[-1] if self.supervised else None
        rd = domains[len(columns) - 1] if self.supervised else None
        self.response_domain = tuple(rd) if rd else None
        thr = _kv(info, "default_threshold")
        self._default_threshold = float(thr) if thr else 0.5

    @property
    def is_classifier(self) -> bool:
        return self.response_domain is not None

    def _design(self, frame) -> np.ndarray:
        """Frame -> (n, n_features) float64 row matrix in MOJO column order.
        CAT columns map through the MOJO's own domain (EasyPredict semantics:
        unseen level behaves as out-of-domain, NA stays NaN)."""
        from h2o3_tpu.frame.types import VecType
        X = np.full((frame.nrows, self.n_features), np.nan, np.float64)
        for j in range(self.n_features):
            name = self.columns[j]
            if name not in frame:
                continue                        # missing column = all NA
            v = frame.vec(name)
            dom = self.domains[j]
            if dom is not None:
                index = {lv: k for k, lv in enumerate(dom)}
                if v.type is VecType.CAT:
                    labels = v.labels()
                else:                           # numeric-coded categories
                    labels = np.array(
                        [None if np.isnan(x) else _fmt_num(x)
                         for x in v.to_numpy().astype(np.float64)],
                        dtype=object)
                col = np.array([np.nan if lv is None
                                else index.get(lv, len(dom)) for lv in labels],
                               np.float64)
            else:
                col = np.asarray(v.to_numpy(), np.float64)[: frame.nrows]
                if v.type is VecType.CAT:       # codes; negative = NA
                    col = np.where(col < 0, np.nan, col)
            X[:, j] = col
        return X

    def _score_raw(self, frame):
        """Padded device predictions — the Model contract is [plen] /
        [plen, nclasses] (model_base.py:103); padded rows are masked out by
        every consumer via frame.row_mask()."""
        import jax.numpy as jnp
        raw = self.score(self._design(frame)).astype(np.float32)
        plen = frame.vecs[0].plen
        pad = plen - frame.nrows
        if pad > 0:
            width = ((0, pad),) + ((0, 0),) * (raw.ndim - 1)
            raw = np.pad(raw, width)
        return jnp.asarray(raw)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.types import VecType
        from h2o3_tpu.frame.vec import Vec
        raw = np.asarray(self._score_raw(frame))
        n = frame.nrows
        raw = raw[:n]                       # drop the device padding rows
        if not self.is_classifier:
            return Frame(["predict"], [Vec.from_numpy(raw)])
        if self.nclasses == 2:
            labels = (raw[:, 1] >= self._default_threshold).astype(np.int32)
        else:
            labels = np.argmax(raw, axis=1).astype(np.int32)
        names = ["predict"] + [f"p{d}" for d in self.response_domain]
        vecs = [Vec.from_numpy(labels, type=VecType.CAT,
                               domain=self.response_domain)]
        for k in range(raw.shape[1]):
            vecs.append(Vec.from_numpy(raw[:, k]))
        return Frame(names, vecs)


def _fmt_num(x: float) -> str:
    """Numeric category label formatting: integral floats render as ints
    (matches how the reference parses numeric-looking factor levels)."""
    return str(int(x)) if float(x).is_integer() else str(x)


class RefTreeModel(_RefModelBase):
    """Imported GBM/DRF MOJO (SharedTreeMojoModel + Gbm/Drf unifyPreds)."""

    def __init__(self, info, columns, domains, trees, algo: str):
        super().__init__(info, columns, domains)
        self.algo = algo
        self.n_groups = int(_kv(info, "n_trees"))
        tpc = _kv(info, "n_trees_per_class")
        if tpc is None:
            bdt = _kv(info, "binomial_double_trees") == "true"
            tpc = 1 if (self.nclasses == 2 and not bdt) else self.nclasses
        self.trees_per_group = int(tpc)
        self.trees = trees                      # [class][group] -> tree|None
        self.family = _kv(info, "distribution")
        # link_function first appears in mojo 1.40; older artifacts default
        # by family (ModelMojoReader.readLinkFunction/defaultLinkFunction)
        self.link = _kv(info, "link_function") or _default_link(self.family)
        self.init_f = float(_kv(info, "init_f", 0.0) or 0.0)
        self.binomial_double_trees = _kv(info, "binomial_double_trees") == "true"
        self._domain_len = np.array(
            [len(d) if d is not None else -1 for d in self.domains], np.int64)

    def score(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        sums = np.zeros((n, self.trees_per_group), np.float64)
        for k in range(self.trees_per_group):
            for t in self.trees[k]:
                if t is not None:
                    sums[:, k] += _score_tree(t, X, self._domain_len)
        if self.algo == "drf":
            return self._unify_drf(sums)
        return self._unify_gbm(sums)

    def _unify_gbm(self, sums):
        """GbmMojoModel.unifyPreds (GbmMojoModel.java:43-66)."""
        fam = self.family
        if fam in ("bernoulli", "quasibinomial", "modified_huber"):
            p1 = _link_inv(self.link, sums[:, 0] + self.init_f)
            return np.stack([1.0 - p1, p1], 1)
        if fam == "multinomial":
            z = sums.copy()
            if self.nclasses == 2:              # 1-tree binomial optimization
                z = np.stack([sums[:, 0] + self.init_f,
                              -(sums[:, 0] + self.init_f)], 1)
            z -= z.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        return _link_inv(self.link, sums[:, 0] + self.init_f)   # regression

    def _unify_drf(self, sums):
        """DrfMojoModel.unifyPreds (DrfMojoModel.java:38-62)."""
        if self.nclasses == 1:
            return sums[:, 0] / self.n_groups
        if self.nclasses == 2 and not self.binomial_double_trees:
            p0 = sums[:, 0] / self.n_groups
            return np.stack([p0, 1.0 - p0], 1)
        s = sums.sum(axis=1, keepdims=True)
        return np.where(s > 0, sums / np.where(s == 0, 1, s), sums)


class RefGlmModel(_RefModelBase):
    """Imported GLM MOJO (GlmMojoModelBase + GlmMojoModel.glmScore0)."""

    algo = "glm"

    def __init__(self, info, columns, domains):
        super().__init__(info, columns, domains)
        self.beta = _kv_doubles(info, "beta")
        self.cats = int(_kv(info, "cats", 0))
        self.nums = int(_kv(info, "nums", 0))
        co = _kv_doubles(info, "cat_offsets")
        self.cat_offsets = (co if co is not None else np.zeros(1)
                            ).astype(np.int64)
        self.use_all_levels = _kv(info, "use_all_factor_levels") == "true"
        self.mean_imputation = _kv(info, "mean_imputation") == "true"
        self.num_means = _kv_doubles(info, "num_means")
        self.cat_modes = (_kv_doubles(info, "cat_modes")
                          if _kv(info, "cat_modes") is not None
                          else np.zeros(0)).astype(np.int64)
        self.family = _kv(info, "family")
        self.link = _kv(info, "link", "identity")

    def score(self, X: np.ndarray) -> np.ndarray:
        X = X.copy()
        if self.mean_imputation:                # GlmMojoModelBase.imputeMissingWithMeans
            for i in range(self.cats):
                m = np.isnan(X[:, i])
                X[m, i] = self.cat_modes[i]
            for i in range(self.nums):
                m = np.isnan(X[:, self.cats + i])
                X[m, self.cats + i] = self.num_means[i]
        if self.family == "multinomial":
            return self._score_multinomial(X)
        eta = np.zeros(X.shape[0], np.float64)
        for i in range(self.cats):
            ok, idx = self._cat_beta_index(X, i, len(self.beta))
            eta += np.where(ok, self.beta[idx], 0.0)
        noff = int(self.cat_offsets[self.cats]) - self.cats
        for i in range(self.cats, self.cats + self.nums):
            eta += self.beta[noff + i] * X[:, i]
        eta += self.beta[-1]                    # intercept
        mu = _link_inv("logit" if self.link == "logit" else self.link, eta)
        if self.family in ("binomial", "fractionalbinomial", "quasibinomial"):
            return np.stack([1.0 - mu, mu], 1)
        return mu

    def _cat_beta_index(self, X: np.ndarray, i: int, clip_bound: int):
        """(ok, idx) for categorical column i's beta entry — the ONE copy of
        the decoding rules: Java (int)NaN == 0 (GlmMojoModel.java:40 without
        imputation; numpy NaN->int64 is undefined), level-0 skip without
        use_all_factor_levels, catOffsets shift, upper-bound mask."""
        ival = np.trunc(np.nan_to_num(X[:, i], nan=0.0)).astype(np.int64)
        if not self.use_all_levels:             # skip level 0 of each factor
            ok = ival != 0
            ival = ival - 1
        else:
            ok = np.ones(ival.shape, bool)
        ival = ival + self.cat_offsets[i]
        ok &= ival < self.cat_offsets[i + 1]
        return ok, np.clip(ival, 0, clip_bound - 1)

    def _score_multinomial(self, X: np.ndarray) -> np.ndarray:
        """GlmMultinomialMojoModel.glmScore0: flat beta of nclasses blocks of
        P (cat one-hots | nums | intercept), per-class eta, softmax."""
        K = self.nclasses
        P = len(self.beta) // K
        if P * K != len(self.beta):
            raise ValueError("incorrect multinomial beta coding")
        B = self.beta.reshape(K, P)
        noff = int(self.cat_offsets[self.cats]) if self.cats else 0
        eta = np.zeros((X.shape[0], K), np.float64)
        for i in range(self.cats):
            ok, idx = self._cat_beta_index(X, i, P)
            eta += np.where(ok[:, None], B[:, idx].T, 0.0)
        for i in range(self.nums):
            eta += np.outer(X[:, self.cats + i], B[:, noff + i])
        eta += B[:, P - 1][None, :]             # intercepts
        z = eta - eta.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


class RefIsoForModel(RefTreeModel):
    """Imported IsolationForest MOJO (IsolationForestMojoReader/-MojoModel):
    trees sum path lengths; score = (max − sum)/(max − min), plus the mean
    path length (and the anomaly flag when the artifact outputs one)."""

    def __init__(self, info, columns, domains, trees):
        super().__init__(info, columns, domains, trees, "isolationforest")
        self.min_path = float(_kv(info, "min_path_length", 0) or 0)
        self.max_path = float(_kv(info, "max_path_length", 0) or 0)
        self.anomaly_flag = _kv(info, "output_anomaly_flag") == "true"

    def _score_raw(self, frame):
        """Model contract: 1-D padded scores (model_base.py:103); the full
        [score, mean_length(, flag)] table is predict()'s shape."""
        import jax.numpy as jnp
        raw = self.score(self._design(frame)).astype(np.float32)
        score = raw[:, 1] if self.anomaly_flag else raw[:, 0]
        pad = frame.vecs[0].plen - frame.nrows
        if pad > 0:
            score = np.pad(score, (0, pad))
        return jnp.asarray(score)

    def score(self, X: np.ndarray) -> np.ndarray:
        sums = np.zeros(X.shape[0], np.float64)
        for t in self.trees[0]:
            if t is not None:
                sums += _score_tree(t, X, self._domain_len)
        mean_len = sums / max(self.n_groups, 1)
        if self.max_path > self.min_path:
            score = (self.max_path - sums) / (self.max_path - self.min_path)
        else:
            score = np.ones_like(sums)
        if self.anomaly_flag:
            # >= : the threshold convention everywhere else (EasyPredict,
            # model_base.py binomial labels)
            return np.stack([(score >= self._default_threshold) * 1.0,
                             score, mean_len], 1)
        return np.stack([score, mean_len], 1)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        raw = self.score(self._design(frame)).astype(np.float32)
        names = (["predict", "score", "mean_length"] if self.anomaly_flag
                 else ["predict", "mean_length"])
        return Frame(names, [Vec.from_numpy(raw[:, j])
                             for j in range(raw.shape[1])])


class RefKMeansModel(_RefModelBase):
    """Imported K-means MOJO (KMeansMojoReader/KMeansMojoModel +
    GenModel.KMeans_distance: Euclidean on numerics, 0/1 mismatch on
    categoricals, NA-dimension upscaling)."""

    algo = "kmeans"

    def __init__(self, info, columns, domains):
        super().__init__(info, columns, domains)
        k = int(_kv(info, "center_num"))
        self.centers = np.stack([_kv_doubles(info, f"center_{i}")
                                 for i in range(k)])
        self.standardize = _kv(info, "standardize") == "true"
        if self.standardize:
            self.means = _kv_doubles(info, "standardize_means")
            self.mults = _kv_doubles(info, "standardize_mults")
            self.modes = _kv_doubles(info, "standardize_modes").astype(np.int64)
        self.is_cat = np.array([domains[j] is not None
                                for j in range(self.n_features)])

    def score(self, X: np.ndarray) -> np.ndarray:
        X = X.copy()
        if self.standardize:                 # Kmeans_preprocessData
            for j in range(self.n_features):
                m = np.isnan(X[:, j])
                if self.modes[j] == -1:      # numeric: impute + scale
                    X[m, j] = self.means[j]
                    X[:, j] = (X[:, j] - self.means[j]) * self.mults[j]
                else:
                    X[m, j] = self.modes[j]
        n, P = X.shape
        d2 = np.zeros((n, len(self.centers)))
        valid = ~np.isnan(X)
        pts = valid.sum(axis=1)
        for c, ctr in enumerate(self.centers):
            diff = np.where(self.is_cat[None, :], (X != ctr[None, :]) * 1.0,
                            (X - ctr[None, :]) ** 2)
            d2[:, c] = np.where(valid, diff, 0.0).sum(axis=1)
        scale = np.where((pts > 0) & (pts < P), P / np.maximum(pts, 1), 1.0)
        d2 *= scale[:, None]
        return np.argmin(d2, axis=1).astype(np.float64)


class RefStackedEnsembleModel(_RefModelBase):
    """Imported StackedEnsemble MOJO (StackedEnsembleMojoReader /
    StackedEnsembleMojoModel.score0): base-model predictions feed the
    metalearner, with per-submodel column remapping by feature name."""

    algo = "stackedensemble"

    def __init__(self, info, columns, domains, base_models, metalearner,
                 mappings):
        super().__init__(info, columns, domains)
        self.base_models = base_models          # list[_RefModelBase | None]
        self.metalearner = metalearner
        self.mappings = mappings                # per-base int[] into parent X
        self.logit_transform = \
            _kv(info, "metalearner_transform", "NONE") == "Logit"

    def score(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        nb = len(self.base_models)
        if self.nclasses > 2:
            base = np.zeros((n, nb * self.nclasses))
            for i, (m, mp) in enumerate(zip(self.base_models, self.mappings)):
                if m is None:
                    continue
                base[:, i * self.nclasses:(i + 1) * self.nclasses] = \
                    m.score(X[:, mp])
        elif self.nclasses == 2:
            base = np.zeros((n, nb))
            for i, (m, mp) in enumerate(zip(self.base_models, self.mappings)):
                if m is not None:
                    base[:, i] = m.score(X[:, mp])[:, 1]
        else:
            base = np.zeros((n, nb))
            for i, (m, mp) in enumerate(zip(self.base_models, self.mappings)):
                if m is not None:
                    base[:, i] = m.score(X[:, mp])
        if self.logit_transform:
            # StackedEnsembleMojoModel.logit: p clipped to [1e-9, 1-1e-9],
            # then max(-19, log odds) — the LOWER side clamps to -19, the
            # upper does not (Java: x==0 ? -19 : max(-19, log(x)))
            b = np.clip(base, 1e-9, 1 - 1e-9)
            base = np.maximum(-19.0, np.log(b / (1 - b)))
        return self.metalearner.score(base)


# -- zip-level entry ---------------------------------------------------------

def is_reference_mojo(path: str) -> bool:
    """True when the zip is an H2O-3 MOJO (model.ini with [info] algo=...)."""
    try:
        with zipfile.ZipFile(path) as z:
            if "model.ini" not in z.namelist():
                return False
            info, _, _ = _parse_ini(z.read("model.ini").decode())
        return "algo" in info and "format" not in info
    except (OSError, zipfile.BadZipFile, KeyError, UnicodeDecodeError):
        return False


def load_ref_mojo(path_or_bytes):
    """Load a reference H2O-3 MOJO zip into a scoring model.

    Supported algos: gbm, drf, isolationforest (tree families, MOJO
    >= 1.20), glm, kmeans, stackedensemble (nested submodels,
    MultiModelMojoReader layout), plus via ``mojo_ref2``: deeplearning,
    pca, glrm, coxph, word2vec, rulefit, targetencoder,
    isotonicregression, xgboost (boosterBytes parsed natively),
    extendedisolationforest.
    Raises with a clear message otherwise — matching ``ModelMojoFactory``'s
    algo dispatch (``hex/genmodel/ModelMojoFactory.java``).
    """
    src = io.BytesIO(path_or_bytes) if isinstance(path_or_bytes, bytes) \
        else path_or_bytes
    with zipfile.ZipFile(src) as z:
        return _load_from_zip(z, "")


def _load_from_zip(z: zipfile.ZipFile, prefix: str):
    """Load the model rooted at ``prefix`` inside the (possibly shared)
    zip — submodels of a StackedEnsemble live under ``models/...`` in the
    parent archive (MultiModelMojoReader.NestedMojoReaderBackend)."""
    info, columns, domain_files = _parse_ini(
        z.read(prefix + "model.ini").decode())
    escape = _kv(info, "escape_domain_values") == "true"
    domains: list = [None] * len(columns)
    for ci, (_card, fname) in domain_files.items():
        lines = z.read(prefix + "domains/" + fname).decode().splitlines()
        domains[ci] = [(_unescape(s) if escape else s).strip()
                       for s in lines]
    algo = _kv(info, "algo")
    mojo_version = float(_kv(info, "mojo_version", 0))
    if algo in ("gbm", "drf", "isolationforest"):
        if mojo_version < 1.20:
            raise ValueError(
                f"tree MOJO version {mojo_version} predates the "
                "ScoreTree2 bytecode; re-export with H2O-3 >= 3.22")
        nclasses = max(1, int(_kv(info, "n_classes", 1)))
        tpc = _kv(info, "n_trees_per_class")
        if tpc is None:
            bdt = _kv(info, "binomial_double_trees") == "true"
            tpc = 1 if (nclasses == 2 and not bdt) else nclasses
        tpc = int(tpc)
        n_groups = int(_kv(info, "n_trees"))
        trees = [[None] * n_groups for _ in range(tpc)]
        names = set(z.namelist())
        for k in range(tpc):
            for g in range(n_groups):
                name = f"{prefix}trees/t{k:02d}_{g:03d}.bin"
                if name in names:
                    trees[k][g] = _decode_tree(z.read(name))
        if algo == "isolationforest":
            return RefIsoForModel(info, columns, domains, trees)
        return RefTreeModel(info, columns, domains, trees, algo)
    if algo == "glm":
        return RefGlmModel(info, columns, domains)
    if algo == "kmeans":
        return RefKMeansModel(info, columns, domains)
    if algo == "stackedensemble":
        subs: dict = {}
        n_sub = int(_kv(info, "submodel_count", 0))
        for i in range(n_sub):
            key = _kv(info, f"submodel_key_{i}")
            sub_dir = _kv(info, f"submodel_dir_{i}")
            subs[key] = _load_from_zip(z, prefix + sub_dir)
        meta_key = _kv(info, "metalearner")
        meta = subs.get(meta_key)
        if meta is None:
            raise ValueError(
                f"stackedensemble MOJO names metalearner {meta_key!r} but "
                f"the archive's submodels are {sorted(subs)}")
        nb = int(_kv(info, "base_models_num", 0))
        base_models, mappings = [], []
        n_feat = int(_kv(info, "n_features"))
        col_index = {c: j for j, c in enumerate(columns[:n_feat])}
        for i in range(nb):
            bkey = _kv(info, f"base_model{i}")
            m = subs.get(bkey)
            base_models.append(m)
            if m is None:
                mappings.append(None)
                continue
            # remap by feature NAME: submodels may order columns differently
            # (StackedEnsembleMojoReader.createMapping)
            feats = m.columns[: m.n_features]
            try:
                mappings.append(np.array([col_index[f] for f in feats],
                                         np.int64))
            except KeyError as e:
                raise ValueError(f"base model {bkey!r} input column {e} "
                                 "missing from the ensemble frame") from None
        return RefStackedEnsembleModel(info, columns, domains, base_models,
                                       meta, mappings)
    # long-tail families (DL/PCA/GLRM/CoxPH/Word2Vec/RuleFit/TargetEncoder/
    # Isotonic) live in mojo_ref2 — same archive grammar, separate module
    from h2o3_tpu.genmodel.mojo_ref2 import load_ext_family
    model = load_ext_family(algo, z, prefix, info, columns, domains,
                            lambda p: _load_from_zip(z, p))
    if model is not None:
        return model
    raise ValueError(
        f"unsupported reference MOJO algo {algo!r}; this importer handles "
        "gbm, drf, isolationforest, glm, kmeans, stackedensemble, "
        "deeplearning, pca, glrm, coxph, word2vec, rulefit, targetencoder, "
        "isotonicregression, xgboost, extendedisolationforest (export other "
        "families from this framework's "
        "own MOJO v2 instead)")
