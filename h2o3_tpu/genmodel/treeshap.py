"""Exact TreeSHAP contributions for dense-heap trees.

Reference: ``h2o-genmodel/.../algos/tree/TreeSHAP.java`` (Lundberg & Lee's
polynomial-time algorithm: a recursion over the tree carrying the subset-path
weights, EXTEND on the way down, UNWIND to read a feature's contribution).

Vectorization note: the recursion's CONTROL FLOW is static per tree (every
node is visited; which features sit on each path is fixed), only the
hot/cold one-fractions depend on the row. So the path state becomes a list of
[rows] numpy arrays and one Python recursion per tree serves every row at
once. This runs on host by design — contributions are an offline
explainability pass, not the serving path (same split as the reference:
TreeSHAP lives in genmodel, not in the cluster scorer).
"""

from __future__ import annotations

import jax
import numpy as np


class _Path:
    """Subset-path state: parallel lists of static feature ids and per-row
    fraction/weight arrays (one list entry per path element)."""

    def __init__(self, rows: int):
        self.d: list[int] = []          # feature id per path entry (-1 = root)
        self.z: list[np.ndarray] = []   # zero (cover) fractions, [rows]
        self.o: list[np.ndarray] = []   # one (decision) fractions, [rows]
        self.w: list[np.ndarray] = []   # permutation weights, [rows]
        self.rows = rows

    def copy(self) -> "_Path":
        p = _Path(self.rows)
        p.d = list(self.d)
        p.z = [a.copy() for a in self.z]
        p.o = [a.copy() for a in self.o]
        p.w = [a.copy() for a in self.w]
        return p

    def extend(self, d: int, z, o) -> None:
        L = len(self.d)
        self.d.append(d)
        self.z.append(np.broadcast_to(np.asarray(z, np.float64),
                                      (self.rows,)).copy())
        self.o.append(np.broadcast_to(np.asarray(o, np.float64),
                                      (self.rows,)).copy())
        self.w.append(np.full(self.rows, 1.0 if L == 0 else 0.0))
        for i in range(L - 1, -1, -1):
            self.w[i + 1] += self.o[-1] * self.w[i] * (i + 1) / (L + 1)
            self.w[i] = self.z[-1] * self.w[i] * (L - i) / (L + 1)

    def unwind(self, i: int) -> None:
        L = len(self.d) - 1
        o, z = self.o[i], self.z[i]
        n = self.w[L].copy()
        for j in range(L - 1, -1, -1):
            wj = self.w[j].copy()
            safe_o = np.where(o != 0, o, 1.0)
            t = n * (L + 1) / ((j + 1) * safe_o)
            self.w[j] = np.where(o != 0, t, wj * (L + 1) / np.maximum(L - j, 1)
                                 / np.where(z != 0, z, 1.0))
            n = np.where(o != 0, wj - self.w[j] * z * (L - j) / (L + 1), n)
        # element i leaves the path; the WEIGHTS shrink from the tail (they
        # are per-path-length, not per-element — Lundberg's UNWIND)
        for lst in (self.d, self.z, self.o):
            del lst[i]
        del self.w[-1]

    def unwound_sum(self, i: int) -> np.ndarray:
        """Σ over path permutations with element i removed (UNWIND without
        mutating)."""
        L = len(self.d) - 1
        o, z = self.o[i], self.z[i]
        total = np.zeros(self.rows)
        n = self.w[L].copy()
        for j in range(L - 1, -1, -1):
            safe_o = np.where(o != 0, o, 1.0)
            with_o = n * (L + 1) / ((j + 1) * safe_o)
            without = self.w[j] * (L + 1) / np.maximum(L - j, 1) \
                / np.where(z != 0, z, 1.0)
            t = np.where(o != 0, with_o, without)
            total += t
            n = np.where(o != 0, self.w[j] - t * z * (L - j) / (L + 1), n)
        return total


def tree_shap(tree, X: np.ndarray, cat_card=None, n_bins: int = 0) -> np.ndarray:
    """[rows, F+1] contributions (last column = bias) of one dense-heap tree.

    X uses the model's raw feature layout (cat codes as floats, NaN = NA).
    Group-split trees (``tree.left_mask`` set) route categorical features by
    bin membership; ``cat_card``/``n_bins`` supply the code→bin mapping.
    """
    feat = np.asarray(jax.device_get(tree.feat))
    tv = np.asarray(jax.device_get(tree.thresh_val))
    nal = np.asarray(jax.device_get(tree.na_left))
    isp = np.asarray(jax.device_get(tree.is_split))
    leaf = np.asarray(jax.device_get(tree.leaf)).astype(np.float64)
    cover = np.asarray(jax.device_get(tree.cover)).astype(np.float64) \
        if getattr(tree, "cover", None) is not None else None
    if cover is None:
        raise ValueError("tree has no cover stats (grown before gain/cover "
                         "channels); retrain to use predict_contributions")
    mask = (np.asarray(jax.device_get(tree.left_mask))
            if getattr(tree, "left_mask", None) is not None else None)
    cc = (np.asarray(jax.device_get(cat_card))
          if cat_card is not None else None)
    rows, F = X.shape
    phi = np.zeros((rows, F + 1))
    if cover[0] <= 0:
        return phi

    def go_left(node: int) -> np.ndarray:
        f = feat[node]
        x = X[:, f]
        if mask is not None and cc is not None and cc[f] > 0:
            code = np.nan_to_num(x, nan=0.0).astype(np.int64)
            b = (code * n_bins) // max(int(cc[f]), 1) \
                if cc[f] > n_bins else code
            b = np.clip(b, 0, mask.shape[1] - 1)
            return np.where(np.isnan(x), nal[node], mask[node, b]).astype(bool)
        return np.where(np.isnan(x), nal[node], x < tv[node]).astype(bool)

    def recurse(node: int, path: _Path):
        if not isp[node]:
            v = leaf[node]
            for i in range(1, len(path.d)):
                phi[:, path.d[i]] += path.unwound_sum(i) * \
                    (path.o[i] - path.z[i]) * v
            return
        d = int(feat[node])
        left, right = 2 * node + 1, 2 * node + 2
        hot = go_left(node)   # [rows] bool: which child the row takes
        rj = max(cover[node], 1e-12)
        iz = np.ones(rows)
        io = np.ones(rows)
        for k in range(1, len(path.d)):
            if path.d[k] == d:
                iz, io = path.z[k].copy(), path.o[k].copy()
                path.unwind(k)
                break
        for child, is_hot in ((left, hot), (right, ~hot)):
            p = path.copy()
            p.extend(d, iz * cover[child] / rj, io * is_hot.astype(np.float64))
            recurse(child, p)

    root = _Path(rows)
    root.extend(-1, 1.0, 1.0)
    recurse(0, root)
    phi[:, F] = _expected_value(leaf, cover, isp)
    return phi


def _expected_value(leaf, cover, isp) -> float:
    """Cover-weighted mean prediction (the bias term)."""
    leaves = ~isp & (cover > 0)
    # exclude internal-split nodes AND unreached heap slots
    tot = cover[leaves].sum()
    if tot <= 0:
        return 0.0
    return float((leaf[leaves] * cover[leaves]).sum() / tot)


def ensemble_contributions(trees, X: np.ndarray, cat_card=None,
                           n_bins: int = 0) -> np.ndarray:
    """Σ per-tree SHAP values (reference: ``PredictTreeSHAPTask``); the bias
    column sums each tree's expected value so row-sums equal the raw margin."""
    out = None
    for t in trees:
        c = tree_shap(t, X, cat_card=cat_card, n_bins=n_bins)
        out = c if out is None else out + c
    return out
