"""Quantile bin-edge computation + feature binning.

Reference: H2O trees bin each feature into ``nbins`` histogram buckets; bin
edges come from global quantiles (``hex/tree/GlobalQuantilesCalc.java``,
``DHistogram.java`` QUANTILES_GLOBAL / UNIFORM_ADAPTIVE) and the XGBoost port
uses the hist method's global quantile sketch. Distributed quantiles in the
reference are an iterative-refinement histogram MRTask
(``hex/quantile/Quantile.java:15,190``).

TPU-native: edges are computed once per training run from a uniform row sample
(the LightGBM/sampled-sketch approach — statistically equivalent for binning
purposes), then the full column is binned on device with a vectorized
``searchsorted`` (log2(B) compares per element, fully parallel). Missing values
get a dedicated bin (B) so trees can learn a default direction, matching
XGBoost's learned-default-direction semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def compute_bin_edges(X_host: np.ndarray, nbins: int,
                      w_host: np.ndarray | None = None) -> np.ndarray:
    """Per-feature quantile edges, shape [F, nbins-1] (inf-padded).

    ``X_host``: a row sample [n, F] (NaNs allowed); ``w_host``: matching
    per-row weights.  Bin b covers [edges[b-1], edges[b]);
    bin(x) = #edges <= x.

    Quantiles are weighted inverted-CDF (the smallest value whose
    cumulative weight reaches q·total).  That definition makes a row
    with weight k bin IDENTICALLY to the same row repeated k times —
    the reference's weights-as-replication contract
    (``pyunit_weights_gbm.py``; ``hex/tree/DHistogram`` sees weighted
    counts the same way).
    """
    n, F = X_host.shape
    qs = np.linspace(0, 1, nbins + 1)[1:-1]
    edges = np.full((F, nbins - 1), np.inf, np.float32)
    if w_host is None:
        w_host = np.ones(n, np.float64)
    for f in range(F):
        col = X_host[:, f]
        m = ~np.isnan(col) & (w_host > 0)
        col, w = col[m], w_host[m]
        if col.size == 0:
            continue
        order = np.argsort(col, kind="stable")
        c, cw = col[order], np.cumsum(w[order])
        pos = np.searchsorted(cw, qs * cw[-1], side="left")
        e = np.unique(c[np.clip(pos, 0, len(c) - 1)])
        edges[f, : len(e)] = e
    return edges


def bin_dtype(nbins: int):
    """Narrowest integer dtype that holds every bin id (0..nbins, where
    ``nbins`` is the NA bin) PLUS the Pallas pad sentinel ``nbins + 2``
    (pallas_hist pads row tiles with ``n_bins_tot + 1``).  The ONE place
    the int8/int16 threshold lives — training (gbm._bin_frame) and
    scoring-frame binning (bin_features) must agree or bins overflow."""
    return jnp.int8 if nbins + 2 <= 127 else jnp.int16


@jax.jit
def bin_features(X: jax.Array, edges: jax.Array) -> jax.Array:
    """Bin a [rows, F] matrix → int8/int16 bins in [0, B]; NaN → B
    (missing bin).  B = edges.shape[1] + 1 regular bins; bin = count of
    edges <= x.

    The narrowest dtype that also holds the Pallas pad sentinel (B + 2)
    is used: int8 up to 125 bins — half the HBM traffic of the histogram
    kernel's dominant input — else int16 (nbins <= 32k).
    """
    nbins = edges.shape[1] + 1
    dtype = bin_dtype(nbins)

    def one(e, col):
        b = jnp.searchsorted(e, col, side="right").astype(dtype)
        return jnp.where(jnp.isnan(col), dtype(nbins), b)

    return jax.vmap(one, in_axes=(0, 1), out_axes=1)(edges, X)


def sample_rows_host(X: jax.Array, nrows: int, max_sample: int = 100_000) -> np.ndarray:
    """Strided row sample fetched to host for edge computation."""
    stride = max(1, nrows // max_sample)
    return np.asarray(jax.device_get(X[: nrows][:: stride]))
