"""Compute substrate: map/reduce over sharded columns, histograms, linalg."""

from h2o3_tpu.ops.map_reduce import map_reduce, map_cols

__all__ = ["map_reduce", "map_cols"]
