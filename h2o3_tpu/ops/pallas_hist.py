"""Pallas TPU kernel for level-synchronous histogram building.

The hot op of tree growth (reference: ``hex/tree/ScoreBuildHistogram2.java``
— per-bin (w, wY, wYY) accumulation, SURVEY.md §2.9's "Pallas histogram-build
kernel"). For every feature f, tree node n, bin b:

    hist[f, n, b, :] = Σ_rows [node==n]·[bin_f==b]·(g, h, w)

XLA's ``segment_sum`` lowering of this inside the fused tree program runs at
~110 ms/level on 500k×28 (scatter-add serialization); this kernel instead
rides the MXU: per (row-tile, feature) grid step it builds the transposed
bin one-hot [S, T] on the VPU and contracts it against a per-tile
node×stat spread matrix ns[T, Nb*3] (computed once per tile into VMEM
scratch), accumulating histograms in a resident VMEM output block.

Tiling (round-3 lift of the depth-6/narrow-F cliff): the grid is
(node-blocks, feature-blocks, row-tiles, features-in-block). The output
block holds one (feature-block × node-block) slab and stays VMEM-resident
across the row sweep; node blocks beyond the first re-read the inputs, so
HBM traffic scales with ``ceil(N / NODE_BLOCK)`` — the dispatch layer caps
how many blocks are worth it (measured crossover vs the scatter path; see
``_MAX_NODE_BLOCKS`` and ROOFLINE.md). FLOP cost is R·F·2·S·3·N MACs and
doubles per level — the MXU wins while the arithmetic stays under the
scatter path's serialization, not asymptotically.

Layout notes (Mosaic constraints): the bin one-hot is built TRANSPOSED
([S, T], bins on sublanes) because dynamic lane indexing is unsupported;
binned is passed pre-transposed [F, 1, R] so each grid step DMAs a
contiguous [1, 1, T] row block; the per-feature output offset uses an
8-aligned padded bin stride S.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_TILE = 1024
#: MXU precision mode for the one-hot contraction. The one-hot operand is
#: EXACTLY representable in bf16 (entries 0/1), so only the stats operand
#: needs splitting: "hilo" = 2 bf16 passes (stats to 16-bit mantissa,
#: ~1.5e-5 relative — vs the ~4e-3 of a single bf16 pass that flips
#: near-tie splits), "hilo3" = 3 passes (24-bit mantissa, f32-exact),
#: "highest" = XLA's 6-pass f32 decomposition (the round-3 default).
#: 2 passes ≈ 3x the MXU throughput of HIGHEST for identical tree quality
#: at the tolerance the split scan already works in (f32 cumsums).
_MXU_MODE = os.environ.get("H2O3TPU_HIST_MXU", "hilo").strip().lower()
if _MXU_MODE not in ("hilo", "hilo3", "highest"):
    raise ValueError(
        f"H2O3TPU_HIST_MXU={_MXU_MODE!r}: expected hilo, hilo3, or highest")
#: tests force interpret mode to validate kernel semantics off-TPU
_INTERPRET = False
_NODE_BLOCK = 64     # nodes per resident output slab
#: node-block count cap: levels needing more blocks fall back to the XLA
#: scatter path. Kernel time grows ~linearly with blocks (input re-reads +
#: MXU FLOPs ∝ N); the scatter path is roughly flat until XLA switches
#: lowering around N≈4096 and speeds up. Measured crossover on v5e at
#: 1M×28×64bins: 3.8× win at N=2048 (32 blocks), loss at N=4096 — so 32
#: blocks ≡ tree depth ≤ 11 stays on the kernel (ROOFLINE.md has the table).
_MAX_NODE_BLOCKS = 32
#: validated up to 10.7MB resident (257 bins × 64 nodes × 28 features in one
#: slab) on v5e's 16MB VMEM — keep 256-bin × F≈28 configs single-block
_VMEM_BUDGET = 11 * 1024 * 1024


def _plan(n_nodes: int, n_feat: int, n_bins_tot: int):
    """(node_block, feat_block) tile sizes, or None if out of envelope."""
    S = ((n_bins_tot + 7) // 8) * 8
    Nb = min(n_nodes, _NODE_BLOCK)
    if (n_nodes + Nb - 1) // Nb > _MAX_NODE_BLOCKS:
        return None
    # resident out slab Fb*S*Nb*3*4 within budget after fixed costs
    fixed = (_TILE * Nb * 3 * 4          # ns scratch
             + S * _TILE * 4             # bin one-hot
             + 3 * _TILE * 128 * 4 * 2)  # padded input double-buffers
    per_feat = S * Nb * 3 * 4
    Fb = max(1, min(n_feat, (_VMEM_BUDGET - fixed) // per_feat))
    if Fb < 1 or fixed + per_feat > _VMEM_BUDGET:
        return None
    return Nb, Fb


def pallas_available(n_nodes: int, n_feat: int, n_bins_tot: int) -> bool:
    if jax.default_backend() != "tpu":
        return False
    return _plan(n_nodes, n_feat, n_bins_tot) is not None


def _hist_kernel(b_ref, n_ref, s_ref, out_ref, ns_ref, *, Nb, S, T, Fb):
    import jax.experimental.pallas as pl

    gb = pl.program_id(0)      # node block
    i = pl.program_id(2)       # row tile
    fi = pl.program_id(3)      # feature within block

    @pl.when(jnp.logical_and(i == 0, fi == 0))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # ns[k, t] = (node[t] == gb*Nb + k//3) * ghw[k%3, t]; built once per
    # (node-block, row-tile). Inputs arrive ROW-MAJOR-TRANSPOSED ([3, R],
    # [1, R]): a narrow [R, 3] array in HBM pads its 3-wide minor dim to 128
    # lanes (42x memory blowup at 11M rows); [3, R] pads 3 sublanes to 8.
    @pl.when(fi == 0)
    def _():
        nd = n_ref[0, :]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (Nb * 3, 1), 0)
        ghw_rep = jnp.concatenate([s_ref[:]] * Nb, axis=0)         # [Nb*3, T]
        ns_ref[:] = jnp.where(nd[None, :] == gb * Nb + iota_k // 3,
                              ghw_rep, 0.0)

    binf = b_ref[0, 0, :].astype(jnp.int32)   # i8/i16 in HBM (gbm._bin_frame
    #                                           packs <=125-bin configs to
    #                                           int8); upcast per tile
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    if _MXU_MODE == "highest":
        bin_oh_T = (iota_r == binf[None, :]).astype(jnp.float32)   # [S, T]
        acc = jax.lax.dot_general(
            bin_oh_T, ns_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)                   # [S, Nb*3]
    else:
        # one-hot is bf16-exact; split only the stats operand into bf16
        # digits and accumulate the partial products in f32 — 2 (or 3)
        # MXU passes instead of HIGHEST's 6 (see _MXU_MODE)
        oh16 = (iota_r == binf[None, :]).astype(jnp.bfloat16)      # [S, T]

        def bdot(rhs16):
            return jax.lax.dot_general(
                oh16, rhs16, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)

        ns = ns_ref[:]
        hi = ns.astype(jnp.bfloat16)
        acc = bdot(hi)
        r1 = ns - hi.astype(jnp.float32)
        m1 = r1.astype(jnp.bfloat16)
        acc += bdot(m1)
        if _MXU_MODE == "hilo3":
            r2 = (r1 - m1.astype(jnp.float32)).astype(jnp.bfloat16)
            acc += bdot(r2)
    out_ref[0, 0, pl.ds(fi * S, S), :] += acc


@partial(jax.jit, static_argnames=("n_nodes", "n_bins_tot"))
def hist_pallas(binned_T, node, g, h, w, n_nodes: int, n_bins_tot: int):
    """[F, n_nodes*n_bins_tot, 3] histograms (same layout as the XLA path)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, Bt, T = n_nodes, n_bins_tot, _TILE
    F, R = binned_T.shape
    S = ((Bt + 7) // 8) * 8
    Nb, Fb = _plan(N, F, Bt)
    n_gb = (N + Nb - 1) // Nb
    n_fb = (F + Fb - 1) // Fb
    padf = n_fb * Fb - F
    if padf:
        # feature padding: rows read a duplicate of the last feature; the
        # surplus output slabs are sliced off below
        binned_T = jnp.pad(binned_T, ((0, padf), (0, 0)), mode="edge")
    pad = (-R) % T
    if pad:
        # padded bin value Bt+1 never matches a one-hot row; padded node -1
        binned_T = jnp.pad(binned_T, ((0, 0), (0, pad)), constant_values=Bt + 1)
        node = jnp.pad(node, (0, pad), constant_values=-1)
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        w = jnp.pad(w, (0, pad))
    Rp = binned_T.shape[1]
    act = node >= 0
    # stats-major [3, R] / [1, R]: see layout note in the kernel
    ghw_T = jnp.stack([g, h, w], 0) * act[None, :].astype(jnp.float32)
    nodec = jnp.where(act, node, -1)[None, :]
    out = pl.pallas_call(
        partial(_hist_kernel, Nb=Nb, S=S, T=T, Fb=Fb),
        interpret=_INTERPRET,
        out_shape=jax.ShapeDtypeStruct((n_gb, n_fb, Fb * S, Nb * 3),
                                       jnp.float32),
        grid=(n_gb, n_fb, Rp // T, Fb),
        in_specs=[
            pl.BlockSpec((1, 1, T), lambda gb, fb, i, fi: (fb * Fb + fi, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda gb, fb, i, fi: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, T), lambda gb, fb, i, fi: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, Fb * S, Nb * 3),
                               lambda gb, fb, i, fi: (gb, fb, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((Nb * 3, T), jnp.float32)],
    )(binned_T[:, None, :], nodec, ghw_T)
    # [n_gb, n_fb, Fb*S, Nb*3] → [F, N, S, 3] → clip padding → [F, N*Bt, 3]
    out = out.reshape(n_gb, n_fb, Fb, S, Nb, 3)
    out = out.transpose(1, 2, 0, 4, 3, 5).reshape(n_fb * Fb, n_gb * Nb, S, 3)
    out = out[:F, :N, :Bt]
    return out.reshape(F, N * Bt, 3)
