"""Pallas TPU kernel for level-synchronous histogram building.

The hot op of tree growth (reference: ``hex/tree/ScoreBuildHistogram2.java``
— per-bin (w, wY, wYY) accumulation, SURVEY.md §2.9's "Pallas histogram-build
kernel"). For every feature f, tree node n, bin b:

    hist[f, n, b, :] = Σ_rows [node==n]·[bin_f==b]·(g, h, w)

XLA's ``segment_sum`` lowering of this inside the fused tree program runs at
~110 ms/level on 500k×28 (scatter-add serialization); this kernel instead
rides the MXU: per (row-tile, feature) grid step it builds the transposed
bin one-hot [S, T] on the VPU and contracts it against a per-tile
node×stat spread matrix ns[T, N*3] (computed once per tile into VMEM
scratch), accumulating all features' histograms in one resident VMEM output
block. ~30 ms/level → ~4× end-to-end tree-growth speedup, measured on
TPU v5e.

Layout notes (Mosaic constraints): the bin one-hot is built TRANSPOSED
([S, T], bins on sublanes) because dynamic lane indexing is unsupported;
binned is passed pre-transposed [F, 1, R] so each grid step DMAs a
contiguous [1, 1, T] row block; the per-feature output offset uses an
8-aligned padded bin stride S.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# VMEM budget: out block F*S*(N*3)*4 + ns scratch T*(N*3)*4 + narrow input
# blocks padded to 128 lanes. T=1024 fits comfortably for N ≤ 64, F ≤ ~100.
_TILE = 1024
_MAX_NODES = 64      # beyond this the resident out block would blow VMEM


def pallas_available(n_nodes: int, n_feat: int, n_bins_tot: int) -> bool:
    if jax.default_backend() != "tpu":
        return False
    if n_nodes > _MAX_NODES:
        return False
    # resident out block + ns scratch + bin one-hot + double-buffered narrow
    # inputs (padded to 128 lanes); 11MB leaves headroom in 16MB VMEM —
    # validated up to 257 bins × 64 nodes × 28 features
    S = ((n_bins_tot + 7) // 8) * 8
    vmem = (n_feat * S * n_nodes * 3 * 4        # out block
            + _TILE * n_nodes * 3 * 4           # ns scratch
            + S * _TILE * 4                     # bin one-hot
            + 3 * _TILE * 128 * 4 * 2)          # padded input double-buffers
    return vmem < 11 * 1024 * 1024


def _hist_kernel(b_ref, n_ref, s_ref, out_ref, ns_ref, *, N, S, T):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    f = pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, f == 0))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # ns[k, t] = (node[t] == k//3) * ghw[k%3, t]; built once per row tile.
    # Inputs arrive ROW-MAJOR-TRANSPOSED ([3, R], [1, R]): a narrow [R, 3]
    # array in HBM pads its 3-wide minor dim to 128 lanes (42x memory blowup
    # at 11M rows — an OOM, not a slowdown); [3, R] pads 3 sublanes to 8.
    @pl.when(f == 0)
    def _():
        nd = n_ref[0, :]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (N * 3, 1), 0)
        ghw_rep = jnp.concatenate([s_ref[:]] * N, axis=0)          # [N*3, T]
        ns_ref[:] = jnp.where(nd[None, :] == iota_k // 3, ghw_rep, 0.0)

    binf = b_ref[0, 0, :].astype(jnp.int32)   # i16 in HBM; upcast per tile
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    bin_oh_T = (iota_r == binf[None, :]).astype(jnp.float32)       # [S, T]
    # HIGHEST: the MXU's default bf16 operand rounding loses ~0.4% on
    # gradient sums — enough to flip near-tie split decisions
    acc = jax.lax.dot_general(bin_oh_T, ns_ref[:], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)  # [S, N*3]
    out_ref[pl.ds(f * S, S), :] += acc


@partial(jax.jit, static_argnames=("n_nodes", "n_bins_tot"))
def hist_pallas(binned_T, node, g, h, w, n_nodes: int, n_bins_tot: int):
    """[F, n_nodes*n_bins_tot, 3] histograms (same layout as the XLA path)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, Bt, T = n_nodes, n_bins_tot, _TILE
    F, R = binned_T.shape
    S = ((Bt + 7) // 8) * 8
    pad = (-R) % T
    if pad:
        # padded bin value Bt+1 never matches a one-hot row; padded node -1
        binned_T = jnp.pad(binned_T, ((0, 0), (0, pad)), constant_values=Bt + 1)
        node = jnp.pad(node, (0, pad), constant_values=-1)
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        w = jnp.pad(w, (0, pad))
    Rp = binned_T.shape[1]
    act = node >= 0
    # stats-major [3, R] / [1, R]: see layout note in the kernel
    ghw_T = jnp.stack([g, h, w], 0) * act[None, :].astype(jnp.float32)
    nodec = jnp.where(act, node, 0)[None, :]
    out = pl.pallas_call(
        partial(_hist_kernel, N=N, S=S, T=T),
        out_shape=jax.ShapeDtypeStruct((F * S, N * 3), jnp.float32),
        grid=(Rp // T, F),
        in_specs=[
            pl.BlockSpec((1, 1, T), lambda i, f: (f, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i, f: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, T), lambda i, f: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F * S, N * 3), lambda i, f: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((N * 3, T), jnp.float32)],
    )(binned_T[:, None, :], nodec, ghw_T)
    # [F, S, N, 3] → clip bin padding → [F, N, Bt, 3] → [F, N*Bt, 3]
    out = out.reshape(F, S, N, 3)[:, :Bt].transpose(0, 2, 1, 3)
    return out.reshape(F, N * Bt, 3)
