"""map_reduce — the MRTask contract on a TPU mesh.

Reference: ``water/MRTask.java:83-118,257-305`` — user code supplies
``map(Chunk[])`` producing per-chunk partial state and ``reduce(MRTask)``
merging two partials; the runtime fans out over nodes in a binary tree, runs
map on every local chunk via recursive fork/join, and reduces partials up the
tree over RPC.

TPU-native expression: the contract — a commutative-associative monoid over
row shards — maps 1:1 onto ``shard_map`` + ``lax.psum``:

- fan-out over nodes + per-chunk fork/join  →  SPMD: each device runs ``map_fn``
  on its shard (XLA vectorizes the "loop over rows" instead of forking tasks);
- tree reduction over RPC                   →  ``lax.psum`` over the ``rows``
  mesh axis (XLA lowers to an ICI all-reduce, which IS a ring/tree reduction
  in hardware).

Two styles are supported, and most algorithm code uses the second:

1. Explicit: ``map_reduce(map_fn, cols...)`` — per-shard partials psum-reduced.
   Use when the partial is a fixed-shape statistic (histogram, Gram, counts).
2. Implicit: write plain ``jnp`` reductions over the sharded column inside
   ``jax.jit`` — the SPMD partitioner inserts the same collectives. (This is
   why most of the framework contains no explicit communication code at all.)
"""

from __future__ import annotations

import itertools
import os
import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import ROWS, get_mesh

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


# Compiled-program cache: jit executables are tied to the wrapper instance, so
# re-wrapping per call would recompile every invocation (deadly in iterative
# algorithms like tree building). Keyed by (fn, mesh, arg ranks, donate) with
# LRU eviction (``move_to_end`` on hit): hot entries survive fresh-lambda
# churn, fresh-lambda callers get no hits but can't grow the dict unboundedly
# (evicted entries simply recompile on reuse). Pass a module-level function or
# a stable partial to benefit from caching. jax.jit's own cache handles
# shape/dtype specialization underneath.
from collections import OrderedDict

_COMPILED_MAX = 256
_compiled: OrderedDict = OrderedDict()


def _cache_key(tag, fn, rest):
    return (tag, fn, rest)


def _cache_get(key):
    value = _compiled.get(key)
    if value is not None:
        _compiled.move_to_end(key)  # LRU: hot entries survive fresh-lambda churn
    return value


def _cache_put(key, value):
    _compiled[key] = value
    while len(_compiled) > _COMPILED_MAX:
        _compiled.popitem(last=False)


# Telemetry sampling for the dispatch path. JAX dispatch is ASYNC: blocking on
# the result to measure an accurate duration (and to stamp per-partition
# readiness) serializes back-to-back collectives — exactly the host-as-clock
# pattern this module exists to avoid. So accurate duration/straggler probes
# are SAMPLED: every Nth dispatch (H2O3TPU_DISPATCH_SAMPLE, default 16; the
# first dispatch always samples so short sessions still measure something)
# pays one sync for the `h2o3_mapreduce_dispatch_seconds` observation and —
# when a trace is active — the straggler attrs. Per-partition sub-spans are
# additionally gated behind H2O3TPU_TRACE_PARTITIONS=1 (full fidelity: every
# traced dispatch syncs and stamps shard readiness). Unsampled dispatches
# record only enqueue-side counters and return un-synced outputs, so the
# device pipelines K-step megasteps without the host in the loop.
_SAMPLE_EVERY = max(int(os.environ.get("H2O3TPU_DISPATCH_SAMPLE", "16") or 16), 1)
_dispatch_seq = itertools.count()


# ---------------------------------------------------------------------------
# Dispatch retry — the UDP-drop tolerance of the reference (water/H2O.java
# -random_udp_drop exercises an RPC retry path) mapped onto this runtime's
# network events: device dispatches. A transient failure (an injected
# FaultInjected drop, a transient XLA RuntimeError) is retried with
# exponential backoff + jitter under a budget; only an exhausted budget
# surfaces, as a structured DispatchFailed carrying the attempt history
# (docs/RELIABILITY.md).

class DispatchFailed(RuntimeError):
    """A dispatch kept failing after its retry budget was exhausted.

    ``fn`` names the call site; ``history`` is the per-attempt record
    (error + backoff) that Job surfaces to pollers."""

    def __init__(self, fn: str, history: "list[dict]"):
        self.fn = fn
        self.history = history
        last = history[-1]["error"] if history else "unknown"
        super().__init__(f"dispatch {fn!r} failed after {len(history)} "
                         f"attempt(s); last error: {last}")


#: elastic-membership ejection hook (parallel/elastic.py): a worker thread
#: running under ``ejection_scope(cb)`` turns an exhausted retry budget into
#: a MEMBERSHIP event instead of a build failure — ``retrying`` invokes the
#: hook with the call-site name and attempt history right before raising
#: DispatchFailed, so the elastic group records the ejection cause while the
#: exception unwinds only the worker's round (the build goes on without it).
import contextlib as _contextlib
import contextvars as _contextvars

_EJECT_HOOK: "_contextvars.ContextVar" = _contextvars.ContextVar(
    "h2o3_eject_hook", default=None)


@_contextlib.contextmanager
def ejection_scope(hook: "Callable[[str, list], None]"):
    """Route retry exhaustion in this context to ``hook(fn, history)``
    (called before :class:`DispatchFailed` is raised). Elastic worker
    threads bind this so a dead dispatch ejects the WORKER, not the job."""
    token = _EJECT_HOOK.set(hook)
    try:
        yield
    finally:
        _EJECT_HOOK.reset(token)


def retry_budget() -> int:
    """Retry attempts after the first try (``H2O3TPU_DISPATCH_RETRIES``,
    default 3; 0 disables the retry machinery — failures pass through
    unchanged)."""
    try:
        return max(int(os.environ.get("H2O3TPU_DISPATCH_RETRIES", "") or 3), 0)
    except ValueError:
        return 3


def _backoff_ms(attempt: int) -> float:
    """Exponential backoff with jitter: base * 2^attempt * U(0.5, 1.5)
    (``H2O3TPU_DISPATCH_BACKOFF_MS``, default 25)."""
    import random
    try:
        base = float(os.environ.get("H2O3TPU_DISPATCH_BACKOFF_MS", "") or 25.0)
    except ValueError:
        base = 25.0
    return base * (2 ** attempt) * (0.5 + random.random())


#: error-status tags that mark a RuntimeError DETERMINISTIC, not transient:
#: re-dispatching an OOM or an invalid program burns device time on a
#: failure that cannot change (XlaRuntimeError subclasses RuntimeError and
#: carries the gRPC-style status name in its message)
_NON_TRANSIENT = ("RESOURCE_EXHAUSTED", "INVALID_ARGUMENT",
                  "FAILED_PRECONDITION", "UNIMPLEMENTED")


def retrying(what: str, thunk: Callable, *, span=None,
             retry_runtime_errors: bool = True):
    """Run ``thunk`` under the dispatch retry budget.

    Fault injection (``FAULTS.maybe_fault(what)``) fires before every
    attempt, so chaos drops exercise this exact path. ``FaultInjected`` is
    always retryable (it is raised before the dispatch); ``RuntimeError``
    from the dispatch itself is retried only when ``retry_runtime_errors``
    (donated buffers are consumed by a real dispatch attempt, so donating
    call sites must not re-run it). Each retry increments
    ``h2o3_dispatch_retries_total{fn,outcome="retried"}`` and notes itself
    on the active Job; exhaustion increments ``outcome="exhausted"`` and
    raises :class:`DispatchFailed` with the attempt history."""
    from h2o3_tpu.utils import telemetry as _tm
    from h2o3_tpu.utils import timeline as _tl
    budget = retry_budget()
    history: list[dict] = []
    attempt = 0
    while True:
        try:
            if _tl.FAULTS is not None:
                _tl.FAULTS.maybe_fault(what)
            out = thunk()
        except (_tl.FaultInjected, RuntimeError) as e:
            if isinstance(e, DispatchFailed):
                raise          # a nested dispatch already exhausted its budget
            if budget == 0:
                raise          # retries disabled: pure pass-through, no
                               # metrics — the machinery never ran
            if not isinstance(e, _tl.FaultInjected) and (
                    not retry_runtime_errors
                    or any(tag in str(e) for tag in _NON_TRANSIENT)):
                raise          # deterministic failure: surface immediately
            history.append({"attempt": attempt,
                            "error": f"{type(e).__name__}: {e}"})
            if attempt >= budget:
                _tm.DISPATCH_RETRIES.labels(fn=what,
                                            outcome="exhausted").inc()
                if span is not None:
                    span.set_attrs(retries=attempt)
                hook = _EJECT_HOOK.get()
                if hook is not None:
                    # elastic worker context: the exhausted budget is an
                    # ejection cause, recorded before the exception unwinds
                    # this worker's round (best-effort — a hook error must
                    # not mask the DispatchFailed it annotates)
                    try:
                        hook(what, history)
                    except Exception as he:   # noqa: BLE001
                        _tl.TIMELINE.record("elastic",
                                            f"eject_hook_error:{he}")
                raise DispatchFailed(what, history) from e
            delay = _backoff_ms(attempt)
            history[-1]["backoff_ms"] = round(delay, 1)
            _tm.DISPATCH_RETRIES.labels(fn=what, outcome="retried").inc()
            from h2o3_tpu.models.job import note_dispatch_retry
            note_dispatch_retry()
            time.sleep(delay / 1000.0)
            attempt += 1
            continue
        if attempt:
            # absorbed faults still read in trace trees: the span carries
            # how many retries the dispatch cost and a "retried" status
            # (overriding the error mark the injected drop left). Builder
            # call sites pass no span of their own — mark the ACTIVE span
            # (their timed_event chunk/megastep span) instead.
            if span is not None:
                span.set_attrs(retries=attempt)
                span.set_status("retried")
            else:
                from h2o3_tpu.utils import tracing as _trc
                # force: the injected drop already marked this span "error";
                # the absorbed outcome overrides it
                _trc.TRACER.mark_active(status="retried", force=True,
                                        retries=attempt)
        return out


def map_reduce(map_fn: Callable, *cols: jax.Array, donate: bool = False):
    """Run ``map_fn`` on each device's row shard; psum-reduce the results.

    ``map_fn(*shards) -> pytree of arrays`` must produce partials whose
    elementwise sum is the correct global result (the MRTask ``reduce``
    contract specialized to addition, which covers every reference use:
    histograms, Gram matrices, gradient sums, counts).
    """
    mesh = get_mesh()
    ndims = tuple(c.ndim for c in cols)
    name = getattr(map_fn, "__name__", "map_reduce")
    key = _cache_key("mr", map_fn, (mesh, ndims, donate))
    fn = _cache_get(key)
    if fn is None:
        in_specs = tuple(P(ROWS, *([None] * (nd - 1))) for nd in ndims)

        def shard_body(*shards):
            return jax.tree.map(lambda p: lax.psum(p, ROWS), map_fn(*shards))

        # accounted AOT compile (utils/costs.py): every collective's
        # signature / compile time / cost_analysis FLOPs land in /3/Compute.
        # sample=False — this module's OWN sampled probe below measures the
        # synced duration and feeds COSTS.observe, so the wrapper must not
        # add a second sync of its own
        from h2o3_tpu.utils.costs import accounted_jit
        fn = accounted_jit(
            f"map_reduce:{name}",
            _shard_map(shard_body, mesh=mesh, in_specs=in_specs,
                       out_specs=P()),
            donate_argnums=tuple(range(len(cols))) if donate else (),
            sample=False)
        _cache_put(key, fn)
    from h2o3_tpu.utils import telemetry as _tm
    from h2o3_tpu.utils import timeline as _tl
    from h2o3_tpu.utils import tracing as _tr
    # child span per dispatch (no-op outside an active trace); faults
    # injected below mark THIS span, so fault runs read in trace trees
    # sampled telemetry sync (see the note at _SAMPLE_EVERY): full partition
    # fidelity under H2O3TPU_TRACE_PARTITIONS=1, else every Nth dispatch
    full = _tr.trace_partitions_enabled()
    sampled = full or (next(_dispatch_seq) % _SAMPLE_EVERY == 0)
    dur_box = [0]
    with _tr.TRACER.span(f"map_reduce:{name}", kind="dispatch",
                         attrs={"fn": name,
                                "partitions": mesh.size,
                                "sampled": sampled}) as span:
        def _attempt():
            # device-byte attribution per TRACED SAMPLED dispatch — only
            # through the runtime's memory_stats counters (~µs): the
            # live-array fallback walks every resident buffer and has no
            # place on this hot path, so backends without stats (CPU) skip
            # it (fast probe returns None)
            mem0 = None
            if span is not None and sampled:
                from h2o3_tpu.utils.memory import fast_device_bytes
                mem0 = fast_device_bytes()
            t0 = time.time_ns()
            # NO unconditional sync: dispatch is async, so back-to-back
            # collectives pipeline on device and the host stops being the
            # clock. Only a SAMPLED dispatch blocks, because an enqueue-time
            # measurement would never see a slow collective — the sync IS
            # the probe.
            out = fn(*cols)
            if sampled:
                # measure BEFORE the full sync (per-shard readiness IS the
                # probe) but EMIT spans only after the attempt succeeds — a
                # failed-then-retried attempt must not leave bogus partition
                # spans in the trace tree
                meas = (_measure_partitions(out, mesh, t0)
                        if span is not None else None)
                out = jax.block_until_ready(out)  # graftlint: ok(sampled telemetry probe — the sync is the measurement)
                if meas is not None:
                    _emit_partition_spans(span, meas, t0)
                dur_box[0] = time.time_ns() - t0
                _tm.MR_DISPATCH_SECONDS.labels(fn=name).observe(
                    dur_box[0] / 1e9)
                # the synced duration is exactly what the compute
                # observatory needs: achieved FLOP/s of this collective
                # against the cost of the signature that actually ran
                # (utils/costs.py; fn is the AccountedJit built above)
                from h2o3_tpu.utils.costs import COSTS
                cflops, cbytes = fn.last_cost()
                COSTS.observe(f"map_reduce:{name}", dur_box[0] / 1e9,
                              flops=cflops, nbytes=cbytes)
                if mem0 is not None:
                    mem1 = fast_device_bytes()
                    if mem1 is not None:
                        # max of the two in-use samples, NOT the runtime's
                        # peak_bytes_in_use counter — that one is
                        # process-lifetime monotonic, so after any big build
                        # every later dispatch would report the global
                        # high-water mark instead of its own footprint (same
                        # semantic as the model-span attr)
                        span.set_attrs(
                            peak_device_bytes=max(mem0[0], mem1[0]),
                            device_bytes_delta=mem1[0] - mem0[0])
            # unmeasured dispatches keep dur_box at 0: the timeline keeps one
            # record per dispatch either way, but an async enqueue time must
            # not pollute the duration series — dur_ns=0 is the ring's
            # established "untimed event" marker; accurate durations live in
            # the SAMPLED observations
            return out

        # transient failures (injected drops, transient runtime errors) are
        # retried with backoff instead of killing the Job; donated buffers
        # are consumed by a real dispatch attempt, so donate=True only
        # retries pre-dispatch FaultInjected
        out = retrying("map_reduce", _attempt, span=span,
                       retry_runtime_errors=not donate)
    _tl.TIMELINE.record("collective", name, dur_box[0])
    # dispatch count + partition (shard) count always; the duration
    # histogram's min/max spread is the straggler signal (under SPMD all
    # shards run one program, so a straggler shows as dispatch max >> min)
    _tm.MR_DISPATCHES.labels(fn=name).inc()
    _tm.MR_PARTITIONS.inc(mesh.size)
    return out


def _measure_partitions(out, mesh, t0: int):
    """Per-partition readiness measurement under a traced SAMPLED dispatch:
    block on each device's output shard in device order and stamp when it
    became ready. Runs only on sampled dispatches / under
    ``H2O3TPU_TRACE_PARTITIONS=1`` — the sequential shard blocking is a
    real serialization, so it must never ride on every dispatch a traced
    request touches. Returns ``(ends, devices)`` or None; SPAN EMISSION is
    separate (:func:`_emit_partition_spans`) so a failed-then-retried
    attempt's measurements are simply discarded. Best-effort: a trace must
    never break a dispatch."""
    try:
        leaves = jax.tree.leaves(out)
        shards0 = getattr(leaves[0], "addressable_shards", None) \
            if leaves else None
        if not shards0:
            return None
        ends = []
        for i in range(len(shards0)):
            for leaf in leaves:
                sh = getattr(leaf, "addressable_shards", ())
                if i < len(sh):
                    # graftlint: ok(sampled straggler probe — per-shard readiness IS the measurement)
                    jax.block_until_ready(sh[i].data)
            ends.append(time.time_ns())
        return ends, [str(s.device) for s in shards0]
    except Exception:   # noqa: BLE001 — tracing is best-effort by contract
        return None


def _emit_partition_spans(span, meas, t0: int) -> None:
    """Turn a successful attempt's readiness measurement into partition
    child spans + straggler attribution attrs (max/argmax of the
    INCREMENTAL waits — see :func:`_shard_waits`)."""
    try:
        from h2o3_tpu.utils import tracing as _tr
        ends, devices = meas
        durs = [e - t0 for e in ends]
        waits = _shard_waits(ends, t0)
        argmax = waits.index(max(waits))
        for i, end in enumerate(ends):
            _tr.TRACER.add_span(f"partition:{i}", "partition", span,
                                start_ns=t0, end_ns=end,
                                attrs={"device": devices[i],
                                       "wait_ns": waits[i]},
                                tid=f"partition-{i}")
        span.set_attrs(part_dur_min_ns=min(durs), part_dur_max_ns=max(durs),
                       straggler=argmax, straggler_device=devices[argmax])
    except Exception:   # noqa: BLE001 — tracing is best-effort by contract
        pass


def _shard_waits(ends: "list[int]", t0: int) -> "list[int]":
    """Per-shard incremental wait from sequential readiness stamps: shards
    are blocked on in device order, so the CUMULATIVE times are monotone
    and their argmax would always name the last shard; the true straggler is
    where the readiness time JUMPS — a shard already finished while an
    earlier one was blocking shows ~zero incremental wait."""
    return [max(e - (ends[i - 1] if i else t0), 0)
            for i, e in enumerate(ends)]


def map_cols(fn: Callable, *cols: jax.Array) -> jax.Array:
    """Elementwise/column transform preserving row sharding.

    Reference analog: MRTask with ``NewChunk`` outputs (``outputFrame``) — a map
    with no reduce. Under jit on sharded inputs this is embarrassingly parallel;
    provided as a named entry point for symmetry and for fusing multi-column
    expressions in one compiled program.
    """
    key = _cache_key("mc", fn, ())
    jfn = _cache_get(key)
    if jfn is None:
        from h2o3_tpu.utils.costs import accounted_jit
        jfn = accounted_jit(
            f"map_cols:{getattr(fn, '__name__', 'map_cols')}", fn,
            sample=False)
        _cache_put(key, jfn)
    return jfn(*cols)


@partial(jax.jit, static_argnames=("num_segments",))
def segment_sum_cols(values: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Global segment-sum over sharded rows (building block for group-by and
    histogram accumulation). values: [rows] or [rows, k]; ids: [rows] int32
    with negative ids dropped."""
    ok = segment_ids >= 0
    ids = jnp.where(ok, segment_ids, 0)
    vals = jnp.where((ok if values.ndim == 1 else ok[:, None]), values, 0)
    return jax.ops.segment_sum(vals, ids, num_segments=num_segments)
