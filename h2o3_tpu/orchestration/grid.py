"""Grid search — hyperparameter space walkers.

Reference: ``hex/grid/`` — ``HyperSpaceWalker.java:409`` (Cartesian),
``:511`` (RandomDiscrete with max_models / max_runtime / early-stopping
budgets), ``GridSearch.java`` driver, ``Grid.java`` container keyed in DKV.

TPU note: independent model builds are host-level task parallelism (the
reference runs them on the F/J pools); each build internally uses the
row-sharded device mesh. Builds run sequentially here — the scheduler that
overlaps small builds across hosts is an AutoML/driver concern (SURVEY.md §7
hard part (e)).
"""

from __future__ import annotations

import hashlib
import itertools
import time
from typing import Any, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.utils.registry import DKV
from h2o3_tpu.utils.tracing import TRACER


def _metric_value(model: Model, metric: str | None, prefer_cv: bool) -> float:
    mm = (model.cross_validation_metrics if prefer_cv and
          model.cross_validation_metrics is not None else
          (model.validation_metrics or model.training_metrics))
    if mm is None:
        return float("nan")
    if metric is None:
        metric = default_metric(model)
    v = getattr(mm, metric, float("nan"))
    return float(v() if callable(v) else v)


def default_metric(model: Model) -> str:
    """Reference defaults: AUC (binomial), logloss (multinomial), rmse."""
    if model.nclasses == 2:
        return "auc"
    if model.nclasses > 2:
        return "logloss"
    return "rmse"


def metric_higher_is_better(metric: str) -> bool:
    return metric in ("auc", "pr_auc", "aucpr", "accuracy", "r2", "gini")


class Grid:
    """Trained-model container, sortable by metric (reference: hex.grid.Grid)."""

    def __init__(self, grid_id: str, models: list[Model], failures: list[tuple[dict, str]],
                 metric: str | None = None):
        self.grid_id = grid_id
        self.models = models
        self.failures = failures
        self._metric = metric
        DKV.put(grid_id, self)

    def sorted_models(self, metric: str | None = None, decreasing: bool | None = None
                      ) -> list[Model]:
        if not self.models:
            return []
        metric = metric or self._metric or default_metric(self.models[0])
        if decreasing is None:
            decreasing = metric_higher_is_better(metric)
        keyed = [(m, _metric_value(m, metric, prefer_cv=True)) for m in self.models]
        keyed.sort(key=lambda t: (np.isnan(t[1]), -t[1] if decreasing else t[1]))
        return [m for m, _ in keyed]

    @property
    def model_ids(self) -> list[str]:
        return [m.key for m in self.models]

    def __repr__(self) -> str:
        lines = [f"Grid(id={self.grid_id!r}, {len(self.models)} models, "
                 f"{len(self.failures)} failed)"]
        for m in self.sorted_models()[:10]:
            lines.append(f"  {m.key}")
        return "\n".join(lines)


class GridSearch:
    """h2o-py surface: ``H2OGridSearch(builder, hyper_params, search_criteria)``.

    search_criteria: ``{"strategy": "Cartesian"}`` (default) or
    ``{"strategy": "RandomDiscrete", "max_models": N, "max_runtime_secs": S,
    "seed": k}`` (reference: ``HyperSpaceSearchCriteria``).
    """

    def __init__(self, builder_cls: type[ModelBuilder] | ModelBuilder,
                 hyper_params: dict[str, Sequence[Any]],
                 grid_id: str | None = None,
                 search_criteria: dict | None = None,
                 recovery_dir: str | None = None,
                 parallelism: int = 1, scheduler=None, **fixed_params):
        if isinstance(builder_cls, ModelBuilder):
            fixed_params = {**builder_cls.params, **fixed_params}
            builder_cls = type(builder_cls)
        self.builder_cls = builder_cls
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        self.fixed_params = fixed_params
        self.search_criteria = dict(search_criteria or {"strategy": "Cartesian"})
        self.grid_id = grid_id or f"{builder_cls.algo}_grid_{int(time.time())}"
        self.recovery_dir = recovery_dir
        # reference: GridSearch.startGridSearch(..., parallelism) — builds
        # overlap on host threads (see orchestration/parallel_build.py),
        # each leasing a disjoint device slice from the scheduler
        # (orchestration/scheduler.py; AutoML shares its run's scheduler)
        self.parallelism = max(1, int(parallelism))
        self.scheduler = scheduler
        self.grid: Grid | None = None

    def _combos(self):
        """Lazy combo stream: Cartesian iterates the product; RandomDiscrete
        samples index tuples without materializing the space (reference:
        ``HyperSpaceWalker.RandomDiscreteValueWalker`` draws one point per
        call — huge spaces must never be enumerated)."""
        keys = sorted(self.hyper_params)
        strategy = str(self.search_criteria.get("strategy", "Cartesian")).lower()
        if strategy == "cartesian":
            for vs in itertools.product(*(self.hyper_params[k] for k in keys)):
                yield dict(zip(keys, vs))
            return
        if strategy != "randomdiscrete":
            raise ValueError(f"unknown search strategy "
                             f"{self.search_criteria.get('strategy')!r}")
        sizes = [len(self.hyper_params[k]) for k in keys]
        total = int(np.prod(sizes)) if sizes else 0
        seed = int(self.search_criteria.get("seed", 0) or 0)
        rng = np.random.default_rng(seed if seed > 0 else None)
        seen: set[tuple] = set()
        misses = 0
        while len(seen) < total and misses < 1000:
            idx = tuple(int(rng.integers(s)) for s in sizes)
            if idx in seen:
                misses += 1
                continue
            misses = 0
            seen.add(idx)
            yield {k: self.hyper_params[k][i] for k, i in zip(keys, idx)}

    def train(self, x=None, y=None, training_frame: Frame | None = None,
              validation_frame: Frame | None = None, **kw) -> Grid:
        # the whole search is one subtree in the caller's trace; each
        # combo's build_one hangs its own span under it
        with TRACER.span(f"grid:{self.grid_id}", kind="orchestration",
                         attrs={"algo": self.builder_cls.algo}):
            return self._train(x, y, training_frame, validation_frame, **kw)

    def _train(self, x, y, training_frame: Frame | None,
               validation_frame: Frame | None, **kw) -> Grid:
        max_models = int(self.search_criteria.get("max_models", 0) or 0)
        max_secs = float(self.search_criteria.get("max_runtime_secs", 0.0) or 0.0)
        t0 = time.time()
        models: list[Model] = []
        failures: list[tuple[dict, str]] = []

        recovery = None
        if self.recovery_dir:
            # resumable grid (reference: Recovery<Grid> + GridSearch resume)
            from h2o3_tpu.persist.recovery import Recovery
            recovery = Recovery(self.recovery_dir)
            if recovery.resuming:
                models.extend(recovery.built_models())
            recovery.begin({"grid_id": self.grid_id,
                            "hyper_params": self.hyper_params,
                            "search_criteria": self.search_criteria})

        from h2o3_tpu.orchestration.parallel_build import windowed_parallel
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        from h2o3_tpu.persist.recovery import combo_key

        scheduler = self.scheduler or MeshScheduler(slices=self.parallelism)
        meta = dict(rows=training_frame.nrows if training_frame else None,
                    algo=self.builder_cls.algo)

        def fresh_combos():
            for combo in self._combos():
                if recovery is not None and recovery.is_done(combo):
                    continue
                yield combo

        def can_submit(n_submitted: int) -> bool:
            if max_models and len(models) + n_submitted >= max_models:
                return False
            return not (max_secs and time.time() - t0 > max_secs)

        def build_one(combo: dict) -> Model:
            params = {**self.fixed_params, **combo}
            # id derived from the combo, stable across recovery resumes (a
            # positional counter would collide with recovered models)
            tag = hashlib.md5(combo_key(combo).encode()).hexdigest()[:8]
            params["model_id"] = f"{self.grid_id}_model_{tag}"
            # child span per grid model: the parent run's trace shows every
            # combo as its own subtree (no-op outside an active trace)
            with TRACER.span(f"grid_model:{self.builder_cls.algo}",
                             kind="build",
                             attrs={"grid": self.grid_id,
                                    "model_id": params["model_id"]}):
                b = self.builder_cls(**params)
                m = b.train(x=x, y=y, training_frame=training_frame,
                            validation_frame=validation_frame, **kw)
            m.output["hyper_values"] = combo
            return m

        if self.parallelism <= 1:
            # sequential: a FAILED build does not consume model budget
            # (reference GridSearch keeps walking the space)
            exhausted = True
            for combo in fresh_combos():
                if max_models and len(models) >= max_models:
                    exhausted = False   # budget stop: recovery stays resumable
                    break
                if max_secs and time.time() - t0 > max_secs:
                    exhausted = False
                    break
                try:
                    # sequential builds lease too: with a forced slice layout
                    # (H2O3TPU_MESH_SLICES) a par=1 run binds the same-sized
                    # slice a par=N run would, so per-model results are
                    # bit-identical across parallelism settings
                    with scheduler.lease(**meta):
                        m = build_one(combo)
                    models.append(m)
                    if recovery is not None:
                        recovery.model_built(combo, m)
                except Exception as e:
                    failures.append((combo, f"{type(e).__name__}: {e}"))
        else:
            results, exhausted = windowed_parallel(
                fresh_combos(), self.parallelism, can_submit, build_one,
                scheduler=scheduler, job_meta=lambda combo: meta)
            for combo, m, exc in results:
                if exc is not None:
                    failures.append((combo, f"{type(exc).__name__}: {exc}"))
                    continue
                models.append(m)
                if recovery is not None:
                    recovery.model_built(combo, m)
        if recovery is not None and exhausted:
            recovery.done()
        self.grid = Grid(self.grid_id, models, failures,
                         metric=self.search_criteria.get("sort_metric"))
        return self.grid

    def get_grid(self, sort_by: str | None = None, decreasing: bool | None = None):
        return self.grid.sorted_models(sort_by, decreasing) if self.grid else []
