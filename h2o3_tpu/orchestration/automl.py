"""AutoML — automatic model search with a modeling plan.

Reference: ``ai/h2o/automl/AutoML.java:49`` and
``modeling/{GLM,DRF,GBM,DeepLearning,StackedEnsemble,XGBoost}StepsProvider.java``:
a job executes a sequence of ModelingSteps (defaults → grids → ensembles)
under time/model budgets (``WorkAllocations.java``), ranks everything on a
Leaderboard, and logs to an EventLog. The default plan trains: GLM defaults,
XGBoost/GBM fixed sets, DRF + extremely-randomized trees, DeepLearning,
random grids for the tree algos, then StackedEnsembles (BestOfFamily + All).

This driver mirrors that plan with the same step families and budget
semantics; every model is built with ``nfolds`` CV and kept OOF predictions
so the ensemble steps can stack them (the reference does exactly this).
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.orchestration.grid import GridSearch, default_metric, metric_higher_is_better
from h2o3_tpu.orchestration.leaderboard import Leaderboard
from h2o3_tpu.utils.tracing import TRACER


class EventLog:
    """Timestamped AutoML event record (reference: ai/h2o/automl/events/
    EventLogEntry.java — rows of timestamp/level/stage/message/name/value;
    the name/value pairs feed h2o-py's ``aml.training_info``)."""

    def __init__(self):
        self.events: list[tuple[float, str, str, str, str, str]] = []

    def log(self, stage: str, message: str, level: str = "Info",
            name: str = "", value: str = "") -> None:
        self.events.append((time.time(), level, stage, message,
                            str(name), str(value)))

    def info(self, name: str, value) -> None:
        """A training_info entry (reference: EventLogEntry name/value rows)."""
        self.log("TrainingInfo", "", name=name, value=value)

    def table_rows(self) -> list[list[str]]:
        return [[time.strftime("%Y.%m.%d %H:%M:%S", time.localtime(t)),
                 lvl, s, m, n, v]
                for t, lvl, s, m, n, v in self.events]

    def as_list(self) -> list[str]:
        return [f"[{time.strftime('%H:%M:%S', time.localtime(t))}] {s}: "
                f"{m or f'{n}={v}'}"
                for t, _lvl, s, m, n, v in self.events]


class AutoML:
    """h2o-py surface: ``H2OAutoML(max_models=…, max_runtime_secs=…)``."""

    def __init__(self, max_models: int = 0, max_runtime_secs: float = 0.0,
                 seed: int = -1, nfolds: int = 5, sort_metric: str | None = None,
                 exclude_algos: Sequence[str] = (), include_algos: Sequence[str] | None = None,
                 project_name: str | None = None,
                 preprocessing: Sequence[str] | None = None,
                 exploitation_ratio: float = 0.1,
                 parallelism: int = 2):
        if not max_models and not max_runtime_secs:
            max_runtime_secs = 3600.0   # reference default budget
        self.max_models = int(max_models)
        self.max_runtime_secs = float(max_runtime_secs)
        self.seed = int(seed)
        self.nfolds = int(nfolds)
        self.sort_metric = sort_metric
        self.exclude_algos = {a.upper() for a in exclude_algos}
        self.include_algos = ({a.upper() for a in include_algos}
                              if include_algos is not None else None)
        self.project_name = project_name or f"automl_{int(time.time())}"
        self.preprocessing = list(preprocessing or [])
        self.exploitation_ratio = float(exploitation_ratio)
        # overlapped base/grid builds (reference runs steps on the F/J pools;
        # see orchestration/parallel_build.py). 1 = strictly sequential.
        self.parallelism = max(1, int(parallelism))
        self.leaderboard: Leaderboard | None = None
        self._scheduler = None      # MeshScheduler, created per train() run
        self.event_log = EventLog()
        self._t0 = 0.0
        self._n_built = 0

    # -- budget --------------------------------------------------------------

    def _budget_left(self) -> bool:
        cap = getattr(self, "_cap", None)
        if cap is None:
            cap = self.max_models
        if cap and self._n_built >= cap:
            return False
        if self.max_runtime_secs and time.time() - self._t0 > self.max_runtime_secs:
            return False
        return True

    def _algo_enabled(self, algo: str) -> bool:
        algo = algo.upper()
        if self.include_algos is not None:
            return algo in self.include_algos
        return algo not in self.exclude_algos

    # -- plan ----------------------------------------------------------------

    def _steps(self):
        """(algo, builder_cls, params) sequence — the reference's default
        modeling plan order (ModelingPlans.java); the same families run for
        classification and regression (each builder adapts to the response)."""
        from h2o3_tpu.models.deeplearning import DeepLearning
        from h2o3_tpu.models.gbm import DRF, GBM
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.models.xgboost import XGBoost

        steps: list[tuple[str, type, dict]] = []
        steps.append(("GLM", GLM, dict(lambda_=1e-4, alpha=0.5)))
        # XGBoost fixed set (XGBoostStepsProvider defaults 1-3)
        for d, sr in ((6, 0.8), (9, 0.6), (3, 0.8)):
            steps.append(("XGBOOST", XGBoost,
                          dict(ntrees=50, max_depth=d, sample_rate=sr,
                               col_sample_rate_per_tree=0.8, learn_rate=0.3)))
        # GBM fixed set (GBMStepsProvider: 5 fixed configs)
        for d in (6, 7, 8, 10, 13):
            steps.append(("GBM", GBM,
                          dict(ntrees=50, max_depth=min(d, 13), learn_rate=0.1,
                               sample_rate=0.8, col_sample_rate=0.8)))
        steps.append(("DRF", DRF, dict(ntrees=50)))
        # XRT: extremely-randomized variant (DRF with deeper trees, full rows)
        steps.append(("DRF", DRF, dict(ntrees=50, sample_rate=1.0, max_depth=16)))
        steps.append(("DEEPLEARNING", DeepLearning,
                      dict(hidden=[64, 64], epochs=10, mini_batch_size=32)))
        return steps

    def _grids(self):
        from h2o3_tpu.models.gbm import GBM
        from h2o3_tpu.models.xgboost import XGBoost
        rng_seed = self.seed if self.seed >= 0 else 42
        return [
            ("GBM", GBM,
             dict(ntrees=50),
             {"max_depth": [3, 5, 7, 9], "learn_rate": [0.05, 0.1, 0.2],
              "sample_rate": [0.6, 0.8, 1.0], "col_sample_rate": [0.4, 0.7, 1.0]},
             rng_seed),
            ("XGBOOST", XGBoost,
             dict(ntrees=50),
             {"max_depth": [4, 6, 8], "learn_rate": [0.1, 0.3],
              "reg_lambda": [0.1, 1.0, 10.0], "sample_rate": [0.6, 0.8, 1.0]},
             rng_seed + 1),
        ]

    # -- driver --------------------------------------------------------------

    def train(self, x: Sequence[str] | None = None, y: str | None = None,
              training_frame: Frame | None = None,
              leaderboard_frame: Frame | None = None) -> Model | None:
        # one span for the whole run: every leaderboard build (base steps,
        # grids, exploitation, ensembles) hangs underneath it, so the
        # creating request's trace holds the full tree
        with TRACER.span(f"automl:{self.project_name}", kind="orchestration",
                         attrs={"max_models": self.max_models,
                                "parallelism": self.parallelism}):
            return self._train(x, y, training_frame, leaderboard_frame)

    def _train(self, x: Sequence[str] | None, y: str | None,
               training_frame: Frame | None,
               leaderboard_frame: Frame | None) -> Model | None:
        if y is None or training_frame is None:
            raise ValueError("y and training_frame are required")
        self._t0 = time.time()
        self.event_log.info("creation_epoch", int(self._t0))
        self.event_log.info("start_epoch", int(self._t0))
        yvec = training_frame.vec(y)
        classification = yvec.is_categorical
        self.leaderboard = Leaderboard(self.sort_metric, leaderboard_frame)
        log = self.event_log
        log.log("init", f"AutoML {self.project_name}: y={y!r} "
                        f"{'classification' if classification else 'regression'}, "
                        f"budget max_models={self.max_models} "
                        f"max_runtime_secs={self.max_runtime_secs}")

        common = dict(nfolds=self.nfolds, seed=self.seed,
                      keep_cross_validation_predictions=True)
        base_models: list[Model] = []
        # reserve the exploitation share of the model budget (reference:
        # WorkAllocations gives the exploitation steps their own allocation).
        # Tiny budgets (< 5 models) skip the reserve: annealing one of two
        # models would starve the base plan and the ensembles behind it
        reserved = (max(1, int(round(self.max_models * self.exploitation_ratio)))
                    if self.max_models >= 5 and self.exploitation_ratio > 0
                    and (self._algo_enabled("GBM") or self._algo_enabled("XGBOOST"))
                    else 0)
        self._cap = (self.max_models - reserved) if self.max_models else None

        # preprocessing phase (reference: ai/h2o/automl/preprocessing/
        # TargetEncoding.java — CV-aware TE on high-cardinality enums, fed
        # to the TREE steps; linear/DL steps keep the raw frame)
        tree_frame, tree_x, te_model = training_frame, x, None
        if "target_encoding" in self.preprocessing and y is not None:
            hi_card = [c for c in training_frame.names
                       if c != y and training_frame.vec(c).is_categorical
                       and training_frame.vec(c).cardinality() > 10]
            if hi_card and classification:
                try:
                    from h2o3_tpu.models.target_encoder import TargetEncoder
                    te = TargetEncoder(data_leakage_handling="KFold",
                                       blending=True, seed=self.seed).train(
                        x=hi_card, y=y, training_frame=training_frame)
                    te_model = te
                    tree_frame = te.transform(training_frame)
                    tree_x = [c for c in tree_frame.names if c != y
                              and c not in hi_card] if x is None else \
                        [c for c in x if c not in hi_card] + \
                        [f"{c}_te" for c in hi_card]
                    log.log("preprocess",
                            f"target-encoded {hi_card} for tree steps")
                except Exception as e:
                    log.log("error", f"target encoding failed: "
                                     f"{type(e).__name__}: {e}")

        tree_algos = {"GBM", "XGBOOST", "DRF"}

        from h2o3_tpu.orchestration.parallel_build import windowed_parallel
        from h2o3_tpu.orchestration.scheduler import MeshScheduler

        # one slice layout per run: overlapped builds lease DISJOINT device
        # slices instead of racing collectives on the global mesh
        # (orchestration/scheduler.py; H2O3TPU_MESH_SLICES overrides)
        self._scheduler = MeshScheduler(slices=self.parallelism)

        def enabled_steps():
            for algo, cls, params in self._steps():
                if self._algo_enabled(algo):
                    yield algo, cls, params

        def can_submit(n_submitted: int) -> bool:
            cap = self._cap if self._cap else 0
            if cap and self._n_built + n_submitted >= cap:
                return False
            return not (self.max_runtime_secs
                        and time.time() - self._t0 > self.max_runtime_secs)

        def build_step(step):
            algo, cls, params = step
            t = time.time()
            fr_s, x_s = ((tree_frame, tree_x) if algo in tree_algos
                         else (training_frame, x))
            with TRACER.span(f"automl_step:{algo}", kind="build",
                             attrs={"algo": algo}):
                m = cls(**{**params, **common}).train(x=x_s, y=y,
                                                      training_frame=fr_s)
            return m, algo, time.time() - t

        results, _ = windowed_parallel(
            enabled_steps(), self.parallelism, can_submit, build_step,
            scheduler=self._scheduler,
            job_meta=lambda step: dict(rows=training_frame.nrows,
                                       algo=step[0]))
        # leaderboard membership follows PLAN order regardless of completion
        # interleaving — identical to the sequential leaderboard
        for step, res, exc in results:
            if exc is not None:
                log.log("error", f"{step[0]} failed: "
                                 f"{type(exc).__name__}: {exc}")
                continue
            m, algo, dt = res
            if te_model is not None and algo in tree_algos:
                m.preprocessors.append(te_model)
            self._n_built += 1
            base_models.append(m)
            self.leaderboard.add(m)
            log.log("model", f"{m.key} ({algo}) in {dt:.1f}s")

        # random grid phase under the remaining budget
        for algo, cls, fixed, hyper, gseed in self._grids():
            if not self._budget_left():
                break
            if not self._algo_enabled(algo):
                continue
            remaining_models = (self.max_models - self._n_built
                                if self.max_models else 5)
            remaining_secs = (self.max_runtime_secs - (time.time() - self._t0)
                              if self.max_runtime_secs else 0.0)
            gs = GridSearch(cls, hyper,
                            search_criteria=dict(strategy="RandomDiscrete",
                                                 max_models=max(remaining_models, 0),
                                                 max_runtime_secs=max(remaining_secs, 0.0),
                                                 seed=gseed),
                            parallelism=self.parallelism,
                            scheduler=self._scheduler,
                            **{**fixed, **common})
            # grids are tree families: same TE frame as the base tree steps
            grid = gs.train(x=tree_x, y=y, training_frame=tree_frame)
            for m in grid.models:
                if te_model is not None:
                    m.preprocessors.append(te_model)
                self._n_built += 1
                base_models.append(m)
                self.leaderboard.add(m)
                log.log("model", f"{m.key} ({algo} grid)")

        # exploitation phase (reference: ModelingPlans exploitation steps —
        # learning-rate annealing on the best GBM/XGBoost: retrain the
        # incumbent with halved learn_rate and doubled trees under the
        # remaining ~exploitation_ratio of the budget)
        self._cap = self.max_models or None  # release the reserved share
        if self.exploitation_ratio > 0 and self._budget_left() \
                and self.leaderboard is not None:
            for fam in ("gbm", "xgboost"):
                if not self._budget_left() or not self._algo_enabled(fam):
                    continue
                cands = [m for m in self.leaderboard.models if m.algo == fam]
                if not cands:
                    continue
                best = cands[0]     # leaderboard models are rank-sorted
                p = dict(best.params)
                anneal = {k: p[k] for k in
                          ("max_depth", "sample_rate", "col_sample_rate",
                           "col_sample_rate_per_tree", "nbins") if k in p}
                anneal["learn_rate"] = float(p.get("learn_rate", 0.1)) / 2
                anneal["ntrees"] = int(p.get("ntrees", 50)) * 2
                try:
                    t = time.time()
                    from h2o3_tpu.models.gbm import GBM
                    from h2o3_tpu.models.xgboost import XGBoost
                    bcls = XGBoost if fam == "xgboost" else GBM
                    fr_s, x_s = ((tree_frame, tree_x)
                                 if fam.upper() in tree_algos else
                                 (training_frame, x))
                    m = bcls(**{**anneal, **common}).train(
                        x=x_s, y=y, training_frame=fr_s)
                    if te_model is not None:
                        m.preprocessors.append(te_model)
                    self._n_built += 1
                    base_models.append(m)
                    self.leaderboard.add(m)
                    log.log("exploit", f"lr-annealed {fam}: {m.key} in "
                                       f"{time.time() - t:.1f}s")
                except Exception as e:
                    log.log("error", f"exploitation {fam} failed: "
                                     f"{type(e).__name__}: {e}")

        # ensemble phase (reference: StackedEnsembleStepsProvider — BestOfFamily + All)
        if self._algo_enabled("STACKEDENSEMBLE") and len(base_models) >= 2:
            from h2o3_tpu.orchestration.stacked_ensemble import StackedEnsemble
            stackable = [m for m in base_models if m.cv_holdout_predictions is not None]
            metric = self.sort_metric or (default_metric(stackable[0]) if stackable else "rmse")
            dec = metric_higher_is_better(metric)

            def mval(m):
                mm = m.cross_validation_metrics or m.training_metrics
                v = getattr(mm, metric, np.nan)
                return float(v() if callable(v) else v)

            best_of_family: dict[str, Model] = {}
            for m in stackable:
                v = mval(m)
                if np.isnan(v):
                    continue   # a model without the sort metric can't represent its family
                cur = best_of_family.get(m.algo)
                if cur is None or np.isnan(mval(cur)) or \
                        ((v > mval(cur)) if dec else (v < mval(cur))):
                    best_of_family[m.algo] = m
            for name, group in (("BestOfFamily", list(best_of_family.values())),
                                ("AllModels", stackable)):
                if len(group) < 2:
                    continue
                try:
                    se = StackedEnsemble(base_models=group,
                                         model_id=f"StackedEnsemble_{name}_{self.project_name}",
                                         ).train(y=y, training_frame=training_frame)
                    # rank the ensemble by the metalearner's metrics on the
                    # OOF level-one frame — out-of-fold w.r.t. the base models,
                    # hence comparable to their CV metrics (training_metrics
                    # would re-score base models in-sample and inflate the AUC)
                    se.cross_validation_metrics = \
                        se.output["metalearner"].training_metrics
                    self.leaderboard.add(se)
                    log.log("model", f"{se.key} over {len(group)} base models")
                except Exception as e:
                    log.log("error", f"StackedEnsemble {name} failed: "
                                     f"{type(e).__name__}: {e}")

        log.log("done", f"{len(self.leaderboard)} models in "
                        f"{time.time() - self._t0:.1f}s")
        log.info("stop_epoch", int(time.time()))
        log.info("duration_secs", round(time.time() - self._t0, 1))
        return self.leader

    def modeling_steps(self) -> list[tuple[str, list[str]]]:
        """Effective plan by provider family (reference:
        ``StepDefinition``/``ModelingPlans.java``; surfaced as
        ``aml.modeling_steps`` in h2o-py)."""
        fams: dict[str, list[str]] = {}
        for algo, _cls, _p in self._steps():
            if self._algo_enabled(algo):
                lst = fams.setdefault(algo, [])
                lst.append(f"def_{len(lst) + 1}")
        for algo, _cls, _f, _h, _s in self._grids():
            if self._algo_enabled(algo):
                fams.setdefault(algo, []).append("grid_1")
        if self._algo_enabled("STACKEDENSEMBLE"):
            fams["StackedEnsemble"] = ["best_of_family", "all"]
        return [(k, v) for k, v in fams.items()]

    @property
    def leader(self) -> Model | None:
        return self.leaderboard.leader if self.leaderboard else None
