"""Leaderboard — ranked model container.

Reference: ``hex/leaderboard/Leaderboard.java`` (+8 extension-column files):
ranks models by a sort metric chosen from the problem type, computes all
metrics on a shared leaderboard frame (or CV/valid metrics), and exposes an
extensible column set (training time, per-row scoring time, algo).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.orchestration.grid import default_metric, metric_higher_is_better


class Leaderboard:
    def __init__(self, sort_metric: str | None = None,
                 leaderboard_frame: Frame | None = None):
        self.sort_metric = sort_metric
        self.leaderboard_frame = leaderboard_frame
        self._rows: list[dict] = []

    def add(self, model: Model) -> None:
        if self.leaderboard_frame is not None and model.response_column in self.leaderboard_frame:
            mm = model.model_performance(self.leaderboard_frame)
        else:
            mm = (model.cross_validation_metrics or model.validation_metrics
                  or model.training_metrics)
        if mm is None:
            return
        row = {"model_id": model.key, "algo": model.algo,
               "training_time_ms": model.run_time_ms, "_model": model}
        for f in ("auc", "pr_auc", "logloss", "mean_per_class_error", "rmse",
                  "mse", "mae", "r2", "accuracy", "rmsle",
                  "mean_residual_deviance"):
            if hasattr(mm, f):
                v = getattr(mm, f)
                row[f] = float(v() if callable(v) else v)
        self._rows.append(row)

    def _sorted(self) -> list[dict]:
        if not self._rows:
            return []
        metric = self.sort_metric or default_metric(self._rows[0]["_model"])
        dec = metric_higher_is_better(metric)
        return sorted(self._rows,
                      key=lambda r: (np.isnan(r.get(metric, np.nan)),
                                     -r.get(metric, np.nan) if dec
                                     else r.get(metric, np.nan)))

    @property
    def models(self) -> list[Model]:
        return [r["_model"] for r in self._sorted()]

    @property
    def leader(self) -> Model | None:
        ms = self.models
        return ms[0] if ms else None

    def as_frame(self) -> Frame:
        """Leaderboard as a Frame (reference: Leaderboard.toTwoDimTable)."""
        rows = self._sorted()
        if not rows:
            return Frame([], [])
        cols = [k for k in rows[0] if k != "_model"]
        data = {c: np.array([r.get(c, np.nan) for r in rows],
                            dtype=object if c in ("model_id", "algo") else float)
                for c in cols}
        return Frame.from_arrays(data)

    def table(self, extensions: Sequence[str] | None = None):
        """Wire-format table spec (reference: ``Leaderboard.toTwoDimTable``,
        ``hex/leaderboard/Leaderboard.java:776``): column specs, row-major
        cells, sort metric/direction/values, ranked model ids. The metric
        column set follows ``defaultMetricsForModel``
        (``Leaderboard.java:681``); ``extensions`` ("ALL" or named) appends
        the extension columns (``hex/leaderboard/TrainingTime.java`` etc.)."""
        rows = self._sorted()
        if not rows:
            return ([("model_id", "string", "%s")], [], self.sort_metric or "auc",
                    True, [], [])
        model = rows[0]["_model"]
        if model.nclasses == 2:
            metrics = ["auc", "logloss", "aucpr", "mean_per_class_error",
                       "rmse", "mse"]
        elif model.nclasses > 2:
            metrics = ["mean_per_class_error", "logloss", "rmse", "mse"]
        else:
            metrics = ["rmse", "mse", "mae", "rmsle", "mean_residual_deviance"]
        sort_metric = self.sort_metric or default_metric(model)
        # the table shows wire names (aucpr), rows store attr names (pr_auc)
        wire_sort = {"pr_auc": "aucpr"}.get(sort_metric, sort_metric)
        if wire_sort in metrics and metrics[0] != wire_sort:
            metrics.remove(wire_sort)
            metrics.insert(0, wire_sort)
        elif wire_sort not in metrics:
            metrics.insert(0, wire_sort)
        sort_metric = wire_sort
        ext = [e.lower() for e in (extensions or [])]
        known_ext = ("training_time_ms", "predict_time_per_row_ms", "algo")
        ext_cols = (list(known_ext) if "all" in ext
                    else [e for e in ext if e in known_ext])

        def cell(r, m):
            # wire names that differ from our metric attr names
            attr = {"aucpr": "pr_auc",
                    "mean_residual_deviance": "mean_residual_deviance"}.get(m, m)
            v = r.get(attr, np.nan)
            return float(v) if v is not None else np.nan

        cols = [("model_id", "string", "%s")]
        cols += [(m, "double", "%.6f") for m in metrics]
        cols += [(("algo", "string", "%s") if e == "algo" else
                  (e, "double", "%.1f")) for e in ext_cols]
        out_rows = []
        for r in rows:
            row = [r["model_id"]] + [cell(r, m) for m in metrics]
            for e in ext_cols:
                if e == "algo":
                    row.append(r.get("algo", ""))
                else:
                    v = r.get(e)
                    row.append(np.nan if v is None else float(v))
            out_rows.append(row)
        sort_vals = [cell(r, sort_metric) for r in rows]
        return (cols, out_rows, sort_metric,
                metric_higher_is_better(sort_metric), sort_vals,
                [r["model_id"] for r in rows])

    def __len__(self) -> int:
        return len(self._rows)

    def __repr__(self) -> str:
        rows = self._sorted()
        metric = self.sort_metric or (default_metric(rows[0]["_model"]) if rows else "")
        lines = [f"Leaderboard({len(rows)} models, sort={metric})"]
        for r in rows[:10]:
            lines.append(f"  {r['model_id']}: {r.get(metric, float('nan')):.5f}")
        return "\n".join(lines)
