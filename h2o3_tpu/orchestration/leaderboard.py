"""Leaderboard — ranked model container.

Reference: ``hex/leaderboard/Leaderboard.java`` (+8 extension-column files):
ranks models by a sort metric chosen from the problem type, computes all
metrics on a shared leaderboard frame (or CV/valid metrics), and exposes an
extensible column set (training time, per-row scoring time, algo).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.orchestration.grid import default_metric, metric_higher_is_better


class Leaderboard:
    def __init__(self, sort_metric: str | None = None,
                 leaderboard_frame: Frame | None = None):
        self.sort_metric = sort_metric
        self.leaderboard_frame = leaderboard_frame
        self._rows: list[dict] = []

    def add(self, model: Model) -> None:
        if self.leaderboard_frame is not None and model.response_column in self.leaderboard_frame:
            mm = model.model_performance(self.leaderboard_frame)
        else:
            mm = (model.cross_validation_metrics or model.validation_metrics
                  or model.training_metrics)
        if mm is None:
            return
        row = {"model_id": model.key, "algo": model.algo,
               "training_time_ms": model.run_time_ms, "_model": model}
        for f in ("auc", "pr_auc", "logloss", "mean_per_class_error", "rmse",
                  "mse", "mae", "r2", "accuracy"):
            if hasattr(mm, f):
                v = getattr(mm, f)
                row[f] = float(v() if callable(v) else v)
        self._rows.append(row)

    def _sorted(self) -> list[dict]:
        if not self._rows:
            return []
        metric = self.sort_metric or default_metric(self._rows[0]["_model"])
        dec = metric_higher_is_better(metric)
        return sorted(self._rows,
                      key=lambda r: (np.isnan(r.get(metric, np.nan)),
                                     -r.get(metric, np.nan) if dec
                                     else r.get(metric, np.nan)))

    @property
    def models(self) -> list[Model]:
        return [r["_model"] for r in self._sorted()]

    @property
    def leader(self) -> Model | None:
        ms = self.models
        return ms[0] if ms else None

    def as_frame(self) -> Frame:
        """Leaderboard as a Frame (reference: Leaderboard.toTwoDimTable)."""
        rows = self._sorted()
        if not rows:
            return Frame([], [])
        cols = [k for k in rows[0] if k != "_model"]
        data = {c: np.array([r.get(c, np.nan) for r in rows],
                            dtype=object if c in ("model_id", "algo") else float)
                for c in cols}
        return Frame.from_arrays(data)

    def __len__(self) -> int:
        return len(self._rows)

    def __repr__(self) -> str:
        rows = self._sorted()
        metric = self.sort_metric or (default_metric(rows[0]["_model"]) if rows else "")
        lines = [f"Leaderboard({len(rows)} models, sort={metric})"]
        for r in rows[:10]:
            lines.append(f"  {r['model_id']}: {r.get(metric, float('nan')):.5f}")
        return "\n".join(lines)
