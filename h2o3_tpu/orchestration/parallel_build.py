"""Overlapped model builds (SURVEY §7 hard part (e); VERDICT r3 weak #6).

The reference overlaps independent model builds on its fork/join pools
(``hex/grid/GridSearch.java`` parallel builds,
``water/ParallelizationTask.java``).  The TPU-native equivalent is
host-thread parallelism over the single device stream: while one build's
jitted step executes on the device, another build's trace/compile (host CPU,
GIL released inside XLA) and host-side orchestration proceed — on small
AutoML-scale frames, wall-clock is dominated by exactly that host work, so
two in-flight builds hide most of it.  JAX dispatch, tracing, and
compilation are thread-safe; DKV and the leaderboard are lock-guarded.
"""

from __future__ import annotations

from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Iterable

from h2o3_tpu.utils import tracing as _tracing


def windowed_parallel(
    items: Iterable[Any],
    par: int,
    can_submit: Callable[[int], bool],
    run_one: Callable[[Any], Any],
    scheduler=None,
    job_meta: Callable[[Any], dict] | None = None,
) -> tuple[list[tuple[Any, Any, Exception | None]], bool]:
    """Run ``run_one(item)`` over a LAZY item stream with at most ``par`` in
    flight.  ``can_submit(n_submitted)`` gates each submission (budget /
    deadline); the stream is never advanced past the gate, so huge spaces
    stay unenumerated (RandomDiscrete walker contract).

    ``can_submit`` receives the count of SUCCESSFUL-or-in-flight builds, so
    a failed build releases its budget and the walker keeps going — the
    reference GridSearch semantics (failed params don't consume max_models).

    When a ``scheduler`` (:class:`~h2o3_tpu.orchestration.scheduler.
    MeshScheduler`) is given, every submission runs inside a slice lease:
    the build binds a disjoint device slice (small work) or the whole mesh
    (big work) per the scheduler's policy, so ``par`` overlapped builds
    never race collectives on a shared device set. ``job_meta(item)``
    supplies the sizing hints (``rows``/``algo``) the policy needs.

    Returns ``(results, stream_exhausted)`` where results are
    ``(item, result, exc)`` in SUBMISSION order — callers get deterministic
    model ordering regardless of completion interleaving — and
    ``stream_exhausted`` is False when a budget/deadline stop (not stream
    end) ended the run.
    """
    if scheduler is not None:
        inner = run_one

        def run_one(item):   # noqa: F811 — leased wrapper shadows on purpose
            meta = job_meta(item) if job_meta is not None else {}
            with scheduler.lease(**meta):
                return inner(item)

    it = iter(items)
    if par <= 1:
        out: list = []
        n_ok = 0
        for item in it:
            if not can_submit(n_ok):
                return out, False
            try:
                out.append((item, run_one(item), None))
                n_ok += 1
            except Exception as e:          # noqa: BLE001 — per-item failures recorded
                out.append((item, None, e))
        return out, True

    results: dict[int, tuple] = {}
    futs: dict = {}
    n_sub = 0
    n_failed = 0
    stream_ended = False
    # pool threads don't inherit the submitter's contextvars: carry the
    # active span context across so overlapped builds stay linked to the
    # parent run's trace (the submitter blocks here, so no retention needed)
    span_ctx = _tracing.TRACER.current()
    with ThreadPoolExecutor(max_workers=par,
                            thread_name_prefix="model-build") as ex:
        while True:
            # gate sees successes + in-flight: completed failures released
            # their budget, so a closed gate can reopen after a failure
            while (not stream_ended and len(futs) < par
                   and can_submit(n_sub - n_failed)):
                try:
                    item = next(it)
                except StopIteration:
                    stream_ended = True
                    break
                futs[ex.submit(_tracing.run_in_context, span_ctx,
                               run_one, item)] = (n_sub, item)
                n_sub += 1
            if not futs:
                break
            done, _ = wait(list(futs), return_when=FIRST_COMPLETED)
            for f in done:
                i, item = futs.pop(f)
                try:
                    results[i] = (item, f.result(), None)
                except Exception as e:      # noqa: BLE001
                    results[i] = (item, None, e)
                    n_failed += 1
    return [results[i] for i in sorted(results)], stream_ended
