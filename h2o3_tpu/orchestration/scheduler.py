"""MeshScheduler — device-slice allocation for concurrent model builds.

ROADMAP item 5 ("AutoML at fleet scale"): AutoML/grid "parallelism" used to
be host threads interleaving builds on ONE global mesh, so two overlapped
builds raced full-device collectives against each other — a documented
correctness hazard (overlapping programs can wedge XLA's collective
rendezvous; the PR 8 chaos AutoML test and the parallel-build tests pinned
``parallelism=1`` because of it). The fix shape is MXNET-MPI's (PAPERS.md):
partition workers into independent communicator groups and run jobs
group-local. Here the group is a **mesh slice** (:func:`~h2o3_tpu.parallel.
mesh.slice_meshes`) and the policy is TensorFlow-placement-shaped: small
builds pack one-per-slice and run concurrently for real; big builds wait
for, and take, the whole mesh.

A lease binds its slice as the context mesh (``bind_mesh``), so everything
the build resolves — ``row_sharding``, ``map_reduce``, frame reshards via
``Frame.on_mesh`` — stays inside the slice's device set and two concurrent
builds never share a collective.

Utilization (busy seconds, builds, queue wait) is exported as the
``h2o3_slice_*`` metrics and served inside ``GET /3/Cloud`` as
``mesh_slices`` (docs/ORCHESTRATION.md).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time

from h2o3_tpu.parallel.mesh import (bind_mesh, get_mesh, mesh_device_ids,
                                    slice_meshes)
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.tracing import TRACER

#: slice label the current thread's build is leased onto — the compute
#: observatory (utils/costs.py) reads it at observation time to fold
#: achieved FLOPs into the per-slice ``mesh_slices`` view
_ACTIVE_SLICE: contextvars.ContextVar["str | None"] = \
    contextvars.ContextVar("h2o3_active_slice", default=None)


def active_slice_label() -> str | None:
    return _ACTIVE_SLICE.get()

#: builds at or above this many rows take the whole mesh (override with
#: ``H2O3TPU_SLICE_ROWS_MAX``) — below it a build packs onto one slice
DEFAULT_SMALL_ROWS = 1_000_000

#: algo families that are slice-sized regardless of rows (the ISSUE's
#: "GLM/DRF-class work": one Gram solve / one fused forest program — their
#: collectives are tiny, so a slice loses nothing)
SMALL_ALGOS = {"glm", "drf"}


def small_rows_threshold() -> int:
    try:
        return int(os.environ.get("H2O3TPU_SLICE_ROWS_MAX", "")
                   or DEFAULT_SMALL_ROWS)
    except ValueError:
        return DEFAULT_SMALL_ROWS


def slices_from_env() -> int | None:
    """Explicit slice count from ``H2O3TPU_MESH_SLICES`` (None = unset)."""
    env = os.environ.get("H2O3TPU_MESH_SLICES", "").strip()
    if not env:
        return None
    try:
        return max(int(env), 1)
    except ValueError:
        return None


class _SliceStats:
    """Process-wide utilization rollup behind ``/3/Cloud``'s ``mesh_slices``
    view (schedulers are per-run; the view must outlive them)."""

    def __init__(self):
        self._lock = lockwitness.lock(
            "orchestration.scheduler._SliceStats._lock")
        self._layout: list[dict] = []
        self._per: dict[str, dict] = {}
        self._full_devices: list | None = None

    def configure(self, meshes) -> int:
        """Merge ``meshes``'s rows into the layout (keyed by slice label) and
        return the slice count. MERGE, not replace: schedulers configure on
        construction, and a later/concurrent run (say a par=1 grid while a
        par=2 AutoML holds slice leases) must not erase the other's slices
        from ``/3/Cloud``. A label re-carved with a different device set
        takes the new row — same-label collisions across *different* layouts
        are the documented pin-``H2O3TPU_MESH_SLICES`` limitation.

        The whole-mesh ``"full"`` row is NOT a carving — it overlaps every
        slice by definition — so it never enters the layout or the count: a
        par=1 run next to a par=2 run reports 2 slices plus a separate
        ``full`` utilization row, not 3 pseudo-slices."""
        with self._lock:
            for i, m in enumerate(meshes):
                if len(meshes) == 1:
                    self._full_devices = list(mesh_device_ids(m))
                    continue
                row = {"slice": str(i), "devices": list(mesh_device_ids(m))}
                if row not in self._layout:
                    self._layout = [r for r in self._layout
                                    if r["slice"] != row["slice"]]
                    self._layout.append(row)
            if len(meshes) > 1:
                # whole-mesh ("full") leases on this layout cover the union
                # of its slices — keep the utilization row's device set real
                self._full_devices = sorted(
                    {d for r in self._layout for d in r["devices"]})
            return self._count_locked()

    def _count_locked(self) -> int:
        return len(self._layout) or (1 if self._full_devices else 0)

    def record(self, label: str, busy_s: float, wait_s: float) -> None:
        with self._lock:
            st = self._per.setdefault(label, {"builds": 0,
                                              "busy_seconds": 0.0,
                                              "queue_wait_seconds": 0.0})
            st["builds"] += 1
            st["busy_seconds"] = round(st["busy_seconds"] + busy_s, 6)
            st["queue_wait_seconds"] = round(
                st["queue_wait_seconds"] + wait_s, 6)

    def add_flops(self, label: str, flops: float) -> None:
        """Fold a sampled dispatch's cost_analysis FLOPs into the slice's
        utilization row (utils/costs.py calls this under an active lease) —
        ``achieved_flops`` is the per-slice share of the observatory's
        compute accounting, so ``/3/Cloud``'s ``mesh_slices`` view shows
        WHERE the arithmetic ran, not just how long slices were busy."""
        with self._lock:
            st = self._per.setdefault(label, {"builds": 0,
                                              "busy_seconds": 0.0,
                                              "queue_wait_seconds": 0.0})
            st["achieved_flops"] = st.get("achieved_flops", 0.0) \
                + float(flops)

    def snapshot(self) -> dict:
        with self._lock:
            slices = []
            for row in self._layout:
                st = self._per.get(row["slice"],
                                   {"builds": 0, "busy_seconds": 0.0,
                                    "queue_wait_seconds": 0.0})
                slices.append({**row, **st})
            full = self._per.get("full")
            if full is not None or (self._full_devices and not slices):
                slices.append({"slice": "full",
                               "devices": list(self._full_devices or []),
                               **(full or {"builds": 0, "busy_seconds": 0.0,
                                           "queue_wait_seconds": 0.0})})
            return {"count": self._count_locked(), "slices": slices}

    def reset(self) -> None:
        with self._lock:
            self._layout = []
            self._per = {}
            self._full_devices = None


#: the process-wide utilization view (``GET /3/Cloud`` → ``mesh_slices``)
SLICE_STATS = _SliceStats()


class _LeaseState:
    """Free-list + condvar for one slice layout, shared PROCESS-WIDE.

    Schedulers are per-run (AutoML and its grids share one), but two
    *independent* concurrent runs each construct their own — with
    per-instance state both would grant "slice 0" at once and the two
    builds' collectives would rendezvous on the same devices, the exact
    wedge the scheduler exists to remove. Keying the lease state by the
    slice layout (the device-id tuples) makes every scheduler carving the
    same layout contend on ONE free list, so a slice is held by at most
    one build in the process regardless of which run leased it.
    Different layouts still overlap (documented limitation —
    docs/ORCHESTRATION.md): pin ``H2O3TPU_MESH_SLICES`` so concurrent
    runs carve identically.
    """

    _registry: dict[tuple, "_LeaseState"] = {}
    _registry_lock = lockwitness.lock(
        "orchestration.scheduler._LeaseState._registry_lock")

    def __init__(self, n: int):
        self.cv = lockwitness.condition(
            "orchestration.scheduler._LeaseState.cv")
        self.free = list(range(n))
        self.big_waiting = 0
        self.n = n

    @classmethod
    def for_layout(cls, layout: tuple) -> "_LeaseState":
        with cls._registry_lock:
            st = cls._registry.get(layout)
            if st is None:
                st = cls._registry[layout] = cls(len(layout))
            return st


class SliceLease:
    """What a build holds while it runs: the bound mesh + attribution."""

    __slots__ = ("mesh", "index", "label", "devices", "queue_wait_s")

    def __init__(self, mesh, index: int, label: str, devices, wait_s: float):
        self.mesh = mesh
        self.index = index          # -1 = whole mesh
        self.label = label
        self.devices = devices
        self.queue_wait_s = wait_s


class MeshScheduler:
    """Allocates disjoint mesh slices to concurrent builds.

    ``slices`` is a REQUEST: the effective count is the largest divisor of
    the global device count that is <= the request (``slice_meshes``), and
    ``H2O3TPU_MESH_SLICES`` overrides it outright. One slice (or one
    device) degrades to exactly today's behavior: every build binds the
    global mesh.
    """

    def __init__(self, slices: int | None = None):
        n = slices_from_env()
        if n is None:
            n = max(int(slices or 1), 1)
        # carve the CALLER'S active mesh (the user's mesh_context/bind_mesh
        # binding when one is live, else the global mesh): a grid/AutoML run
        # confined to a submesh must stay confined — leases sub-divide it,
        # big builds take it, artifacts re-home onto it. Captured here, on
        # the caller's thread, because pool workers don't inherit the
        # caller's contextvars.
        self.base = get_mesh()
        self.meshes = slice_meshes(n, base=self.base)
        self.n = len(self.meshes)
        # lease state is shared process-wide per LAYOUT: two concurrent
        # runs carving the same slices contend on one free list, so a
        # slice is never granted to two builds at once (see _LeaseState)
        self._state = _LeaseState.for_layout(
            tuple(mesh_device_ids(m) for m in self.meshes))
        # gauge follows the merged process-wide layout, not just this run
        _tm.SLICE_COUNT.set(SLICE_STATS.configure(self.meshes))

    # -- policy --------------------------------------------------------------

    def is_small(self, rows: int | None = None,
                 algo: str | None = None) -> bool:
        """Small = packs onto one slice; big = takes the whole mesh."""
        if self.n <= 1:
            return False
        if algo and str(algo).lower() in SMALL_ALGOS:
            return True
        return rows is not None and int(rows) < small_rows_threshold()

    def free_count(self) -> int:
        """Slices currently unleased on this scheduler's layout. The
        degenerate (<=1 slice) layout never leases, so it is always
        "fully free". Introspection only — serving-replica tests pin that
        lifetime leases (serving/replicas.py, parallel/elastic.py) release
        cleanly on stop/shutdown instead of leaking slices."""
        if self.n <= 1:
            return self.n
        with self._state.cv:
            return len(self._state.free)

    # -- leasing -------------------------------------------------------------

    @contextlib.contextmanager
    def lease(self, rows: int | None = None, algo: str | None = None,
              small: bool | None = None):
        """Acquire a slice (small builds) or the whole mesh (big builds),
        bind it as the context mesh, and release on exit. Blocks until
        capacity frees up; a waiting big build gates new small leases so it
        cannot starve. ``small=True`` forces the one-slice policy outright —
        elastic local-SGD workers (parallel/elastic.py) lease one slice each
        for the LIFETIME of the training group, whatever the row count, so
        membership maps 1:1 onto disjoint device slices."""
        if small is None:
            small = self.is_small(rows=rows, algo=algo)
        elif small:
            small = self.n > 1     # 1 slice has no sub-slice to pack onto
        t0 = time.monotonic()
        if self.n <= 1:
            # degenerate layout (1 slice / 1 device) = today's behavior:
            # builds overlap freely on the one mesh (host-thread overlap
            # hides compile/dispatch latency; there is no second rendezvous
            # to race), so the lease must not serialize them
            t1 = time.monotonic()
            mesh = self.meshes[0]
            try:
                # the one mesh IS the global mesh — nothing to re-home
                with bind_mesh(mesh, rehome_models=False):
                    yield SliceLease(mesh, -1, "full",
                                     mesh_device_ids(mesh), 0.0)
            finally:
                busy = time.monotonic() - t1
                _tm.SLICE_BUSY.labels(slice="full").inc(busy)
                _tm.SLICE_BUILDS.labels(slice="full").inc()
                SLICE_STATS.record("full", busy, 0.0)
            return
        st = self._state
        # acquisition happens INSIDE the try: ``idx`` flips from None the
        # instant a slice (or the whole mesh) leaves the free list, so an
        # exception (or KeyboardInterrupt) landing anywhere after that —
        # even between acquisition and yield — still releases it in the
        # finally (a leaked slice would wedge every later lease
        # process-wide)
        idx: int | None = None
        t1 = t0
        try:
            # waits are BOUNDED (timeout + predicate recheck): a notify lost
            # to a dying/stalled holder re-checks within a second instead of
            # parking this thread forever — the deadlock class a dead
            # elastic worker turns fatal (graftlint WTX001)
            if small:
                with st.cv:
                    while not st.free or st.big_waiting:
                        st.cv.wait(timeout=1.0)
                    idx = st.free.pop(0)
            else:
                with st.cv:
                    st.big_waiting += 1
                    try:
                        while len(st.free) < self.n:
                            st.cv.wait(timeout=1.0)
                        st.free.clear()
                        idx = -1
                    finally:
                        st.big_waiting -= 1
                        st.cv.notify_all()
            t1 = time.monotonic()
            if idx >= 0:
                mesh, label = self.meshes[idx], str(idx)
            else:
                mesh, label = self.base, "full"
            wait_s = t1 - t0
            devices = mesh_device_ids(mesh)
            _tm.SLICE_QUEUE_WAIT.observe(wait_s)
            # whole-mesh leases need no re-homing (artifacts are already
            # on the base device set); slice leases re-home onto the base
            slice_token = _ACTIVE_SLICE.set(label)
            try:
                with bind_mesh(mesh, rehome_models=idx >= 0,
                               rehome_to=self.base):
                    with TRACER.span(f"mesh_slice:{label}",
                                     kind="orchestration",
                                     attrs={"slice": label,
                                            "devices":
                                                ",".join(map(str, devices)),
                                            "n_devices": len(devices),
                                            "queue_wait_ms":
                                                round(wait_s * 1e3, 3)}):
                        yield SliceLease(mesh, idx, label, devices, wait_s)
            finally:
                _ACTIVE_SLICE.reset(slice_token)
        finally:
            if idx is not None:
                busy = time.monotonic() - t1
                label = str(idx) if idx >= 0 else "full"
                _tm.SLICE_BUSY.labels(slice=label).inc(busy)
                _tm.SLICE_BUILDS.labels(slice=label).inc()
                SLICE_STATS.record(label, busy, t1 - t0)
                with st.cv:
                    if idx >= 0:
                        st.free.append(idx)
                    else:
                        st.free.extend(range(self.n))
                    st.cv.notify_all()
