"""Segment models — train one model per partition of a frame.

Reference: ``hex/segments/SegmentModelsBuilder.java`` (+ ``SegmentModels.java``
results container; h2o-py ``estimator.train_segments``): enumerate the unique
combinations of the segment columns, train the same algorithm/params on each
segment's rows, collect per-segment model keys + status + errors.

TPU-native: segments are trained by weight-masking the SHARED device-resident
frame (zero weight = excluded row) — every segment's program has identical
static shapes, so XLA compiles the algorithm once and segments differ only in
an input array. The reference instead carves physical sub-frames per segment
(``SegmentModelsBuilder.makeSegmentFrame``).
"""

from __future__ import annotations

import traceback

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.utils.registry import DKV


class SegmentModels:
    """Per-segment training results (reference: hex/segments/SegmentModels.java)."""

    def __init__(self, key: str, segment_cols: list[str], rows: list[dict]):
        self.key = key
        self.segment_cols = segment_cols
        self.rows = rows          # dicts: segment values + model/status/errors
        DKV.put(key, self)

    def as_frame(self) -> Frame:
        """Columns: segment cols…, model_id, status, errors (h2o-py
        ``H2OSegmentModels.as_frame``)."""
        names, vecs = [], []
        for c in self.segment_cols:
            vals = np.array([str(r["segment"][c]) for r in self.rows], dtype=object)
            names.append(c)
            vecs.append(Vec.from_numpy(vals, VecType.STR))
        for field in ("model_id", "status", "errors"):
            vals = np.array([r.get(field) or "" for r in self.rows], dtype=object)
            names.append(field)
            vecs.append(Vec.from_numpy(vals, VecType.STR))
        return Frame(names, vecs)

    def get_model(self, **segment_values):
        for r in self.rows:
            if all(str(r["segment"].get(k)) == str(v)
                   for k, v in segment_values.items()):
                if r["model_id"]:
                    return DKV.get(r["model_id"])
                return None
        raise KeyError(f"no segment {segment_values}")

    def __len__(self):
        return len(self.rows)


def train_segments(builder, segments: list[str], frame: Frame, y: str,
                   x: list[str] | None = None,
                   segment_models_id: str | None = None) -> SegmentModels:
    """Train ``builder``'s algorithm once per unique segment combo.

    ``builder``: a configured ModelBuilder instance (its params are reused for
    every segment; a fresh builder is constructed per segment)."""
    seg_cols = list(segments)
    if not seg_cols:
        raise ValueError("segments must name at least one column")
    xs = [c for c in (x if x is not None else frame.names)
          if c != y and c not in seg_cols]

    # enumerate observed combos (host side — segment counts are small)
    seg_vals = []
    for c in seg_cols:
        v = frame.vec(c)
        seg_vals.append(v.labels() if v.is_categorical else
                        np.asarray(v.to_numpy(), dtype=object))
    def _is_na(e):
        return e is None or (isinstance(e, (float, np.floating)) and np.isnan(e))

    # NA segment values are excluded, as the reference does
    combos = sorted({tuple(t) for t in zip(*seg_vals)
                     if not any(_is_na(e) for e in t)}, key=str)

    rows = []
    for combo in combos:
        mask_host = np.ones(frame.nrows, bool)
        for vals, want in zip(seg_vals, combo):
            mask_host &= np.array([v == want for v in vals])
        plen = frame.plen
        padded = np.zeros(plen, np.float32)
        padded[: frame.nrows] = mask_host.astype(np.float32)
        wseg = jnp.asarray(padded)
        seg_desc = dict(zip(seg_cols, combo))
        entry = dict(segment=seg_desc, model_id=None, status="PENDING", errors=None)
        try:
            b = type(builder)(**builder.params)
            model = b.train(x=xs, y=y, training_frame=frame, weights=wseg)
            entry["model_id"] = model.key
            entry["status"] = "SUCCEEDED"
        except Exception as e:                        # noqa: BLE001
            entry["status"] = "FAILED"
            entry["errors"] = f"{type(e).__name__}: {e}"
            entry["traceback"] = traceback.format_exc()
        rows.append(entry)

    import uuid
    key = segment_models_id or f"segment_models_{uuid.uuid4().hex[:8]}"
    return SegmentModels(key, seg_cols, rows)
