"""Orchestration layer: grid search, leaderboard, stacked ensembles, AutoML.

Reference: ``hex/grid/``, ``hex/leaderboard/``, ``hex/ensemble/``,
``ai/h2o/automl/`` (SURVEY.md §2.3, §2.5).
"""

from h2o3_tpu.orchestration.automl import AutoML, EventLog
from h2o3_tpu.orchestration.grid import Grid, GridSearch
from h2o3_tpu.orchestration.leaderboard import Leaderboard
from h2o3_tpu.orchestration.scheduler import MeshScheduler, SLICE_STATS
from h2o3_tpu.orchestration.stacked_ensemble import StackedEnsemble, StackedEnsembleModel
from h2o3_tpu.orchestration.segments import SegmentModels, train_segments

__all__ = [
    "AutoML", "EventLog", "Grid", "GridSearch", "Leaderboard",
    "MeshScheduler", "SLICE_STATS",
    "StackedEnsemble", "StackedEnsembleModel",
    "SegmentModels", "train_segments",
]
