"""StackedEnsemble — metalearner over base-model out-of-fold predictions.

Reference: ``hex/ensemble/StackedEnsemble.java`` (1.8 kLoC): collects the
base models' cross-validation holdout predictions into a "levelone" frame,
trains a metalearner (default GLM with non-negative weights) on it, and
scores by running every base model then the metalearner.

TPU-native: the levelone matrix is assembled directly from the device-resident
OOF prediction arrays each base model kept
(``keep_cross_validation_predictions``) — no frame materialization — and the
metalearner sees it as a plain Frame of numeric columns.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _base_columns(model: Model, raw) -> list:
    """Columns a base model contributes to the levelone frame: p(class) for
    classifiers (dropping the last, redundant class), the prediction for
    regression."""
    if model.nclasses == 2:
        return [raw[:, 1]]
    if model.nclasses > 2:
        return [raw[:, k] for k in range(model.nclasses - 1)]
    return [raw]


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def _score_raw(self, frame: Frame):
        cols = []
        for bm in self.output["base_models"]:
            raw = bm._score_raw(frame)
            cols.extend(_base_columns(bm, raw))
        lvl1 = Frame(list(self.output["levelone_names"]),
                     [Vec.from_device(c, frame.nrows, VecType.NUM) for c in cols])
        return self.output["metalearner"]._score_raw(lvl1)


class StackedEnsemble(ModelBuilder):
    """h2o-py surface: ``H2OStackedEnsembleEstimator``."""

    algo = "stackedensemble"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            base_models=[],
            metalearner_algorithm="AUTO",   # AUTO → GLM (reference default)
            metalearner_params=None,
        )

    def train(self, x=None, y=None, training_frame=None, **kw):
        base = self.params["base_models"]
        if not base:
            raise ValueError("base_models is required")
        if any(m.cv_holdout_predictions is None for m in base):
            raise ValueError("all base models need "
                             "keep_cross_validation_predictions=True and nfolds>=2")
        return super().train(x=x, y=y, training_frame=training_frame, **kw)

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> StackedEnsembleModel:
        p = self.params
        base: list[Model] = list(p["base_models"])
        yvec = frame.vec(y)
        for m in base:
            if m.response_column != y:
                raise ValueError(f"base model {m.key} trained on response "
                                 f"{m.response_column!r}, not {y!r}")

        # levelone frame from the kept OOF predictions
        cols, names = [], []
        hold = None
        for m in base:
            raw = m.cv_holdout_predictions
            for i, c in enumerate(_base_columns(m, raw)):
                cols.append(c)
                names.append(f"{m.key}_{i}")
            hmask = m.cv_holdout_mask
            hold = hmask if hold is None else (hold & hmask)
        lvl1_names = names + [y]
        lvl1 = Frame(lvl1_names,
                     [Vec.from_device(c, frame.nrows, VecType.NUM) for c in cols]
                     + [frame.vec(y)])

        algo = str(p["metalearner_algorithm"]).upper()
        mparams = dict(p["metalearner_params"] or {})
        if algo in ("AUTO", "GLM"):
            from h2o3_tpu.models.glm import GLM
            if algo == "AUTO":
                # reference default metalearner: GLM, non-negative weights
                mparams.setdefault("non_negative", True)
                mparams.setdefault("lambda_", 0.0)
            family = ("binomial" if yvec.cardinality() == 2 else
                      "multinomial" if yvec.is_categorical else "gaussian")
            mparams.setdefault("family", family)
            mbuilder = GLM(**mparams)
        elif algo == "GBM":
            from h2o3_tpu.models.gbm import GBM
            mbuilder = GBM(**mparams)
        elif algo == "DRF":
            from h2o3_tpu.models.gbm import DRF
            mbuilder = DRF(**mparams)
        elif algo == "DEEPLEARNING":
            from h2o3_tpu.models.deeplearning import DeepLearning
            mbuilder = DeepLearning(**mparams)
        else:
            raise ValueError(f"unsupported metalearner_algorithm {algo!r}")

        # train only on rows that are OOF-covered for every base model
        meta_w = weights * hold
        meta = mbuilder.train(x=names, y=y, training_frame=lvl1, weights=meta_w)

        return StackedEnsembleModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(base_models=base, metalearner=meta,
                        levelone_names=names),
        )
