"""h2o3_tpu — a TPU-native distributed ML framework with H2O-3's capabilities.

Architecture (see SURVEY.md for the reference analysis):

- The reference (H2O-3) is a JVM peer-to-peer cluster: a distributed K/V store
  (``water/DKV.java``) holding column-compressed chunks (``water/fvec/Chunk.java``),
  map/reduce tasks over chunk-local data with tree reductions over a custom RPC
  (``water/MRTask.java``).
- Here the same contracts are expressed TPU-first: a :class:`~h2o3_tpu.frame.Frame`
  is a set of row-sharded ``jax.Array`` columns living in HBM across a
  ``jax.sharding.Mesh``; the MRTask map/reduce contract (commutative-associative
  reduce of per-shard partials) becomes ``shard_map`` + ``lax.psum`` over ICI
  (:mod:`h2o3_tpu.ops.map_reduce`), or — for most algorithms — plain ``jnp``
  programs ``jit``-compiled over sharded inputs, letting XLA's SPMD partitioner
  insert the collectives.

Public surface mirrors the h2o-py client (``h2o-py/h2o/h2o.py``): ``import_file``,
``H2OFrame``-like :class:`Frame`, estimator classes under :mod:`h2o3_tpu.models`.
"""

from h2o3_tpu.frame import Frame, Vec, VecType
from h2o3_tpu.frame.parse import import_file, parse_raw, upload_file
from h2o3_tpu.frame.utils import create_frame, interaction, rebalance, tf_idf
from h2o3_tpu.frame.sql import import_sql_select, import_sql_table
from h2o3_tpu.parallel.mesh import (bind_mesh, get_mesh, set_mesh,
                                    mesh_context, num_devices, slice_meshes)
from h2o3_tpu.persist import (export_file, load_frame, load_model, save_frame,
                              save_model)
from h2o3_tpu.genmodel import import_mojo
from h2o3_tpu.explanation import explain, ice, partial_dependence, shap_summary
from h2o3_tpu.utils.registry import DKV
from h2o3_tpu.session import cluster, connect, connection, init, shutdown

__version__ = "0.1.0"

__all__ = [
    "Frame",
    "Vec",
    "VecType",
    "import_file",
    "parse_raw",
    "upload_file",
    "create_frame",
    "interaction",
    "tf_idf",
    "rebalance",
    "import_sql_table",
    "import_sql_select",
    "export_file",
    "save_frame",
    "load_frame",
    "save_model",
    "load_model",
    "import_mojo",
    "explain",
    "partial_dependence",
    "ice",
    "shap_summary",
    "get_mesh",
    "set_mesh",
    "bind_mesh",
    "mesh_context",
    "num_devices",
    "slice_meshes",
    "DKV",
    "init",
    "connect",
    "connection",
    "cluster",
    "shutdown",
    "__version__",
]
