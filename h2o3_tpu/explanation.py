"""Model explainability — partial dependence, ICE, SHAP summaries, varimp maps.

Reference: h2o-py ``h2o/explanation/_explain.py`` (varimp heatmap, model
correlation, SHAP summary, PD plots, ICE) and the server-side partial
dependence task ``h2o-core/.../water/api/ModelMetricsHandler`` +
``hex/PartialDependence.java`` (grid of column values → mean prediction with
the column overridden, std over rows).

All functions return DATA (Frames / dicts) rather than figures — the client
side of the reference renders matplotlib from the same tables.

TPU-native: a PD grid point overrides one column of the device-resident
design and re-scores — each grid value is one jitted batch score; ICE keeps
the per-row predictions instead of the mean. SHAP summaries ride the exact
TreeSHAP contributions (``h2o3_tpu/genmodel/treeshap.py``).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec


def _response_col(model, raw: np.ndarray) -> np.ndarray:
    """Collapse a prediction matrix to the 'response' curve: p(class 1) for
    binomial (reference PD plots track the positive class), else the raw
    regression prediction."""
    if raw.ndim == 2 and raw.shape[1] == 2:
        return raw[:, 1]
    if raw.ndim == 2:
        return raw.max(axis=1)
    return raw


def _grid_for(frame: Frame, col: str, nbins: int):
    v = frame.vec(col)
    if v.is_categorical:
        return list(range(len(v.domain))), list(v.domain)
    x = np.asarray(v.to_numpy(), np.float64)
    x = x[np.isfinite(x)]
    if x.size == 0:
        raise ValueError(f"column {col!r} has no finite values")
    grid = np.linspace(x.min(), x.max(), nbins)
    return list(grid), [float(g) for g in grid]


def partial_dependence(model, frame: Frame, cols: list[str] | str,
                       nbins: int = 20, weight_column: str | None = None
                       ) -> list[Frame]:
    """Per-column PD tables (h2o-py ``model.partial_plot(..., plot=False)``):
    rows = (value, mean_response, stddev_response, std_error_mean_response)."""
    import jax
    if isinstance(cols, str):
        cols = [cols]
    w = None
    if weight_column is not None:
        w = np.asarray(frame.vec(weight_column).to_numpy(), np.float64)
    out = []
    for col in cols:
        grid, labels = _grid_for(frame, col, nbins)
        means, sds, ses = [], [], []
        for gv in grid:
            fr2 = _override(frame, col, gv)
            raw = np.asarray(jax.device_get(model._score_raw(fr2)))[: frame.nrows]
            resp = _response_col(model, raw)
            if w is not None:
                m = float(np.average(resp, weights=w))
                sd = float(np.sqrt(np.average((resp - m) ** 2, weights=w)))
            else:
                m, sd = float(resp.mean()), float(resp.std())
            means.append(m)
            sds.append(sd)
            ses.append(sd / np.sqrt(max(len(resp), 1)))
        value_vec = (Vec.from_numpy(np.array(labels, dtype=object), VecType.STR)
                     if frame.vec(col).is_categorical
                     else Vec.from_numpy(np.array(labels, np.float32)))
        out.append(Frame(
            [col, "mean_response", "stddev_response", "std_error_mean_response"],
            [value_vec,
             Vec.from_numpy(np.array(means, np.float32)),
             Vec.from_numpy(np.array(sds, np.float32)),
             Vec.from_numpy(np.array(ses, np.float32))]))
    return out


def _override(frame: Frame, col: str, value) -> Frame:
    """Frame view with one column replaced by a constant (device-side fill)."""
    import jax.numpy as jnp
    v = frame.vec(col)
    names, vecs = [], []
    for name in frame.names:
        if name != col:
            names.append(name)
            vecs.append(frame.vec(name))
            continue
        if v.is_categorical:
            data = jnp.full_like(v.data, int(value))
            nv = Vec.from_device(data, v.nrows, VecType.CAT, domain=v.domain)
        else:
            data = jnp.full_like(v.data, float(value))
            nv = Vec.from_device(data, v.nrows, v.type)
        names.append(name)
        vecs.append(nv)
    return Frame(names, vecs)


def ice(model, frame: Frame, col: str, nbins: int = 20,
        max_rows: int = 100, seed: int = 42) -> Frame:
    """Individual Conditional Expectation curves (h2o-py ``ice_plot`` data):
    one row per (sampled original row, grid value)."""
    import jax
    rng = np.random.default_rng(seed)
    n = min(max_rows, frame.nrows)
    row_ids = np.sort(rng.choice(frame.nrows, size=n, replace=False))
    grid, labels = _grid_for(frame, col, nbins)
    rows_id, rows_val, rows_resp = [], [], []
    for gv, lab in zip(grid, labels):
        fr2 = _override(frame, col, gv)
        raw = np.asarray(jax.device_get(model._score_raw(fr2)))[: frame.nrows]
        resp = _response_col(model, raw)[row_ids]
        rows_id.extend(row_ids.tolist())
        rows_val.extend([lab] * n)
        rows_resp.extend(resp.tolist())
    value_vec = (Vec.from_numpy(np.array(rows_val, dtype=object), VecType.STR)
                 if frame.vec(col).is_categorical
                 else Vec.from_numpy(np.array(rows_val, np.float32)))
    return Frame(["row", col, "response"],
                 [Vec.from_numpy(np.array(rows_id, np.float32)),
                  value_vec,
                  Vec.from_numpy(np.array(rows_resp, np.float32))])


def shap_summary(model, frame: Frame, top_n: int = 20):
    """Mean |SHAP| per feature (the bar data of h2o-py's shap_summary_plot).

    Requires a model with ``predict_contributions`` (tree models)."""
    if not hasattr(model, "predict_contributions"):
        raise ValueError(f"{model.algo} does not support SHAP contributions")
    contrib = model.predict_contributions(frame)
    rows = []
    for name in contrib.names:
        if name == "BiasTerm":
            continue
        phi = np.asarray(contrib.vec(name).to_numpy())
        rows.append((name, float(np.abs(phi).mean()), float(phi.mean())))
    rows.sort(key=lambda r: -r[1])
    return rows[:top_n]


def permutation_varimp(model, frame: Frame, metric: str | None = None,
                       n_repeats: int = 1, seed: int = -1,
                       features: list[str] | None = None,
                       n_samples: int = -1):
    """Permutation feature importance (reference: ``AstPermutationVarImp`` /
    h2o-py ``model.permutation_importance``): shuffle one column at a time,
    rescore, and report the metric degradation.

    ``n_repeats == 1`` → rows (variable, relative_importance,
    scaled_importance, percentage); ``n_repeats > 1`` → per-run rows
    (variable, run_1..run_N), the reference's repeated-run table shape.
    ``n_samples`` > 0 subsamples that many rows first (speed knob)."""
    from h2o3_tpu.rapids.munge import gather_rows

    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    if n_samples and 0 < n_samples < frame.nrows:
        idx = np.sort(rng.choice(frame.nrows, int(n_samples), replace=False))
        frame = gather_rows(frame, idx)
    base_mm = model.model_performance(frame)
    if not metric or metric.upper() == "AUTO":
        metric = "logloss" if model.is_classifier else "rmse"
    higher_is_better = metric.lower() in ("auc", "pr_auc", "r2", "accuracy")

    def mval(mm):
        v = getattr(mm, metric.lower(), None)
        if v is None:
            raise ValueError(f"metric {metric!r} not available")
        return float(v() if callable(v) else v)

    base = mval(base_mm)
    cols = features or [c for c in model.output.get("x_cols", frame.names)
                        if c in frame and c != model.response_column]
    reps = max(1, int(n_repeats))
    rows = []
    for c in cols:
        deltas = []
        v = frame.vec(c)
        host = v.to_numpy()
        for _ in range(reps):
            perm = host.copy()
            rng.shuffle(perm)
            shuffled = Frame(list(frame.names),
                             [Vec.from_numpy(perm, type=v.type,
                                             domain=v.domain)
                              if n == c else frame.vec(n)
                              for n in frame.names])
            d = mval(model.model_performance(shuffled)) - base
            deltas.append(-d if higher_is_better else d)
        rows.append({"variable": c, "deltas": deltas,
                     "relative_importance": float(np.mean(deltas))})
    if reps > 1:
        # reference repeated-run shape: Variable + Run 1..Run N
        return [{"variable": r["variable"],
                 **{f"run_{i + 1}": float(d)
                    for i, d in enumerate(r["deltas"])}} for r in rows]
    for r in rows:
        del r["deltas"]
    mx = max((r["relative_importance"] for r in rows), default=0.0)
    tot = sum(max(r["relative_importance"], 0.0) for r in rows) or 1.0
    for r in rows:
        r["scaled_importance"] = (r["relative_importance"] / mx
                                  if mx > 0 else 0.0)
        r["percentage"] = max(r["relative_importance"], 0.0) / tot
    rows.sort(key=lambda r: -r["relative_importance"])
    return rows


def varimp_heatmap(models) -> dict:
    """Scaled variable importances per model (h2o-py ``varimp_heatmap`` data):
    {'columns': [...], 'models': [...], 'matrix': [[...]]}."""
    all_cols: list[str] = []
    per_model = []
    names = []
    for m in models:
        vi = {r[0]: r[2] for r in m.varimp()}     # scaled importance
        per_model.append(vi)
        names.append(m.key)
        for c in vi:
            if c not in all_cols:
                all_cols.append(c)
    matrix = [[vi.get(c, 0.0) for c in all_cols] for vi in per_model]
    return {"columns": all_cols, "models": names, "matrix": matrix}


def model_correlation(models, frame: Frame) -> dict:
    """Pairwise correlation of model predictions on a frame (h2o-py
    ``model_correlation_heatmap`` data)."""
    import jax
    preds = []
    names = []
    for m in models:
        raw = np.asarray(jax.device_get(m._score_raw(frame)))[: frame.nrows]
        preds.append(_response_col(m, raw))
        names.append(m.key)
    P = np.stack(preds)
    C = np.corrcoef(P)
    return {"models": names, "matrix": C.tolist()}


def explain(models, frame: Frame, top_n_features: int = 5) -> dict:
    """One-call explanation bundle (h2o-py ``h2o.explain``): leaderboard-ish
    summary, varimp heatmap (multi-model), model correlation, per-model PD
    for the top features, SHAP summary where supported."""
    if not isinstance(models, (list, tuple)):
        models = [models]
    result: dict = {}
    with_vi = [m for m in models if hasattr(m, "varimp")]
    if len(models) > 1:
        if with_vi:
            result["varimp_heatmap"] = varimp_heatmap(with_vi)
        result["model_correlation"] = model_correlation(models, frame)
    per_model = {}
    for m in models:
        entry: dict = {}
        if hasattr(m, "varimp"):
            vi = m.varimp()
            entry["varimp"] = vi
            top = [r[0] for r in vi[:top_n_features]]
            entry["partial_dependence"] = {
                c: pd for c, pd in zip(top, partial_dependence(m, frame, top))}
        try:
            entry["shap_summary"] = shap_summary(m, frame)
        except (ValueError, KeyError):
            pass
        per_model[m.key] = entry
    result["models"] = per_model
    return result
