"""h2o.init / connect / cluster — the client-session entry points.

Reference: ``h2o-py/h2o/h2o.py`` — ``h2o.init()`` starts-or-attaches a local
node and keeps a module-level connection; ``h2o.connect()`` attaches to a
running cluster; ``h2o.cluster()`` exposes status/shutdown.

Here ``init`` boots the in-process REST server (the "node" is this process +
its TPU mesh) and returns a client bound to it; ``connect`` attaches to any
running h2o3_tpu server.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:   # lazy at runtime: api.server imports h2o3_tpu.__version__
    from h2o3_tpu.api.client import H2OClient
    from h2o3_tpu.api.server import H2OServer

_server = None
_client = None


def init(port: int = 54321, strict_port: bool = False,
         coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None) -> "H2OClient":
    """Start (once) an in-process server and bind the module client
    (h2o-py: ``h2o.init``). Falls back to an ephemeral port unless
    ``strict_port``.

    Multi-host: pass ``coordinator_address`` (+ ``num_processes`` /
    ``process_id``) to join a process-spanning cloud first — every process
    calls ``init`` with the same coordinator, blocks until the cloud forms
    (reference ``waitForCloudSize``, ``water/H2O.java:2099``), and installs
    a mesh over all hosts' devices. See also ``python -m h2o3_tpu.launch``.
    Only process 0 serves REST (the reference: any node serves, one answers).
    """
    from h2o3_tpu.api.client import H2OClient
    from h2o3_tpu.api.server import H2OServer
    from h2o3_tpu.utils import compile_cache
    from h2o3_tpu.utils.telemetry import install_log_ring
    install_log_ring()   # session startup: /3/Logs serves from here on
    # persistent XLA compile cache (H2O3TPU_COMPILE_CACHE=1|path; repeated
    # same-shape builds across sessions skip compile — ROADMAP item 5)
    compile_cache.enable()
    global _server, _client
    if _client is not None:
        return _client
    if coordinator_address is not None:
        from h2o3_tpu.parallel.distributed import init_distributed
        init_distributed(coordinator_address, num_processes, process_id)
        import jax
        if jax.process_index() != 0:
            return None
    try:
        _server = H2OServer(port=port).start()
    except OSError:
        if strict_port:
            raise
        _server = H2OServer(port=0).start()
    _client = H2OClient(_server.url)
    return _client


def connect(url: str) -> "H2OClient":
    """Attach to a running server (h2o-py: ``h2o.connect``)."""
    from h2o3_tpu.api.client import H2OClient
    global _client
    _client = H2OClient(url)
    _client.cloud_status()      # fail fast on a dead address
    return _client


def cluster() -> dict:
    """Cluster status (h2o-py: ``h2o.cluster().show_status()``)."""
    if _client is None:
        raise RuntimeError("not connected: call h2o3_tpu.init() or connect()")
    return _client.cloud_status()


def shutdown() -> None:
    """Stop the in-process server and drop the connection.

    When this process OWNS the server, stop it directly — issuing the REST
    /3/Shutdown as well would race two teardowns of the same socketserver
    from different threads."""
    global _server, _client
    if _server is not None:
        _server.stop()
    elif _client is not None:
        try:
            _client.shutdown()
        except Exception:    # noqa: BLE001 — server may already be gone
            pass
    _server = _client = None


def connection():
    return _client
