"""graftlint lock-discipline rules (LCK) — unguarded shared-state mutation.

- **LCK001** — inconsistent guarding: an instance attribute that is
  mutated under a lock somewhere in its class (``with self._lock:``)
  is mutated WITHOUT the lock elsewhere in the same class. Half-guarded
  state is worse than unguarded: the lock documents an invariant the
  unguarded site silently breaks. ``__init__`` is exempt (construction
  is single-threaded).
- **LCK002** — thread-shared class without locking: a class that runs one
  of its own methods on a worker thread (``threading.Thread(
  target=self.method)``) mutates instance attributes outside any lock.
  Those attributes are read concurrently by definition.
- **LCK003** — cross-object private mutation: module code reaching into a
  singleton's underscore state (``CLEANER._touch.pop(...)``) bypasses
  whatever locking the owning class provides. Add a method on the owner
  that takes its own lock.
"""

from __future__ import annotations

import ast
import re

from h2o3_tpu.tools.core import Finding, ModuleInfo, PackageIndex

#: method names that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "add", "discard", "setdefault", "sort",
             "appendleft", "extendleft"}

_LOCKISH = re.compile(r"lock|cond|_mu\b|mutex|sem", re.IGNORECASE)
_SINGLETON = re.compile(r"^[A-Z][A-Z0-9_]+$")


def _is_lockish_with(node: ast.With) -> bool:
    for item in node.items:
        try:
            src = ast.unparse(item.context_expr)
        except Exception:   # pragma: no cover - unparse is total on 3.9+
            continue
        if _LOCKISH.search(src):
            return True
    return False


def _self_attr_of(node: ast.AST) -> str | None:
    """``self.X`` (possibly through a subscript ``self.X[...]``) -> X."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _mutations(stmt: ast.AST) -> list[tuple[str, int]]:
    """(attr, line) pairs for direct mutations of ``self.X`` in one node
    (no recursion into children — the walker handles that)."""
    out: list[tuple[str, int]] = []
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            tgts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
            for t in tgts:
                attr = _self_attr_of(t)
                if attr:
                    out.append((attr, t.lineno))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        attr = _self_attr_of(stmt.target)
        if attr and getattr(stmt, "value", True) is not None:
            out.append((attr, stmt.target.lineno))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            attr = _self_attr_of(t)
            if attr:
                out.append((attr, t.lineno))
    elif isinstance(stmt, ast.Call):
        f = stmt.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr_of(f.value)
            if attr:
                out.append((attr, stmt.lineno))
    return out


def _walk_method(fn: ast.AST):
    """Yield ``(node, under_lock)`` for every node in a method body,
    tracking lock-protected ``with`` regions; skips nested defs."""

    def visit(node: ast.AST, locked: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            child_locked = locked or (
                isinstance(child, ast.With) and _is_lockish_with(child))
            yield child, child_locked
            yield from visit(child, child_locked)

    yield from visit(fn, False)


def _thread_target_methods(cls: ast.ClassDef) -> set[str]:
    """Own methods handed to ``threading.Thread(target=self.m)``."""
    out: set[str] = set()
    method_names = {n.name for n in cls.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_thread = (isinstance(f, ast.Name) and f.id == "Thread") or (
            isinstance(f, ast.Attribute) and f.attr == "Thread")
        if not is_thread:
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                attr = _self_attr_of(kw.value)
                if attr in method_names:
                    out.add(attr)
    return out


def _check_class(mod: ModuleInfo, cls: ast.ClassDef,
                 findings: list[Finding]) -> None:
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    guarded: set[str] = set()
    for m in methods:
        for node, locked in _walk_method(m):
            if locked:
                for attr, _line in _mutations(node):
                    guarded.add(attr)
    thread_methods = _thread_target_methods(cls)
    for m in methods:
        if m.name == "__init__":
            continue
        for node, locked in _walk_method(m):
            if locked:
                continue
            for attr, line in _mutations(node):
                qual = f"{cls.name}.{m.name}"
                if attr in guarded:
                    findings.append(Finding(
                        "LCK001", mod.path, line, qual,
                        f"`self.{attr}` is mutated under a lock elsewhere "
                        f"in {cls.name} but not here — take the same lock "
                        "or make the update atomic", detail=attr))
                elif thread_methods:
                    findings.append(Finding(
                        "LCK002", mod.path, line, qual,
                        f"{cls.name} runs on a worker thread "
                        f"(Thread(target=self.{next(iter(sorted(thread_methods)))})) "
                        f"but mutates `self.{attr}` without a lock — "
                        "concurrent readers can observe torn multi-field "
                        "state", detail=attr))


def _check_singletons(mod: ModuleInfo, findings: list[Finding]) -> None:
    def base_singleton_attr(node: ast.AST) -> str | None:
        """``NAME._attr`` (optionally through a subscript) -> 'NAME._attr'
        for ALL_CAPS module singletons."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and node.attr.startswith("_") and \
                isinstance(node.value, ast.Name) and \
                _SINGLETON.match(node.value.id):
            return f"{node.value.id}.{node.attr}"
        return None

    # singletons INSTANTIATED in this module: their defining module is the
    # owner and may manage the private state next to the class
    own = {n.targets[0].id for n in ast.walk(mod.tree)
           if isinstance(n, ast.Assign) and len(n.targets) == 1
           and isinstance(n.targets[0], ast.Name)
           and isinstance(n.value, ast.Call)}

    for node in ast.walk(mod.tree):
        hits: list[tuple[str, int]] = []
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                ref = base_singleton_attr(tgt)
                if ref:
                    hits.append((ref, tgt.lineno))
        elif isinstance(node, ast.AugAssign):
            ref = base_singleton_attr(node.target)
            if ref:
                hits.append((ref, node.target.lineno))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                ref = base_singleton_attr(tgt)
                if ref:
                    hits.append((ref, tgt.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                ref = base_singleton_attr(f.value)
                if ref:
                    hits.append((ref, node.lineno))
        for ref, line in hits:
            if ref.split(".")[0] in own:
                continue
            findings.append(Finding(
                "LCK003", mod.path, line, "",
                f"mutation of `{ref}` reaches into another object's "
                "private state, bypassing its locking — add a locked "
                "method on the owner", detail=ref))


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(mod, node, findings)
        _check_singletons(mod, findings)
    return findings
