"""graftlint retry-discipline rules (RTY) — retry loops done wrong.

The reliability layer (docs/RELIABILITY.md) standardizes transient-failure
handling on ``ops/map_reduce.retrying``: budgeted attempts, exponential
backoff WITH jitter, structured ``DispatchFailed`` on exhaustion. These
rules flag hand-rolled retry loops that regress on that contract:

- **RTY001** — a retry loop (a ``for``/``while`` whose body contains a
  ``try``/``except``) that sleeps a CONSTANT between attempts. A fixed
  ``time.sleep(0.5)`` has no backoff and no jitter: under a correlated
  failure every retrier re-fires in lockstep (the thundering-herd the
  jittered exponential exists to prevent). Compute the delay from the
  attempt number, or use ``retrying``.
- **RTY002** — an ``except``/``except Exception``/``except BaseException``
  inside a retry-loop body whose handler only ``pass``/``continue``s. A
  swallow-everything handler turns a bounded retry into an unbounded spin
  and erases the error the exhaustion report needs; record the failure
  (history, metric, log) or narrow the exception type.

Both are inline-suppressible with ``# graftlint: ok(<reason>)`` like every
other rule family.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, call_name

_BROAD = {"Exception", "BaseException"}


def _is_sleep(node: ast.Call) -> bool:
    name = call_name(node)
    return bool(name) and name.split(".")[-1] == "sleep"


def _const_sleep_arg(node: ast.Call) -> bool:
    """True when every positional arg is a literal constant (no args counts
    as non-constant — not a duration we can judge)."""
    return bool(node.args) and all(isinstance(a, ast.Constant)
                                   for a in node.args)


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    if h.type is None:                      # bare except:
        return True
    names = []
    if isinstance(h.type, ast.Tuple):
        names = [t for t in h.type.elts]
    else:
        names = [h.type]
    for t in names:
        tn = (t.id if isinstance(t, ast.Name)
              else t.attr if isinstance(t, ast.Attribute) else None)
        if tn in _BROAD:
            return True
    return False


def _handler_swallows(h: ast.ExceptHandler) -> bool:
    """Only ``pass``/``continue`` in the body — the failure vanishes."""
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in h.body)


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        # innermost-enclosing-function attribution, same scheme as SYN001
        qual_of: dict[int, str] = {}
        for fn in sorted((f for f in index.functions.values()
                          if f.module is mod),
                         key=lambda f: f.node.lineno):
            for sub in ast.walk(fn.node):
                qual_of[id(sub)] = fn.qualname
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = node.body + node.orelse
            tries = [s for stmt in body for s in ast.walk(stmt)
                     if isinstance(s, ast.Try)]
            if not tries:
                continue        # a sleep without except is polling, not retry
            sleeps = [sub for stmt in body for sub in ast.walk(stmt)
                      if isinstance(sub, ast.Call) and _is_sleep(sub)]
            # RETRY loop discriminator: a `while` re-attempts the same
            # operation; a `for` over a collection with except/continue is
            # the skip-bad-items idiom (legitimate) UNLESS it also waits —
            # iteration that sleeps between failures is retry in disguise
            is_retry = isinstance(node, ast.While) or bool(sleeps)
            if not is_retry:
                continue
            for sub in sleeps:
                if _const_sleep_arg(sub):
                    findings.append(Finding(
                        "RTY001", mod.path, sub.lineno,
                        qual_of.get(id(sub), ""),
                        "retry loop sleeps a CONSTANT between attempts "
                        "— no backoff, no jitter (compute the delay "
                        "from the attempt number, or use "
                        "ops.map_reduce.retrying)",
                        detail="constant-sleep-retry"))
            for t in tries:
                for h in t.handlers:
                    if _handler_is_broad(h) and _handler_swallows(h):
                        findings.append(Finding(
                            "RTY002", mod.path, h.lineno,
                            qual_of.get(id(h), ""),
                            "broad `except` swallowing inside a retry body "
                            "— the failure vanishes and the retry spins "
                            "blind (record it or narrow the type)",
                            detail="swallowing-retry-except"))
    return findings
