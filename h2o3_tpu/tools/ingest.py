"""graftlint ingest-discipline rule (ING) — unbounded reads in stage bodies.

The streaming ingest pipeline (``ingest/pipeline.py``, docs/INGEST.md)
exists so host peak memory is O(chunk), not O(file). One careless
``fh.read()`` inside a stage body silently reverts the whole subsystem to
the all-at-once parse it replaced — the pipeline still *looks* streamed
(stages, queues, progress), but the first stage materializes the file and
every memory claim downstream is fiction. The same applies to
``readlines()`` (every line at once) and ``np.loadtxt`` (whole-file
loader).

- **ING001** — inside any function defined under the ``ingest/`` package:
  a zero-argument ``.read()`` call (no size bound), any ``.readlines()``
  call, or a call to ``loadtxt``/``genfromtxt``/``read_file`` whole-file
  loaders. Bounded reads (``fh.read(1 << 20)``) and chunk-sized parsing
  are the fix shape; a deliberate whole-file read (a tiny sidecar header,
  say) carries an inline ``# graftlint: ok(<reason>)`` suppression like
  every other rule.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, call_name

#: callables that materialize an entire file regardless of its size
_WHOLE_FILE_LOADERS = {"loadtxt", "genfromtxt", "read_file"}


def _in_ingest(path: str) -> bool:
    return path.startswith("ingest/") or "/ingest/" in path


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        if not _in_ingest(mod.path):
            continue
        # map AST nodes to their enclosing stage/function qualname
        qual_of: dict[int, str] = {}
        for fn in sorted((f for f in index.functions.values()
                          if f.module is mod),
                         key=lambda f: f.node.lineno):
            for sub in ast.walk(fn.node):
                qual_of[id(sub)] = fn.qualname
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            where = qual_of.get(id(node), "")
            if not where:
                continue          # module scope: not a stage body
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth == "read" and not node.args and not node.keywords:
                    findings.append(Finding(
                        "ING001", mod.path, node.lineno, where,
                        "unbounded `.read()` in an ingest stage body — "
                        "this materializes the whole file and reverts the "
                        "pipeline's O(chunk) memory contract; read bounded "
                        "blocks (`fh.read(1 << 20)`) instead",
                        detail="unbounded-read"))
                    continue
                if meth == "readlines":
                    findings.append(Finding(
                        "ING001", mod.path, node.lineno, where,
                        "`.readlines()` in an ingest stage body loads "
                        "every line at once; iterate bounded blocks and "
                        "re-assemble lines incrementally",
                        detail="readlines"))
                    continue
            name = call_name(node)
            if name and name.split(".")[-1] in _WHOLE_FILE_LOADERS:
                findings.append(Finding(
                    "ING001", mod.path, node.lineno, where,
                    f"whole-file loader `{name}` in an ingest stage body "
                    "— O(file) host memory by construction; parse "
                    "fixed-row chunks through the staged pipeline instead",
                    detail="whole-file-loader"))
    return findings
