"""graftlint metric-cardinality rule (CRD) — unbounded label values.

Every labelled child of a metric family lives forever in the registry and
in every ``/metrics`` scrape, every flight-recorder sample, and every
diagnostic bundle. The label-cardinality contract (telemetry.py, the
flight recorder's ``max_series`` cap) is that label VALUES come from small
closed sets — route patterns, algo names, outcome enums — never DKV keys,
file paths, or raw tenant strings. One ``labels(model=frame_key)`` in a
hot path turns a fixed-memory recorder into an unbounded one.

- **CRD001** — a ``.labels(...)`` call with keyword arguments where some
  keyword's value mentions an identifier whose name says "unbounded":
  a segment like ``key``/``path``/``file``/``url``/``user``/``raw``/
  ``id``/``token``. String literals and values routed through a
  sanitizer-shaped call (``*sanitize*``, ``*bound*``, ``*bucket*``,
  ``*label*``) are accepted — that is the fix shape: map the raw value
  onto a closed set first (see ``ops_plane/tenancy.py``'s tenant-label
  sanitizer). Deliberate bounded exceptions (e.g. a label whose residency
  is capped by an LRU) carry an inline ``# graftlint: ok(<reason>)``.

Only keyword-form calls are examined, so ``Vec.labels()`` / categorical
``v.labels()`` accessors (always positional-free, argument-free) never
match.
"""

from __future__ import annotations

import ast
import re

from h2o3_tpu.tools.core import Finding, PackageIndex, dotted_name

#: identifier SEGMENTS (underscore-split) that mark a value as drawn from
#: an open set: object keys, filesystem paths, user-supplied strings, ids
_UNBOUNDED = re.compile(
    r"(?:^|_)(?:key|keys|path|paths|file|filename|files|dir|url|uri|"
    r"user|users|raw|query|sql|token|secret|id|ids|uid|dest|dst|src)(?:_|$)")

#: callables whose NAME promises the value was folded onto a closed set
_SANITIZER = re.compile(r"sanitiz|bound|bucket|label|enum|classify",
                        re.IGNORECASE)


def _is_sanitized(value: ast.AST) -> bool:
    """True when the value is produced by a sanitizer-shaped call —
    ``route_label(path)``, ``_bounded_tenant(raw)`` — whose name is the
    documented promise of bounded output."""
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    return bool(name and _SANITIZER.search(name.rsplit(".", 1)[-1]))


def _unbounded_ident(value: ast.AST) -> str | None:
    """The first identifier inside ``value`` whose name marks an open
    set, or None. Walks the whole expression so f-strings and arithmetic
    over a key are caught, not just bare names."""
    for node in ast.walk(value):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident and _UNBOUNDED.search(ident):
            return ident
    return None


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels" and node.keywords):
                continue
            for kw in node.keywords:
                if kw.arg is None or _is_sanitized(kw.value):
                    continue
                ident = _unbounded_ident(kw.value)
                if ident is None:
                    continue
                findings.append(Finding(
                    "CRD001", mod.path, node.lineno, "",
                    f"label `{kw.arg}={ident}` feeds an open set into a "
                    "metric family — every distinct value is a child that "
                    "lives forever in the registry, the /metrics scrape, "
                    "and the flight recorder; fold it onto a closed set "
                    "via a bounded-label helper or suppress with the "
                    "bound's reason",
                    detail=f"unbounded-label:{kw.arg}={ident}"))
    return findings
