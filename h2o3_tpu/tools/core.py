"""graftlint core — package AST index, call graph, findings, suppressions.

Analysis is pure stdlib (``ast`` + ``re``): the code UNDER ANALYSIS is
never imported or executed — no backend initializes because a model file
was scanned. (The CLI itself lives inside ``h2o3_tpu``, so running it does
import the package's ``__init__``; point ``run_lint`` at any source tree
to analyze code that isn't importable here.)
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

#: inline suppression marker — put ``# graftlint: ok(<reason>)`` on the
#: offending line (or on its own line directly above) to accept a finding
#: as a documented, deliberate exception.
SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*ok\b")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # e.g. "TRC003"
    path: str       # package-relative posix path
    line: int
    where: str      # qualname of the enclosing function/class ("" = module)
    message: str
    detail: str = ""   # short, line-number-free slug for fingerprinting

    @property
    def fingerprint(self) -> str:
        """Stable identity across unrelated edits: no line numbers, so a
        baseline survives code motion above the finding."""
        return f"{self.rule}:{self.path}:{self.where}:{self.detail}"

    def render(self) -> str:
        where = f" [{self.where}]" if self.where else ""
        return f"{self.path}:{self.line}: {self.rule}{where} {self.message}"


@dataclasses.dataclass
class FunctionInfo:
    qualname: str            # module-relative, e.g. "GLM._irls_fit"
    module: "ModuleInfo"
    node: ast.AST            # FunctionDef | AsyncFunctionDef
    class_name: str | None   # enclosing class, if a method
    parent: str | None       # qualname of enclosing function (nested defs)
    is_jit_root: bool = False


@dataclasses.dataclass
class ModuleInfo:
    name: str                # dotted module name relative to scan root
    path: str                # posix relpath
    tree: ast.Module
    lines: list[str]
    suppressed: set[int]
    # local name -> module-relative qualname for top-level defs
    top_defs: dict[str, str] = dataclasses.field(default_factory=dict)
    # class name -> {method name -> qualname}
    classes: dict[str, dict[str, str]] = dataclasses.field(default_factory=dict)
    # imported name -> dotted source ("h2o3_tpu.models.glm._irls_step")
    imports: dict[str, str] = dataclasses.field(default_factory=dict)


#: compound statements — a marker inside their BODY must not blanket the
#: whole block, only the simple statement it sits on
_COMPOUND = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
             ast.AsyncWith, ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
             ast.ClassDef, ast.Match)


def _suppressed_lines(lines: list[str], tree: ast.Module) -> set[int]:
    """1-based line numbers covered by a suppression marker, scoped to the
    STATEMENT the marker annotates: a trailing marker covers every physical
    line of its own (possibly multi-line) simple statement; a comment-only
    marker line covers the statement starting directly below. Nothing
    leaks to neighbouring statements."""
    marked = {i for i, text in enumerate(lines, start=1)
              if SUPPRESS_RE.search(text)}
    out = set(marked)
    if not marked:
        return out
    comment_only = {i for i in marked if lines[i - 1].lstrip().startswith("#")}
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt) or isinstance(node, _COMPOUND):
            continue
        lo = node.lineno
        hi = getattr(node, "end_lineno", None) or lo
        span = set(range(lo, hi + 1))
        if (span & marked) or (lo - 1) in comment_only:
            out |= span
    return out


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


_JIT_MARKERS = {"jit", "pjit"}


def decorator_is_jit(dec: ast.AST) -> bool:
    """True for ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)`` and
    friends — any decorator expression mentioning a ``jit`` name."""
    for node in ast.walk(dec):
        if isinstance(node, ast.Name) and node.id in _JIT_MARKERS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _JIT_MARKERS:
            return True
    return False


class PackageIndex:
    """Parsed view of every ``*.py`` under a root directory."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}  # "mod::qual" -> info
        self.errors: list[str] = []
        self._edges: dict[str, set[str]] | None = None

    # -- construction --------------------------------------------------------

    @classmethod
    def scan(cls, root: Path) -> "PackageIndex":
        idx = cls(root)
        for path in sorted(Path(root).rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(root).as_posix()
            try:
                src = path.read_text()
                tree = ast.parse(src, filename=rel)
            except (SyntaxError, UnicodeDecodeError) as e:
                idx.errors.append(f"{rel}: unparseable: {e}")
                continue
            lines = src.splitlines()
            name = rel[:-3].replace("/", ".")
            if name.endswith(".__init__"):
                name = name[: -len(".__init__")]
            mod = ModuleInfo(name=name, path=rel, tree=tree, lines=lines,
                             suppressed=_suppressed_lines(lines, tree))
            idx.modules[name] = mod
            idx._index_module(mod)
        return idx

    def _index_module(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = alias.name

        def register(fn: ast.AST, qual: str, cls: str | None,
                     parent: str | None) -> None:
            info = FunctionInfo(
                qualname=qual, module=mod, node=fn, class_name=cls,
                parent=parent,
                is_jit_root=any(decorator_is_jit(d)
                                for d in fn.decorator_list))
            self.functions[f"{mod.name}::{qual}"] = info
            for child in ast.iter_child_nodes(fn):
                visit(child, cls, qual)

        def visit(node: ast.AST, cls: str | None, parent: str | None) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{parent}.{node.name}" if parent else (
                    f"{cls}.{node.name}" if cls else node.name)
                if cls and not parent:
                    mod.classes.setdefault(cls, {})[node.name] = qual
                elif not cls and not parent:
                    mod.top_defs[node.name] = qual
                register(node, qual, cls, parent)
            elif isinstance(node, ast.ClassDef):
                for child in ast.iter_child_nodes(node):
                    visit(child, node.name, None)
            else:
                for child in ast.iter_child_nodes(node):
                    visit(child, cls, parent)

        for child in ast.iter_child_nodes(mod.tree):
            visit(child, None, None)

    # -- call resolution -----------------------------------------------------

    def resolve_call(self, fn: FunctionInfo, call: ast.Call) -> str | None:
        """Resolve a call inside ``fn`` to a ``mod::qual`` key, if it names
        a function defined in the scanned package."""
        mod = fn.module
        name = call_name(call)
        if name is None:
            return None
        # self.method() -> same-class method
        if name.startswith("self.") and fn.class_name:
            meth = name[5:]
            if "." not in meth:
                qual = mod.classes.get(fn.class_name, {}).get(meth)
                if qual:
                    return f"{mod.name}::{qual}"
            return None
        head, _, rest = name.partition(".")
        # bare local name: nested sibling, top-level def, or import
        if not rest:
            if fn.parent:
                key = f"{mod.name}::{fn.parent}.{head}"
                if key in self.functions:
                    return key
            if head in mod.top_defs:
                return f"{mod.name}::{mod.top_defs[head]}"
            src = mod.imports.get(head)
            if src:
                return self._resolve_dotted(src)
            return None
        # imported-module attribute: ``mod_alias.fn``
        src = mod.imports.get(head)
        if src:
            return self._resolve_dotted(f"{src}.{rest}")
        return None

    def _resolve_dotted(self, dotted: str) -> str | None:
        """``pkg.mod.func`` -> ``mod-name::func`` if scanned. Module names
        in the index are root-relative; accept full package paths too by
        matching on suffixes."""
        mod_part, _, fn_part = dotted.rpartition(".")
        if not mod_part:
            return None
        for mname, mod in self.modules.items():
            if mname == mod_part or mod_part.endswith("." + mname):
                if fn_part in mod.top_defs:
                    return f"{mname}::{mod.top_defs[fn_part]}"
        return None

    # -- traced / dispatcher sets -------------------------------------------

    def jit_roots(self) -> set[str]:
        """jit-decorated functions plus functions dispatched through
        ``map_reduce`` (the MRTask substrate traces its map_fn)."""
        roots = {k for k, f in self.functions.items() if f.is_jit_root}
        for key, fn in self.functions.items():
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    nm = call_name(node)
                    if nm and nm.split(".")[-1] == "map_reduce" and node.args:
                        arg = node.args[0]
                        if isinstance(arg, ast.Name):
                            tgt = self.resolve_call(fn, ast.Call(
                                func=arg, args=[], keywords=[]))
                            if tgt:
                                roots.add(tgt)
        return roots

    def call_edges(self) -> dict[str, set[str]]:
        """Package-local call graph; memoized — the AST walk + call
        resolution dominates lint wall time and both traced_functions()
        and dispatchers() need it."""
        if self._edges is not None:
            return self._edges
        edges: dict[str, set[str]] = {}
        for key, fn in self.functions.items():
            out: set[str] = set()
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    tgt = self.resolve_call(fn, node)
                    if tgt and tgt != key:
                        out.add(tgt)
            edges[key] = out
        self._edges = edges
        return edges

    def traced_functions(self) -> set[str]:
        """Functions whose bodies run under a jax trace: jit roots, their
        nested defs, and everything reachable through package-local calls."""
        edges = self.call_edges()
        nested: dict[str, list[str]] = {}
        for key in self.functions:
            mod, _, qual = key.partition("::")
            parent = self.functions[key].parent
            if parent:
                nested.setdefault(f"{mod}::{parent}", []).append(key)
        seen: set[str] = set()
        work = list(self.jit_roots())
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(edges.get(cur, ()))
            work.extend(nested.get(cur, ()))
        return seen

    def dispatchers(self, traced: set[str] | None = None) -> set[str]:
        """Non-traced functions from which a jit root is reachable — the
        host-side drivers whose loops pay per-iteration dispatch latency."""
        traced = self.traced_functions() if traced is None else traced
        edges = self.call_edges()
        roots = self.jit_roots()
        # reverse-reachability from roots
        rev: dict[str, set[str]] = {}
        for src, outs in edges.items():
            for dst in outs:
                rev.setdefault(dst, set()).add(src)
        seen: set[str] = set()
        work = list(roots)
        while work:
            cur = work.pop()
            for caller in rev.get(cur, ()):
                if caller not in seen:
                    seen.add(caller)
                    work.append(caller)
        return (seen | roots) - (traced - roots)
