"""graftlint — AST-based static analysis for the h2o3_tpu runtime.

Three rule families guard the invariants this codebase lives and dies by
(see docs/STATIC_ANALYSIS.md for the full catalog):

- **tracer-safety** (``TRC``): no implicit device→host syncs or trace
  breaks inside jit-traced code, and no un-batched per-iteration
  ``device_get`` in host convergence loops that dispatch jitted programs
  (the TensorFlow paper's "unintended host round-trips in the hot path").
- **lock-discipline** (``LCK``): an attribute mutated under a lock
  anywhere must be mutated under that lock everywhere; thread-shared
  classes must not mutate state unlocked; module singletons' private
  state is owned by their class, not by callers.
- **REST-surface** (``RST``): every registered route has a handler of
  matching arity producing a schema-typed reply, and every client
  accessor targets a registered route.

Run ``python -m h2o3_tpu.tools.lint`` (or the ``lint`` console script).
"""
