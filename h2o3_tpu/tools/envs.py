"""graftlint env-discipline rule (ENV) — import-time capture of tunables.

``H2O3TPU_*`` environment variables are the package's runtime tunables:
batch windows, SLO targets, budgets, retry counts. A module-level read —
``WINDOW_S = float(os.environ.get("H2O3TPU_SCORE_WINDOW_MS", ...))`` —
freezes the value at IMPORT time, so anything that sets the variable
after the first import is silently ignored: ``monkeypatch.setenv`` in
tests, a launcher exporting config before calling ``serve()``, a bench
scenario tuning a knob between runs. That is exactly the bug ISSUE 13's
batcher satellite fixed (the fixed scoring window could never be changed
once ``serving.batcher`` was imported).

- **ENV001** — a read of an ``H2O3TPU_*`` variable (``os.environ.get``,
  ``os.getenv``, ``os.environ[...]``) in code that executes at import
  time: module level, a class body, a decorator, or a function
  DEFAULT (defaults evaluate at ``def`` time). Reads inside function
  bodies resolve per call and are fine — that is the fix shape: a
  ``*_from_env()`` helper called at construction/use time. Deliberate
  one-shot captures carry an inline ``# graftlint: ok(<reason>)``.

Pre-existing sites ship warn-only in the baseline
(``tools/baseline.json``) — new ones fail the run.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, dotted_name

#: env-read call forms (dotted receiver suffixes)
_GET_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
#: env-read subscript receivers
_SUBSCRIPTS = {"os.environ", "environ"}


def _env_name(node: ast.AST) -> str | None:
    """The H2O3TPU_* variable a Call/Subscript reads, or None."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name not in _GET_CALLS or not node.args:
            return None
        key = node.args[0]
    elif isinstance(node, ast.Subscript):
        if dotted_name(node.value) not in _SUBSCRIPTS:
            return None
        key = node.slice
    else:
        return None
    if isinstance(key, ast.Constant) and isinstance(key.value, str) \
            and key.value.startswith("H2O3TPU_"):
        return key.value
    return None


def _runtime_nodes(tree: ast.Module) -> set[int]:
    """ids of nodes that execute at CALL time, not import time: function
    and lambda BODIES. Defaults and decorators stay import-time — they
    evaluate when the ``def`` executes."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    out.add(id(sub))
        elif isinstance(node, ast.Lambda):
            for sub in ast.walk(node.body):
                out.add(id(sub))
    return out


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        runtime = _runtime_nodes(mod.tree)
        for node in ast.walk(mod.tree):
            if id(node) in runtime:
                continue
            var = _env_name(node)
            if var is None:
                continue
            findings.append(Finding(
                "ENV001", mod.path, node.lineno, "",
                f"`{var}` read at import time — the value freezes before "
                "late env changes (tests' monkeypatch.setenv, launcher "
                "exports) can land; resolve it at construction/call time "
                "via a *_from_env() helper",
                detail=f"import-time-env:{var}"))
    return findings
