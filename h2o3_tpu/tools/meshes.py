"""graftlint mesh-discipline rules (MSH) — stale-mesh hazards in builders.

- **MSH001** — a direct ``get_mesh()`` call inside builder hot paths
  (modules under ``models/``). Mesh resolution is two-level
  (``parallel/mesh.py``): ``get_mesh()`` answers from the *context* — the
  bound slice of the build that happens to be running — so a builder that
  grabs it mid-build can (a) bake a mesh into a jit trace that the compile
  cache later serves to a build bound to a DIFFERENT slice (the
  ``tree.py:hist_mesh`` stale-mesh bug class: shard_map bakes its mesh in
  at trace time), or (b) resolve a foreign thread's mesh when called from
  a helper outside the lease. Builder code must take the mesh from its
  INPUT sharding (the ``hist_mesh`` pattern — the data already knows where
  it lives) or receive it as an explicit argument threaded from the
  slice-bound frame. Intentional sites carry an inline
  ``# graftlint: ok(<reason>)`` suppression like every other rule family.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, call_name

#: package-relative directory whose modules are builder hot paths
BUILDER_DIRS = ("models",)


def _in_builder_dir(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(d in parts[:-1] for d in BUILDER_DIRS)


def _is_get_mesh_call(node: ast.Call) -> bool:
    """Both spellings: bare ``get_mesh()`` (from-import) and the attribute
    form ``mesh.get_mesh()`` / ``parallel.mesh.get_mesh()``."""
    name = call_name(node)
    return bool(name) and name.split(".")[-1] == "get_mesh"


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        if not _in_builder_dir(mod.path):
            continue
        qual_of: dict[int, str] = {}
        for fn in sorted((f for f in index.functions.values()
                          if f.module is mod),
                         key=lambda f: f.node.lineno):
            for sub in ast.walk(fn.node):
                qual_of[id(sub)] = fn.qualname
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_get_mesh_call(node):
                findings.append(Finding(
                    "MSH001", mod.path, node.lineno,
                    qual_of.get(id(node), ""),
                    "direct `get_mesh()` in a builder hot path — the mesh "
                    "must come from the input arrays' sharding (the "
                    "tree.py:hist_mesh pattern) or an explicit argument; a "
                    "context lookup here can bake a stale/foreign slice "
                    "into a compiled program",
                    detail="get_mesh"))
    return findings
