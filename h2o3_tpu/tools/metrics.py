"""graftlint metric-documentation rule (MTR) — metric/doc drift.

``docs/OBSERVABILITY.md``'s metric catalog is the operator contract: an
alert, a dashboard, or a capacity review starts from that table, not from
grepping the source. Every PR so far has added ``h2o3_*`` instruments and
(manually) their doc rows — MTR001 makes the drift structural instead of
reviewed:

- **MTR001** — a metric family registered in code (a ``counter`` /
  ``gauge`` / ``histogram`` call whose literal name starts ``h2o3_``) has
  no row in ``docs/OBSERVABILITY.md``. Counters match with or without the
  OpenMetrics ``_total`` suffix the doc rows use. One finding per metric
  NAME (the first registration site), not per call site — a shared lazy
  registration (``h2o3_telemetry_rejected``) is one contract, not N.

The doc file is looked up next to the scanned package root
(``<root>/docs/OBSERVABILITY.md`` or ``<root>/../docs/OBSERVABILITY.md``
— the repo layout puts ``docs/`` beside ``h2o3_tpu/``). A tree with no
doc file produces no findings: there is nothing to be in drift *with*
(fixture packages opt in by shipping a doc file).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from h2o3_tpu.tools.core import Finding, PackageIndex

#: registry factory methods whose first literal argument names a family
_REG_METHODS = {"counter", "gauge", "histogram"}

#: the documentation file metric rows live in
DOC_NAME = "OBSERVABILITY.md"


def _metric_name(node: ast.AST) -> str | None:
    """The ``h2o3_*`` family name a registration call declares, or None."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REG_METHODS and node.args):
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str) \
            and first.value.startswith("h2o3_"):
        return first.value
    return None


def find_doc(root: Path) -> Path | None:
    for base in (Path(root), Path(root).parent):
        cand = base / "docs" / DOC_NAME
        if cand.is_file():
            return cand
    return None


def check(index: PackageIndex) -> list[Finding]:
    doc = find_doc(index.root)
    if doc is None:
        return []
    # only CATALOG ROWS satisfy the rule — a prose mention elsewhere in
    # the doc ("unlike `h2o3_foo`, this gauge…") is not the name/type/
    # labels/meaning contract the rule enforces
    text = "\n".join(ln for ln in doc.read_text().splitlines()
                     if ln.lstrip().startswith("|"))
    findings: list[Finding] = []
    seen: set[str] = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            name = _metric_name(node)
            if name is None or name in seen:
                continue
            seen.add(name)
            # counters are documented in exposition form (name_total);
            # gauges/histograms by their family name — accept either
            if re.search(rf"\b{re.escape(name)}(?:_total)?\b", text):
                continue
            findings.append(Finding(
                "MTR001", mod.path, node.lineno, "",
                f"metric `{name}` is registered here but has no row in "
                f"docs/{DOC_NAME} — the metric catalog is the operator "
                "contract; add a row (name, type, labels, meaning) or "
                "suppress with a reason",
                detail=f"undocumented-metric:{name}"))
    return findings
