"""graftlint memory rule (MEM) — silent host copies of device arrays.

- **MEM001** — an ``np.asarray``/``np.array`` call over a device (jax)
  value inside a ``timed_event``-wrapped **hot loop** (the call sits under
  both a ``for``/``while`` loop and a ``with timed_event(...)`` block, in
  either nesting order). Each such call materializes a full host copy of
  the device buffer *per iteration* — the array then exists twice (HBM +
  host RSS), a silent 2× memory cost in exactly the loops the memory meter
  watches (``h2o3_iteration_seconds`` call sites). Fetch once outside the
  loop, batch the transfer (``jax.device_get`` of a tuple), or keep the
  computation on-device.

The deviceish-argument test mirrors the tracer family's taint rules: the
argument mentions a jax/jnp/lax name, reads a ``.data`` buffer (a Vec's
device chunk) or ``.as_float()``/``.matrix()`` device views, or names a
variable assigned from such an expression in the same function.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import (Finding, FunctionInfo, PackageIndex,
                                 call_name)
from h2o3_tpu.tools.tracer import _mentions_jax, _NP_SYNC

#: attribute reads that yield device buffers/views on framework objects
_DEVICE_ATTRS = {"data"}
_DEVICE_METHODS = {"as_float", "matrix"}


def _deviceish_expr(node: ast.AST, tainted: set[str]) -> bool:
    if _mentions_jax(node):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _DEVICE_ATTRS:
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _DEVICE_METHODS:
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
    return False


def _device_tainted_names(fn: ast.AST) -> set[str]:
    """Names assigned from deviceish expressions — one forward pass,
    transitive through names (the tracer family's taint discipline)."""
    assigned: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is None:
                continue
            if not _deviceish_expr(value, assigned):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        assigned.add(sub.id)
    return assigned


def _already_host(node: ast.AST) -> bool:
    """``np.asarray(jax.device_get(x))`` wraps a value that is ALREADY on
    host — the transfer is explicit and the asarray is zero-copy. That
    pattern is TRC003's business (sync placement), not a silent 2× copy."""
    if isinstance(node, ast.Call):
        nm = call_name(node)
        if nm and nm.split(".")[-1] in ("device_get", "to_numpy", "fetch"):
            return True
    return False


def _is_timed_event_with(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        call = item.context_expr
        if isinstance(call, ast.Call):
            nm = call_name(call)
            if nm and nm.split(".")[-1] == "timed_event":
                return True
    return False


def _check_fn(info: FunctionInfo, findings: list[Finding]) -> None:
    fn = info.node
    tainted = _device_tainted_names(fn)

    def flag(call: ast.Call) -> None:
        nm = call_name(call)
        if nm in _NP_SYNC and call.args and \
                not _already_host(call.args[0]) and \
                _deviceish_expr(call.args[0], tainted):
            findings.append(Finding(
                "MEM001", info.module.path, call.lineno, info.qualname,
                f"`{nm}` copies a device array to host inside a "
                "timed_event-wrapped hot loop — the buffer exists "
                "twice (HBM + host RSS) every iteration; hoist the "
                "fetch out of the loop or batch it into one "
                "device_get", detail=nm))

    def visit(node: ast.AST, in_loop: bool, in_timed: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not fn:
            return           # nested defs get their own FunctionInfo pass
        if in_loop and in_timed and isinstance(node, ast.Call):
            flag(node)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # the iter expression runs ONCE per loop entry — the
            # recommended hoisted-fetch form must not re-flag
            visit(node.target, in_loop, in_timed)
            visit(node.iter, in_loop, in_timed)
            for stmt in node.body + node.orelse:
                visit(stmt, True, in_timed)
            return
        if isinstance(node, ast.While):
            # unlike a For header, the While test re-runs every iteration
            visit(node.test, True, in_timed)
            for stmt in node.body + node.orelse:
                visit(stmt, True, in_timed)
            return
        timed = in_timed or _is_timed_event_with(node)
        for child in ast.iter_child_nodes(node):
            visit(child, in_loop, timed)

    visit(fn, False, False)


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for info in index.functions.values():
        _check_fn(info, findings)
    return findings
