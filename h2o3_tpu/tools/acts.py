"""graftlint remediation-audit rule (ACT) — unaudited ops-plane mutations.

The remediation engine's whole safety story is the append-only ActionLog:
every change it makes to live policy (replica counts, admission targets,
Cleaner budgets, shard ownership, compile-bucket pins) is recorded with
its trigger incident, parameters, outcome, and rollback token — that is
what lets an operator audit "what did the machine do and why" and undo
it. The contract holds only if ops-plane code CANNOT reach a policy
setter except through a catalogued ``act_*`` function executed by
``ActionLog.record``.

- **ACT001** — inside ``ops_plane/`` modules, a call to a live-policy
  setter (``configure_replicas``, ``widen_admission``/``restore_admission``,
  ``set_target``, ``enable_cleaner``/``disable_cleaner``, ``force_spill``,
  ``preempt_reassign``, ``request_join``, ``eject``, ``pin_bucket``/
  ``unpin_bucket``) or an assignment to a ``.budget`` attribute, from a
  function NOT rooted in a top-level ``act_*`` catalog function; also a
  direct call to an ``act_*`` function from anywhere but ``ActionLog``
  (bypassing the audit record). Rollback closures nested inside an
  ``act_*`` body are fine — their audit trail is the recording action's.

The rule scopes to ``ops_plane/`` on purpose: the setters themselves live
in serving/elastic/memory modules and are legitimate API for tests, the
REST layer, and operators — only the *automation* must be audited.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import (Finding, FunctionInfo, ModuleInfo,
                                 PackageIndex, dotted_name)

#: live-policy setters the engine may only touch through the catalog
_POLICY_SETTERS = {
    "configure_replicas", "widen_admission", "restore_admission",
    "set_target", "enable_cleaner", "disable_cleaner", "force_spill",
    "preempt_reassign", "request_join", "eject", "pin_bucket",
    "unpin_bucket",
}
#: attribute stores that ARE policy mutations (Cleaner.budget)
_POLICY_ATTRS = {"budget"}


def _owners(index: PackageIndex, mod: ModuleInfo) -> dict[int, FunctionInfo]:
    """node id -> innermost enclosing FunctionInfo. Parents are painted
    first, nested defs overwrite — innermost wins. Lambda bodies map to
    the function the lambda sits in (they have no FunctionInfo), which is
    exactly the audit scope they execute under."""
    fns = [f for f in index.functions.values() if f.module is mod]

    def depth(fn: FunctionInfo) -> int:
        d, cur = 0, fn
        while cur is not None and cur.parent:
            cur = index.functions.get(f"{mod.name}::{cur.parent}")
            d += 1
        return d

    out: dict[int, FunctionInfo] = {}
    for fn in sorted(fns, key=depth):
        for node in ast.walk(fn.node):
            out[id(node)] = fn
    return out


def _rooted_in_act(index: PackageIndex, mod: ModuleInfo,
                   fn: FunctionInfo | None) -> bool:
    """True when ``fn``'s outermost enclosing def is a top-level ``act_*``
    catalog function — the only scope allowed to mutate live policy."""
    cur = fn
    while cur is not None and cur.parent:
        cur = index.functions.get(f"{mod.name}::{cur.parent}")
    return (cur is not None and cur.class_name is None
            and cur.qualname.startswith("act_"))


def _in_action_log(index: PackageIndex, mod: ModuleInfo,
                   fn: FunctionInfo | None) -> bool:
    cur = fn
    while cur is not None and cur.parent:
        cur = index.functions.get(f"{mod.name}::{cur.parent}")
    return cur is not None and cur.class_name == "ActionLog"


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        if "ops_plane/" not in mod.path and not \
                mod.path.startswith("ops_plane"):
            continue
        owners = _owners(index, mod)
        for node in ast.walk(mod.tree):
            fn = owners.get(id(node))
            where = fn.qualname if fn else ""
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                leaf = name.rpartition(".")[2]
                if leaf in _POLICY_SETTERS and not _rooted_in_act(
                        index, mod, fn):
                    findings.append(Finding(
                        "ACT001", mod.path, node.lineno, where,
                        f"ops-plane call to live-policy setter `{name}` "
                        "outside an act_* catalog function — policy "
                        "mutations must flow through ActionLog.record so "
                        "they are audited and rollback-able",
                        detail=f"unaudited-mutation:{leaf}"))
                elif leaf.startswith("act_") and not _in_action_log(
                        index, mod, fn):
                    findings.append(Finding(
                        "ACT001", mod.path, node.lineno, where,
                        f"direct call to catalog action `{name}` bypasses "
                        "ActionLog.record — no audit entry, no rollback "
                        "token, no metric; record it via "
                        "ActionLog.record(action, rule, incident_id, mode)",
                        detail=f"direct-action-call:{leaf}"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    # self.budget is an object's OWN state (a dataclass
                    # field, an exception attribute) — the policy
                    # mutation is a store through a FOREIGN receiver
                    # (cleaner.budget = ...)
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr in _POLICY_ATTRS and not \
                            (isinstance(tgt.value, ast.Name)
                             and tgt.value.id == "self") and not \
                            _rooted_in_act(index, mod, fn):
                        findings.append(Finding(
                            "ACT001", mod.path, node.lineno, where,
                            f"ops-plane store to `.{tgt.attr}` outside an "
                            "act_* catalog function — budget changes are "
                            "live-policy mutations and must be audited "
                            "through ActionLog.record",
                            detail=f"unaudited-mutation:.{tgt.attr}"))
    return findings
