"""graftlint wait-discipline rule (WTX) — unbounded blocking waits.

Thread-coordination waits with no timeout are the deadlock class a dead
participant turns fatal: a worker that crashes (or is ejected by the
elastic membership layer, docs/RELIABILITY.md) between taking a resource
and notifying its condition leaves every `Condition.wait()` /
`Event.wait()` / `Queue.get()` parked FOREVER — no recheck, no recovery,
a wedged process. The fix shape is a bounded wait in a predicate-recheck
loop: ``while not pred: cv.wait(timeout=1.0)`` costs one spurious wakeup
a second and can never park past a lost notify.

- **WTX001** — a ``.wait()`` call with no positional argument and no
  ``timeout=`` keyword (``Condition``/``Event`` style), or a ``.get()``
  call with no arguments and no ``timeout=``/``block=False`` on a
  queue-named receiver (the name contains ``queue``/``inbox`` or is
  ``q``). ``ContextVar.get()``/``dict.get(key)`` are not flagged: the
  former's receiver is never queue-named, the latter always has an
  argument. Deliberate forever-waits (a serve-forever main) carry an
  inline ``# graftlint: ok(<reason>)`` suppression like every other rule.
"""

from __future__ import annotations

import ast
import re

from h2o3_tpu.tools.core import Finding, PackageIndex

#: receiver names that mark a zero-arg ``.get()`` as a blocking queue read
_QUEUEISH = re.compile(r"(^|_)(q|queue|inbox|mailbox|work_?items?)$",
                       re.IGNORECASE)


def _recv_name(func: ast.Attribute) -> str:
    """Rightmost name of the receiver expression (``self._cond.wait`` →
    ``_cond``; ``q.get`` → ``q``)."""
    v = func.value
    if isinstance(v, ast.Attribute):
        return v.attr
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Call):
        # constructed-inline receiver: threading.Event().wait()
        f = v.func
        return (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else "")
    return ""


def _has_kw(node: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in node.keywords)


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        qual_of: dict[int, str] = {}
        for fn in sorted((f for f in index.functions.values()
                          if f.module is mod),
                         key=lambda f: f.node.lineno):
            for sub in ast.walk(fn.node):
                qual_of[id(sub)] = fn.qualname
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            if meth == "wait":
                if node.args or _has_kw(node, "timeout"):
                    continue
                findings.append(Finding(
                    "WTX001", mod.path, node.lineno,
                    qual_of.get(id(node), ""),
                    "unbounded `.wait()` — a dead notifier parks this "
                    "thread forever; wait with a timeout inside a "
                    "predicate-recheck loop "
                    "(`while not pred: cv.wait(timeout=...)`)",
                    detail="unbounded-wait"))
            elif meth == "get":
                if node.args or _has_kw(node, "timeout", "block"):
                    continue
                if not _QUEUEISH.search(_recv_name(node.func)):
                    continue
                findings.append(Finding(
                    "WTX001", mod.path, node.lineno,
                    qual_of.get(id(node), ""),
                    "unbounded `Queue.get()` — a dead producer parks this "
                    "thread forever; poll with `get(timeout=...)` and "
                    "recheck the stop condition",
                    detail="unbounded-queue-get"))
    return findings
