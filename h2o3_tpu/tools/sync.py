"""graftlint sync-discipline rules (SYN) — blocking device syncs in library code.

- **SYN001** — ``jax.block_until_ready(...)`` / ``x.block_until_ready()``
  outside the telemetry/tracing/timeline modules. JAX dispatch is async by
  design: a blocking sync in library code serializes the device pipeline
  and makes the host the clock (the exact pattern ISSUE 7 removed from the
  ``map_reduce`` dispatch path). Measurement probes belong in the telemetry
  modules — or, where the sync IS the measurement (a sampled duration
  probe, a latency endpoint), carry an inline
  ``# graftlint: ok(<reason>)`` suppression like every other rule family.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, call_name

#: module basenames whose whole purpose is timing/observability — the sync
#: there IS the product (matched on basename so fixture packages can opt a
#: file into the exemption the same way the live package does)
EXEMPT_BASENAMES = {"telemetry.py", "tracing.py", "timeline.py"}


def _is_block_call(node: ast.Call) -> bool:
    """Both spellings: ``jax.block_until_ready(x)`` and the method form
    ``x.block_until_ready()``."""
    name = call_name(node)
    if name and name.split(".")[-1] == "block_until_ready":
        return True
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready")


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        base = mod.path.rsplit("/", 1)[-1]
        if base in EXEMPT_BASENAMES:
            continue
        # one walk per module: the rule is purely syntactic (no call-graph),
        # so function scoping only matters for the `where` attribution.
        # Walk outer functions first (lower lineno) so nested defs overwrite
        # their parents' claim — the innermost enclosing function wins.
        qual_of: dict[int, str] = {}
        for fn in sorted((f for f in index.functions.values()
                          if f.module is mod),
                         key=lambda f: f.node.lineno):
            for sub in ast.walk(fn.node):
                qual_of[id(sub)] = fn.qualname
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_block_call(node):
                findings.append(Finding(
                    "SYN001", mod.path, node.lineno,
                    qual_of.get(id(node), ""),
                    "blocking `block_until_ready` in library code — JAX "
                    "dispatch is async; a sync here serializes the device "
                    "pipeline (move the probe into telemetry/tracing or "
                    "suppress with a reason)",
                    detail="block_until_ready"))
    return findings
