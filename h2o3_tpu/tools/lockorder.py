"""graftlint DLK — whole-program lock-order analysis.

The remaining deadlock class after WTX (bounded waits) is inconsistent
lock *acquisition order* across threads.  This module inventories every
lock in the package, computes the "acquired-while-held" edge set via
interprocedural call-graph reachability, and reports:

- **DLK001** — cycle in the lock-order graph (potential deadlock); the
  finding carries the full cycle path with one evidence site per hop.
- **DLK002** — blocking operation (``Event.wait``/``Condition.wait`` on a
  lock other than the one held, blocking ``queue.get``, socket/HTTP
  calls, ``block_until_ready``/``device_get``, ``time.sleep``) reachable
  while a lock is held — the lock-held-across-dispatch class.
- **DLK003** — user-supplied callback/listener invoked while a lock is
  held: arbitrary user code can re-enter the runtime and acquire in the
  wrong order, and the stall is unbounded.

Lock identity
-------------
Stable, line-number-free, shared with the runtime witness
(``h2o3_tpu.utils.lockwitness``):

- instance or class-attribute locks: ``<module>.<Class>.<attr>``
  (e.g. ``utils.cleaner.Cleaner._io_lock``);
- module-level locks: ``<module>.<NAME>`` (e.g. ``native._LOCK``);
- a string literal passed to a ``lockwitness`` factory wins outright, so
  static identity and witnessed identity agree by construction;
- ``threading.Condition(existing_lock)`` aliases the condition to the
  underlying lock — acquiring either is one identity.

Like every graftlint family this is pure stdlib AST work; the code under
analysis is never imported.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from h2o3_tpu.tools.core import (Finding, FunctionInfo, PackageIndex,
                                 call_name, dotted_name)

#: attribute/variable names accepted as locks under the naming contract
#: even when the creation site wasn't seen (mirrors LCK001).
_LOCKISH = re.compile(r"lock|cond|mutex|_mu$|sem", re.IGNORECASE)

#: collections/parameters holding user-supplied code (DLK003).  The
#: ``(^|[._])`` boundary keeps e.g. ``admission_base`` from matching
#: ``on_`` mid-word (``_`` is a word char, so ``\b`` can't do this).
_CALLBACKISH = re.compile(
    r"(^|[._])(listeners?|callbacks?|hooks?|subscribers?|observers?"
    r"|on_[a-z]\w*)", re.IGNORECASE)

#: attribute calls that *manage* a callback collection rather than invoke
#: user code — ``self._listeners.append(cb)`` is registration, not a call.
_CB_MGMT = re.compile(r"^(add|remove|register|unregister|set|clear|del"
                      r"|emit)_|^(append|remove|clear|discard|add|pop"
                      r"|extend|insert|update|setdefault|get|index|count"
                      r"|copy|items|keys|values)$", re.IGNORECASE)

#: queue-like receiver names whose blocking ``.get`` stalls the holder
#: (same contract as WTX).
_QUEUEISH = re.compile(r"(^|_)(q|queue|inbox|mailbox|work_?items?)$",
                       re.IGNORECASE)

_SOCKETISH_ATTRS = {"recv", "recv_into", "accept", "sendall", "getresponse"}
_BLOCKING_TAILS = {"urlopen": "urlopen", "block_until_ready":
                   "block_until_ready", "device_get": "device_get",
                   "sleep": "sleep"}

#: method names too common for the unique-owner call-resolution fallback —
#: they appear constantly on stdlib/third-party objects, so a single
#: package-local definition is no evidence the call lands there.
_COMMON_METHODS = {
    "get", "put", "pop", "append", "add", "update", "items", "keys",
    "values", "join", "start", "run", "close", "read", "write", "clear",
    "remove", "copy", "send", "recv", "release", "acquire", "wait",
    "notify", "notify_all", "flush", "stop", "reset", "submit", "result",
    "register", "encode", "decode", "strip", "split", "format", "sort",
    "extend", "insert", "index", "count", "open", "seek", "tell", "exists",
    "mkdir", "unlink", "lower", "upper", "replace", "match", "search",
    "group", "setdefault", "discard", "name", "sample", "snapshot",
}

_FACTORY_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
                  "lock": "lock", "rlock": "rlock", "condition": "condition"}


@dataclasses.dataclass(frozen=True)
class LockSite:
    ident: str      # canonical identity (see module docstring)
    kind: str       # lock | rlock | condition
    path: str       # creation-site file (posix relpath)
    line: int


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    path: str       # evidence: where dst was first seen acquired under src
    line: int
    where: str
    via: str        # "" for a direct nested acquisition, else the callee


def _factory_kind(mod, call: ast.Call) -> str | None:
    """``threading.Lock()`` / ``lockwitness.rlock("...")`` -> kind."""
    name = call_name(call)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    tail = name.rsplit(".", 1)[-1]
    kind = _FACTORY_KINDS.get(tail)
    if kind is None:
        return None
    src = mod.imports.get(head, head)
    if rest:  # dotted: threading.Lock / lockwitness.lock
        base = src
    else:     # bare: from threading import Lock / from ..lockwitness import lock
        base = src.rsplit(".", 1)[0] if "." in src else src
    if base == "threading" or base.split(".")[-1] == "lockwitness":
        return kind
    return None


def _literal_name(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _condition_source(call: ast.Call) -> ast.expr | None:
    """The underlying-lock expression of ``Condition(lock)`` /
    ``lockwitness.condition(name, lock=...)``, if any."""
    for kw in call.keywords:
        if kw.arg == "lock":
            return kw.value
    name = call_name(call)
    tail = name.rsplit(".", 1)[-1] if name else ""
    if tail == "Condition" and call.args:
        return call.args[0]
    if tail == "condition" and len(call.args) > 1:
        return call.args[1]
    return None


class LockInventory:
    """Every lock creation site in the package, with canonical identities."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.locks: dict[str, LockSite] = {}
        self._attr: dict[tuple[str, str, str], str] = {}    # (mod,cls,attr)
        self._module: dict[tuple[str, str], str] = {}       # (mod,NAME)
        self._singletons: dict[tuple[str, str], tuple[str, str]] = {}
        self._canon: dict[str, str] = {}                    # alias -> canonical
        # (mod,cls,attr) -> (mod,cls) of the *object* stored there, from
        # `self.x = PackageClass(...)` or an annotated ctor parameter
        self._attr_types: dict[tuple[str, str, str], tuple[str, str]] = {}
        # lowercased class name -> [(mod, cls)] for attr-name type matching
        self._class_by_lname: dict[str, list[tuple[str, str]]] = {}
        self._build()

    # -- canonicalisation ----------------------------------------------------

    def canon(self, ident: str) -> str:
        while ident in self._canon:
            ident = self._canon[ident]
        return ident

    def _union(self, keep: str, alias: str) -> None:
        keep, alias = self.canon(keep), self.canon(alias)
        if keep != alias:
            self._canon[alias] = keep
            self.locks.pop(alias, None)

    # -- construction --------------------------------------------------------

    def _register(self, ident: str, kind: str, path: str, line: int) -> str:
        ident = self.canon(ident)
        if ident not in self.locks:
            self.locks[ident] = LockSite(ident, kind, path, line)
        return ident

    def _build(self) -> None:
        deferred: list[tuple] = []  # condition-alias pass after plain locks
        aliases: list[tuple] = []   # ctor-parameter lock aliases, same idea
        factory_calls: list[tuple] = []  # NAME = SINGLETON.method(...) sites
        for mname, mod in self.index.modules.items():
            for cname in mod.classes:
                self._class_by_lname.setdefault(
                    cname.lower(), []).append((mname, cname))
        for mod in self.index.modules.values():
            for stmt in mod.tree.body:
                tgt, val = _simple_assign(stmt)
                if tgt is None or not isinstance(val, ast.Call):
                    continue
                kind = _factory_kind(mod, val)
                if kind:
                    ident = _literal_name(val) or f"{mod.name}.{tgt}"
                    self._module[(mod.name, tgt)] = self._register(
                        ident, kind, mod.path, stmt.lineno)
                    continue
                cls = self._resolve_class(mod, call_name(val))
                if cls:
                    self._singletons[(mod.name, tgt)] = cls
                    continue
                factory_calls.append((mod, tgt, val))
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.ClassDef):
                    continue
                for sub in stmt.body:
                    tgt, val = _simple_assign(sub)
                    if tgt is None or not isinstance(val, ast.Call):
                        continue
                    kind = _factory_kind(mod, val)
                    if kind:
                        ident = _literal_name(val) or \
                            f"{mod.name}.{stmt.name}.{tgt}"
                        self._attr[(mod.name, stmt.name, tgt)] = \
                            self._register(ident, kind, mod.path, sub.lineno)
        # METRICS.counter("...") and friends, after every plain singleton
        # is known: a module-level name built by a factory *method* is a
        # singleton of whatever package class that method constructs
        # (one-hop return-type inference, `return self._helper(...)`
        # chains included)
        for mod, tgt, val in factory_calls:
            cls = self._factory_method_class(mod, val)
            if cls:
                self._singletons[(mod.name, tgt)] = cls
        # self.X = <factory>() inside methods, in source order so a
        # Condition(self._mu) alias sees the earlier _mu registration
        for key in sorted(self.index.functions):
            fn = self.index.functions[key]
            if not fn.class_name:
                continue
            mod = fn.module
            params = {a.arg: a.annotation for a in _all_args(fn.node)}
            for node in ast.walk(fn.node):
                tgt, val = _self_attr_assign(node)
                if tgt is None:
                    continue
                akey = (mod.name, fn.class_name, tgt)
                if isinstance(val, ast.Call):
                    kind = _factory_kind(mod, val)
                    if kind:
                        ident = _literal_name(val) or \
                            f"{mod.name}.{fn.class_name}.{tgt}"
                        self._attr[akey] = self._register(
                            ident, kind, mod.path, node.lineno)
                        src = _condition_source(val)
                        if src is not None:
                            deferred.append((akey, mod, fn.class_name, src))
                        continue
                    # self.x = PackageClass(...): remember the attr's type
                    owner = self._resolve_class(mod, call_name(val))
                    if owner:
                        self._attr_types[akey] = owner
                    continue
                # self.x = param (annotated): attr type from the annotation
                if isinstance(val, ast.Name) and val.id in params:
                    owner = self._annotation_class(mod, params[val.id])
                    if owner:
                        self._attr_types[akey] = owner
                    continue
                # self._lock = registry._lock (annotated ctor param): the
                # attr ALIASES the other object's lock — one identity
                if isinstance(val, ast.Attribute) \
                        and isinstance(val.value, ast.Name) \
                        and val.value.id in params:
                    owner = self._annotation_class(
                        mod, params[val.value.id])
                    if owner:
                        aliases.append((akey, owner + (val.attr,)))
        for akey, mod, cls, src in deferred:
            under = dotted_name(src)
            if under and under.startswith("self."):
                ukey = (mod.name, cls, under[5:])
                uid = self._attr.get(ukey)
                if uid:
                    # condition wraps an existing lock: one identity, the
                    # condition's name wins (it is the acquisition surface)
                    self._union(self._attr[akey], uid)
                    self._attr[ukey] = self.canon(self._attr[akey])
        for akey, ukey in aliases:
            uid = self._attr.get(ukey)
            if uid and akey not in self._attr:
                self._attr[akey] = self.canon(uid)

    def _resolve_class(self, mod, name: str | None
                       ) -> tuple[str, str] | None:
        """Class name (possibly imported) -> (defining module, class)."""
        if not name or "." in name:
            return None
        if name in mod.classes:
            return (mod.name, name)
        src = mod.imports.get(name)
        if not src:
            return None
        mod_part, _, cls = src.rpartition(".")
        for mname, m in self.index.modules.items():
            if mname == mod_part or mod_part.endswith("." + mname):
                if cls in m.classes:
                    return (mname, cls)
        return None

    def _annotation_class(self, mod, ann) -> tuple[str, str] | None:
        """A parameter annotation -> package class, accepting the quoted
        forward-reference form (``registry: "MetricsRegistry"``)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().rsplit(".", 1)[-1]
        else:
            name = dotted_name(ann)
            if name:
                name = name.rsplit(".", 1)[-1]
        return self._resolve_class(mod, name)

    def _factory_method_class(self, mod, call: ast.Call
                              ) -> tuple[str, str] | None:
        """``SINGLETON.method(...)`` -> the package class that method's
        ``return`` statements construct, if unambiguous."""
        name = call_name(call)
        if name is None or "." not in name:
            return None
        head, _, meth = name.rpartition(".")
        if "." in head:
            return None
        owner = self.resolve_singleton(mod, head)
        if owner is None:
            return None
        return self._returned_class(owner[0], owner[1], meth)

    def _returned_class(self, mname: str, cname: str, meth: str,
                        depth: int = 3) -> tuple[str, str] | None:
        """The unique package class a method returns instances of,
        following ``return self._helper(...)`` one class-local hop at a
        time (bounded)."""
        if depth == 0:
            return None
        mod = self.index.modules.get(mname)
        qual = mod.classes.get(cname, {}).get(meth) if mod else None
        fn = self.index.functions.get(f"{mname}::{qual}") if qual else None
        if fn is None:
            return None
        local_ctor: dict[str, tuple[str, str]] = {}
        for node in ast.walk(fn.node):
            tgt, val = _simple_assign(node)
            if tgt and isinstance(val, ast.Call):
                hit = self._resolve_class(mod, call_name(val))
                if hit:
                    local_ctor[tgt] = hit
        found: set[tuple[str, str]] = set()
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            hit = None
            if isinstance(node.value, ast.Name):
                hit = local_ctor.get(node.value.id)  # fam = _Family(...)
            elif isinstance(node.value, ast.Call):
                name = call_name(node.value)
                hit = self._resolve_class(mod, name)
                if hit is None and name and name.startswith("self.") \
                        and "." not in name[5:]:
                    hit = self._returned_class(mname, cname, name[5:],
                                               depth - 1)
            if hit:
                found.add(hit)
        return found.pop() if len(found) == 1 else None

    # -- use-site resolution -------------------------------------------------

    def resolve_module(self, mod, alias: str) -> str | None:
        """An imported-module alias (``_tm``) -> scanned module name."""
        src = mod.imports.get(alias)
        if not src:
            return None
        for mname in self.index.modules:
            if mname == src or src.endswith("." + mname):
                return mname
        return None

    def resolve_attr_type(self, fn: FunctionInfo,
                          attr: str) -> tuple[str, str] | None:
        """The package class stored in ``self.<attr>``: a recorded
        assignment type if one was seen, else the attr name itself names
        exactly one package class (``self._job`` -> ``Job``)."""
        if fn.class_name:
            hit = self._attr_types.get(
                (fn.module.name, fn.class_name, attr))
            if hit:
                return hit
        owners = self._class_by_lname.get(
            attr.strip("_").replace("_", "").lower(), [])
        return owners[0] if len(owners) == 1 else None

    def resolve_singleton(self, mod, name: str) -> tuple[str, str] | None:
        hit = self._singletons.get((mod.name, name))
        if hit:
            return hit
        src = mod.imports.get(name)
        if not src:
            return None
        mod_part, _, obj = src.rpartition(".")
        for mname in self.index.modules:
            if mname == mod_part or mod_part.endswith("." + mname):
                return self._singletons.get((mname, obj))
        return None

    def resolve_lock_expr(self, fn: FunctionInfo,
                          expr: ast.expr) -> str | None:
        """A ``with``-item / ``.acquire()`` receiver -> canonical identity."""
        mod = fn.module
        name = dotted_name(expr)
        if name is None:
            return None
        if "." not in name:
            ident = self._module.get((mod.name, name))
            if ident:
                return self.canon(ident)
            src = mod.imports.get(name)
            if src:
                mod_part, _, nm = src.rpartition(".")
                for mname in self.index.modules:
                    if mname == mod_part or mod_part.endswith("." + mname):
                        ident = self._module.get((mname, nm))
                        if ident:
                            return self.canon(ident)
            if _LOCKISH.search(name):
                return self._register(f"{mod.name}.{name}", "lock",
                                      mod.path, expr.lineno)
            return None
        head, _, rest = name.partition(".")
        if head == "self" and fn.class_name and "." not in rest:
            ident = self._attr.get((mod.name, fn.class_name, rest))
            if ident:
                return self.canon(ident)
            if _LOCKISH.search(rest):
                return self._register(
                    f"{mod.name}.{fn.class_name}.{rest}", "lock",
                    mod.path, expr.lineno)
            return None
        if head == "self" and fn.class_name and rest.count(".") == 1:
            # with self._job._lock: — another object's lock, typed via the
            # attr's recorded assignment or its name matching one class
            attr, _, sub = rest.partition(".")
            owner = self.resolve_attr_type(fn, attr)
            if owner:
                ident = self._attr.get(owner + (sub,))
                if ident:
                    return self.canon(ident)
            return None
        # SINGLETON._lock (e.g. DKV._lock from another module)
        if "." not in rest:
            owner = self.resolve_singleton(mod, head)
            if owner:
                dmod, dcls = owner
                ident = self._attr.get((dmod, dcls, rest))
                if ident:
                    return self.canon(ident)
                if _LOCKISH.search(rest):
                    site = self.index.modules[dmod]
                    return self._register(f"{dmod}.{dcls}.{rest}", "lock",
                                          site.path, expr.lineno)
        return None


def _simple_assign(stmt) -> tuple[str | None, ast.expr | None]:
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        return stmt.targets[0].id, stmt.value
    if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name) \
            and stmt.value is not None:
        return stmt.target.id, stmt.value
    return None, None


def _self_attr_assign(node) -> tuple[str | None, ast.expr | None]:
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        t = node.targets[0]
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr, node.value
    return None, None


# -- per-function walk -------------------------------------------------------

@dataclasses.dataclass
class _CallSite:
    caller: str          # mod::qual
    target: str          # mod::qual
    held: tuple[str, ...]
    line: int
    via: str             # rendered callee name for messages


@dataclasses.dataclass
class _BlockingOp:
    slug: str
    line: int
    local_exempt: bool   # cond.wait on a lock held *locally* (legal pattern)
    held: str | None     # innermost lock held at the op, if any


class _FunctionFacts:
    """Everything DLK needs from one function body."""

    def __init__(self) -> None:
        self.acquires: list[tuple[str, int]] = []
        self.edges: list[Edge] = []
        self.blocking: list[_BlockingOp] = []
        self.callsites: list[_CallSite] = []
        self.callbacks: list[tuple[str, str, int]] = []  # (held, desc, line)
        self.yield_held: set[str] = set()
        self.return_calls: list[str] = []  # resolved targets of `return f()`


class LockOrderGraph:
    """Static lock-order graph for one scanned package."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.inventory = LockInventory(index)
        self.facts: dict[str, _FunctionFacts] = {}
        self.edges: dict[tuple[str, str], Edge] = {}
        self._reach_acq: dict[str, set[str]] = {}
        self._reach_blk: dict[str, dict[str, tuple[str, int, str]]] = {}
        self._dlk_edges: dict[str, set[str]] = {}
        self._yield_memo: dict[str, set[str]] = {}
        self._build()

    # -- call resolution (superset of PackageIndex.resolve_call) -------------

    def _method_owners(self) -> dict[str, list[str]]:
        owners: dict[str, list[str]] = {}
        for key, fn in self.index.functions.items():
            if fn.class_name and not fn.parent:
                owners.setdefault(fn.node.name, []).append(key)
        return owners

    def _resolve_call(self, fn: FunctionInfo, call: ast.Call,
                      key: str) -> str | None:
        tgt = self.index.resolve_call(fn, call)
        if tgt:
            return tgt
        name = call_name(call)
        if name is None or "." not in name:
            return None
        head, _, rest = name.partition(".")
        owner = None
        if head != "self" and "." not in rest:
            owner = self.inventory.resolve_singleton(fn.module, head)
            meth = rest
        elif rest.count(".") == 1:
            mid, _, meth = rest.partition(".")
            if head == "self":
                # self._job.cancel() — receiver typed via the attr
                owner = self.inventory.resolve_attr_type(fn, mid)
            else:
                # _tm.DKV_PUTS.inc() — singleton through a module alias
                mname = self.inventory.resolve_module(fn.module, head)
                if mname:
                    owner = self.inventory._singletons.get((mname, mid))
        if owner:
            dmod, dcls = owner
            qual = self.index.modules[dmod].classes.get(dcls, {}).get(meth)
            if qual:
                return f"{dmod}::{qual}"
        # unique-owner fallback: obj.meth() where exactly one class in the
        # package defines meth and the name isn't ubiquitous — keeps the
        # static graph a superset of what the runtime witness can observe
        meth = name.rsplit(".", 1)[-1]
        if meth not in _COMMON_METHODS:
            owners = self._owners.get(meth, [])
            if len(owners) == 1 and owners[0] != key:
                return owners[0]
        return None

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        self._owners = self._method_owners()
        for key in sorted(self.index.functions):
            self.facts[key] = self._walk_function(key)
        self._dlk_edges = {
            key: {cs.target for cs in f.callsites}
            for key, f in self.facts.items()
        }
        self._close_summaries()
        self._add_interprocedural_edges()

    def _walk_function(self, key: str) -> _FunctionFacts:
        fn = self.index.functions[key]
        facts = _FunctionFacts()
        cbvars: set[str] = {
            a.arg for a in _all_args(fn.node) if _CALLBACKISH.search(a.arg)}
        held: list[str] = []

        def emit_acquire(ident: str, line: int) -> None:
            facts.acquires.append((ident, line))
            if ident in held:
                return  # reentrant (RLock) — no ordering edge
            for h in held:
                facts.edges.append(Edge(h, ident, fn.module.path, line,
                                        fn.qualname, ""))

        def classify_call(call: ast.Call) -> None:
            name = call_name(call)
            tail = name.rsplit(".", 1)[-1] if name else None
            # lock method calls
            if tail in ("acquire", "release") and isinstance(
                    call.func, ast.Attribute):
                ident = self.inventory.resolve_lock_expr(fn, call.func.value)
                if ident:
                    if tail == "acquire":
                        emit_acquire(ident, call.lineno)
                        held.append(ident)
                    elif ident in held:
                        held.reverse(); held.remove(ident); held.reverse()
                    return
            # blocking operations
            slug = self._blocking_slug(fn, call, tail)
            if slug:
                exempt = False
                if slug == "cond-wait":
                    ident = self.inventory.resolve_lock_expr(
                        fn, call.func.value)
                    exempt = ident is not None and ident in held
                facts.blocking.append(
                    _BlockingOp(slug, call.lineno, exempt,
                                held[-1] if held else None))
            # user-supplied callback invocation
            desc = self._callback_desc(call, cbvars)
            if desc and held:
                facts.callbacks.append((held[-1], desc, call.lineno))
            # package-local call site
            tgt = self._resolve_call(fn, call, key)
            if tgt and tgt != key:
                facts.callsites.append(_CallSite(
                    key, tgt, tuple(held), call.lineno, name or "?"))

        def visit_expr(node: ast.AST) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    classify_call(sub)
                elif isinstance(sub, (ast.Yield, ast.YieldFrom)):
                    facts.yield_held.update(held)

        def walk_block(stmts: list[ast.stmt]) -> None:
            for st in stmts:
                walk_stmt(st)

        def walk_stmt(st: ast.stmt) -> None:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return  # separate unit; reached via the call graph
            if isinstance(st, (ast.With, ast.AsyncWith)):
                depth = len(held)
                for item in st.items:
                    ctx = item.context_expr
                    ident = None
                    if isinstance(ctx, (ast.Name, ast.Attribute)):
                        ident = self.inventory.resolve_lock_expr(fn, ctx)
                    if ident:
                        emit_acquire(ident, ctx.lineno)
                        held.append(ident)
                    else:
                        visit_expr(ctx)
                        if isinstance(ctx, ast.Call):
                            tgt = self._resolve_call(fn, ctx, key)
                            if tgt:
                                for got in self._held_at_yield(tgt):
                                    if got not in held:
                                        held.append(got)
                walk_block(st.body)
                del held[depth:]
                return
            if isinstance(st, (ast.For, ast.AsyncFor)):
                visit_expr(st.iter)
                for tr in _iter_callback_targets(st):
                    cbvars.add(tr)
                walk_block(st.body)
                walk_block(st.orelse)
                return
            if isinstance(st, ast.While):
                visit_expr(st.test)
                walk_block(st.body)
                walk_block(st.orelse)
                return
            if isinstance(st, ast.If):
                visit_expr(st.test)
                walk_block(st.body)
                walk_block(st.orelse)
                return
            if isinstance(st, ast.Try):
                walk_block(st.body)
                for h in st.handlers:
                    walk_block(h.body)
                walk_block(st.orelse)
                walk_block(st.finalbody)
                return
            if isinstance(st, ast.Return):
                if isinstance(st.value, ast.Call):
                    tgt = self._resolve_call(fn, st.value, key)
                    if tgt:
                        facts.return_calls.append(tgt)
                if st.value is not None:
                    visit_expr(st.value)
                return
            tgt_var = _callbackish_binding(st)
            if tgt_var:
                cbvars.add(tgt_var)
            visit_expr(st)

        walk_block(list(fn.node.body))
        return facts

    def _blocking_slug(self, fn: FunctionInfo, call: ast.Call,
                       tail: str | None) -> str | None:
        if tail is None:
            return None
        if tail in ("wait", "wait_for") and isinstance(
                call.func, ast.Attribute):
            return "cond-wait"
        if tail == "get" and isinstance(call.func, ast.Attribute):
            recv = dotted_name(call.func.value)
            last = recv.rsplit(".", 1)[-1] if recv else ""
            if _QUEUEISH.search(last) and not _nonblocking_get(call):
                return "queue-get"
        if tail in _SOCKETISH_ATTRS and isinstance(call.func, ast.Attribute):
            return f"socket-{tail}"
        return _BLOCKING_TAILS.get(tail)

    def _callback_desc(self, call: ast.Call, cbvars: set[str]) -> str | None:
        f = call.func
        if isinstance(f, ast.Name) and f.id in cbvars:
            return f.id
        if isinstance(f, ast.Subscript):
            # self._callbacks[name](...) — direct invocation out of a
            # user-code collection
            try:
                src = ast.unparse(f.value)
            except Exception:  # pragma: no cover - malformed tree
                return None
            if _CALLBACKISH.search(src):
                return src + "[...]"
        if isinstance(f, ast.Attribute):
            # self.on_progress(...) — the invoked attribute itself must be
            # callback-ish; managing a listener list is registration
            if _CALLBACKISH.search(f.attr) and not _CB_MGMT.search(f.attr):
                try:
                    return ast.unparse(f)
                except Exception:  # pragma: no cover
                    return f.attr
        return None

    # -- interprocedural closure ---------------------------------------------

    def _held_at_yield(self, key: str, _seen: frozenset = frozenset()
                       ) -> set[str]:
        """Locks held at the yield of a generator contextmanager (what a
        ``with f():`` body runs under).  Follows ``return g()`` chains."""
        if key in self._yield_memo:
            return self._yield_memo[key]
        if key in _seen or key not in self.facts:
            return set()
        facts = self.facts[key]
        out = set(facts.yield_held)
        if not out:
            for tgt in facts.return_calls:
                out |= self._held_at_yield(tgt, _seen | {key})
        self._yield_memo[key] = out
        return out

    def _close_summaries(self) -> None:
        """Fixpoint transitive closure of per-function acquire/blocking
        summaries over the package call graph (cycle-safe)."""
        acq = {k: {i for i, _ in f.acquires} for k, f in self.facts.items()}
        blk: dict[str, dict[str, tuple[str, int, str]]] = {}
        for k, f in self.facts.items():
            mod = self.index.functions[k].module
            blk[k] = {op.slug: (mod.path, op.line,
                                self.index.functions[k].qualname)
                      for op in f.blocking}
        changed = True
        while changed:
            changed = False
            for k, outs in self._dlk_edges.items():
                for tgt in outs:
                    if tgt not in acq:
                        continue
                    before = len(acq[k])
                    acq[k] |= acq[tgt]
                    if len(acq[k]) != before:
                        changed = True
                    for slug, ev in blk[tgt].items():
                        if slug not in blk[k]:
                            blk[k][slug] = ev
                            changed = True
        self._reach_acq = acq
        self._reach_blk = blk

    def _add_interprocedural_edges(self) -> None:
        for key in sorted(self.facts):
            facts = self.facts[key]
            for e in facts.edges:
                self.edges.setdefault((e.src, e.dst), e)
            for cs in facts.callsites:
                if not cs.held:
                    continue
                mod = self.index.functions[key].module
                for ident in sorted(self._reach_acq.get(cs.target, ())):
                    for h in cs.held:
                        if h == ident:
                            continue
                        self.edges.setdefault(
                            (h, ident),
                            Edge(h, ident, mod.path, cs.line,
                                 self.index.functions[key].qualname, cs.via))

    # -- outputs -------------------------------------------------------------

    def edge_pairs(self) -> set[tuple[str, str]]:
        return set(self.edges)

    def lock_ids(self) -> set[str]:
        return set(self.inventory.locks)

    def cycles(self) -> list[list[str]]:
        """Each cycle once, as a canonical node path (smallest node first,
        closed implicitly: last -> first)."""
        sccs = _tarjan_sccs(sorted(self.lock_ids() | {
            n for e in self.edges for n in e}),
            {a: sorted(b for (x, b) in self.edges if x == a)
             for a in {s for s, _ in self.edges}})
        out = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            start = min(scc)
            path = _cycle_through(start, set(scc), self.edges)
            if path:
                out.append(path)
        out.sort()
        return out

    def to_dot(self) -> str:
        cyc_nodes = {n for c in self.cycles() for n in c}
        lines = ["digraph lockorder {", "  rankdir=LR;",
                 '  node [shape=box, fontname="monospace"];']
        for ident in sorted(self.lock_ids() | {
                n for e in self.edges for n in e}):
            attrs = f'label="{ident}"'
            site = self.inventory.locks.get(ident)
            if site:
                attrs += f', tooltip="{site.path}:{site.line} ({site.kind})"'
            if ident in cyc_nodes:
                attrs += ", color=red, penwidth=2"
            lines.append(f'  "{ident}" [{attrs}];')
        for (a, b) in sorted(self.edges):
            e = self.edges[(a, b)]
            style = ", color=red" if a in cyc_nodes and b in cyc_nodes else ""
            lines.append(f'  "{a}" -> "{b}" '
                         f'[tooltip="{e.path}:{e.line}"{style}];')
        lines.append("}")
        return "\n".join(lines)

    # -- findings ------------------------------------------------------------

    def findings(self) -> list[Finding]:
        out: list[Finding] = []
        for path in self.cycles():
            hops = []
            ring = path + [path[0]]
            for a, b in zip(ring, ring[1:]):
                e = self.edges[(a, b)]
                hops.append(f"{b} ({e.path}:{e.line} in {e.where})")
            first = self.edges[(path[0], path[1])]
            out.append(Finding(
                rule="DLK001", path=first.path, line=first.line,
                where=first.where,
                message=("potential deadlock: lock-order cycle "
                         + " -> ".join([path[0]] + hops)),
                detail="cycle:" + "->".join(path)))
        for key in sorted(self.facts):
            facts = self.facts[key]
            fn = self.index.functions[key]
            seen: set[tuple[str, str]] = set()

            def blocked(ident: str, slug: str, line: int, via: str) -> None:
                if (ident, slug) in seen:
                    return
                seen.add((ident, slug))
                note = f" (via {via})" if via else ""
                out.append(Finding(
                    rule="DLK002", path=fn.module.path, line=line,
                    where=fn.qualname,
                    message=(f"blocking operation [{slug}] reachable while "
                             f"holding {ident}{note}: the lock is stalled "
                             f"for the full wait"),
                    detail=f"{slug}-under-{ident}"))

            for op in facts.blocking:
                if op.held and not op.local_exempt:
                    blocked(op.held, op.slug, op.line, "")
            for cs in facts.callsites:
                if not cs.held:
                    continue
                for slug, ev in sorted(
                        self._reach_blk.get(cs.target, {}).items()):
                    blocked(cs.held[-1], slug, cs.line, cs.via)
            for ident, desc, line in facts.callbacks:
                out.append(Finding(
                    rule="DLK003", path=fn.module.path, line=line,
                    where=fn.qualname,
                    message=(f"user-supplied callback `{desc}` invoked while "
                             f"holding {ident}: user code can re-enter the "
                             f"runtime and acquire locks in any order — "
                             f"snapshot under the lock, call outside it"),
                    detail=f"callback-under-{ident}"))
        return out

def _nonblocking_get(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return False


def _all_args(fn_node) -> list[ast.arg]:
    a = fn_node.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs,
            *( [a.vararg] if a.vararg else []),
            *( [a.kwarg] if a.kwarg else [])]


def _iter_callback_targets(st) -> list[str]:
    """``for cb in self._listeners:`` -> loop vars bound to user code."""
    try:
        src = ast.unparse(st.iter)
    except Exception:  # pragma: no cover
        return []
    if not _CALLBACKISH.search(src):
        return []
    tgt = st.target
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, ast.Tuple):
        return [e.id for e in tgt.elts if isinstance(e, ast.Name)]
    return []


def _callbackish_binding(st) -> str | None:
    """``cb = self._callbacks[name]`` -> "cb"."""
    if not isinstance(st, ast.Assign) or len(st.targets) != 1:
        return None
    t = st.targets[0]
    if not isinstance(t, ast.Name):
        return None
    if isinstance(st.value, (ast.Subscript, ast.Attribute, ast.Call)):
        try:
            src = ast.unparse(st.value)
        except Exception:  # pragma: no cover
            return None
        if _CALLBACKISH.search(src) and not isinstance(st.value, ast.Call):
            return t.id
    return None


# -- cycle machinery ---------------------------------------------------------

def _tarjan_sccs(nodes: list[str], succ: dict[str, list[str]]
                 ) -> list[list[str]]:
    """Iterative Tarjan — the lock graph is tiny but recursion limits are
    a silly way to die."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(succ.get(nxt, ()))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))
    return sccs


def _cycle_through(start: str, scc: set[str],
                   edges: dict[tuple[str, str], Edge]) -> list[str] | None:
    """Shortest cycle through ``start`` inside one SCC (BFS)."""
    succ: dict[str, list[str]] = {}
    for (a, b) in sorted(edges):
        if a in scc and b in scc:
            succ.setdefault(a, []).append(b)
    best: list[str] | None = None
    frontier = [[start]]
    seen = {start}
    while frontier and best is None:
        nxt: list[list[str]] = []
        for path in frontier:
            for b in succ.get(path[-1], ()):
                if b == start:
                    best = path
                    break
                if b not in seen:
                    seen.add(b)
                    nxt.append(path + [b])
            if best:
                break
        frontier = nxt
    return best


# -- entry points ------------------------------------------------------------

def analyze(index: PackageIndex) -> LockOrderGraph:
    return LockOrderGraph(index)


def check(index: PackageIndex) -> list[Finding]:
    return analyze(index).findings()
