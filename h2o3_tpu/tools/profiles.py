"""graftlint profiling-attribution rules (PRF) — executables must be
nameable.

- **PRF001** — anonymous ``jax.jit``: ``jit``/``pjit`` called on an
  expression (a ``lambda``, a transform like ``jax.grad(f)``, a
  ``partial(...)``) instead of a named function reference. The resulting
  executable renders as ``<lambda>`` / ``<unnamed function>`` in device
  profiler captures (``POST /3/Profiler/capture``) and cannot be credited
  to a site in the cost registry (``utils/costs.py``) — dead weight in
  exactly the views built to attribute compile time and FLOPs. Fix: jit a
  named ``def`` (decorator or direct form both keep ``__name__``), or
  route the site through ``accounted_jit(site, fn)``, which registers the
  executable under an explicit stable site name.

Decorator forms (``@jax.jit``, ``@partial(jax.jit, ...)``) are never
flagged: the decorated ``def`` carries its own stable name. Calls on a
plain ``Name``/``Attribute`` reference (``jax.jit(step)``,
``jax.jit(jnp.matmul)``) keep the referent's name and pass too.
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import Finding, PackageIndex, call_name

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}


def _jit_decorator_calls(fn_node: ast.AST) -> set[int]:
    """ids of Call nodes that ARE decorator expressions (or live inside
    one) — ``@partial(jax.jit, ...)`` contains a Call on ``partial`` and
    must not be mistaken for an anonymous jit of ``partial(...)``."""
    out: set[int] = set()
    for dec in getattr(fn_node, "decorator_list", ()):
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Call):
                out.add(id(sub))
    return out


def _describe(arg: ast.AST) -> str:
    if isinstance(arg, ast.Lambda):
        return "a lambda"
    if isinstance(arg, ast.Call):
        nm = call_name(arg)
        return f"`{nm}(...)`" if nm else "a call expression"
    return "an expression"


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        decorator_calls: set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorator_calls |= _jit_decorator_calls(node)
        # enclosing qualname per call node, for finding attribution
        owner: dict[int, str] = {}
        for key, info in index.functions.items():
            if info.module is not mod:
                continue
            for sub in ast.walk(info.node):
                if isinstance(sub, ast.Call):
                    owner.setdefault(id(sub), info.qualname)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or id(node) in decorator_calls:
                continue
            if call_name(node) not in _JIT_NAMES:
                continue
            if not node.args:
                continue   # jit(**only_kwargs) — not a compile site
            arg = node.args[0]
            if isinstance(arg, (ast.Name, ast.Attribute)):
                continue   # named reference: executable keeps its __name__
            findings.append(Finding(
                "PRF001", mod.path, node.lineno, owner.get(id(node), ""),
                f"`{call_name(node)}` over {_describe(arg)} — the "
                "executable has no stable name, so profiler captures and "
                "the cost registry cannot attribute it; jit a named def "
                "or use accounted_jit(site, fn)",
                detail=_describe(arg)))
    return findings
