"""graftlint REST-surface rules (RST) — route ↔ schema ↔ client consistency.

Cross-checks the three files that define the wire surface:
``api/server.py`` (the ``_ROUTES`` table + handlers), ``api/schemas.py``
(serializer functions), ``api/client.py`` (accessor methods).

- **RST001** — a registered route's handler produces neither a
  schema-typed reply (``self._reply`` / ``schemas.*`` / ``_done_job``)
  nor a raw byte response: the route would 200 with no body contract.
- **RST002** — handler arity drift: the route regex captures N groups but
  the handler does not accept N path arguments (dispatch calls
  ``fn(self, *match.groups())`` — a mismatch is a guaranteed 500).
- **RST003** — a client accessor requests a (method, path) no route
  serves: the call can only ever 404.
- **RST004** — duplicate (pattern, method) registration: the second
  entry is dead code the first shadows.
- **RST005** — ``schemas.<name>`` referenced by the server but not
  defined in ``api/schemas.py``.
"""

from __future__ import annotations

import ast
import re

from h2o3_tpu.tools.core import Finding, ModuleInfo, PackageIndex

#: placeholder substituted for f-string fields in client paths; matches
#: every capture class the route table uses ([^/]+, [^/]*, \d+, -?\d+, .+)
_PLACEHOLDER = "0"


def _find_module(index: PackageIndex, suffix: str) -> ModuleInfo | None:
    for name, mod in index.modules.items():
        if name == suffix or name.endswith("." + suffix):
            return mod
    return None


def _routes_table(server: ModuleInfo) -> list[tuple[str, str, str, int]]:
    """(pattern, method, handler_name, line) rows from the ``_ROUTES``
    literal."""
    rows: list[tuple[str, str, str, int]] = []
    for node in ast.walk(server.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_ROUTES"
                and isinstance(node.value, (ast.List, ast.Tuple))):
            continue
        for elt in node.value.elts:
            if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 3):
                continue
            pat, method, fn = elt.elts
            if not (isinstance(pat, ast.Constant)
                    and isinstance(method, ast.Constant)):
                continue
            handler = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "?")
            rows.append((str(pat.value), str(method.value), handler,
                         elt.lineno))
    return rows


def _handler_classes(server: ModuleInfo) -> dict[str, ast.FunctionDef]:
    """Every method defined on any class in server.py, by name (handlers
    live on the request-handler class; name collisions don't matter for
    arity/reply checks)."""
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(server.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.setdefault(item.name, item)
    return out


_REPLY_CALLS = {"_reply", "_error"}
_RAW_MARKERS = {"wfile", "send_response"}


def _replies(fn: ast.FunctionDef, methods: dict[str, ast.FunctionDef],
             seen: set[str] | None = None) -> bool:
    """True if the handler (transitively through same-class helpers)
    produces a schema-typed or raw-byte reply."""
    seen = seen or set()
    if fn.name in seen:
        return False
    seen.add(fn.name)
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            if node.attr in _RAW_MARKERS:
                return True
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _REPLY_CALLS:
                return True
            if isinstance(f.value, ast.Name) and f.value.id == "schemas":
                return True
            if isinstance(f.value, ast.Name) and f.value.id == "self" and \
                    f.attr in methods and _replies(methods[f.attr],
                                                   methods, seen):
                return True
        elif isinstance(f, ast.Name):
            if f.id == "_done_job":
                return True
            if f.id in methods and _replies(methods[f.id], methods, seen):
                return True
    return False


def _arity(fn: ast.FunctionDef) -> tuple[int, int]:
    """(required, max) positional path-arg counts, excluding self."""
    args = fn.args
    pos = [a for a in list(args.posonlyargs) + list(args.args)
           if a.arg != "self"]
    required = len(pos) - len(args.defaults)
    maxn = len(pos) if args.vararg is None else 10**6
    return max(required, 0), maxn


def _client_paths(client: ModuleInfo) -> list[tuple[str, str, int]]:
    """(method, path_template, line) for every ``self.request(...)`` call;
    f-string fields become the placeholder, query strings are stripped."""
    out: list[tuple[str, str, int]] = []
    for node in ast.walk(client.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "request"
                and isinstance(f.value, ast.Name) and f.value.id == "self"):
            continue
        if len(node.args) < 2 or not isinstance(node.args[0], ast.Constant):
            continue
        method = str(node.args[0].value)
        path_node = node.args[1]
        if isinstance(path_node, ast.Constant):
            template = str(path_node.value)
        elif isinstance(path_node, ast.JoinedStr):
            parts = []
            for v in path_node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append(_PLACEHOLDER)
            template = "".join(parts)
        else:
            continue        # dynamically-built path: out of scope
        template = template.split("?", 1)[0]
        out.append((method, template, node.lineno))
    return out


def check(index: PackageIndex) -> list[Finding]:
    server = _find_module(index, "api.server")
    schemas = _find_module(index, "api.schemas")
    client = _find_module(index, "api.client")
    if server is None:
        return []
    findings: list[Finding] = []
    routes = _routes_table(server)
    methods = _handler_classes(server)

    seen_keys: set[tuple[str, str]] = set()
    compiled: list[tuple[re.Pattern, str]] = []
    for pat, method, handler, line in routes:
        key = (pat, method)
        if key in seen_keys:
            findings.append(Finding(
                "RST004", server.path, line, "_ROUTES",
                f"duplicate registration of {method} {pat} — the first "
                "entry shadows this one", detail=f"{method} {pat}"))
        seen_keys.add(key)
        try:
            rx = re.compile(pat)
        except re.error as e:
            findings.append(Finding(
                "RST002", server.path, line, "_ROUTES",
                f"unparseable route pattern {pat!r}: {e}", detail=pat))
            continue
        compiled.append((rx, method))
        fn = methods.get(handler)
        if fn is None:
            findings.append(Finding(
                "RST002", server.path, line, "_ROUTES",
                f"route {method} {pat} names handler {handler!r} which is "
                "not defined on the handler class", detail=f"{handler}"))
            continue
        required, maxn = _arity(fn)
        if not (required <= rx.groups <= maxn):
            findings.append(Finding(
                "RST002", server.path, line, "_ROUTES",
                f"route {method} {pat} captures {rx.groups} group(s) but "
                f"handler {handler} takes {required}"
                + (f"..{maxn}" if maxn != required else "")
                + " path arg(s) — dispatch would raise TypeError",
                detail=f"{handler}/{rx.groups}"))
        if not _replies(fn, methods):
            findings.append(Finding(
                "RST001", server.path, fn.lineno, handler,
                f"handler {handler} for {method} {pat} produces no "
                "schema-typed or raw reply — the route has no response "
                "contract", detail=handler))

    # schemas.* references must exist
    if schemas is not None:
        defined = set(schemas.top_defs)
        for node in ast.walk(server.tree):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "schemas" and node.attr not in defined:
                findings.append(Finding(
                    "RST005", server.path, node.lineno, "",
                    f"`schemas.{node.attr}` is referenced but not defined "
                    "in api/schemas.py", detail=node.attr))

    # client accessors must hit registered routes
    if client is not None:
        for method, template, line in _client_paths(client):
            if any(m == method and rx.fullmatch(template)
                   for rx, m in compiled):
                continue
            findings.append(Finding(
                "RST003", client.path, line, "",
                f"client requests {method} {template} but no route "
                "serves it — the call can only 404",
                detail=f"{method} {template}"))
    return findings
