"""graftlint tracer-safety rules (TRC) — device→host syncs and trace breaks.

- **TRC001** — host-sync call inside jit-traced code: ``float()/int()/
  bool()`` / ``np.asarray()`` on array-derived values, ``.item()``,
  ``jax.device_get``. Under a trace each is either a
  ``ConcretizationTypeError`` waiting to happen or a silent host round-trip
  that serializes the device pipeline.
- **TRC002** — Python ``if``/``while`` branching on a tracer value inside
  jit-traced code: breaks the trace (use ``jnp.where``/``lax.cond``).
  Branching on *static* parameters (strings, config flags) is the normal
  ``static_argnames`` pattern and is not flagged — only tests over values
  produced by jax ops inside the function.
- **TRC003** — per-iteration host sync in a host loop that dispatches
  device work: ``jax.device_get``/``.item()``/``np.asarray(jax value)``
  inside a ``for``/``while`` whose body also calls into a jitted program
  (or runs eager jax ops). Each sync blocks on device completion once per
  iteration — batch them into one transfer per iteration, or keep the
  check on-device (PAPER.md §1; Abadi et al. §3.3).
"""

from __future__ import annotations

import ast

from h2o3_tpu.tools.core import (Finding, FunctionInfo, PackageIndex,
                                 call_name)

_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_CAST = {"float", "int", "bool"}
_JAX_HEADS = {"jax", "jnp", "lax"}
#: attribute reads that are static under a trace (aval metadata, not data)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval"}
#: jax calls that return host constants, not tracers
_STATIC_CALLS = {"jax.default_backend", "jax.devices", "jax.device_count",
                 "jax.local_device_count"}


def _is_item_call(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "item" and not call.args)


def _mentions_jax(node: ast.AST) -> bool:
    """Expression references a jax/jnp/lax name anywhere."""
    return any(isinstance(sub, ast.Name) and sub.id in _JAX_HEADS
               for sub in ast.walk(node))


def _jaxish_call(call: ast.Call) -> bool:
    return call_name(call) not in _STATIC_CALLS and _mentions_jax(call)


def _static_ids(node: ast.AST) -> set[int]:
    """ids of Name nodes appearing under a static-attribute chain
    (``x.shape[1]`` uses x's metadata, not its device buffer)."""
    out: set[int] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            for d in ast.walk(sub):
                if isinstance(d, ast.Name):
                    out.add(id(d))
    return out


def _arg_tainted(call: ast.Call, tainted: set[str]) -> bool:
    if not call.args:
        return False
    arg = call.args[0]
    if isinstance(arg, ast.Constant):
        return False
    static = _static_ids(arg)
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Name) and sub.id in tainted and \
                id(sub) not in static:
            return True
        if isinstance(sub, ast.Call) and _jaxish_call(sub):
            return True
    return False


def _traced_sync_kind(call: ast.Call, tainted: set[str]) -> str | None:
    """Host-sync classification inside traced code. Builtin casts and
    np.asarray are gated on the taint set so trace-time work on static
    values (shapes, config) stays legal."""
    name = call_name(call)
    if name in _DEVICE_GET:
        return name
    if _is_item_call(call):
        return ".item()"
    if (name in _NP_SYNC or name in _CAST) and _arg_tainted(call, tainted):
        return f"{name}()"
    return None


def _loop_sync_kind(call: ast.Call) -> str | None:
    """Host-sync classification in host loops: only unambiguous syncs —
    ``device_get``, ``.item()``, and ``np.asarray`` over a jax expression."""
    name = call_name(call)
    if name in _DEVICE_GET:
        return name
    if _is_item_call(call):
        return ".item()"
    if name in _NP_SYNC and call.args and _mentions_jax(call.args[0]):
        return f"{name}()"
    return None


def _tainted_names(fn: ast.AST) -> set[str]:
    """Names assigned from jax expressions inside the function —
    transitively through names, one forward pass. Bare parameters are
    deliberately excluded: jit params may be static (``static_argnames``
    strings, config scalars), and branching on or casting those is the
    normal pattern."""
    assigned: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is None:
                continue
            src_tainted = _mentions_jax(value) or any(
                isinstance(s, ast.Name) and s.id in assigned
                for s in ast.walk(value))
            if not src_tainted:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        assigned.add(sub.id)
    return assigned


def _own_nodes(fn: ast.AST) -> list[ast.AST]:
    """Descendant nodes EXCLUDING nested function/class bodies (nested
    defs have their own FunctionInfo and are checked separately)."""
    out: list[ast.AST] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            out.append(child)
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                visit(child)

    visit(fn)
    return out


def _check_traced(info: FunctionInfo, findings: list[Finding]) -> None:
    fn = info.node
    tainted = _tainted_names(fn)
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            kind = _traced_sync_kind(node, tainted)
            if kind:
                findings.append(Finding(
                    "TRC001", info.module.path, node.lineno, info.qualname,
                    f"host sync `{kind}` inside jit-traced code — a "
                    "device→host round-trip (or trace error) in the "
                    "compiled hot path", detail=kind))
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            # identity tests (`x is None`) inspect trace-time structure,
            # not device data — static, never a trace break
            if isinstance(test, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                continue
            static = _static_ids(test)
            is_tracer = any(
                isinstance(s, ast.Call) and _jaxish_call(s)
                for s in ast.walk(test)) or any(
                isinstance(s, ast.Name) and s.id in tainted
                and id(s) not in static
                for s in ast.walk(test))
            if is_tracer:
                kw = "while" if isinstance(node, ast.While) else "if"
                findings.append(Finding(
                    "TRC002", info.module.path, node.lineno, info.qualname,
                    f"Python `{kw}` branches on a tracer value inside "
                    "jit-traced code — breaks the trace (use jnp.where / "
                    "lax.cond / lax.while_loop)", detail=kw))


def _check_loops(info: FunctionInfo, index: PackageIndex,
                 dispatchers: set[str], findings: list[Finding]) -> None:
    def loop_nodes(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, (ast.For, ast.While)):
                yield child
            yield from loop_nodes(child)

    for loop in loop_nodes(info.node):
        body_nodes = [n for stmt in loop.body for n in ast.walk(stmt)]
        dispatches = False
        for n in body_nodes:
            if not isinstance(n, ast.Call):
                continue
            nm = call_name(n)
            if nm and (nm.split(".", 1)[0] in _JAX_HEADS
                       or nm.split(".")[-1] == "map_reduce"):
                dispatches = True
                break
            if index.resolve_call(info, n) in dispatchers:
                dispatches = True
                break
        if not dispatches:
            continue
        syncs = [n for n in body_nodes
                 if isinstance(n, ast.Call) and _loop_sync_kind(n)]
        # a sync nested inside another flagged sync is the same round-trip
        # (np.asarray(jax.device_get(x)) is ONE transfer, not two)
        inner: set[int] = set()
        for s in syncs:
            for sub in ast.walk(s):
                if sub is not s and isinstance(sub, ast.Call) and \
                        _loop_sync_kind(sub):
                    inner.add(id(sub))
        for s in syncs:
            if id(s) in inner:
                continue
            kind = _loop_sync_kind(s)
            findings.append(Finding(
                "TRC003", info.module.path, s.lineno, info.qualname,
                f"per-iteration host sync `{kind}` in a loop that "
                "dispatches device work — batch transfers into one "
                "device_get per iteration or keep the check on-device",
                detail=kind or "sync"))


def check(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    traced = index.traced_functions()
    dispatchers = index.dispatchers(traced)
    for key, info in index.functions.items():
        if key in traced:
            _check_traced(info, findings)
        else:
            _check_loops(info, index, dispatchers, findings)
    return findings
