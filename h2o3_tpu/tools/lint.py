"""graftlint driver — run every rule family, diff against the baseline.

Analysis never imports the code it scans (pure AST); only this CLI's own
import pulls in the ``h2o3_tpu`` package it ships inside.

Usage::

    python -m h2o3_tpu.tools.lint            # human output, repo baseline
    python -m h2o3_tpu.tools.lint --json     # machine output
    python -m h2o3_tpu.tools.lint --update-baseline
    python -m h2o3_tpu.tools.lint path/to/pkg --no-baseline

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings, 2 = internal/usage error.

The baseline (``h2o3_tpu/tools/baseline.json``) holds fingerprint counts
of accepted pre-existing findings: they print as warnings and do not fail
the run, so the analyzer can land before every legacy site is fixed while
still failing on *new* violations. Fingerprints carry no line numbers, so
unrelated edits don't churn the file.
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
from pathlib import Path

from h2o3_tpu.tools import (acts, cardinality, envs, ingest, locks, mem,
                            meshes, metrics, profiles, rest, retry, sync,
                            tracer, waits)
from h2o3_tpu.tools.core import Finding, PackageIndex

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def run_lint(root: Path) -> list[Finding]:
    """All non-suppressed findings for the package at ``root``, in stable
    (path, line, rule) order."""
    index = PackageIndex.scan(Path(root))
    findings = (tracer.check(index) + locks.check(index) + rest.check(index)
                + mem.check(index) + sync.check(index) + retry.check(index)
                + meshes.check(index) + profiles.check(index)
                + waits.check(index) + envs.check(index)
                + ingest.check(index) + metrics.check(index)
                + acts.check(index) + cardinality.check(index))
    out = []
    for f in findings:
        mod = next((m for m in index.modules.values() if m.path == f.path),
                   None)
        if mod is not None and f.line in mod.suppressed:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> dict[str, int]:
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    return {str(k): int(v) for k, v in data.get("fingerprints", {}).items()}


def save_baseline(path: Path, findings: list[Finding]) -> None:
    counts = collections.Counter(f.fingerprint for f in findings)
    doc = {
        "comment": "graftlint accepted pre-existing findings; regenerate "
                   "with `python -m h2o3_tpu.tools.lint --update-baseline`",
        "fingerprints": dict(sorted(counts.items())),
    }
    Path(path).write_text(json.dumps(doc, indent=1) + "\n")


def split_findings(findings: list[Finding], baseline: dict[str, int]
                   ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined): occurrences beyond a fingerprint's baselined
    count are new."""
    budget = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# -- CLI ---------------------------------------------------------------------

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m h2o3_tpu.tools.lint",
        description="graftlint: tracer-safety, lock-discipline, "
                    "REST-surface, memory, sync- and retry-discipline "
                    "analysis for h2o3_tpu")
    ap.add_argument("root", nargs="?", default=None,
                    help="package root to scan (default: the installed "
                         "h2o3_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding fails the run")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[1]
    if not root.exists():
        print(f"graftlint: no such path: {root}", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE

    findings = run_lint(root)

    if args.update_baseline:
        save_baseline(baseline_path, findings)
        print(f"graftlint: baselined {len(findings)} finding(s) -> "
              f"{baseline_path}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, old = split_findings(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "baselined": [vars(f) | {"fingerprint": f.fingerprint}
                          for f in old],
        }, indent=1))
    else:
        for f in old:
            print(f"warning: {f.render()} (baselined)")
        for f in new:
            print(f"error: {f.render()}")
        print(f"graftlint: {len(new)} new, {len(old)} baselined, "
              f"{len(findings)} total finding(s)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
