"""graftlint driver — run every rule family, diff against the baseline.

Analysis never imports the code it scans (pure AST); only this CLI's own
import pulls in the ``h2o3_tpu`` package it ships inside.

Usage::

    python -m h2o3_tpu.tools.lint            # human output, repo baseline
    python -m h2o3_tpu.tools.lint --json     # machine output (+ per-family
                                             # wall time under "timings")
    python -m h2o3_tpu.tools.lint --rules DLK,LCK   # family filter
    python -m h2o3_tpu.tools.lint --graph    # lock-order graph as DOT
    python -m h2o3_tpu.tools.lint --update-baseline
    python -m h2o3_tpu.tools.lint --prune-baseline
    python -m h2o3_tpu.tools.lint path/to/pkg --no-baseline

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings, 2 = internal/usage error.

The baseline (``h2o3_tpu/tools/baseline.json``) holds fingerprint counts
of accepted pre-existing findings: they print as warnings and do not fail
the run, so the analyzer can land before every legacy site is fixed while
still failing on *new* violations. Fingerprints carry no line numbers, so
unrelated edits don't churn the file. The optional ``reasons`` map pins a
documented justification to a fingerprint (required for DLK entries — a
baselined deadlock finding without a written invariant is just a silenced
deadlock); ``--prune-baseline`` drops entries (and their reasons) that no
longer match any current finding.
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
import time
from pathlib import Path

from h2o3_tpu.tools import (acts, cardinality, envs, ingest, lockorder,
                            locks, mem, meshes, metrics, profiles, rest,
                            retry, sync, tracer, waits)
from h2o3_tpu.tools.core import Finding, PackageIndex

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

#: rule-family registry: prefix -> checker module (order = report order).
FAMILIES: tuple[tuple[str, object], ...] = (
    ("TRC", tracer), ("LCK", locks), ("RST", rest), ("MEM", mem),
    ("SYN", sync), ("RTY", retry), ("MSH", meshes), ("PRF", profiles),
    ("WTX", waits), ("ENV", envs), ("ING", ingest), ("MTR", metrics),
    ("ACT", acts), ("CRD", cardinality), ("DLK", lockorder),
)

FAMILY_NAMES = tuple(name for name, _ in FAMILIES)


def run_lint(root: Path, families: tuple[str, ...] | None = None,
             timings: dict[str, float] | None = None) -> list[Finding]:
    """All non-suppressed findings for the package at ``root``, in stable
    (path, line, rule) order. ``families`` restricts to the given rule
    prefixes; ``timings`` (if given) is filled with per-family wall
    seconds so slow families are attributable."""
    index = PackageIndex.scan(Path(root))
    findings: list[Finding] = []
    for name, checker in FAMILIES:
        if families is not None and name not in families:
            continue
        t0 = time.perf_counter()
        findings += checker.check(index)
        if timings is not None:
            timings[name] = time.perf_counter() - t0
    out = []
    for f in findings:
        mod = next((m for m in index.modules.values() if m.path == f.path),
                   None)
        if mod is not None and f.line in mod.suppressed:
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> dict[str, int]:
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    return {str(k): int(v) for k, v in data.get("fingerprints", {}).items()}


def load_reasons(path: Path) -> dict[str, str]:
    """Documented justifications per baselined fingerprint."""
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    return {str(k): str(v) for k, v in data.get("reasons", {}).items()}


def save_baseline(path: Path, findings: list[Finding],
                  reasons: dict[str, str] | None = None) -> None:
    """Write fingerprint counts; ``reasons`` defaults to the existing
    file's reasons, pruned to fingerprints that still exist."""
    counts = collections.Counter(f.fingerprint for f in findings)
    if reasons is None:
        reasons = load_reasons(path)
    doc = {
        "comment": "graftlint accepted pre-existing findings; regenerate "
                   "with `python -m h2o3_tpu.tools.lint --update-baseline`",
        "fingerprints": dict(sorted(counts.items())),
        "reasons": {k: v for k, v in sorted(reasons.items())
                    if k in counts},
    }
    Path(path).write_text(json.dumps(doc, indent=1) + "\n")


def split_findings(findings: list[Finding], baseline: dict[str, int]
                   ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined): occurrences beyond a fingerprint's baselined
    count are new."""
    budget = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def stale_entries(baseline: dict[str, int],
                  findings: list[Finding]) -> dict[str, int]:
    """Baseline counts no current finding backs: fingerprints with zero
    matches, plus the excess where the count exceeds today's occurrences.
    Non-empty means dead suppressions are accumulating."""
    current = collections.Counter(f.fingerprint for f in findings)
    out: dict[str, int] = {}
    for fp, n in baseline.items():
        excess = n - current.get(fp, 0)
        if excess > 0:
            out[fp] = excess
    return out


def prune_baseline(path: Path, findings: list[Finding]) -> dict[str, int]:
    """Clamp every baselined count to the current occurrence count and
    drop fingerprints (and their reasons) with none. Returns what was
    removed."""
    baseline = load_baseline(path)
    reasons = load_reasons(path)
    current = collections.Counter(f.fingerprint for f in findings)
    stale = stale_entries(baseline, findings)
    kept: list[Finding] = []
    budget = {fp: min(n, current.get(fp, 0)) for fp, n in baseline.items()}
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            kept.append(f)
    save_baseline(path, kept, reasons)
    return stale


# -- CLI ---------------------------------------------------------------------

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m h2o3_tpu.tools.lint",
        description="graftlint: tracer-safety, lock-discipline, lock-order, "
                    "REST-surface, memory, sync- and retry-discipline "
                    "analysis for h2o3_tpu")
    ap.add_argument("root", nargs="?", default=None,
                    help="package root to scan (default: the installed "
                         "h2o3_tpu package)")
    ap.add_argument("--rules", default=None, metavar="FAM[,FAM...]",
                    help="run only these rule families, e.g. DLK,LCK "
                         f"(known: {','.join(FAMILY_NAMES)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document (includes "
                         "per-family wall time under 'timings')")
    ap.add_argument("--graph", action="store_true",
                    help="emit the DLK lock-order graph as DOT and exit")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding fails the run")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as the new baseline "
                         "(existing reasons for surviving entries are kept)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline fingerprints no current finding "
                         "matches (and clamp over-counts)")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[1]
    if not root.exists():
        print(f"graftlint: no such path: {root}", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE

    families: tuple[str, ...] | None = None
    if args.rules:
        families = tuple(r.strip().upper() for r in args.rules.split(",")
                         if r.strip())
        unknown = [r for r in families if r not in FAMILY_NAMES]
        if unknown:
            print(f"graftlint: unknown rule famil"
                  f"{'y' if len(unknown) == 1 else 'ies'}: "
                  f"{','.join(unknown)} (known: {','.join(FAMILY_NAMES)})",
                  file=sys.stderr)
            return 2

    if args.graph:
        graph = lockorder.analyze(PackageIndex.scan(root))
        print(graph.to_dot())
        return 0

    timings: dict[str, float] = {}
    findings = run_lint(root, families=families, timings=timings)

    if args.update_baseline:
        save_baseline(baseline_path, findings)
        print(f"graftlint: baselined {len(findings)} finding(s) -> "
              f"{baseline_path}")
        return 0

    if args.prune_baseline:
        stale = prune_baseline(baseline_path, findings)
        n = sum(stale.values())
        print(f"graftlint: pruned {n} stale baseline entr"
              f"{'y' if n == 1 else 'ies'} -> {baseline_path}")
        for fp, excess in sorted(stale.items()):
            print(f"  -{excess} {fp}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, old = split_findings(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "baselined": [vars(f) | {"fingerprint": f.fingerprint}
                          for f in old],
            "timings": {k: round(v, 4) for k, v in timings.items()},
        }, indent=1))
    else:
        for f in old:
            print(f"warning: {f.render()} (baselined)")
        for f in new:
            print(f"error: {f.render()}")
        print(f"graftlint: {len(new)} new, {len(old)} baselined, "
              f"{len(findings)} total finding(s)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
