"""scikit-learn adapters — fit/predict wrappers around the estimators.

Reference: ``h2o-py/h2o/sklearn/`` (generated ``H2O*Classifier`` /
``H2O*Regressor`` wrappers implementing the sklearn estimator protocol:
``fit(X, y) → self``, ``predict``, ``predict_proba``, ``get_params`` /
``set_params``, ``score``). No hard sklearn dependency — the protocol is
duck-typed, so these work standalone and also pass sklearn's
``check_estimator``-style usage inside pipelines.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec


def _to_frame(X, y=None, classification=False) -> tuple[Frame, list[str], str | None]:
    X = np.asarray(X)
    if X.ndim != 2:
        raise ValueError("X must be 2-D")
    cols = {f"x{i}": X[:, i].astype(np.float32) for i in range(X.shape[1])}
    names = list(cols)
    ycol = None
    if y is not None:
        y = np.asarray(y)
        ycol = "target"
        if classification:
            cols[ycol] = np.array([str(v) for v in y], dtype=object)
        else:
            cols[ycol] = y.astype(np.float32)
    fr = Frame.from_arrays(cols)
    return fr, names, ycol


class _H2OSklearnBase:
    """Mixin implementing the sklearn estimator protocol over a ModelBuilder."""

    _builder_cls = None
    _classification = False

    def __init__(self, **params):
        self._params = dict(params)
        self.model_ = None

    # sklearn protocol ------------------------------------------------------
    def get_params(self, deep=True):
        return dict(self._params)

    def set_params(self, **params):
        self._params.update(params)
        return self

    def fit(self, X, y=None):
        fr, names, ycol = _to_frame(X, y, self._classification)
        builder = self._builder_cls(**self._params)
        if getattr(builder, "unsupervised", False) or ycol is None:
            self.model_ = builder.train(x=names, training_frame=fr)
        else:
            self.model_ = builder.train(x=names, y=ycol, training_frame=fr)
        if self._classification and self.model_.response_domain:
            self.classes_ = np.array(list(self.model_.response_domain))
        return self

    def _check_fitted(self):
        if self.model_ is None:
            raise RuntimeError("call fit() first")

    def predict(self, X):
        self._check_fitted()
        fr, _, _ = _to_frame(X)
        pred = self.model_.predict(fr)
        v = pred.vec("predict")
        if v.is_categorical:
            return np.asarray(v.labels())
        return np.asarray(v.to_numpy())

    def score(self, X, y):
        if self._classification:
            return float((self.predict(X) == np.array([str(v) for v in y])).mean())
        pred = self.predict(X).astype(np.float64)
        y = np.asarray(y, np.float64)
        ss_res = np.sum((y - pred) ** 2)
        ss_tot = np.sum((y - y.mean()) ** 2)
        return float(1.0 - ss_res / max(ss_tot, 1e-30))


class _H2OSklearnClassifier(_H2OSklearnBase):
    _classification = True

    def predict_proba(self, X):
        self._check_fitted()
        fr, _, _ = _to_frame(X)
        pred = self.model_.predict(fr)
        probs = [np.asarray(pred.vec(f"p{d}").to_numpy())
                 for d in self.model_.response_domain]
        return np.stack(probs, axis=1)


def _make(name: str, builder_path: str, classifier: bool):
    """Build a named wrapper class; the builder import is resolved lazily at
    first fit() to avoid import cycles."""
    import importlib
    mod_name, cls_name = builder_path.rsplit(".", 1)
    base = _H2OSklearnClassifier if classifier else _H2OSklearnBase
    orig_fit = base.fit

    def fit(self, X, y=None):
        if type(self)._builder_cls is None:
            type(self)._builder_cls = getattr(
                importlib.import_module(mod_name), cls_name)
        return orig_fit(self, X, y)

    return type(name, (base,), {"fit": fit, "_builder_cls": None,
                                "__qualname__": name})


H2OGradientBoostingClassifier = _make(
    "H2OGradientBoostingClassifier", "h2o3_tpu.models.gbm.GBM", True)
H2OGradientBoostingRegressor = _make(
    "H2OGradientBoostingRegressor", "h2o3_tpu.models.gbm.GBM", False)
H2ORandomForestClassifier = _make(
    "H2ORandomForestClassifier", "h2o3_tpu.models.gbm.DRF", True)
H2ORandomForestRegressor = _make(
    "H2ORandomForestRegressor", "h2o3_tpu.models.gbm.DRF", False)
H2OGeneralizedLinearClassifier = _make(
    "H2OGeneralizedLinearClassifier", "h2o3_tpu.models.glm.GLM", True)
H2OGeneralizedLinearRegressor = _make(
    "H2OGeneralizedLinearRegressor", "h2o3_tpu.models.glm.GLM", False)
H2ODeepLearningClassifier = _make(
    "H2ODeepLearningClassifier", "h2o3_tpu.models.deeplearning.DeepLearning", True)
H2ODeepLearningRegressor = _make(
    "H2ODeepLearningRegressor", "h2o3_tpu.models.deeplearning.DeepLearning", False)
H2OXGBoostClassifier = _make(
    "H2OXGBoostClassifier", "h2o3_tpu.models.xgboost.XGBoost", True)
H2OXGBoostRegressor = _make(
    "H2OXGBoostRegressor", "h2o3_tpu.models.xgboost.XGBoost", False)
H2OKMeansEstimator = _make(
    "H2OKMeansEstimator", "h2o3_tpu.models.kmeans.KMeans", False)
