"""Rapids expression engine — lisp-like AST over frames.

Reference: ``water/rapids/Rapids.java`` (parser), ``Env.java`` (scopes),
``Session.java`` (temp-frame lifecycle). The h2o-py client never sends raw
Java; every lazy ``H2OFrame`` expression compiles to one of these s-expressions
and POSTs it to ``/99/Rapids`` — so this module is what makes a client shim
possible. Grammar (Rapids.java header): ``(op args…)``, numbers, ``"strings"``,
``[num-list]``, identifiers (DKV keys / special ops).

Evaluation is eager here (the laziness lives client-side), each primitive
dispatching to the XLA-backed ops in :mod:`h2o3_tpu.rapids`.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.rapids import munge, ops
from h2o3_tpu.utils.registry import DKV

# ---------------------------------------------------------------------------
# parser


def _tokenize(s: str) -> list[str]:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "()[]":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = s.index(c, i + 1)
            out.append(s[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()[]":
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _parse(tokens: list[str]) -> Any:
    tok = tokens.pop(0)
    if tok == "(":
        expr = []
        while tokens[0] != ")":
            expr.append(_parse(tokens))
        tokens.pop(0)
        return expr
    if tok == "[":
        lst = []
        while tokens[0] != "]":
            lst.append(_parse(tokens))
        tokens.pop(0)
        return np.array(lst, dtype=np.float64)
    if tok[0] in "\"'":
        return ("str", tok[1:-1])
    try:
        return float(tok)
    except ValueError:
        return ("id", tok)


# ---------------------------------------------------------------------------
# evaluator

_BINOPS = {"+": "__add__", "-": "__sub__", "*": "__mul__", "/": "__truediv__",
           "^": "__pow__", "%": "__mod__",
           "<": "__lt__", "<=": "__le__", ">": "__gt__", ">=": "__ge__",
           "==": "__eq__", "!=": "__ne__", "&": "__and__", "|": "__or__"}

import operator as _op_mod

_PYOPS = {"+": _op_mod.add, "-": _op_mod.sub, "*": _op_mod.mul,
          "/": _op_mod.truediv, "^": _op_mod.pow, "%": _op_mod.mod,
          "<": _op_mod.lt, "<=": _op_mod.le, ">": _op_mod.gt,
          ">=": _op_mod.ge, "==": _op_mod.eq, "!=": _op_mod.ne,
          "&": lambda a, b: float(bool(a) and bool(b)),
          "|": lambda a, b: float(bool(a) or bool(b))}

_REDUCERS = {"sum": ops.vsum, "mean": ops.vmean, "min": ops.vmin,
             "max": ops.vmax, "sd": ops.vsd, "var": ops.vvar,
             "median": ops.vmedian, "any": ops.vany, "all": ops.vall,
             "prod": ops.vprod}


def _as_vec(x) -> Vec:
    if isinstance(x, Frame):
        if x.ncols != 1:
            raise ValueError("expected a single-column frame")
        return x.vecs[0]
    if isinstance(x, Vec):
        return x
    raise TypeError(f"expected a column, got {type(x).__name__}")


def _colwise(frame_or_vec, fn) -> Frame:
    if isinstance(frame_or_vec, Frame):
        return Frame(list(frame_or_vec.names), [fn(v) for v in frame_or_vec.vecs])
    return Frame(["C1"], [fn(frame_or_vec)])


class Session:
    """Temp-frame scope (reference: ``water/rapids/Session.java``)."""

    def __init__(self):
        self._tmp: dict[str, Frame] = {}

    def lookup(self, name: str):
        if name in self._tmp:
            return self._tmp[name]
        return DKV.get(name)

    def assign(self, name: str, value: Frame):
        self._tmp[name] = value
        # session temps are DKV-resident until rm'd (reference Session
        # semantics) so clients can fetch them via /3/Frames/{name}
        if isinstance(value, Frame):
            value.key = name
            DKV.put(name, value)
        return value

    def remove(self, name: str):
        """Drop a temp or DKV key (reference: ``AstRm``)."""
        if name in self._tmp:
            del self._tmp[name]
        elif name in DKV:
            DKV.remove(name)

    def end(self):
        self._tmp.clear()


def rapids(expr: str, session: Session | None = None):
    """Parse and evaluate one Rapids expression (reference: ``Rapids.exec``)."""
    session = session or Session()
    return _eval(_parse(_tokenize(expr)), session)


def _eval(node, s: Session):
    if isinstance(node, float) or isinstance(node, np.ndarray):
        return node
    if isinstance(node, tuple):
        kind, val = node
        if kind == "str":
            return val
        obj = s.lookup(val)
        if obj is None:
            raise KeyError(f"unknown identifier {val!r}")
        return obj
    op = node[0]
    op = op[1] if isinstance(op, tuple) else op

    if op in ("tmp=", "assign"):
        name = node[1][1] if isinstance(node[1], tuple) else str(node[1])
        return s.assign(name, _eval(node[2], s))
    if op in ("rm", "h2o.rm"):
        name = node[1][1] if isinstance(node[1], tuple) else str(node[1])
        s.remove(name)
        return 0.0

    args = [_eval(a, s) for a in node[1:]]

    if op in _BINOPS:
        a, b = args
        if isinstance(a, Frame) and isinstance(b, Frame):
            return Frame(list(a.names),
                         [getattr(x, _BINOPS[op])(y)
                          for x, y in zip(a.vecs, b.vecs)])
        if isinstance(a, Frame):
            return _colwise(a, lambda v: getattr(v, _BINOPS[op])(b))
        if isinstance(b, Frame):
            swapped = {"__add__": "__radd__", "__mul__": "__rmul__",
                       "__sub__": "__rsub__", "__truediv__": "__rtruediv__",
                       "__pow__": "__rpow__"}
            m = swapped.get(_BINOPS[op])
            if m:
                return _colwise(b, lambda v: getattr(v, m)(a))
            inverse = {"<": "__gt__", "<=": "__ge__", ">": "__lt__",
                       ">=": "__le__", "==": "__eq__", "!=": "__ne__",
                       "&": "__and__", "|": "__or__"}
            return _colwise(b, lambda v: getattr(v, inverse[op])(a))
        return float(_PYOPS[op](a, b))   # scalar ⋅ scalar

    if op in ops._UNARY:
        return _colwise(args[0], lambda v: ops.math_op(op, v))
    if op in _REDUCERS:
        return _REDUCERS[op](_as_vec(args[0]))
    if op == "ifelse":
        c, yes, no = args
        return _colwise(c, lambda v: ops.ifelse(
            v, _as_vec(yes) if isinstance(yes, Frame) else yes,
            _as_vec(no) if isinstance(no, Frame) else no))
    if op == "cols":
        fr, sel = args
        names = [sel] if isinstance(sel, str) else \
            [fr.names[int(i)] for i in np.atleast_1d(sel)]
        return fr[names]
    if op == "rows":
        fr, sel = args
        if isinstance(sel, Frame):
            return munge.filter_rows(fr, sel.vecs[0])
        return munge.gather_rows(fr, np.atleast_1d(sel).astype(np.int64))
    if op == "nrow":
        return float(args[0].nrows)
    if op == "ncol":
        return float(args[0].ncols)
    if op == "rbind":
        return munge.rbind(*args)
    if op == "cbind":
        return munge.cbind(*args)
    if op == "unique":
        return munge.unique(args[0])
    if op == "sort":
        fr, sel = args[0], args[1]
        cols = [sel] if isinstance(sel, str) else \
            [fr.names[int(i)] for i in np.atleast_1d(sel)]
        asc = [bool(a) for a in np.atleast_1d(args[2])] if len(args) > 2 else True
        return munge.sort(fr, cols, asc)
    if op == "merge":
        return munge.merge(args[0], args[1])
    if op == "h2o.runif":
        fr, seed = args
        rng = np.random.default_rng(int(seed) if seed >= 0 else None)
        return Frame(["rnd"], [Vec.from_numpy(
            rng.random(fr.nrows).astype(np.float32))])

    # -- string prims (reference: ast/prims/string/) ------------------------
    if op == "strsplit":
        from h2o3_tpu.rapids import strings as st
        parts = st.strsplit(_as_vec(args[0]), str(args[1]))
        return Frame([f"C{i + 1}" for i in range(len(parts))], parts)
    if op in _STRING_OPS:
        from h2o3_tpu.rapids import strings as st
        fn = getattr(st, _STRING_OPS[op])
        extra = [int(a) if isinstance(a, float) and float(a).is_integer() else a
                 for a in args[1:]]
        return _colwise(args[0], lambda v: fn(v, *extra))
    # -- time prims (reference: ast/prims/time/) ----------------------------
    if op in _TIME_OPS:
        from h2o3_tpu.rapids import timeops as tt
        fn = getattr(tt, _TIME_OPS[op])
        return _colwise(args[0], fn)
    # -- advmath / munger prims (reference: ast/prims/advmath, mungers) -----
    if op == "quantile":
        probs = np.atleast_1d(args[1]).astype(np.float64) if len(args) > 1 \
            else np.array([0.25, 0.5, 0.75])
        return args[0].quantile(list(probs))
    if op in ("cumsum", "cumprod", "cummin", "cummax"):
        return _colwise(args[0], lambda v: getattr(ops, op)(v))
    if op == "cut":
        fr = args[0]
        breaks = np.atleast_1d(args[1]).astype(np.float64)
        return _colwise(fr, lambda v: ops.cut(v, breaks))
    if op == "hist":
        nbins = int(args[1]) if len(args) > 1 else 20
        return ops.hist(_as_vec(args[0]), nbins)
    if op in ("h2o.impute", "impute"):
        col = args[1] if len(args) > 1 else None
        method = args[2] if len(args) > 2 else "mean"
        return args[0].impute(col, method=method)
    if op == "scale":
        center = bool(args[1]) if len(args) > 1 else True
        sc = bool(args[2]) if len(args) > 2 else True
        return args[0].scale(center=center, scale=sc)
    if op == "round":
        digits = int(args[1]) if len(args) > 1 else 0
        return _colwise(args[0], lambda v: ops.round_(v, digits))
    if op == "signif":
        digits = int(args[1]) if len(args) > 1 else 6
        return _colwise(args[0], lambda v: ops.signif(v, digits))
    if op == "table":
        return munge.table(args[0])
    if op == "GB" or op == "groupby":
        fr, by, agg_col, how = args[0], args[1], args[2], args[3]
        by = [by] if isinstance(by, str) else \
            [fr.names[int(i)] for i in np.atleast_1d(by)]
        return munge.group_by(fr, by, {str(agg_col): str(how)})
    if op == "pivot":
        return munge.pivot(args[0], str(args[1]), str(args[2]), str(args[3]))
    if op == "melt":
        ids = [str(v) for v in (args[1] if isinstance(args[1], list)
                                else [args[1]])]
        return munge.melt(args[0], ids)
    # -- type coercions (reference: ast/prims/operators As*) ----------------
    if op in ("as.factor", "as.character", "as.numeric", "is.na",
              "is.factor", "is.numeric"):
        fr = args[0]
        from h2o3_tpu.frame.types import VecType
        import jax.numpy as jnp

        def coerce(v: Vec) -> Vec:
            if op == "as.factor":
                if v.is_categorical:
                    return v
                vals = np.asarray(v.to_numpy())
                return Vec.from_numpy(np.array(
                    ["" if (isinstance(x, float) and np.isnan(x)) else str(x)
                     for x in vals], dtype=object))
            if op == "as.character":
                lab = v.labels() if v.is_categorical else \
                    np.array([str(x) for x in v.to_numpy()], dtype=object)
                return Vec.from_numpy(np.asarray(lab, dtype=object),
                                      type=VecType.STR)
            if op == "as.numeric":
                return Vec.from_device(v.as_float(), v.nrows, VecType.NUM)
            if op == "is.na":
                isna = (jnp.isnan(v.as_float())
                        if v.data is not None else
                        jnp.zeros(v.plen, bool))
                return Vec.from_device(isna.astype(jnp.float32), v.nrows,
                                       VecType.INT)
            flag = v.is_categorical if op == "is.factor" else v.is_numeric
            return Vec.from_numpy(np.full(v.nrows, float(flag), np.float32))
        return _colwise(fr, coerce)
    if op == "colnames":
        return [str(n) for n in args[0].names]
    if op == "levels":
        v = _as_vec(args[0])
        return list(v.domain or [])
    raise ValueError(f"unknown rapids op {op!r}")


#: ops handled by the dispatch if-chain above (kept in sync by
#: tests/test_rapids.py::test_prims_inventory exercising /99/Rapids/help)
_CHAIN_OPS = (
    "tmp=", "assign", "rm", "h2o.rm", "ifelse", "cols", "rows", "nrow",
    "ncol", "rbind",
    "cbind", "unique", "sort", "merge", "h2o.runif", "strsplit", "quantile",
    "cumsum", "cumprod", "cummin", "cummax", "cut", "hist", "h2o.impute",
    "impute", "scale", "round", "signif", "table", "GB", "groupby", "pivot",
    "melt", "as.factor", "as.character", "as.numeric", "is.na", "is.factor",
    "is.numeric", "colnames", "levels",
)


def known_prims() -> set[str]:
    """Every rapids primitive this engine evaluates (the `/99/Rapids/help`
    surface; reference: ast/prims/* file inventory)."""
    return (set(_BINOPS) | set(ops._UNARY) | set(_REDUCERS)
            | set(_STRING_OPS) | set(_TIME_OPS) | set(_CHAIN_OPS))


_STRING_OPS = {
    "toupper": "toupper", "tolower": "tolower", "trim": "trim",
    "lstrip": "lstrip", "rstrip": "rstrip", "nchar": "nchar",
    "substring": "substring", "sub": "sub", "gsub": "gsub",
    "grep": "grep", "entropy": "entropy",
    "startsWith": "startswith", "endsWith": "endswith",
}

_TIME_OPS = {
    "year": "year", "month": "month", "day": "day", "hour": "hour",
    "minute": "minute", "second": "second", "millis": "millis",
    "dayOfWeek": "day_of_week", "week": "week",
}
