"""Rapids expression engine — lisp-like AST over frames.

Reference: ``water/rapids/Rapids.java`` (parser), ``Env.java`` (scopes),
``Session.java`` (temp-frame lifecycle). The h2o-py client never sends raw
Java; every lazy ``H2OFrame`` expression compiles to one of these s-expressions
and POSTs it to ``/99/Rapids`` — so this module is what makes a client shim
possible. Grammar (Rapids.java header): ``(op args…)``, numbers, ``"strings"``,
``[num-list]``, identifiers (DKV keys / special ops).

Evaluation is eager here (the laziness lives client-side), each primitive
dispatching to the XLA-backed ops in :mod:`h2o3_tpu.rapids`.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.rapids import munge, ops
from h2o3_tpu.utils.registry import DKV

# ---------------------------------------------------------------------------
# parser


def _tokenize(s: str) -> list[str]:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "()[]":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = s.index(c, i + 1)
            out.append(s[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()[]":
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _parse(tokens: list[str]) -> Any:
    tok = tokens.pop(0)
    if tok == "(":
        expr = []
        while tokens[0] != ")":
            expr.append(_parse(tokens))
        tokens.pop(0)
        return expr
    if tok == "[":
        lst = []
        while tokens[0] != "]":
            lst.append(_parse(tokens))
        tokens.pop(0)
        # numeric literals stay an ndarray (row/col index lists); string
        # lists (domains, match tables, pattern lists) stay Python lists
        if all(isinstance(x, (float, np.ndarray)) for x in lst):
            return (np.concatenate([np.atleast_1d(x) for x in lst])
                    if lst else np.array([], dtype=np.float64))
        return [x[1] if isinstance(x, tuple) else x for x in lst]
    if tok[0] in "\"'":
        return ("str", tok[1:-1])
    if tok in ("TRUE", "True", "true"):
        return 1.0
    if tok in ("FALSE", "False", "false"):
        return 0.0
    if tok in ("NA", "NaN", "nan"):
        return float("nan")
    if tok.startswith("#"):      # reference numeric literal syntax
        tok = tok[1:]
    if ":" in tok:
        # AstNumList range entry (reference: water/rapids/ast/params/
        # AstNumList.java:16 — base:cnt or base:cnt:stride); h2o-py emits
        # these for frame slices (expr.py serializes fr[1:] as "[1:N]")
        parts = tok.split(":")
        if 2 <= len(parts) <= 3:
            try:
                base = float(parts[0])
                cnt = float(parts[1])
                stride = float(parts[2]) if len(parts) == 3 else 1.0
                if not np.isfinite(cnt):
                    raise ValueError(f"open-ended range {tok!r} unsupported")
                return base + stride * np.arange(int(cnt), dtype=np.float64)
            except ValueError as e:
                if "open-ended" in str(e):
                    raise
    try:
        return float(tok)
    except ValueError:
        return ("id", tok)


# ---------------------------------------------------------------------------
# evaluator

_BINOPS = {"+": "__add__", "-": "__sub__", "*": "__mul__", "/": "__truediv__",
           "^": "__pow__", "%": "__mod__",
           "<": "__lt__", "<=": "__le__", ">": "__gt__", ">=": "__ge__",
           "==": "__eq__", "!=": "__ne__", "&": "__and__", "|": "__or__"}

import operator as _op_mod

_PYOPS = {"+": _op_mod.add, "-": _op_mod.sub, "*": _op_mod.mul,
          "/": _op_mod.truediv, "^": _op_mod.pow, "%": _op_mod.mod,
          "<": _op_mod.lt, "<=": _op_mod.le, ">": _op_mod.gt,
          ">=": _op_mod.ge, "==": _op_mod.eq, "!=": _op_mod.ne,
          "&": lambda a, b: float(bool(a) and bool(b)),
          "|": lambda a, b: float(bool(a) or bool(b))}

_REDUCERS = {"sum": ops.vsum, "mean": ops.vmean, "min": ops.vmin,
             "max": ops.vmax, "sd": ops.vsd, "var": ops.vvar,
             "median": ops.vmedian, "any": ops.vany, "all": ops.vall,
             "prod": ops.vprod}


def _as_vec(x) -> Vec:
    if isinstance(x, Frame):
        if x.ncols != 1:
            raise ValueError("expected a single-column frame")
        return x.vecs[0]
    if isinstance(x, Vec):
        return x
    raise TypeError(f"expected a column, got {type(x).__name__}")


def _colwise(frame_or_vec, fn) -> Frame:
    if isinstance(frame_or_vec, Frame):
        return Frame(list(frame_or_vec.names), [fn(v) for v in frame_or_vec.vecs])
    return Frame(["C1"], [fn(frame_or_vec)])


class Session:
    """Temp-frame scope (reference: ``water/rapids/Session.java``)."""

    def __init__(self):
        self._tmp: dict[str, Frame] = {}

    def lookup(self, name: str):
        if name in self._tmp:
            return self._tmp[name]
        return DKV.get(name)

    def assign(self, name: str, value: Frame):
        self._tmp[name] = value
        # session temps are DKV-resident until rm'd (reference Session
        # semantics) so clients can fetch them via /3/Frames/{name}
        if isinstance(value, Frame):
            value.key = name
            DKV.put(name, value)
        return value

    def remove(self, name: str):
        """Drop a temp or DKV key (reference: ``AstRm``). Temps are also
        DKV-resident (assign puts them there), so both stores are cleared."""
        if name in self._tmp:
            del self._tmp[name]
        if name in DKV:
            DKV.remove(name)

    def end(self):
        """Session teardown drops every temp's DKV copy too (reference:
        ``Session.end`` → ``Scope`` temp-key cleanup)."""
        for name in list(self._tmp):
            self.remove(name)
        self._tmp.clear()


def rapids(expr: str, session: Session | None = None):
    """Parse and evaluate one Rapids expression (reference: ``Rapids.exec``)."""
    session = session or Session()
    return _eval(_parse(_tokenize(expr)), session)


def _sel_names(fr, sel) -> list[str]:
    """Column selection: name, list of names, or numeric index array."""
    if isinstance(sel, str):
        return [sel]
    if isinstance(sel, list):          # string-list literal ['name' 'value']
        return [str(x) for x in sel]
    return [fr.names[int(i)] for i in np.atleast_1d(sel)]


def _eval(node, s: Session):
    if isinstance(node, float) or isinstance(node, np.ndarray):
        return node
    if isinstance(node, str):
        return node
    if isinstance(node, list) and (not node or
                                   not isinstance(node[0], (tuple, list))):
        # literal list from _parse (string lists: domains, column names) —
        # expression nodes always head with an ('id', op) tuple or a nested
        # list, so a plain-value head means this IS the value
        return node
    if isinstance(node, tuple):
        kind, val = node
        if kind == "str":
            return val
        obj = s.lookup(val)
        if obj is None:
            raise KeyError(f"unknown identifier {val!r}")
        return obj
    op = node[0]
    op = op[1] if isinstance(op, tuple) else op

    if op in ("tmp=", "assign"):
        name = node[1][1] if isinstance(node[1], tuple) else str(node[1])
        return s.assign(name, _eval(node[2], s))
    if op in ("rm", "h2o.rm"):
        name = node[1][1] if isinstance(node[1], tuple) else str(node[1])
        s.remove(name)
        return 0.0

    args = [_eval(a, s) for a in node[1:]]

    if op in _BINOPS:
        a, b = args
        if isinstance(a, Frame) and isinstance(b, Frame):
            return Frame(list(a.names),
                         [getattr(x, _BINOPS[op])(y)
                          for x, y in zip(a.vecs, b.vecs)])
        if isinstance(a, Frame):
            return _colwise(a, lambda v: getattr(v, _BINOPS[op])(b))
        if isinstance(b, Frame):
            swapped = {"__add__": "__radd__", "__mul__": "__rmul__",
                       "__sub__": "__rsub__", "__truediv__": "__rtruediv__",
                       "__pow__": "__rpow__"}
            m = swapped.get(_BINOPS[op])
            if m:
                return _colwise(b, lambda v: getattr(v, m)(a))
            inverse = {"<": "__gt__", "<=": "__ge__", ">": "__lt__",
                       ">=": "__le__", "==": "__eq__", "!=": "__ne__",
                       "&": "__and__", "|": "__or__"}
            return _colwise(b, lambda v: getattr(v, inverse[op])(a))
        return float(_PYOPS[op](a, b))   # scalar ⋅ scalar

    if op in ops._UNARY:
        return _colwise(args[0], lambda v: ops.math_op(op, v))
    if op in _REDUCERS:
        return _REDUCERS[op](_as_vec(args[0]))
    if op == "ifelse":
        c, yes, no = args
        return _colwise(c, lambda v: ops.ifelse(
            v, _as_vec(yes) if isinstance(yes, Frame) else yes,
            _as_vec(no) if isinstance(no, Frame) else no))
    if op == "cols":
        fr, sel = args
        return fr[_sel_names(fr, sel)]
    if op == ":=":                  # AstRectangleAssign (dst src cols rows)
        from h2o3_tpu.rapids import advprims
        return advprims.rectangle_assign(args[0], args[1], args[2], args[3])
    if op == "rows":
        fr, sel = args
        if isinstance(sel, Frame):
            return munge.filter_rows(fr, sel.vecs[0])
        return munge.gather_rows(fr, np.atleast_1d(sel).astype(np.int64))
    if op == "nrow":
        return float(args[0].nrows)
    if op == "ncol":
        return float(args[0].ncols)
    if op == "rbind":
        return munge.rbind(*args)
    if op == "cbind":
        return munge.cbind(*args)
    if op == "unique":
        return munge.unique(args[0])
    if op == "sort":
        fr, sel = args[0], args[1]
        cols = _sel_names(fr, sel)
        asc = [bool(a) for a in np.atleast_1d(args[2])] if len(args) > 2 else True
        return munge.sort(fr, cols, asc)
    if op == "merge":
        return munge.merge(args[0], args[1])
    if op == "h2o.runif":
        fr, seed = args
        rng = np.random.default_rng(int(seed) if seed >= 0 else None)
        return Frame(["rnd"], [Vec.from_numpy(
            rng.random(fr.nrows).astype(np.float32))])

    # -- string prims (reference: ast/prims/string/) ------------------------
    if op == "strsplit":
        from h2o3_tpu.rapids import strings as st
        parts = st.strsplit(_as_vec(args[0]), str(args[1]))
        return Frame([f"C{i + 1}" for i in range(len(parts))], parts)
    if op in _STRING_OPS:
        from h2o3_tpu.rapids import strings as st
        fn = getattr(st, _STRING_OPS[op])
        extra = [int(a) if isinstance(a, float) and float(a).is_integer() else a
                 for a in args[1:]]
        return _colwise(args[0], lambda v: fn(v, *extra))
    # -- time prims (reference: ast/prims/time/) ----------------------------
    if op in _TIME_OPS:
        from h2o3_tpu.rapids import timeops as tt
        fn = getattr(tt, _TIME_OPS[op])
        return _colwise(args[0], fn)
    # -- advmath / munger prims (reference: ast/prims/advmath, mungers) -----
    if op == "quantile":
        probs = np.atleast_1d(args[1]).astype(np.float64) if len(args) > 1 \
            else np.array([0.25, 0.5, 0.75])
        return args[0].quantile(list(probs))
    if op in ("cumsum", "cumprod", "cummin", "cummax"):
        return _colwise(args[0], lambda v: getattr(ops, op)(v))
    if op == "cut":
        fr = args[0]
        breaks = np.atleast_1d(args[1]).astype(np.float64)
        return _colwise(fr, lambda v: ops.cut(v, breaks))
    if op == "hist":
        nbins = int(args[1]) if len(args) > 1 else 20
        return ops.hist(_as_vec(args[0]), nbins)
    if op in ("h2o.impute", "impute"):
        col = args[1] if len(args) > 1 else None
        method = args[2] if len(args) > 2 else "mean"
        return args[0].impute(col, method=method)
    if op == "scale":
        center = bool(args[1]) if len(args) > 1 else True
        sc = bool(args[2]) if len(args) > 2 else True
        return args[0].scale(center=center, scale=sc)
    if op == "round":
        digits = int(args[1]) if len(args) > 1 else 0
        return _colwise(args[0], lambda v: ops.round_(v, digits))
    if op == "signif":
        digits = int(args[1]) if len(args) > 1 else 6
        return _colwise(args[0], lambda v: ops.signif(v, digits))
    if op == "table":
        return munge.table(args[0])
    if op == "GB" or op == "groupby":
        fr, by, agg_col, how = args[0], args[1], args[2], args[3]
        by = [by] if isinstance(by, str) else \
            [fr.names[int(i)] for i in np.atleast_1d(by)]
        return munge.group_by(fr, by, {str(agg_col): str(how)})
    if op == "pivot":
        return munge.pivot(args[0], str(args[1]), str(args[2]), str(args[3]))
    if op == "melt":
        ids = [str(v) for v in (args[1] if isinstance(args[1], list)
                                else [args[1]])]
        return munge.melt(args[0], ids)
    # -- type coercions (reference: ast/prims/operators As*) ----------------
    if op in ("as.factor", "as.character", "as.numeric", "is.na",
              "is.factor", "is.numeric"):
        fr = args[0]
        from h2o3_tpu.frame.types import VecType
        import jax.numpy as jnp

        def coerce(v: Vec) -> Vec:
            if op == "as.factor":
                if v.is_categorical:
                    return v
                vals = np.asarray(v.to_numpy())
                return Vec.from_numpy(np.array(
                    ["" if (isinstance(x, float) and np.isnan(x)) else str(x)
                     for x in vals], dtype=object))
            if op == "as.character":
                lab = v.labels() if v.is_categorical else \
                    np.array([str(x) for x in v.to_numpy()], dtype=object)
                return Vec.from_numpy(np.asarray(lab, dtype=object),
                                      type=VecType.STR)
            if op == "as.numeric":
                return Vec.from_device(v.as_float(), v.nrows, VecType.NUM)
            if op == "is.na":
                isna = (jnp.isnan(v.as_float())
                        if v.data is not None else
                        jnp.zeros(v.plen, bool))
                return Vec.from_device(isna.astype(jnp.float32), v.nrows,
                                       VecType.INT)
            flag = v.is_categorical if op == "is.factor" else v.is_numeric
            return Vec.from_numpy(np.full(v.nrows, float(flag), np.float32))
        return _colwise(fr, coerce)
    if op == "colnames":
        return [str(n) for n in args[0].names]
    if op == "colnames=":
        # AstColNames (mungers/AstColNames.java:17-55): rename selected
        # columns IN PLACE — h2o-py's ``frame.columns = [...]`` setter
        # speaks exactly this, and the reference mutates fr._names so
        # every alias (session temps, DKV entry) sees the new names
        fr = args[0]
        cols = args[1]
        cols = (list(np.atleast_1d(cols)) if isinstance(cols, np.ndarray)
                else cols if isinstance(cols, list) else [cols])
        names = args[2] if isinstance(args[2], list) else [args[2]]
        if len(cols) != len(names):
            raise ValueError("Must have the same number of column choices "
                             "as names")
        for c, nm in zip(cols, names):
            ci = int(c)
            if not 0 <= ci < fr.ncols:
                raise ValueError(f"colnames=: column index {ci} out of "
                                 f"range for {fr.ncols} columns")
            fr.names[ci] = str(nm)
        return fr
    if op == "levels":
        v = _as_vec(args[0])
        return list(v.domain or [])

    # -- prim closure (reference: remaining ast/prims families; exact op
    #    names from each Ast*.str()) — h2o3_tpu/rapids/advprims.py ---------
    from h2o3_tpu.rapids import advprims as ap

    def _vec1(i=0) -> Vec:
        return _as_vec(args[i])

    def _wrap(v):
        if isinstance(v, Vec):
            return Frame(["C1"], [v])
        return v

    if op == "cor":
        fr2 = args[1] if len(args) > 1 and isinstance(args[1], Frame) else None
        use = args[2] if len(args) > 2 else "complete.obs"
        method = args[3] if len(args) > 3 else "Pearson"
        return ap.cor(args[0], fr2, str(use), str(method))
    if op == "spearman":
        return ap.cor(args[0], None, "complete.obs", "Spearman")
    if op == "distance":
        return ap.distance(args[0], args[1],
                           str(args[2]) if len(args) > 2 else "l2")
    if op == "kfold_column":
        return _wrap(ap.kfold_column(args[0], int(args[1]),
                                     int(args[2]) if len(args) > 2 else -1))
    if op == "modulo_kfold_column":
        return _wrap(ap.modulo_kfold_column(args[0], int(args[1])))
    if op == "stratified_kfold_column":
        return _wrap(ap.stratified_kfold_column(
            _vec1(), int(args[1]), int(args[2]) if len(args) > 2 else -1))
    if op in ("h2o.random_stratified_split", "stratified_split"):
        return _wrap(ap.stratified_split(
            _vec1(), float(args[1]) if len(args) > 1 else 0.2,
            int(args[2]) if len(args) > 2 else -1))
    if op == "skewness":
        return ap.skewness(_vec1(), bool(args[1]) if len(args) > 1 else True)
    if op == "kurtosis":
        return ap.kurtosis(_vec1(), bool(args[1]) if len(args) > 1 else True)
    if op == "mode":
        return ap.mode(_vec1())
    if op == "dropdup":
        keep = str(args[2]) if len(args) > 2 else "first"
        by = args[1] if len(args) > 1 else None
        if isinstance(by, (str, float, int)):
            by = [by]
        return ap.drop_duplicates(args[0], by, keep)
    if op == "x":
        return ap.mmult(args[0], args[1])
    if op == "t":
        return ap.transpose(args[0])
    if op == "ddply":
        return ap.ddply(args[0], args[1], args[2], str(args[3]))
    if op == "h2o.fillna":
        return ap.fillna(args[0], str(args[1]) if len(args) > 1 else "forward",
                         int(args[2]) if len(args) > 2 else 0,
                         int(args[3]) if len(args) > 3 else 1)
    if op == "filterNACols":
        return ap.filter_na_cols(args[0],
                                 float(args[1]) if len(args) > 1 else 0.2)
    if op == "na.omit":
        return ap.na_omit(args[0])
    if op == "nlevels":
        return ap.nlevels(_vec1())
    if op == "rank_within_groupby":
        asc = args[3] if len(args) > 3 else None
        if asc is not None and not isinstance(asc, (list, tuple, np.ndarray)):
            asc = [asc]
        return ap.rank_within_group_by(
            args[0], _aslist(args[1]), _aslist(args[2]),
            [bool(a) for a in asc] if asc is not None else None,
            str(args[4]) if len(args) > 4 else "rank",
            bool(args[5]) if len(args) > 5 else False)
    if op == "relevel":
        return _wrap(ap.relevel(_vec1(), str(args[1])))
    if op == "relevel.by.freq":
        return _wrap(ap.relevel_by_freq(
            _vec1(), None, int(args[1]) if len(args) > 1 else -1))
    if op == "rename":
        # AstRename (mungers/AstRename.java:20-46): a DKV KEY rename —
        # (rename "old" "new") — not a column rename (that is colnames=);
        # h2o.rename / model re-keying speak this form
        if len(args) == 2:
            old, new = str(args[0]), str(args[1])
            obj = s.lookup(old)
            if obj is None:
                raise KeyError(f"rename: unknown key {old!r}")
            was_temp = old in s._tmp
            s.remove(old)
            if hasattr(obj, "key"):
                obj.key = new
            if was_temp:
                # a renamed session temp stays session-scoped (reclaimed
                # by Session.end like before the rename)
                s._tmp[new] = obj
            DKV.put(new, obj)
            return float("nan")
        # legacy column-rename form (frame, col, name) kept for callers
        # that used it before colnames= existed
        return ap.rename(args[0], args[1], str(args[2]))
    if op == "setDomain":
        return _wrap(ap.set_domain(_vec1(), [str(s) for s in args[1]]))
    if op == "setLevel":
        return _wrap(ap.set_level(_vec1(), str(args[1])))
    if op == "appendLevels":
        return _wrap(ap.append_levels(_vec1(), [str(s) for s in args[1]]))
    if op == "any.factor":
        return float(ap.any_factor(args[0]))
    if op == "columnsByType":
        return ap.columns_by_type(args[0], str(args[1]))
    if op == "apply":
        return ap.apply_margin(args[0], int(args[1]), str(args[2]))
    if op == "flatten":
        return ap.flatten(args[0])
    if op == "getrow":
        return ap.getrow(args[0])
    if op == "h2o.mad":
        return ap.mad(_vec1(), float(args[1]) if len(args) > 1 else 1.4826)
    if op == "maxNA":
        return ap.max_na(_vec1())
    if op == "minNA":
        return ap.min_na(_vec1())
    if op == "sumNA":
        return ap.sum_na(_vec1())
    if op == "prod.na":
        return ap.prod_na(_vec1())
    if op == "naCnt":
        return ap.na_cnt(_vec1())
    if op == "any.na":
        return float(ap.any_na(args[0]))
    if op == "sumaxis":
        return ap.sum_axis(args[0], bool(args[1]) if len(args) > 1 else True,
                           int(args[2]) if len(args) > 2 else 0)
    if op == "topn":
        return ap.topn(args[0], args[1], float(args[2]),
                       "bottom" if len(args) > 3 and args[3] else "top")
    if op == "seq":
        return _wrap(ap.seq(float(args[0]), float(args[1]),
                            float(args[2]) if len(args) > 2 else 1.0))
    if op == "seq_len":
        return _wrap(ap.seq_len(float(args[0])))
    if op == "rep_len":
        x = _as_vec(args[0]) if isinstance(args[0], Frame) else args[0]
        return _wrap(ap.rep_len(x, float(args[1])))
    if op == "match":
        table = args[1]
        if isinstance(table, np.ndarray):
            table = [float(t) for t in table]
        elif not isinstance(table, (list, tuple)):
            table = [table]
        nomatch = float(args[2]) if len(args) > 2 else np.nan
        start = float(args[3]) if len(args) > 3 else 1
        return _wrap(ap.match(_vec1(), table, nomatch, start))
    if op == "which":
        return _wrap(ap.which(_vec1()))
    if op == "which.max":
        return ap.which_max(args[0], axis=int(args[2]) if len(args) > 2 else 0)
    if op == "which.min":
        return ap.which_min(args[0], axis=int(args[2]) if len(args) > 2 else 0)
    if op == "countmatches":
        pat = args[1] if isinstance(args[1], (list, tuple)) else [str(args[1])]
        return _colwise(args[0], lambda v: ap.count_matches(v, pat))
    if op == "strDistance":
        return _wrap(ap.str_distance(
            _vec1(0), _as_vec(args[1]), str(args[2]) if len(args) > 2 else "lv",
            bool(args[3]) if len(args) > 3 else True))
    if op == "tokenize":
        return ap.tokenize(args[0], str(args[1]))
    if op == "difflag1":
        return _wrap(ap.difflag1(_vec1()))
    if op == "isax":
        return ap.isax(args[0], int(args[1]), int(args[2]),
                       bool(args[3]) if len(args) > 3 else False)
    if op == "perfectAUC":
        return ap.perfect_auc(_vec1(0), _as_vec(args[1]))
    if op in ("replaceall", "replacefirst"):       # AstReplaceAll/First
        from h2o3_tpu.rapids import strings as st
        fn = st.gsub if op == "replaceall" else st.sub
        ic = bool(args[3]) if len(args) > 3 else False
        return _colwise(args[0],
                        lambda v: fn(v, str(args[1]), str(args[2]), ic))
    if op == "num_valid_substrings":               # AstCountSubstringsWords
        from h2o3_tpu.rapids import strings as st
        words = [str(wd) for wd in (args[1] if isinstance(args[1], list)
                                    else [args[1]])]
        return _colwise(args[0], lambda v: st.num_valid_substrings(v, words))
    if op == "append":                             # AstAppend: add a column
        fr, col, name = args[0], args[1], str(args[2])
        return Frame(list(fr.names), list(fr.vecs),
                     key=fr.key).add(name, _as_vec(col))
    if op == "cols_py":                            # AstColPySlice
        fr, sel = args[0], args[1]
        return fr[_sel_names(fr, sel)]
    if op == "moment":                             # AstMoment → epoch ms
        from h2o3_tpu.rapids import timeops as tt
        return _colwise_or_scalar_moment(args)
    if op == "grouped_permute":                    # AstGroupedPermute
        return ap.grouped_permute(args[0], args[1], args[2], args[3],
                                  args[4])
    if op == "PermutationVarImp":
        # AstPermutationVarImp args: (model frame metric n_samples n_repeats
        # features seed) — h2o-py model_base.py:1788 sends exactly this order
        from h2o3_tpu.explanation import permutation_varimp
        from h2o3_tpu.frame.types import VecType
        model = DKV[str(args[0])] if isinstance(args[0], str) else args[0]
        feats = args[5] if len(args) > 5 and isinstance(args[5], list) \
            else None
        rows = permutation_varimp(
            model, args[1], metric=str(args[2]) if len(args) > 2 else None,
            n_samples=int(args[3]) if len(args) > 3 else -1,
            n_repeats=int(args[4]) if len(args) > 4 else 1,
            features=feats,
            seed=int(args[6]) if len(args) > 6 else -1)
        names = ["Variable"] + [k for k in rows[0] if k != "variable"]
        titles = {"relative_importance": "Relative Importance",
                  "scaled_importance": "Scaled Importance",
                  "percentage": "Percentage"}
        vecs = [Vec.from_numpy(np.array([r["variable"] for r in rows],
                                        dtype=object), type=VecType.STR)]
        out_names = ["Variable"]
        for k in names[1:]:
            out_names.append(titles.get(k, k.replace("run_", "Run ")))
            vecs.append(Vec.from_numpy(np.float32([r[k] for r in rows])))
        return Frame(out_names, vecs)
    if op == "makeLeaderboard":
        # AstMakeLeaderboard (models leaderboardFrame sortMetric extensions
        # scoringData) → ranked frame (h2o.make_leaderboard)
        from h2o3_tpu.models.model_base import Model
        from h2o3_tpu.orchestration.leaderboard import Leaderboard
        mods = args[0] if isinstance(args[0], list) else [args[0]]
        lbfr = None
        if len(args) > 1 and args[1] not in (None, ""):
            lbfr = args[1] if isinstance(args[1], Frame) else DKV.get(str(args[1]))
        metric = str(args[2]) if len(args) > 2 and args[2] else None
        lb = Leaderboard(sort_metric=None if metric in (None, "AUTO") else
                         metric.lower(), leaderboard_frame=lbfr)
        for mk in mods:
            lb.add(mk if isinstance(mk, Model) else DKV[str(mk)])
        return lb.as_frame()
    if op == "model.reset.threshold":
        # AstModelResetThreshold: set the binomial decision threshold used by
        # predict(); returns the previous one (0.5 = argmax default)
        model = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        prev = getattr(model, "_default_threshold", None)
        old = 0.5 if prev is None else float(prev)   # 0.0 is a valid threshold
        model._default_threshold = float(args[1])
        DKV.put(model.key, model)
        return old
    if op == "segment_models_as_frame":            # AstSegmentModelsAsFrame
        sm = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        return sm.as_frame()
    if op == "result":                             # AstResultFrame
        # reference: ModelSelection/ANOVAGLM expose their summary as a frame
        model = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        res = getattr(model, "result", None)
        if res is None:
            raise ValueError(f"model {getattr(model, 'key', args[0])!r} has "
                             "no result frame")
        rows = res() if callable(res) else res
        if isinstance(rows, Frame):
            return rows
        from h2o3_tpu.frame.types import VecType
        names = list(rows[0].keys())
        vecs = []
        for nm in names:
            col = [r.get(nm) for r in rows]
            if any(isinstance(c, (str, list, tuple)) for c in col):
                col = [", ".join(map(str, c)) if isinstance(c, (list, tuple))
                       else c for c in col]
                vecs.append(Vec.from_numpy(np.array(col, dtype=object),
                                           type=VecType.STR))
            else:
                vecs.append(Vec.from_numpy(np.float32(
                    [np.nan if c is None else c for c in col])))
        return Frame(names, vecs)
    if op == "transform":
        # AstTransformFrame (model frame) — transformer models (TargetEncoder,
        # Word2Vec) applied via Rapids
        model = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        fr = args[1] if isinstance(args[1], Frame) else DKV[str(args[1])]
        return model.transform(fr)
    if op == "fairnessMetrics":
        # AstFairnessMetrics (model frame protected_cols reference
        # favourable_class) → per-protected-group metrics frame
        from h2o3_tpu.models.infogram import fairness_metrics
        model = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        fr = args[1] if isinstance(args[1], Frame) else DKV[str(args[1])]
        prot = args[2] if isinstance(args[2], list) else [args[2]]
        return fairness_metrics(model, fr, [str(c) for c in prot],
                                reference=[str(r) for r in args[3]]
                                if len(args) > 3 and isinstance(args[3], list)
                                else None,
                                favorable_class=str(args[4])
                                if len(args) > 4 else None)
    if op == "model.testJavaScoring":
        # AstTestJavaScoring analog: the reference cross-checks in-cluster
        # scoring against the generated POJO; here against the exported
        # dependency-free numpy scorer module (genmodel/codegen.py)
        from h2o3_tpu.genmodel.codegen import generate_pojo
        model = args[0] if not isinstance(args[0], str) else DKV[args[0]]
        fr = args[1] if isinstance(args[1], Frame) else DKV[str(args[1])]
        eps = float(args[3]) if len(args) > 3 else 1e-6
        ns: dict = {}
        exec(compile(generate_pojo(model), "<pojo>", "exec"), ns)
        cols = []
        for c in model.output["x_cols"]:
            v = fr.vec(c)
            x = np.asarray(v.to_numpy(), np.float64)
            if v.is_categorical:
                x = np.where(x < 0, np.nan, x)
            cols.append(x)
        got = np.asarray(ns["score_batch"](np.stack(cols, axis=1)))
        ours = model.predict(fr)
        if model.is_classifier:
            a = np.stack([np.asarray(ours.vec(nm).to_numpy())
                          for nm in ours.names[1:]], axis=1)
            b = got[:, 1:] if got.shape[1] == a.shape[1] + 1 else got
        else:
            a = np.asarray(ours.vec("predict").to_numpy())
            b = got[:, 0] if got.ndim == 2 else got
        return float(np.allclose(a, b, atol=eps, rtol=eps))
    if op == "ls":                                 # AstLs → key listing
        from h2o3_tpu.frame.types import VecType
        keys = DKV.keys()
        return Frame(["key"], [Vec.from_numpy(
            np.array(keys, dtype=object), type=VecType.STR)])
    if op == "getTimeZone":
        return "UTC"      # device times are canonical UTC epoch ms
    if op == "listTimeZones":
        import zoneinfo
        return sorted(zoneinfo.available_timezones())
    if op == "setTimeZone":
        raise ValueError("time zone is fixed to UTC in this runtime "
                         "(reference ParseTime zone applies at parse)")
    if op in ("mod", "%%", "intDiv", "%/%"):     # ("%" routes via _BINOPS)
        import jax.numpy as jnp
        fn = jnp.mod if op in ("mod", "%%") else jnp.floor_divide

        def asf(x):
            return _as_vec(x).as_float() if isinstance(x, Frame) else (
                x.as_float() if isinstance(x, Vec) else float(x))
        a, b = args[0], args[1]
        if isinstance(a, (Frame, Vec)):
            bb = asf(b)
            return _colwise(a, lambda v: _vec_binop(v, bb, fn))
        if isinstance(b, (Frame, Vec)):          # scalar on the left
            aa = float(a)
            return _colwise(b, lambda v: _vec_binop(v, aa,
                                                    lambda x, y: fn(y, x)))
        return float(fn(float(a), float(b)))
    raise ValueError(f"unknown rapids op {op!r}")


def _colwise_or_scalar_moment(args):
    """AstMoment: (moment yr mo dy hr mi se ms) of scalars and/or columns
    → single TIME column."""
    from h2o3_tpu.rapids import timeops as tt
    vals = list(args[:7]) + [0.0] * (7 - len(args))
    n = max((a.nrows for a in vals if isinstance(a, (Frame, Vec))), default=1)

    def as_v(x, default):
        if isinstance(x, Frame):
            x = _as_vec(x)
        if isinstance(x, Vec):
            return x
        return Vec.from_numpy(np.full(n, float(default if x is None else x),
                                      np.float32))
    y, mo, d, h, mi, s, ms = (as_v(vals[0], 1970), as_v(vals[1], 1),
                              as_v(vals[2], 1), as_v(vals[3], 0),
                              as_v(vals[4], 0), as_v(vals[5], 0),
                              as_v(vals[6], 0))
    from h2o3_tpu.frame.types import VecType
    t = tt.mktime(y, mo, d, h, mi, s)
    msec = ms.to_numpy().astype(np.float64)
    vals_ms = t.host_values[: t.nrows] + msec[: t.nrows]
    out = np.full(t.nrows, np.datetime64("NaT"), "datetime64[ms]")
    ok = ~np.isnan(vals_ms)
    out[ok] = vals_ms[ok].astype(np.int64).astype("datetime64[ms]")
    return Frame(["time"], [Vec.from_numpy(out, type=VecType.TIME)])


def _aslist(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    if isinstance(x, np.ndarray):
        return [float(v) for v in x]
    return [x]


def _vec_binop(v: Vec, b, fn) -> Vec:
    from h2o3_tpu.frame.types import VecType
    return Vec.from_device(fn(v.as_float(), b).astype("float32"), v.nrows,
                           VecType.NUM)


#: ops handled by the dispatch if-chain above (kept in sync by
#: tests/test_rapids.py::test_prims_inventory exercising /99/Rapids/help)
_CHAIN_OPS = (
    "tmp=", "assign", "rm", "h2o.rm", "ifelse", "cols", "rows", "nrow",
    "ncol", "rbind",
    "cbind", "unique", "sort", "merge", "h2o.runif", "strsplit", "quantile",
    "cumsum", "cumprod", "cummin", "cummax", "cut", "hist", "h2o.impute",
    "impute", "scale", "round", "signif", "table", "GB", "groupby", "pivot",
    "melt", "as.factor", "as.character", "as.numeric", "is.na", "is.factor",
    "is.numeric", "colnames", "colnames=", "levels",
    # prim closure (rapids/advprims.py)
    "cor", "spearman", "distance", "kfold_column", "modulo_kfold_column",
    "stratified_kfold_column", "h2o.random_stratified_split", "skewness",
    "kurtosis", "mode", "dropdup", "x", "t", "ddply", "h2o.fillna",
    "filterNACols", "na.omit", "nlevels", "rank_within_groupby", "relevel",
    "relevel.by.freq", "rename", "setDomain", "setLevel", "appendLevels",
    "any.factor", "columnsByType", "apply", "flatten", "getrow", "h2o.mad",
    "maxNA", "minNA", "sumNA", "prod.na", "naCnt", "any.na", "sumaxis",
    "topn", "seq", "seq_len", "rep_len", "match", "which", "which.max",
    "which.min", "countmatches", "strDistance", "tokenize", "difflag1",
    "isax", "perfectAUC", "mod", "%%", "intDiv", "%/%", ":=",
    "replaceall", "replacefirst", "num_valid_substrings", "append",
    "cols_py", "moment", "getTimeZone", "listTimeZones", "setTimeZone", "ls",
    "PermutationVarImp", "grouped_permute",
    # models family closure (ast/prims/models/)
    "makeLeaderboard", "model.reset.threshold", "segment_models_as_frame",
    "result", "transform", "fairnessMetrics", "model.testJavaScoring",
)


def known_prims() -> set[str]:
    """Every rapids primitive this engine evaluates (the `/99/Rapids/help`
    surface; reference: ast/prims/* file inventory)."""
    return (set(_BINOPS) | set(ops._UNARY) | set(_REDUCERS)
            | set(_STRING_OPS) | set(_TIME_OPS) | set(_CHAIN_OPS))


_STRING_OPS = {
    "toupper": "toupper", "tolower": "tolower", "trim": "trim",
    "lstrip": "lstrip", "rstrip": "rstrip", "nchar": "nchar",
    "substring": "substring", "sub": "sub", "gsub": "gsub",
    "grep": "grep", "entropy": "entropy",
    "startsWith": "startswith", "endsWith": "endswith",
}

_TIME_OPS = {
    "year": "year", "month": "month", "day": "day", "hour": "hour",
    "minute": "minute", "second": "second", "millis": "millis",
    "dayOfWeek": "day_of_week", "week": "week",
}
