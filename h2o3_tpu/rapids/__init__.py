"""Rapids — the frame-munging layer (reference: ``water/rapids/``, ~25 kLoC:
mungers, math, reducers, operators, string, time ops + the lisp expression
engine).
"""

from __future__ import annotations

from typing import Sequence

from h2o3_tpu.rapids import ops, strings, timeops
from h2o3_tpu.rapids.exec import Session, rapids
from h2o3_tpu.rapids.munge import (cbind, filter_rows, gather_rows, group_by,
                                   melt, merge, pivot, rbind, sort, table,
                                   unique)
from h2o3_tpu.rapids.ops import (cut, hist, ifelse, impute, quantile, scale)


class GroupBy:
    """Chained-aggregation surface mirroring h2o-py's ``H2OGroupBy``:
    ``frame.group_by("k").sum("x").mean(["y","z"]).count().get_frame()``."""

    def __init__(self, frame, by):
        self._frame = frame
        self._by = [by] if isinstance(by, str) else list(by)
        self._aggs: list[tuple[str, str]] = []

    def _add(self, op, cols):
        if cols is None:
            cols = [c for c in self._frame.names
                    if c not in self._by and self._frame.vec(c).is_numeric]
        for c in ([cols] if isinstance(cols, str) else cols):
            self._aggs.append((op, c))
        return self

    def count(self): self._aggs.append(("nrow", self._by[0])); return self
    def sum(self, cols=None): return self._add("sum", cols)
    def mean(self, cols=None): return self._add("mean", cols)
    def min(self, cols=None): return self._add("min", cols)
    def max(self, cols=None): return self._add("max", cols)
    def sd(self, cols=None): return self._add("sd", cols)
    def var(self, cols=None): return self._add("var", cols)
    def median(self, cols=None): return self._add("median", cols)

    def get_frame(self):
        return group_by(self._frame, self._by, self._aggs)


__all__ = [
    "GroupBy", "Session", "cbind", "cut", "filter_rows", "gather_rows",
    "group_by", "hist", "ifelse", "impute", "melt", "merge", "ops", "pivot",
    "quantile", "rapids", "rbind", "scale", "sort", "strings", "table",
    "timeops", "unique",
]
