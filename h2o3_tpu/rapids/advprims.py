"""Rapids prim closure — the advmath / munger / reducer / search / repeater /
matrix / timeseries primitives beyond the core engine.

Reference: ``water/rapids/ast/prims/*/`` (207 prim files; each function here
names its Ast* counterpart). Residency policy (VERDICT r3 weak #4): every
row-scale computation — correlations, ranks, dedup, fills, top-n, arg
extremes, AUC, moments — runs on the row-sharded device mesh; only
result-sized payloads (a [k,k] matrix, k winners, group counts) cross to the
host. The remaining host touches are each justified at the site: seeded
host-RNG creation prims (stratified folds/splits, numpy-shuffle parity),
exact f64 TIME payloads (host-resident by design, vec.py:94), 1-row
extractors (flatten/getrow), and string-typed outputs.
"""

from __future__ import annotations

from functools import partial as _partial

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.parallel.distributed import fetch
from h2o3_tpu.rapids import munge


# -- advmath ----------------------------------------------------------------

@jax.jit
def _avg_ranks(X, w):
    """Average (tie-mid) 1-based ranks of the valid entries of each column,
    computed entirely on device: sort once per column, then two binary
    searches give (#strictly-below, #at-or-below); their mean is the
    tie-averaged rank.  Invalid rows sort to +inf and never affect valid
    counts.  O(P log P) per column — no host transfer."""
    Xv = jnp.where(w[:, None] > 0, X, jnp.inf)
    srt = jnp.sort(Xv, axis=0)
    lo = jax.vmap(lambda s, x: jnp.searchsorted(s, x, side="left"),
                  in_axes=(1, 1), out_axes=1)(srt, Xv)
    hi = jax.vmap(lambda s, x: jnp.searchsorted(s, x, side="right"),
                  in_axes=(1, 1), out_axes=1)(srt, Xv)
    return (lo + hi + 1).astype(jnp.float32) / 2.0


@jax.jit
def _weighted_corr(X, w):
    """Pearson correlation of columns of X over rows with weight w — one
    masked-moment pass and one MXU Gram product, all on device."""
    ws = jnp.maximum(w.sum(), 1.0)
    Xz = jnp.where(w[:, None] > 0, X, 0.0)
    mu = Xz.sum(0) / ws          # XLA tree-reduction: ~log2(P)*eps error
    Xc = jnp.where(w[:, None] > 0, X - mu[None, :], 0.0)
    cov = (Xc.T @ Xc) / jnp.maximum(ws - 1.0, 1.0)
    sd = jnp.sqrt(jnp.maximum(jnp.diag(cov), 0.0))
    denom = jnp.outer(sd, sd)
    return jnp.where(denom > 0, cov / jnp.where(denom == 0, 1.0, denom),
                     jnp.nan)


def cor(frame: Frame, frame2: Frame | None = None, use: str = "complete.obs",
        method: str = "Pearson") -> Frame:
    """AstCorrelation / AstSpearmanCorrelation: column correlation matrix.

    Device-resident end to end (VERDICT r3 weak #4): complete-obs masking,
    Spearman rank transform, moments, and the Gram product all run on the
    row-sharded mesh; only the [k, k] result lands on the host."""
    cols = [c for c in frame.names if frame.vec(c).is_numeric]
    X = frame.matrix(cols)                       # [plen, k] device
    valid = frame.row_mask() & ~jnp.isnan(X).any(axis=1)
    w = valid.astype(jnp.float32)
    if method.lower().startswith("spearman"):
        X = _avg_ranks(X, w)
    C = np.asarray(jax.device_get(_weighted_corr(X, w)), np.float64)
    C = C.reshape(len(cols), len(cols))
    return Frame(cols, [Vec.from_numpy(C[:, j].astype(np.float32))
                        for j in range(len(cols))])


def distance(frame: Frame, other: Frame, measure: str = "l2") -> Frame:
    """AstDistance: [nx, ny] pairwise distances (device matmul for the
    inner products — the MXU path)."""
    X = frame.matrix()[: frame.nrows]
    Y = other.matrix()[: other.nrows]
    if measure in ("cosine", "cosine_sq"):
        xn = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True), 1e-30)
        yn = Y / jnp.maximum(jnp.linalg.norm(Y, axis=1, keepdims=True), 1e-30)
        sim = xn @ yn.T
        D = sim * sim if measure == "cosine_sq" else sim
    elif measure == "l1":
        D = jnp.abs(X[:, None, :] - Y[None, :, :]).sum(axis=2)
    else:                                   # l2
        x2 = (X * X).sum(1)[:, None]
        y2 = (Y * Y).sum(1)[None, :]
        D = jnp.sqrt(jnp.maximum(x2 + y2 - 2.0 * (X @ Y.T), 0.0))
    Dh = np.asarray(jax.device_get(D))
    return Frame([f"C{j + 1}" for j in range(Dh.shape[1])],
                 [Vec.from_numpy(Dh[:, j].astype(np.float32))
                  for j in range(Dh.shape[1])])


def kfold_column(frame: Frame, nfolds: int, seed: int = -1) -> Vec:
    """AstKFold: uniform random fold assignment."""
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    return Vec.from_numpy(rng.integers(0, nfolds, frame.nrows)
                          .astype(np.float32))


def modulo_kfold_column(frame: Frame, nfolds: int) -> Vec:
    """AstModuloKFold: fold = row % nfolds."""
    return Vec.from_numpy((np.arange(frame.nrows) % nfolds).astype(np.float32))


def stratified_kfold_column(vec: Vec, nfolds: int, seed: int = -1) -> Vec:
    """AstStratifiedKFold: per-class balanced folds."""
    y = vec.to_numpy()
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    out = np.zeros(len(y), np.float32)
    for cls in np.unique(y[~np.isnan(y.astype(np.float64))]
                         if y.dtype.kind == "f" else np.unique(y)):
        idx = np.nonzero(y == cls)[0]
        rng.shuffle(idx)
        out[idx] = np.arange(len(idx)) % nfolds
    return Vec.from_numpy(out)


def stratified_split(vec: Vec, test_frac: float = 0.2, seed: int = -1) -> Vec:
    """AstStratifiedSplit: per-class train/test factor column."""
    y = vec.to_numpy()
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    out = np.zeros(len(y), np.int32)
    for cls in np.unique(y):
        idx = np.nonzero(y == cls)[0]
        rng.shuffle(idx)
        k = int(round(test_frac * len(idx)))
        out[idx[:k]] = 1
    return Vec.from_numpy(out, type=VecType.CAT, domain=("train", "test"))


@jax.jit
def _central_moments(x, mask):
    """(n, m2, m3, m4, n_na) over valid rows — one fused device pass."""
    ok = mask & ~jnp.isnan(x)
    w = ok.astype(jnp.float32)
    n = w.sum()
    xz = jnp.where(ok, x, 0.0)
    m = xz.sum() / jnp.maximum(n, 1.0)
    d = jnp.where(ok, x - m, 0.0)
    d2 = d * d
    return (n, (d2).sum() / jnp.maximum(n, 1.0),
            (d2 * d).sum() / jnp.maximum(n, 1.0),
            (d2 * d2).sum() / jnp.maximum(n, 1.0),
            mask.sum() - n)


def skewness(vec: Vec, na_rm: bool = True) -> float:
    """AstSkewness: sample skewness g1 * sqrt(n(n-1))/(n-2) (bias-corrected,
    matching the reference's MathUtils). Device-side moment pass."""
    n, m2, m3, _, n_na = map(float, jax.device_get(_central_moments(
        vec.as_float(), _mask_for(vec))))
    if not na_rm and n_na > 0:
        return float("nan")
    g1 = m3 / max(m2, 1e-300) ** 1.5
    return float(g1 * np.sqrt(n * (n - 1)) / max(n - 2, 1))


def kurtosis(vec: Vec, na_rm: bool = True) -> float:
    """AstKurtosis: Pearson kurtosis m4/m2² (≈3 for a normal)."""
    n, m2, _, m4, n_na = map(float, jax.device_get(_central_moments(
        vec.as_float(), _mask_for(vec))))
    if not na_rm and n_na > 0:
        return float("nan")
    return float(m4 / max(m2, 1e-300) ** 2)


def _mask_for(vec: Vec):
    from h2o3_tpu.frame.frame import _row_mask
    return _row_mask(vec.plen, jnp.int32(vec.nrows))


def mode(vec: Vec) -> float:
    """AstMode: most frequent categorical level code (device bincount)."""
    if not vec.is_categorical:
        raise ValueError("mode requires a categorical column")
    card = vec.cardinality()
    codes = jnp.where(_mask_for(vec), vec.data, -1)
    cnt = jnp.bincount(jnp.maximum(codes, 0),
                       weights=(codes >= 0).astype(jnp.float32),
                       length=max(card, 1))
    best, total = jax.device_get((jnp.argmax(cnt), cnt.sum()))
    return float(best) if total > 0 else -1.0


# -- filters ----------------------------------------------------------------

@_partial(jax.jit, static_argnames=("last",))
def _dedup_pick(gid, mask, last: bool):
    """Row index of the first (or last) row of every duplicate group, padded
    with plen at the tail — one stable device sort, no host group scan."""
    plen = gid.shape[0]
    ridx = jnp.arange(plen)
    gkey = jnp.where(mask, gid, jnp.iinfo(jnp.int32).max)   # padding last
    tie = plen - 1 - ridx if last else ridx
    order = jnp.lexsort((tie, gkey))
    gs = gkey[order]
    first = jnp.concatenate([jnp.ones(1, bool), gs[1:] != gs[:-1]])
    first &= mask[order]
    picked = jnp.where(first, order, plen)
    return jnp.sort(picked)


def drop_duplicates(frame: Frame, by=None, keep: str = "first") -> Frame:
    """Astdropduplicates: keep first/last row of each duplicate group.
    Group ids, the dedup sort, and the pick mask all run on device; only the
    surviving row indices (one int per unique row) reach the host for the
    gather."""
    cols = list(by) if by else list(frame.names)
    cols = [frame.names[int(c)] if isinstance(c, (int, float)) else c
            for c in cols]
    gid, _, _ = munge.frame_group_ids(frame, cols)
    picked = np.asarray(jax.device_get(
        _dedup_pick(gid, frame.row_mask(), last=keep == "last")))
    picked = picked[picked < frame.vecs[0].plen]
    return munge.gather_rows(frame, picked)


# -- matrix -----------------------------------------------------------------

def mmult(a: Frame, b: Frame) -> Frame:
    """AstMMult: matrix product on the MXU."""
    X = a.matrix()[: a.nrows]
    Y = b.matrix()[: b.nrows]
    Z = np.asarray(jax.device_get(X @ Y))
    return Frame([f"C{j + 1}" for j in range(Z.shape[1])],
                 [Vec.from_numpy(Z[:, j].astype(np.float32))
                  for j in range(Z.shape[1])])


def transpose(frame: Frame) -> Frame:
    """AstTranspose. The result materializes nrows-many columns, so it is a
    host-shaped op by construction — but the gather is ONE device fetch of
    the [k, n] block, not per-column downloads."""
    X = np.asarray(jax.device_get(frame.matrix().T[:, : frame.nrows]))
    return Frame([f"C{j + 1}" for j in range(X.shape[1])],
                 [Vec.from_numpy(X[:, j]) for j in range(X.shape[1])])


# -- mungers ----------------------------------------------------------------

def any_factor(frame: Frame) -> bool:
    """AstAnyFactor."""
    return any(v.is_categorical for v in frame.vecs)


def append_levels(vec: Vec, levels) -> Vec:
    """AstAppendLevels: extend the domain (codes unchanged)."""
    if not vec.is_categorical:
        raise ValueError("appendLevels requires a categorical column")
    dom = tuple(vec.domain) + tuple(l for l in levels if l not in vec.domain)
    return Vec(vec.data, VecType.CAT, vec.nrows, domain=dom)


def columns_by_type(frame: Frame, coltype: str = "numeric") -> list[float]:
    """AstColumnsByType: 0-based indices of columns of the given type."""
    def match(v: Vec) -> bool:
        t = coltype.lower()
        if t == "numeric":
            return v.type in (VecType.NUM, VecType.INT)
        if t == "categorical":
            return v.type is VecType.CAT
        if t == "string":
            return v.type is VecType.STR
        if t == "time":
            return v.type is VecType.TIME
        if t == "uuid":
            return v.type is VecType.UUID
        if t == "bad":
            return v.type is VecType.BAD
        raise ValueError(f"unknown column type {coltype!r}")
    return [float(i) for i, v in enumerate(frame.vecs) if match(v)]


def ddply(frame: Frame, by, col, fn: str) -> Frame:
    """AstDdply: per-group reduction (the lambda subset the engine runs:
    named reducers over one column; reference ships the same built-ins)."""
    cols = [frame.names[int(c)] if isinstance(c, (int, float)) else c
            for c in (by if isinstance(by, (list, tuple)) else [by])]
    col = frame.names[int(col)] if isinstance(col, (int, float)) else col
    return munge.group_by(frame, cols, {col: fn})


@_partial(jax.jit, static_argnames=("fwd", "maxlen"))
def _fill_scan(X, fwd: bool, maxlen: int):
    """Directional NA fill with run-length cap as one lax.scan over rows,
    vectorized across the [plen, k] column block (stays on device; the
    reference runs the same carry per chunk in AstFillNA's MRTask)."""
    if not fwd:
        X = X[::-1]

    def step(carry, x):
        last, run = carry
        isn = jnp.isnan(x)
        fill = isn & (run < maxlen) & ~jnp.isnan(last)
        y = jnp.where(fill, last, x)
        run2 = jnp.where(isn, jnp.where(fill, run + 1, run),
                         jnp.zeros_like(run))
        last2 = jnp.where(isn, last, x)
        return (last2, run2), y

    k = X.shape[1]
    init = (jnp.full(k, jnp.nan), jnp.zeros(k, jnp.int32))
    _, Y = jax.lax.scan(step, init, X)
    return Y[::-1] if not fwd else Y


def fillna(frame: Frame, method: str = "forward", axis: int = 0,
           maxlen: int = 1) -> Frame:
    """AstFillNA: directional fill with a run-length cap (device scan)."""
    fwd = method.lower().startswith("f")
    dev = [v for v in frame.vecs if v.type.on_device and v.type != VecType.TIME]
    Y = None
    if dev:
        X = jnp.stack([jnp.where(v.data < 0, jnp.nan, v.as_float())
                       if v.is_categorical else v.as_float() for v in dev], 1)
        # padding rows must not leak values backward into logical rows
        X = jnp.where(frame.row_mask()[:, None], X, jnp.nan)
        Y = _fill_scan(X, fwd, int(maxlen))
    out, j = [], 0
    for v in frame.vecs:
        if not v.type.on_device:
            out.append(v)
        elif v.type == VecType.TIME:
            # exact f64 epoch ms lives host-side; fill there to preserve it
            a = np.asarray(v.to_numpy(), np.float64).copy()
            run, last = 0, np.nan
            for i in (range(len(a)) if fwd else range(len(a) - 1, -1, -1)):
                if np.isnan(a[i]):
                    if run < maxlen and not np.isnan(last):
                        a[i] = last
                        run += 1
                else:
                    last, run = a[i], 0
            ns = np.full(len(a), np.datetime64("NaT"), "datetime64[ns]")
            fin = np.isfinite(a)
            whole = np.floor(a[fin])
            ns[fin] = (whole.astype(np.int64) * 1_000_000
                       + np.round((a[fin] - whole) * 1e6).astype(np.int64)
                       ).astype("datetime64[ns]")
            out.append(Vec.from_numpy(ns, type=VecType.TIME))
        else:
            col = Y[:, j]
            j += 1
            if v.is_categorical:
                codes = jnp.where(jnp.isnan(col), -1, col).astype(jnp.int32)
                out.append(Vec.from_device(codes, v.nrows, VecType.CAT,
                                           domain=v.domain))
            else:
                out.append(Vec.from_device(col.astype(jnp.float32), v.nrows,
                                           v.type))
    return Frame(list(frame.names), out)


def filter_na_cols(frame: Frame, frac: float = 0.2) -> list[float]:
    """AstFilterNaCols: indices of columns with NA fraction below frac."""
    keep = []
    for i, v in enumerate(frame.vecs):
        na = int(v.rollups().na_cnt)
        if na / max(frame.nrows, 1) < frac:
            keep.append(float(i))
    return keep


def flatten(frame: Frame):
    """AstFlatten: 1x1 frame → scalar/string."""
    if frame.nrows != 1 or frame.ncols != 1:
        raise ValueError("flatten requires a 1x1 frame")
    v = frame.vecs[0]
    if v.is_categorical:
        return v.labels()[0]
    val = v.to_numpy()[0]
    return float(val) if v.type.on_device else val


def getrow(frame: Frame) -> list:
    """AstGetrow: single-row frame → list of values."""
    if frame.nrows != 1:
        raise ValueError(f"getrow requires a 1-row frame, got {frame.nrows}")
    out = []
    for v in frame.vecs:
        out.append(float(v.to_numpy()[0]) if v.type.on_device else
                   v.host_values[0])
    return out


def na_omit(frame: Frame) -> Frame:
    """AstNaOmit: drop rows containing any NA. The validity mask reduces on
    device; only the surviving indices transfer."""
    ok_dev = frame.row_mask()
    for v in frame.vecs:
        if not v.type.on_device:
            continue
        ok_dev &= (v.data >= 0) if v.is_categorical else ~jnp.isnan(v.data)
    ok = np.asarray(jax.device_get(ok_dev))[: frame.nrows]
    for v in frame.vecs:
        if not v.type.on_device:
            ok &= np.array([x is not None
                            for x in v.host_values[: frame.nrows]])
    return munge.gather_rows(frame, np.nonzero(ok)[0])


def nlevels(vec: Vec) -> float:
    """AstNLevels."""
    return float(vec.cardinality())


def rank_within_group_by(frame: Frame, group_cols, sort_cols, ascending=None,
                         new_col: str = "rank", sort_cols_sorted: bool = False
                         ) -> Frame:
    """AstRankWithinGroupBy: dense 1-based rank of each row within its
    group under the sort order (ties broken by row order, reference
    semantics)."""
    gcols = [frame.names[int(c)] if isinstance(c, (int, float)) else c
             for c in group_cols]
    scols = [frame.names[int(c)] if isinstance(c, (int, float)) else c
             for c in sort_cols]
    asc = list(ascending) if ascending is not None else [True] * len(scols)
    gid, _, _ = munge.frame_group_ids(frame, gcols)
    keys = [jnp.arange(frame.vecs[0].plen)]      # row order breaks ties
    for c, a in zip(scols[::-1], asc[::-1]):
        k = frame.vec(c).as_float()
        keys.append(k if a else -k)
    mask = frame.row_mask()
    keys.append(jnp.where(mask, gid, jnp.iinfo(jnp.int32).max))
    rank = _rank_in_runs(jnp.lexsort(tuple(keys)), keys[-1], mask)
    out = Frame(list(frame.names), list(frame.vecs))
    out.add(new_col, Vec.from_device(rank, frame.nrows, VecType.NUM))
    if sort_cols_sorted:
        out = munge.sort(out, gcols + scols, True)
    return out


@jax.jit
def _rank_in_runs(order, gkey, mask):
    """Scatter 1-based within-group ranks back to row positions: after the
    lexsort, each group is a contiguous run; rank = position − run start,
    via a cummax over run-start markers. All device — the host group scan
    this replaces was O(rows) python (VERDICT r3 weak #4)."""
    plen = order.shape[0]
    gs = gkey[order]
    idx = jnp.arange(plen)
    new_run = jnp.concatenate([jnp.ones(1, bool), gs[1:] != gs[:-1]])
    start = jax.lax.cummax(jnp.where(new_run, idx, 0))
    rank_sorted = (idx - start + 1).astype(jnp.float32)
    out = jnp.zeros(plen, jnp.float32).at[order].set(rank_sorted)
    return jnp.where(mask, out, jnp.nan)


def _remap_codes(vec: Vec, dom: list[str]) -> Vec:
    """Device LUT remap of categorical codes onto a reordered domain."""
    lut = jnp.asarray(np.array([dom.index(d) for d in vec.domain], np.int32))
    new = jnp.where(vec.data >= 0, lut[jnp.clip(vec.data, 0, None)],
                    vec.data)
    return Vec.from_device(new.astype(jnp.int32), vec.nrows, VecType.CAT,
                           domain=tuple(dom))


def relevel(vec: Vec, level: str) -> Vec:
    """AstReLevel: make ``level`` the first (baseline) domain entry."""
    if not vec.is_categorical or level not in (vec.domain or ()):
        raise ValueError(f"level {level!r} not in domain")
    return _remap_codes(vec, [level] + [d for d in vec.domain if d != level])


def relevel_by_freq(vec: Vec, weights: Vec | None = None,
                    top_n: int = -1) -> Vec:
    """AstRelevelByFreq: reorder domain by descending frequency (device
    weighted bincount; only the [cardinality] counts reach the host)."""
    card = max(vec.cardinality(), 1)
    w = weights.as_float() if weights is not None else \
        jnp.ones(vec.plen, jnp.float32)
    w = jnp.where(_mask_for(vec) & (vec.data >= 0)
                  & ~jnp.isnan(w), w, 0.0)
    cnt = np.asarray(jax.device_get(
        jnp.bincount(jnp.maximum(vec.data, 0), weights=w, length=card)),
        np.float64)
    order = np.argsort(-cnt, kind="stable")
    if top_n > 0:   # only promote the top_n most frequent
        rest = np.sort(order[top_n:])
        order = np.concatenate([order[:top_n], rest])
    dom = [vec.domain[i] for i in order]
    return _remap_codes(vec, dom)


def rename(frame: Frame, old, new: str) -> Frame:
    """AstRename (colnames<- single)."""
    i = frame._index(old if not isinstance(old, float) else int(old))
    names = list(frame.names)
    names[i] = new
    return Frame(names, list(frame.vecs), key=frame.key)


def set_domain(vec: Vec, domain) -> Vec:
    """AstSetDomain: replace the level names (codes unchanged)."""
    if not vec.is_categorical:
        raise ValueError("setDomain requires a categorical column")
    if len(domain) != len(vec.domain or ()):
        raise ValueError(f"new domain has {len(domain)} levels, column has "
                         f"{len(vec.domain or ())}")
    return Vec(vec.data, VecType.CAT, vec.nrows, domain=tuple(domain))


def set_level(vec: Vec, level: str) -> Vec:
    """AstSetLevel: constant column at the given level."""
    if level not in (vec.domain or ()):
        raise ValueError(f"level {level!r} not in domain")
    code = vec.domain.index(level)
    return Vec.from_numpy(np.full(vec.nrows, code, np.int32),
                          type=VecType.CAT, domain=vec.domain)


def apply_margin(frame: Frame, margin: int, fn: str) -> Frame:
    """AstApply (named-reducer subset): margin 1 = per row, 2 = per column."""
    from h2o3_tpu.rapids import ops
    X = frame.matrix()[: frame.nrows]
    axis = 1 if int(margin) == 1 else 0
    fns = {"sum": jnp.nansum, "mean": jnp.nanmean, "min": jnp.nanmin,
           "max": jnp.nanmax, "median": lambda a, axis: jnp.nanmedian(a, axis),
           "sd": lambda a, axis: jnp.sqrt(jnp.nanvar(a, axis, ddof=1)),
           "var": lambda a, axis: jnp.nanvar(a, axis, ddof=1),
           "abs": None, "sqrt": None}
    if fn in ("abs", "sqrt"):   # elementwise: margin irrelevant
        Y = np.asarray(jax.device_get(getattr(jnp, fn)(X)))
        return Frame(list(frame.names),
                     [Vec.from_numpy(Y[:, j]) for j in range(Y.shape[1])])
    if fn not in fns:
        raise ValueError(f"apply supports {sorted(fns)}, got {fn!r}")
    r = np.asarray(jax.device_get(fns[fn](X, axis=axis))).ravel()
    if axis == 1:
        return Frame([fn], [Vec.from_numpy(r.astype(np.float32))])
    return Frame(list(frame.names),
                 [Vec.from_numpy(np.float32([v])) for v in r])


# -- reducers ---------------------------------------------------------------

@jax.jit
def _mad_dev(x, mask):
    xv = jnp.where(mask & ~jnp.isnan(x), x, jnp.nan)
    med = jnp.nanmedian(xv)
    return jnp.nanmedian(jnp.abs(xv - med))


def mad(vec: Vec, constant: float = 1.4826) -> float:
    """AstMad: median absolute deviation, scaled (device medians)."""
    return float(constant
                 * jax.device_get(_mad_dev(vec.as_float(), _mask_for(vec))))


def _na_poison(vec: Vec, base: float) -> float:
    return float("nan") if int(vec.rollups().na_cnt) > 0 else base


def max_na(vec: Vec) -> float:
    """AstMaxNa: NA if any NA present (AstNaRollupOp semantics)."""
    from h2o3_tpu.rapids import ops
    return _na_poison(vec, ops.vmax(vec))


def min_na(vec: Vec) -> float:
    from h2o3_tpu.rapids import ops
    return _na_poison(vec, ops.vmin(vec))


def sum_na(vec: Vec) -> float:
    from h2o3_tpu.rapids import ops
    return _na_poison(vec, ops.vsum(vec))


def prod_na(vec: Vec) -> float:
    from h2o3_tpu.rapids import ops
    return _na_poison(vec, ops.vprod(vec))


def na_cnt(vec: Vec) -> float:
    """AstNaCnt."""
    return float(vec.rollups().na_cnt)


def any_na(frame: Frame) -> bool:
    """AstAnyNa."""
    return any(int(v.rollups().na_cnt) > 0 for v in frame.vecs)


def sum_axis(frame: Frame, na_rm: bool = True, axis: int = 0) -> Frame:
    """AstSumAxis: per-column (axis 0) or per-row (axis 1) sums."""
    X = frame.matrix()[: frame.nrows]
    red = jnp.nansum if na_rm else jnp.sum
    if int(axis) == 1:
        r = np.asarray(jax.device_get(red(X, axis=1)))
        return Frame(["sum"], [Vec.from_numpy(r.astype(np.float32))])
    r = np.asarray(jax.device_get(red(X, axis=0))).ravel()
    return Frame(list(frame.names),
                 [Vec.from_numpy(np.float32([v])) for v in r])


def topn(frame: Frame, col, n_percent: float, grab: str = "top") -> Frame:
    """AstTopN: rows (original index, value) of the top/bottom n% values.
    The sort runs on device; only the k winners transfer."""
    col = frame.names[int(col)] if isinstance(col, (int, float)) else col
    v = frame.vec(col)
    n_valid = v.nrows - int(v.rollups().na_cnt)
    k = min(n_valid, max(1, int(round(n_valid * n_percent / 100.0))))
    if k == 0:
        return Frame(["index", col],
                     [Vec.from_numpy(np.zeros(0, np.float32)),
                      Vec.from_numpy(np.zeros(0, np.float32))])
    a = v.as_float()
    top = grab == "top"
    # NA / padding always sorts to the losing end
    key = jnp.where(_mask_for(v) & ~jnp.isnan(a),
                    -a if top else a, jnp.inf)
    order = jnp.argsort(key)[:k]
    pick, vals = jax.device_get((order, a[order]))
    return Frame(["index", col],
                 [Vec.from_numpy(np.asarray(pick, np.float32)),
                  Vec.from_numpy(np.asarray(vals, np.float32))])


# -- repeaters --------------------------------------------------------------

def seq(frm: float, to: float, by: float = 1.0) -> Vec:
    """AstSeq."""
    return Vec.from_numpy(np.arange(frm, to + by * 0.5 * np.sign(by), by)
                          .astype(np.float32))


def seq_len(n: float) -> Vec:
    """AstSeqLen: 1..n."""
    return Vec.from_numpy(np.arange(1, int(n) + 1).astype(np.float32))


def rep_len(x, length: float) -> Vec:
    """AstRepLen: recycle x (vec or scalar) to the given length (device
    modulo-gather; no column download)."""
    n = int(length)
    if isinstance(x, Vec):
        from h2o3_tpu.frame.vec import padded_len
        from h2o3_tpu.parallel.mesh import row_sharding
        idx = jax.device_put(np.arange(padded_len(n)) % max(x.nrows, 1),
                             row_sharding(1))
        out = jnp.take(x.data, idx)
        if x.is_categorical:
            return Vec.from_device(out.astype(jnp.int32), n, VecType.CAT,
                                   domain=x.domain)
        return Vec.from_device(out.astype(jnp.float32), n, VecType.NUM)
    return Vec.from_numpy(np.full(n, float(x), np.float32))


# -- search -----------------------------------------------------------------

def match(vec: Vec, table, nomatch: float = np.nan, start_index: float = 1
          ) -> Vec:
    """AstMatch: position of each value in ``table`` (1-based)."""
    table = list(table) if isinstance(table, (list, tuple)) else [table]
    if vec.is_categorical:
        vals = vec.labels()
        lut = {str(t): i + start_index for i, t in enumerate(table)}
        out = np.array([lut.get(v, nomatch) if v is not None else nomatch
                        for v in vals], np.float64)
    else:
        # device: [plen, m] equality against the (small) table, first hit wins
        tbl = jnp.asarray(np.array([float(t) for t in table], np.float32))
        a = vec.as_float()
        hit = a[:, None] == tbl[None, :]
        pos = jnp.argmax(hit, axis=1).astype(jnp.float32) + float(start_index)
        out_dev = jnp.where(hit.any(axis=1), pos, float(nomatch))
        return Vec.from_device(out_dev.astype(jnp.float32), vec.nrows,
                               VecType.NUM)
    return Vec.from_numpy(out.astype(np.float32))


def which(vec: Vec) -> Vec:
    """AstWhich: 0-based row numbers where the value is truthy (mask
    reduces on device; one bool per row transfers)."""
    a = vec.as_float()
    m = np.asarray(jax.device_get(_mask_for(vec) & ~jnp.isnan(a) & (a != 0)))
    idx = np.nonzero(m[: vec.nrows])[0]
    return Vec.from_numpy(idx.astype(np.float32))


def which_max(frame: Frame, na_rm: bool = True, axis: int = 0) -> Frame:
    return _which_extreme(frame, jnp.nanargmax, axis)


def which_min(frame: Frame, na_rm: bool = True, axis: int = 0) -> Frame:
    return _which_extreme(frame, jnp.nanargmin, axis)


def _which_extreme(frame: Frame, red, axis: int) -> Frame:
    X = frame.matrix()
    if int(axis) == 1:       # per-row arg-extreme: stays device-resident
        r = red(X, axis=1).astype(jnp.float32)
        return Frame(["which"], [Vec.from_device(r, frame.nrows, VecType.NUM)])
    Xl = jnp.where(frame.row_mask()[:, None], X, jnp.nan)
    r = np.asarray(jax.device_get(red(Xl, axis=0))).astype(np.float32).ravel()
    return Frame(list(frame.names),
                 [Vec.from_numpy(np.float32([v])) for v in r])


# -- string extras ----------------------------------------------------------

def count_matches(vec: Vec, pattern) -> Vec:
    """AstCountMatches: occurrences of pattern(s) per string."""
    pats = list(pattern) if isinstance(pattern, (list, tuple)) else [pattern]
    vals = vec.labels() if vec.is_categorical else vec.host_values
    out = np.array([sum(str(v).count(p) for p in pats) if v is not None
                    else np.nan for v in vals[: vec.nrows]], np.float64)
    return Vec.from_numpy(out.astype(np.float32))


def str_distance(vec: Vec, other: Vec, measure: str = "lv",
                 compare_empty: bool = True) -> Vec:
    """AstStrDistance: per-row Levenshtein (lv) / Jaccard (jaccard)."""
    a = vec.labels() if vec.is_categorical else vec.host_values
    b = other.labels() if other.is_categorical else other.host_values

    def lev(s, t):
        if s is None or t is None:
            return np.nan
        if not compare_empty and (s == "" or t == ""):
            return np.nan
        prev = list(range(len(t) + 1))
        for i, cs in enumerate(s, 1):
            cur = [i]
            for j, ct in enumerate(t, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (cs != ct)))
            prev = cur
        return prev[-1]

    def jac(s, t):
        if s is None or t is None:
            return np.nan
        A, B = set(s), set(t)
        return 1.0 - len(A & B) / max(len(A | B), 1)

    fn = jac if measure == "jaccard" else lev
    out = np.array([fn(x, y) for x, y in
                    zip(a[: vec.nrows], b[: other.nrows])], np.float64)
    return Vec.from_numpy(out.astype(np.float32))


def tokenize(frame: Frame, split: str) -> Frame:
    """AstTokenize: one token per row, NA row between documents (the
    Word2Vec ingest format)."""
    import re as _re
    toks: list = []
    for v in frame.vecs:
        vals = v.labels() if v.is_categorical else v.host_values
        for s in vals[: v.nrows]:
            if s is None:
                toks.append(None)
                continue
            toks.extend(t for t in _re.split(split, str(s)) if t)
            toks.append(None)
    return Frame(["token"], [Vec.from_numpy(np.array(toks, dtype=object),
                                            type=VecType.STR)])


# -- timeseries -------------------------------------------------------------

def difflag1(vec: Vec) -> Vec:
    """AstDiffLag1: x[i] - x[i-1] (first row NA) — device shift-subtract."""
    a = vec.as_float()
    d = (a - jnp.roll(a, 1)).at[0].set(jnp.nan)
    return Vec.from_device(d.astype(jnp.float32), vec.nrows, VecType.NUM)


def isax(frame: Frame, num_words: int, max_cardinality: int,
         optimize_card: bool = False) -> Frame:
    """AstIsax: per-row iSAX word — PAA over ``num_words`` segments, each
    quantized into ``max_cardinality`` gaussian breakpoints. Z-normalize,
    PAA, and quantization run on device; the [n, words] code block is the
    one transfer (the word strings are host-typed output)."""
    from scipy.stats import norm
    X = frame.matrix()
    mu = jnp.nanmean(X, axis=1, keepdims=True)
    sd = jnp.nanstd(X, axis=1, keepdims=True)
    Z = (X - mu) / jnp.maximum(sd, 1e-12)
    segs = np.array_split(np.arange(X.shape[1]), num_words)
    paa = jnp.stack([Z[:, int(s[0]): int(s[-1]) + 1].mean(axis=1)
                     for s in segs], 1)
    breaks = jnp.asarray(
        norm.ppf(np.linspace(0, 1, max_cardinality + 1)[1:-1]).astype(
            np.float32))
    codes_dev = jnp.searchsorted(breaks, paa.reshape(-1)).reshape(paa.shape)
    codes = np.asarray(jax.device_get(codes_dev))[: frame.nrows]
    words = np.array(["^".join(str(c) for c in row) for row in codes],
                     dtype=object)
    out = Frame(["iSax_index"], [Vec.from_numpy(words, type=VecType.STR)])
    for j in range(num_words):
        out.add(f"c{j}", Vec.from_numpy(codes[:, j].astype(np.float32)))
    return out


# -- models -----------------------------------------------------------------

@jax.jit
def _perfect_auc_dev(p, y, mask):
    """Mann-Whitney AUC with tie-averaged ranks on device: one sort + two
    binary searches give the average rank of each probability (ties get the
    midpoint), then the rank-sum statistic reduces."""
    ok = mask & ~jnp.isnan(p) & ~jnp.isnan(y)
    pv = jnp.where(ok, p, jnp.inf)
    srt = jnp.sort(pv)
    lo = jnp.searchsorted(srt, pv, side="left")
    hi = jnp.searchsorted(srt, pv, side="right")
    ranks = (lo + hi + 1).astype(jnp.float32) / 2.0
    pos = ok & (y > 0)
    # counts in f32: int32 npos*nneg wraps above ~46k x 46k rows; f32 keeps
    # ~1e-7 relative accuracy and XLA's tree reduction bounds the rank-sum
    # error at ~log2(n)*eps relative — AUC good to ~1e-5 at 10M rows
    npos = pos.sum().astype(jnp.float32)
    nneg = ok.sum().astype(jnp.float32) - npos
    s = jnp.where(pos, ranks, 0.0).sum()
    denom = jnp.maximum(npos * nneg, 1.0)
    return (s - npos * (npos + 1.0) / 2.0) / denom, npos, nneg


def perfect_auc(probs: Vec, acts: Vec) -> float:
    """AstPerfectAUC: exact (not binned) AUC from raw probabilities."""
    auc, npos, nneg = jax.device_get(_perfect_auc_dev(
        probs.as_float(), acts.as_float(), _mask_for(probs)))
    if int(npos) == 0 or int(nneg) == 0:
        return 1.0
    return float(auc)


def grouped_permute(frame: Frame, perm_col, group_by, permute_by, keep_col
                    ) -> Frame:
    """AstGroupedPermute: per group, cross-join the ``perm_col`` ids whose
    ``permute_by`` level is "D" (→ In) against the rest (→ Out), carrying
    summed ``keep_col`` amounts — output (group…, In, Out, InAmnt, OutAmnt).
    Plan-shaped (dynamic output size): grouped host pass like the
    reference's per-node hash build."""
    def col(i):
        return frame.names[int(i)] if isinstance(i, (int, float)) else i

    perm_col, keep_col = col(perm_col), col(keep_col)
    pb = frame.vec(col(permute_by))
    gcols = [col(g) for g in (group_by if isinstance(group_by, (list, tuple,
                                                               np.ndarray))
                              else [group_by])]
    if not pb.is_categorical:
        raise ValueError("permuteBy must be categorical")
    # aggregate (group, id, side) -> sum(amount) on DEVICE first; the host
    # cross-join then runs over unique combos, not raw rows
    d_code = pb.domain.index("D") if "D" in (pb.domain or ()) else -2
    side_dev = (pb.data == d_code).astype(jnp.float32)
    tmp = Frame(list(frame.names), list(frame.vecs))
    tmp.add("__side", Vec.from_device(side_dev, frame.nrows, VecType.NUM))
    agg = munge.group_by(tmp, gcols + [perm_col, "__side"],
                         {keep_col: "sum"})
    gvals = (np.stack([np.asarray(fetch(agg.vec(g).as_float()))[: agg.nrows]
                       for g in gcols], 1).astype(np.float64)
             if gcols else np.zeros((agg.nrows, 0)))
    rid = np.asarray(fetch(agg.vec(perm_col).as_float()))[: agg.nrows]
    is_in = np.asarray(fetch(agg.vec("__side").as_float()))[: agg.nrows] > 0
    amt = np.asarray(fetch(agg.vec(f"sum_{keep_col}").as_float())
                     )[: agg.nrows]

    groups: dict = {}
    for r in range(agg.nrows):
        key = tuple(gvals[r])
        ins, outs = groups.setdefault(key, ({}, {}))
        side = ins if is_in[r] else outs
        side[rid[r]] = side.get(rid[r], 0.0) + amt[r]

    rows: list[list[float]] = []
    for key, (ins, outs) in groups.items():
        for i_id, i_amt in ins.items():
            for o_id, o_amt in outs.items():
                rows.append(list(key) + [i_id, o_id, i_amt, o_amt])
    names = gcols + ["In", "Out", "InAmnt", "OutAmnt"]
    if not rows:
        return Frame(names, [Vec.from_numpy(np.zeros(0, np.float32))
                             for _ in names])
    arr = np.asarray(rows, np.float32)
    return Frame(names, [Vec.from_numpy(arr[:, j])
                         for j in range(arr.shape[1])])


def rectangle_assign(dst: Frame, src, cols, rows) -> Frame:
    """AstRectangleAssign ``(:= dst src col_expr row_expr)`` — assign a
    scalar/string/NA or a Frame into a row×column slice of ``dst``
    (reference ``ast/prims/assign/AstRectangleAssign.java``; h2o-py emits it
    for ``fr[rows, cols] = value``). Returns a fresh Frame (the reference is
    copy-on-write; device arrays here are immutable anyway)."""
    n = dst.nrows

    def _empty_sel(s):
        # the Rapids parser yields "[]" as an empty ndarray, clients may also
        # send [] — both mean "all" (reference AstRectangleAssign special case)
        return s is None or (isinstance(s, (list, tuple, np.ndarray))
                             and len(s) == 0)

    # -- column selection ([] = all; numbers or names) -----------------------
    if _empty_sel(cols):
        cidx = list(range(dst.ncols))
    else:
        sel = cols if isinstance(cols, (list, tuple, np.ndarray)) else [cols]
        cidx = [dst.names.index(c) if isinstance(c, str) else int(c)
                for c in sel]
    # -- row selection ([] = all; boolean-mask Frame/Vec; index list) --------
    if _empty_sel(rows):
        ridx = np.arange(n)
    elif isinstance(rows, Frame) or isinstance(rows, Vec):
        mv = rows.vecs[0] if isinstance(rows, Frame) else rows
        m = np.asarray(fetch(mv.as_float()))[:n]
        ridx = np.nonzero((m > 0) & ~np.isnan(m))[0]
    else:
        ridx = np.atleast_1d(np.asarray(rows)).astype(np.int64)
    if np.any((ridx < 0) | (ridx >= n)):
        raise ValueError("row index out of range in rectangle assign")

    def src_col(j_pos: int):
        """Source values aligned to ridx for the j-th selected column."""
        v = src.vecs[j_pos]
        if v.type == VecType.CAT:
            vals = v.labels()
        elif v.type in (VecType.STR, VecType.UUID):
            vals = v.host_values
        elif v.type == VecType.TIME:
            # exact ABSOLUTE epoch ms — device data is shifted by the
            # source's own time_offset and would land decades off
            vals = np.asarray(v.to_numpy(), np.float64)
        else:
            vals = np.asarray(fetch(v.as_float()))[: src.nrows]
        if src.nrows == n:              # full-height source: pick slice rows
            return vals[ridx]
        if src.nrows == len(ridx):      # slice-height source: direct
            return vals
        raise ValueError(
            f"source frame has {src.nrows} rows; need {n} or {len(ridx)}")

    new_vecs = list(dst.vecs)
    for j_pos, j in enumerate(cidx):
        v = dst.vecs[j]
        if isinstance(src, Frame):
            vals = src_col(j_pos)
        else:
            vals = src                   # scalar / string / None broadcast
        if v.type == VecType.CAT:
            cur = v.labels()             # object array of labels (None = NA)
            cur[ridx] = vals
            new_vecs[j] = Vec.from_numpy(cur, type=VecType.CAT)
        elif v.type in (VecType.STR, VecType.UUID):
            cur = np.array(v.host_values, dtype=object)
            cur[ridx] = vals
            new_vecs[j] = Vec.from_numpy(cur, type=v.type)
        elif v.type == VecType.TIME:
            # TIME device data is *shifted* f32 ms; the exact absolute epoch
            # ms live host-side in f64 (vec.py:94-97). Mutate the f64 host
            # values (rapids time scalars are absolute epoch ms, vec.py:240)
            # and rebuild through the datetime64 path so the ms-offset device
            # encoding and exact host values are preserved — storing absolute
            # epoch ms (~1.7e12) as raw f32 would corrupt every row by up to
            # ~131 s (f32 resolution at that magnitude).
            cur = (np.array(v.host_values, dtype=np.float64)[:n]
                   if v.host_values is not None else
                   np.asarray(fetch(v.as_float()))[:n].astype(np.float64)
                   + v.time_offset)
            fv = (np.nan if vals is None else
                  np.asarray(vals, np.float64) if not np.isscalar(vals)
                  else float(vals))
            cur[ridx] = fv
            ns = np.full(n, np.datetime64("NaT"), dtype="datetime64[ns]")
            fin = np.isfinite(cur)
            # integer-exact ms->ns: cur*1e6 in f64 is inexact above 2^53
            # (~0.24 us drift on ~25% of epoch-ms values); split whole ms
            # (exact int64) from sub-ms remainder
            whole = np.floor(cur[fin])
            ns_i = (whole.astype(np.int64) * 1_000_000
                    + np.round((cur[fin] - whole) * 1e6).astype(np.int64))
            ns[fin] = ns_i.astype("datetime64[ns]")
            new_vecs[j] = Vec.from_numpy(ns, type=VecType.TIME)
        else:
            cur = np.asarray(fetch(v.as_float()))[:n].astype(np.float64)
            fv = (np.nan if vals is None else
                  np.asarray(vals, np.float64) if not np.isscalar(vals)
                  else float(vals))
            cur[ridx] = fv
            new_vecs[j] = Vec.from_numpy(cur.astype(np.float32), type=VecType.NUM)
    return Frame(list(dst.names), new_vecs)
