"""Frame mungers: sort, group-by, merge/join, rbind/cbind, pivot, melt, unique.

Reference: ``water/rapids/ast/prims/mungers/`` (``AstGroup``, ``AstMerge``,
``AstSort``, ``AstPivot``, ``AstMelt``, ``AstRBind``/``AstCBind``, ``AstUnique``)
and the distributed sort/merge engine (``water/rapids/RadixOrder.java:20-105``,
``BinaryMerge.java``, ``Merge.java``, ``SortCombine.java``).

TPU-native redesign: the reference's MSB-radix distributed sort + chunked
binary merge becomes **one XLA lexsort over the row-sharded columns** (XLA sort
is a distributed bitonic network over ICI) and group identification becomes
sorted-boundary cumsum + ``segment_sum`` reductions — the standard accelerator
database idiom. Join plans (which output row pairs exist) are control-flow
heavy and sized dynamically, so they are computed with numpy on the host from
the device-computed group ids; the actual data movement is device gathers.
"""

from __future__ import annotations

from functools import reduce
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import CAT_NA, VecType
from h2o3_tpu.frame.vec import Vec, padded_len
from h2o3_tpu.parallel.distributed import fetch
from h2o3_tpu.parallel.mesh import row_sharding

# ---------------------------------------------------------------------------
# gather plumbing


def _put(arr: np.ndarray | jax.Array) -> jax.Array:
    """Row-shard onto the global mesh; multi-process safe. Device inputs
    that span processes are gathered host-side first (the join planners are
    host algorithms anyway), then re-uploaded via the process-local-shard
    path shared with Frame ingest."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        arr = fetch(arr)
    if isinstance(arr, jax.Array):
        return jax.device_put(arr, row_sharding(1))
    from h2o3_tpu.frame.vec import _put as _vec_put
    return _vec_put(np.asarray(arr), row_sharding(1))


def _pad_to(arr: jax.Array, plen: int, fill) -> jax.Array:
    if arr.shape[0] == plen:
        return arr
    if arr.shape[0] > plen:
        return arr[:plen]
    return jnp.concatenate([arr, jnp.full(plen - arr.shape[0], fill, arr.dtype)])


def _gather_vec(v: Vec, idx_dev: jax.Array, idx_host: np.ndarray, new_nrows: int) -> Vec:
    """New Vec of ``v``'s values at source rows ``idx`` (−1 → NA)."""
    if v.type is VecType.TIME and v.host_values is not None:
        ms = np.full(new_nrows, np.nan)
        ok = idx_host >= 0
        ms[ok] = v.host_values[idx_host[ok]]
        from h2o3_tpu.rapids.timeops import ms_to_datetime64
        return Vec.from_numpy(ms_to_datetime64(ms), type=VecType.TIME)
    if not v.type.on_device:
        out = np.full(new_nrows, None, dtype=object)
        ok = idx_host >= 0
        out[ok] = v.host_values[idx_host[ok]]
        return Vec(None, v.type, new_nrows, host_values=out)
    safe = jnp.clip(idx_dev, 0, v.plen - 1)
    g = v.data[safe]
    fill = CAT_NA if v.type is VecType.CAT else jnp.nan
    g = jnp.where(idx_dev < 0, jnp.asarray(fill, g.dtype), g)
    return Vec(_put(g), v.type, new_nrows, domain=v.domain)


def gather_rows(frame: Frame, idx: np.ndarray) -> Frame:
    """Frame of ``frame``'s rows at host indices ``idx`` (−1 → all-NA row).
    This is the reference's row-slice / merge materialization step."""
    idx = np.asarray(idx, np.int32)
    n = len(idx)
    idx_dev = _put(_pad_host(idx, padded_len(n)))
    return Frame(list(frame.names),
                 [_gather_vec(v, idx_dev, idx, n) for v in frame.vecs],
                 key=None)


def _pad_host(idx: np.ndarray, plen: int) -> np.ndarray:
    out = np.full(plen, -1, np.int32)
    out[: len(idx)] = idx
    return out


# ---------------------------------------------------------------------------
# sort


def _float_keys(frame: Frame, by: Sequence[str], ascending: Sequence[bool]):
    keys = []
    for col, asc in zip(by, ascending):
        k = frame.vec(col).as_float()
        if not asc:
            k = -k
        keys.append(jnp.where(jnp.isnan(k), jnp.inf, k))   # NAs sort last
    return keys


def sort_perm(frame: Frame, by: Sequence[str], ascending) -> np.ndarray:
    """Host permutation of logical rows ordering ``frame`` by ``by``."""
    if isinstance(ascending, bool):
        ascending = [ascending] * len(by)
    keys = _float_keys(frame, by, ascending)
    is_pad = (jnp.arange(frame.plen) >= frame.nrows).astype(jnp.int32)
    # lexsort: LAST key is primary — padding first, then by[0], by[1], ...
    perm = jnp.lexsort(tuple(reversed(keys)) + (is_pad,))
    return fetch(perm)[: frame.nrows]


def sort(frame: Frame, by: str | Sequence[str], ascending=True) -> Frame:
    """Reference: ``AstSort`` / ``Merge.sort`` — stable multi-column sort,
    NAs last."""
    by = [by] if isinstance(by, str) else list(by)
    return gather_rows(frame, sort_perm(frame, by, ascending))


# ---------------------------------------------------------------------------
# group ids (shared by group_by / merge / pivot / unique)


def _group_ids(key_cols: list[jax.Array], valid: jax.Array):
    """(gid [plen] int32 in original row order — invalid rows get id ngroups,
    ngroups, rep_idx [ngroups] host int32 of one source row per group).

    Sorted-boundary trick: lexsort keys (invalid rows forced last), boundary
    where any key differs from the previous row, cumsum → dense group ids.
    """
    plen = key_cols[0].shape[0]
    keys = [jnp.where(jnp.isnan(k), jnp.inf, k) for k in key_cols]
    inval = (~valid).astype(jnp.int32)
    perm = jnp.lexsort(tuple(reversed(keys)) + (inval,))
    skeys = [k[perm] for k in keys]
    svalid = valid[perm]
    differs = reduce(jnp.logical_or,
                     [jnp.concatenate([jnp.zeros(1, bool), k[1:] != k[:-1]])
                      for k in skeys])
    gid_sorted = jnp.cumsum(differs.astype(jnp.int32))
    nvalid = int(fetch(valid.sum()))
    if nvalid == 0:
        return jnp.zeros(plen, jnp.int32), 0, np.empty(0, np.int32)
    ngroups = int(fetch(gid_sorted[nvalid - 1])) + 1
    gid = jnp.zeros(plen, jnp.int32).at[perm].set(gid_sorted)
    gid = jnp.where(valid, gid, ngroups).astype(jnp.int32)
    # representative source row per group = min original index
    rep = jax.ops.segment_min(jnp.arange(plen, dtype=jnp.int32), gid,
                              num_segments=ngroups + 1)[:ngroups]
    return gid, ngroups, fetch(rep)


def frame_group_ids(frame: Frame, by: Sequence[str]):
    cols = [frame.vec(c).as_float() for c in by]
    return _group_ids(cols, frame.row_mask())


# ---------------------------------------------------------------------------
# group-by

_AGG_OPS = ("count", "nrow", "sum", "mean", "min", "max", "var", "sd",
            "median", "first", "last")


def group_by(frame: Frame, by: str | Sequence[str],
             aggs: Mapping[str, Sequence[str]] | Sequence[tuple[str, str]]) -> Frame:
    """Grouped aggregation (reference: ``AstGroup``; h2o-py ``H2OFrame.group_by``).

    ``aggs``: ``{"col": ["mean", "sum"], ...}`` or ``[("mean", "col"), ...]``.
    NAs in aggregated columns are ignored (reference ``na="rm"`` default);
    NA key rows form their own group (reference groups NAs together).
    """
    by = [by] if isinstance(by, str) else list(by)
    if isinstance(aggs, Mapping):
        pairs = [(op, col) for col, ops in aggs.items()
                 for op in ([ops] if isinstance(ops, str) else ops)]
    else:
        pairs = [(op, col) for op, col in aggs]
    for op, col in pairs:
        if op not in _AGG_OPS:
            raise ValueError(f"unknown agg {op!r}; have {_AGG_OPS}")
        frame.vec(col)   # raises on missing column

    gid, ng, rep = frame_group_ids(frame, by)
    nseg = ng + 1   # junk bucket for padding/invalid rows
    out_names: list[str] = []
    out_vals: list[np.ndarray] = []

    for op, col in pairs:
        x = frame.vec(col).as_float()
        valid = ~jnp.isnan(x) & frame.row_mask()
        xv = jnp.where(valid, x, 0.0)
        cnt = jax.ops.segment_sum(valid.astype(jnp.float32), gid, nseg)
        if op in ("count", "nrow"):
            # row count per group: rows with an NA aggregate value (or an NA
            # key — the NA group) still count (reference AstGroup.nrow)
            agg = jax.ops.segment_sum(
                frame.row_mask().astype(jnp.float32), gid, nseg)
        elif op == "sum":
            agg = jax.ops.segment_sum(xv, gid, nseg)
        elif op == "mean":
            agg = jax.ops.segment_sum(xv, gid, nseg) / jnp.maximum(cnt, 1.0)
        elif op == "min":
            agg = jax.ops.segment_min(jnp.where(valid, x, jnp.inf), gid, nseg)
        elif op == "max":
            agg = jax.ops.segment_max(jnp.where(valid, x, -jnp.inf), gid, nseg)
        elif op in ("var", "sd"):
            s = jax.ops.segment_sum(xv, gid, nseg)
            ss = jax.ops.segment_sum(xv * xv, gid, nseg)
            var = (ss - s * s / jnp.maximum(cnt, 1.0)) / jnp.maximum(cnt - 1.0, 1.0)
            agg = jnp.sqrt(jnp.maximum(var, 0.0)) if op == "sd" else var
        elif op in ("first", "last"):
            seg = jax.ops.segment_min if op == "first" else jax.ops.segment_max
            sentinel = jnp.iinfo(jnp.int32).max if op == "first" else -1
            ridx = seg(jnp.where(valid, jnp.arange(x.shape[0], dtype=jnp.int32),
                                 sentinel), gid, nseg)
            safe = jnp.clip(ridx, 0, x.shape[0] - 1)
            agg = jnp.where((ridx >= 0) & (ridx < x.shape[0]), x[safe], jnp.nan)
        elif op == "median":
            # median needs values ordered within each group: one extra lexsort
            # with the value as the minor key (reference AstGroup medians also
            # re-sort)
            agg = _group_median(frame, col, gid, nseg)
        agg = jnp.where(cnt > 0, agg, jnp.nan) if op not in ("count", "nrow") else agg
        out_names.append(f"{op}_{col}" if op != "nrow" else "nrow")
        out_vals.append(fetch(agg)[:ng])

    # key columns: representative source row per group
    out = gather_rows(frame[by], rep)
    for n, v in zip(out_names, out_vals):
        name = n
        while name in out.names:
            name += "_"
        out.add(name, Vec.from_numpy(v.astype(np.float64)))
    return sort(out, by)


def _group_median(frame: Frame, col, gid, nseg):
    x = frame.vec(col).as_float()
    valid = ~jnp.isnan(x) & frame.row_mask()
    plen = x.shape[0]
    # sort by (gid, value); invalid rows last
    perm = jnp.lexsort((jnp.where(valid, x, jnp.inf),
                        jnp.where(valid, gid, nseg)))
    sx = x[perm]
    cnt = jax.ops.segment_sum(valid.astype(jnp.int32), gid, nseg)
    start = jnp.concatenate([jnp.zeros(1, jnp.int32),
                             jnp.cumsum(cnt)[:-1].astype(jnp.int32)])
    lo = start + (jnp.maximum(cnt, 1) - 1) // 2
    hi = start + jnp.maximum(cnt, 1) // 2
    lo = jnp.clip(lo, 0, plen - 1)
    hi = jnp.clip(hi, 0, plen - 1)
    return (sx[lo] + sx[hi]) / 2.0


# ---------------------------------------------------------------------------
# merge / join


def merge(left: Frame, right: Frame, by: Sequence[str] | None = None,
          all_x: bool = False, all_y: bool = False) -> Frame:
    """Equi-join on key columns (reference: ``AstMerge`` over
    ``BinaryMerge``; h2o-py ``H2OFrame.merge(all_x=, all_y=)``).

    Group ids are computed over the concatenated key columns of both frames
    (one shared sort), then the join plan (left row, right row) pairs is
    assembled on the host and materialized with two device gathers.
    """
    if by is None:
        by = [c for c in left.names if c in right.names]
    by = list(by)
    if not by:
        raise ValueError("no common key columns to merge on")

    # shared dense group ids across both frames' keys
    kl = [left.vec(c).as_float() for c in by]
    kr = [_align_key(left.vec(c), right.vec(c)) for c in by]
    keys = [jnp.concatenate([a, b]) for a, b in zip(kl, kr)]
    valid = jnp.concatenate([left.row_mask(), right.row_mask()])
    gid, ng, _ = _group_ids(keys, valid)
    g = fetch(gid)
    gl, gr = g[: left.plen][: left.nrows], g[left.plen:][: right.nrows]

    order_r = np.argsort(gr, kind="stable")
    grs = gr[order_r]
    starts = np.searchsorted(grs, gl, "left")
    ends = np.searchsorted(grs, gl, "right")
    cnt = (ends - starts).astype(np.int64)

    keep = cnt > 0
    out_cnt = np.where(keep, cnt, 1 if all_x else 0)
    tot = int(out_cnt.sum())
    left_plan = np.repeat(np.arange(left.nrows, dtype=np.int64), out_cnt)
    cum = np.cumsum(out_cnt) - out_cnt
    pos = np.arange(tot, dtype=np.int64) - np.repeat(cum, out_cnt)
    rp_base = np.repeat(np.where(keep, starts, -1), out_cnt)
    right_plan = np.where(rp_base >= 0, order_r[np.clip(rp_base + pos, 0, max(len(order_r) - 1, 0))], -1)
    right_plan = np.where(np.repeat(keep, out_cnt), right_plan, -1)

    if all_y:
        matched = np.zeros(right.nrows, bool)
        matched[right_plan[right_plan >= 0]] = True
        extra = np.nonzero(~matched)[0]
        left_plan = np.concatenate([left_plan, np.full(len(extra), -1, np.int64)])
        right_plan = np.concatenate([right_plan, extra])

    lf = gather_rows(left, left_plan)
    right_rest = [c for c in right.names if c not in by]
    rf = gather_rows(right[right_rest], right_plan) if right_rest else None
    if all_y and len(right_plan):
        # key values for right-only rows come from the right frame; rebuild
        # the key columns host-side so differing categorical domains union
        # cleanly (the device codes are not comparable across frames)
        rk = gather_rows(right[by], right_plan)
        miss = left_plan < 0
        for c in by:
            lv, rv = lf.vec(c), rk.vec(c)
            if lv.is_categorical:
                vals = lv.labels()
                vals[miss] = rv.labels()[miss]
                lf.replace_vec(c, Vec.from_numpy(vals, type=VecType.CAT))
            else:
                vals = lv.to_numpy().copy()
                vals[miss] = rv.to_numpy()[miss]
                lf.replace_vec(c, Vec.from_numpy(vals, type=lv.type))
    if rf is not None:
        for c in right_rest:
            name = c if c not in lf.names else c + "_y"
            lf.add(name, rf.vec(c))
    return lf


def _align_key(lv: Vec, rv: Vec) -> jax.Array:
    """Right key column as floats comparable with the left's: categorical
    levels are remapped onto the left's domain (unknown levels → NaN+offset
    sentinel so they join nothing but stay valid rows); TIME columns are
    shifted into the left's offset frame (their device data is relative)."""
    if lv.is_categorical != rv.is_categorical:
        raise TypeError("merge key type mismatch (categorical vs numeric)")
    if lv.type is VecType.TIME or rv.type is VecType.TIME:
        return rv.as_float() + (rv.time_offset - lv.time_offset)
    if not rv.is_categorical or lv.domain == rv.domain:
        return rv.as_float()
    lut = np.full(len(rv.domain) + 1, -2.0, np.float32)
    ldom = {s: i for i, s in enumerate(lv.domain)}
    for i, s in enumerate(rv.domain):
        lut[i] = ldom.get(s, -2.0)
    mapped = jnp.asarray(lut)[jnp.clip(rv.data, -1, len(rv.domain) - 1)]
    mapped = jnp.where(rv.data < 0, jnp.nan, mapped)
    # unknown levels: distinct finite sentinel per level so they never equal a
    # left key (-2 - code keeps them unique and < any real code)
    return jnp.where(mapped < -1.5, -2.0 - rv.data.astype(jnp.float32), mapped)


# ---------------------------------------------------------------------------
# rbind / cbind


def rbind(*frames: Frame) -> Frame:
    """Stack frames by rows (reference: ``AstRBind``); categorical domains are
    unioned and codes remapped (the parser's ``PackedDomains`` merge)."""
    if len(frames) == 1:
        return frames[0]
    base = frames[0]
    for f in frames[1:]:
        if f.names != base.names:
            raise ValueError("rbind: column names differ")
    total = sum(f.nrows for f in frames)
    out_vecs = []
    for ci, name in enumerate(base.names):
        vs = [f.vecs[ci] for f in frames]
        t = vs[0].type
        if any(v.type is not t for v in vs):
            raise ValueError(f"rbind: column {name!r} types differ")
        if t is VecType.CAT:
            dom = sorted(set().union(*(v.domain for v in vs)))
            lut = {s: i for i, s in enumerate(dom)}
            parts = []
            for v in vs:
                m = np.array([lut[s] for s in v.domain] + [CAT_NA], np.int32)
                codes = fetch(v.data)[: v.nrows]
                parts.append(m[np.where(codes >= 0, codes, len(m) - 1)])
            out_vecs.append(Vec.from_numpy(np.concatenate(parts), type=t,
                                           domain=dom))
        elif t.on_device and t is not VecType.TIME:
            parts = [fetch(v.data)[: v.nrows] for v in vs]
            host = np.concatenate(parts)
            out_vecs.append(Vec.from_numpy(host, type=t))
        elif t is VecType.TIME:
            from h2o3_tpu.rapids.timeops import ms_to_datetime64
            ms = np.concatenate([v.host_values[: v.nrows] for v in vs])
            out_vecs.append(Vec.from_numpy(ms_to_datetime64(ms), type=t))
        else:
            host = np.concatenate([v.host_values[: v.nrows] for v in vs])
            out_vecs.append(Vec(None, t, total, host_values=host))
    return Frame(list(base.names), out_vecs)


def cbind(*frames: Frame) -> Frame:
    """Bind frames by columns (reference: ``AstCBind``); duplicate names get
    numeric suffixes like the reference."""
    names: list[str] = []
    vecs: list[Vec] = []
    nrows = frames[0].nrows
    for f in frames:
        if f.nrows != nrows:
            raise ValueError("cbind: row counts differ")
        for n, v in zip(f.names, f.vecs):
            name, i = n, 0
            while name in names:
                name = f"{n}{i}"
                i += 1
            names.append(name)
            vecs.append(v)
    return Frame(names, vecs)


# ---------------------------------------------------------------------------
# unique / table / pivot / melt


def unique(frame: Frame, cols: Sequence[str] | None = None) -> Frame:
    """Distinct rows of the selected columns (reference: ``AstUnique``)."""
    cols = list(cols) if cols is not None else list(frame.names)
    _, ng, rep = frame_group_ids(frame, cols)
    return sort(gather_rows(frame[cols], rep), cols)


def table(frame: Frame, cols: Sequence[str] | None = None) -> Frame:
    """Level-combination counts (reference: ``AstTable``)."""
    cols = list(cols) if cols is not None else list(frame.names)
    first = cols[0]
    return group_by(frame, cols, [("nrow", first)])


def pivot(frame: Frame, index: str, column: str, value: str,
          agg: str = "mean") -> Frame:
    """Long→wide (reference: ``AstPivot``): one row per ``index`` group, one
    output column per level of categorical ``column``."""
    cv = frame.vec(column)
    if not cv.is_categorical:
        raise TypeError("pivot column must be categorical")
    K = cv.cardinality()
    gid, ng, rep = frame_group_ids(frame, [index])
    nseg = ng + 1
    x = frame.vec(value).as_float()
    code = cv.data
    valid = frame.row_mask() & ~jnp.isnan(x) & (code >= 0)
    comb = jnp.where(valid, gid * K + jnp.clip(code, 0, K - 1), nseg * K)
    xv = jnp.where(valid, x, 0.0)
    cnt = jax.ops.segment_sum(valid.astype(jnp.float32), comb, nseg * K + 1)
    if agg == "count":
        cells = cnt
    elif agg == "sum":
        cells = jax.ops.segment_sum(xv, comb, nseg * K + 1)
    elif agg == "mean":
        cells = jax.ops.segment_sum(xv, comb, nseg * K + 1) / jnp.maximum(cnt, 1.0)
    elif agg == "min":
        cells = jax.ops.segment_min(jnp.where(valid, x, jnp.inf), comb, nseg * K + 1)
    elif agg == "max":
        cells = jax.ops.segment_max(jnp.where(valid, x, -jnp.inf), comb, nseg * K + 1)
    else:
        raise ValueError(f"unknown pivot agg {agg!r}")
    cells = jnp.where(cnt > 0, cells, jnp.nan) if agg != "count" else cells
    host = fetch(cells)[: ng * K].reshape(ng, K)
    out = gather_rows(frame[[index]], rep)
    for k, lev in enumerate(cv.domain):
        out.add(str(lev), Vec.from_numpy(host[:, k].astype(np.float64)))
    return sort(out, [index])


def melt(frame: Frame, id_vars: Sequence[str], value_vars: Sequence[str] | None = None,
         var_name: str = "variable", value_name: str = "value") -> Frame:
    """Wide→long (reference: ``AstMelt``)."""
    id_vars = list(id_vars)
    value_vars = list(value_vars) if value_vars is not None else \
        [c for c in frame.names if c not in id_vars]
    blocks = []
    for var in value_vars:
        b = Frame(list(id_vars), [frame.vec(c) for c in id_vars])
        b.add(var_name, Vec.from_numpy(
            np.full(frame.nrows, var, dtype=object), type=VecType.CAT))
        b.add(value_name, Vec(frame.vec(var).as_float(), VecType.NUM, frame.nrows))
        blocks.append(b)
    return rbind(*blocks)


# ---------------------------------------------------------------------------
# row filtering


def filter_rows(frame: Frame, mask: Vec | jax.Array) -> Frame:
    """Rows where ``mask`` is truthy (reference: boolean row slice
    ``AstRowSlice``); NA mask values drop the row."""
    m = mask.as_float() if isinstance(mask, Vec) else jnp.asarray(mask)
    if m.dtype == bool:
        m = m.astype(jnp.float32)
    keep = (m > 0) & ~jnp.isnan(m) & frame.row_mask()
    idx = np.nonzero(fetch(keep))[0]
    return gather_rows(frame, idx)
