"""Elementwise math, reducers, cumulative ops, and frame utilities.

Reference: ``water/rapids/ast/prims/math/`` (36 files), ``reducers/`` (26),
``advmath/`` (18) — each a tiny AST node wrapping a scalar loop over chunks.
Here each op is one XLA elementwise kernel over the padded row-sharded column
(padding is NaN, so it never contaminates reductions, which mask by row).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec

# -- elementwise math --------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "sqrt": jnp.sqrt,
    "floor": jnp.floor, "ceiling": jnp.ceil, "trunc": jnp.trunc,
    "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "exp": jnp.exp, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "trigamma": lambda x: jax.scipy.special.polygamma(1, x),
    "cospi": lambda x: jnp.cos(jnp.pi * x),
    "sinpi": lambda x: jnp.sin(jnp.pi * x),
    "tanpi": lambda x: jnp.tan(jnp.pi * x),
    "logistic": jax.nn.sigmoid,
    "not": lambda x: (x == 0).astype(jnp.float32),
    "none": lambda x: x,                       # AstNoOp
}


def math_op(name: str, vec: Vec) -> Vec:
    """Apply a named unary math op (reference: one ``AstUniOp`` per name)."""
    try:
        fn = _UNARY[name]
    except KeyError:
        raise ValueError(f"unknown math op {name!r}; have {sorted(_UNARY)}") from None
    return Vec(fn(vec.as_float()).astype(jnp.float32), VecType.NUM, vec.nrows)


def __getattr__(name):   # ops.log(v), ops.exp(v), ... without 30 defs
    if name in _UNARY:
        return lambda vec: math_op(name, vec)
    raise AttributeError(name)


def round_(vec: Vec, digits: int = 0) -> Vec:
    s = 10.0 ** digits
    return Vec(jnp.round(vec.as_float() * s) / s, VecType.NUM, vec.nrows)


def signif(vec: Vec, digits: int = 6) -> Vec:
    x = vec.as_float()
    mag = jnp.power(10.0, digits - 1 - jnp.floor(jnp.log10(jnp.abs(x))))
    out = jnp.where(x == 0, 0.0, jnp.round(x * mag) / mag)
    return Vec(out.astype(jnp.float32), VecType.NUM, vec.nrows)


def ifelse(cond: Vec, yes, no) -> Vec:
    """Vectorized conditional (reference: ``AstIfElse``); NA test → NA."""
    c = cond.as_float()
    yv = yes.as_float() if isinstance(yes, Vec) else float(yes)
    nv = no.as_float() if isinstance(no, Vec) else float(no)
    out = jnp.where(jnp.isnan(c), jnp.nan, jnp.where(c != 0, yv, nv))
    return Vec(out.astype(jnp.float32), VecType.NUM, cond.nrows)


# -- reducers (host scalars; padding is NaN so nan-reductions skip it) -------


def _valid(vec: Vec):
    x = vec.as_float()
    return x, ~jnp.isnan(x)


def vsum(vec: Vec) -> float:
    x, ok = _valid(vec)
    return float(jax.device_get(jnp.where(ok, x, 0.0).sum()))


def vmean(vec: Vec) -> float:
    x, ok = _valid(vec)
    return float(jax.device_get(jnp.where(ok, x, 0.0).sum() /
                                jnp.maximum(ok.sum(), 1)))


def vmin(vec: Vec) -> float:
    return float(jax.device_get(jnp.nanmin(vec.as_float())))


def vmax(vec: Vec) -> float:
    return float(jax.device_get(jnp.nanmax(vec.as_float())))


def vvar(vec: Vec) -> float:
    x, ok = _valid(vec)
    cnt = ok.sum()
    n = jnp.maximum(cnt, 2)
    s = jnp.where(ok, x, 0.0).sum()
    ss = jnp.where(ok, x * x, 0.0).sum()
    var = jnp.where(cnt >= 2, (ss - s * s / n) / (n - 1), jnp.nan)
    return float(jax.device_get(var))   # NaN: sample variance needs n>=2


def vsd(vec: Vec) -> float:
    return float(np.sqrt(max(vvar(vec), 0.0)))


def vprod(vec: Vec) -> float:
    x, ok = _valid(vec)
    return float(jax.device_get(jnp.where(ok, x, 1.0).prod()))


def vmedian(vec: Vec) -> float:
    return float(jax.device_get(jnp.nanmedian(vec.as_float())))


def vany(vec: Vec) -> bool:
    x, ok = _valid(vec)
    return bool(jax.device_get((jnp.where(ok, x, 0.0) != 0).any()))


def vall(vec: Vec) -> bool:
    x, ok = _valid(vec)
    return bool(jax.device_get(jnp.where(ok, x != 0, True).all()))


def quantile(frame: Frame, probs: Sequence[float] = (0.001, 0.01, 0.1, 0.25, 0.333,
                                                     0.5, 0.667, 0.75, 0.9, 0.99, 0.999)
             ) -> Frame:
    """Per-column quantiles (reference: ``hex/quantile/Quantile.java`` —
    TYPE_7 linear interpolation; one device sort per column via nanquantile,
    padding NaN is skipped for free)."""
    probs = list(probs)
    p = jnp.asarray(probs, jnp.float32)
    cols = {"Probs": np.asarray(probs, np.float64)}
    for n, v in zip(frame.names, frame.vecs):
        if v.type.on_device and not v.is_categorical:
            q = jnp.nanquantile(v.as_float(), p)
            cols[n] = np.asarray(jax.device_get(q), np.float64)
    return Frame.from_arrays(cols)


# -- cumulative --------------------------------------------------------------


def _cum(vec: Vec, fn, neutral) -> Vec:
    x = vec.as_float()
    filled = jnp.where(jnp.isnan(x), neutral, x)
    out = jnp.where(jnp.isnan(x), jnp.nan, fn(filled))
    return Vec(out.astype(jnp.float32), VecType.NUM, vec.nrows)


def cumsum(vec: Vec) -> Vec: return _cum(vec, jnp.cumsum, 0.0)
def cumprod(vec: Vec) -> Vec: return _cum(vec, jnp.cumprod, 1.0)
def cummin(vec: Vec) -> Vec: return _cum(vec, jnp.minimum.accumulate, jnp.inf)
def cummax(vec: Vec) -> Vec: return _cum(vec, jnp.maximum.accumulate, -jnp.inf)


# -- advmath utilities -------------------------------------------------------


def cut(vec: Vec, breaks: Sequence[float], labels: Sequence[str] | None = None,
        include_lowest: bool = False, right: bool = True) -> Vec:
    """Numeric → categorical binning (reference: ``AstCut``)."""
    br = np.asarray(breaks, np.float64)
    x = vec.as_float()
    code = jnp.searchsorted(jnp.asarray(br, jnp.float32), x,
                            side="left" if right else "right") - 1
    # right=True bins are (b[i], b[i+1]]: the lowest break itself is out of
    # range unless include_lowest (R/reference cut semantics)
    oob = jnp.isnan(x) | (x < br[0]) | (x > br[-1])
    if right and not include_lowest:
        oob = oob | (x == br[0])
    if not right:
        oob = oob | (x == br[-1])
    if include_lowest and right:
        code = jnp.where(x == br[0], 0, code)
    code = jnp.where(oob, -1, jnp.clip(code, 0, len(br) - 2)).astype(jnp.int32)
    if labels is None:
        op, cl = ("(", "]") if right else ("[", ")")
        labels = [f"{op}{br[i]:g},{br[i+1]:g}{cl}" for i in range(len(br) - 1)]
    return Vec(code, VecType.CAT, vec.nrows, domain=tuple(labels))


def hist(vec: Vec, breaks: int | Sequence[float] = 20):
    """(counts, edges) histogram (reference: ``AstHist``)."""
    x = vec.as_float()
    if isinstance(breaks, int):
        lo, hi = vmin(vec), vmax(vec)
        edges = np.linspace(lo, hi, breaks + 1)
    else:
        edges = np.asarray(breaks, np.float64)
    e = jnp.asarray(edges, jnp.float32)
    idx = jnp.clip(jnp.searchsorted(e, x, side="right") - 1, 0, len(edges) - 2)
    ok = ~jnp.isnan(x) & (x >= e[0]) & (x <= e[-1])
    counts = jax.ops.segment_sum(ok.astype(jnp.float32),
                                 jnp.where(ok, idx, len(edges) - 1),
                                 len(edges))[: len(edges) - 1]
    return np.asarray(jax.device_get(counts)), edges


def impute(frame: Frame, column: str, method: str = "mean",
           by: Sequence[str] | None = None) -> Frame:
    """Fill NAs in place (reference: ``AstImpute``; h2o-py ``H2OFrame.impute``).

    Numeric: method mean|median|min|max (grouped: mean|median); categorical:
    mode (grouped or global), type and domain preserved. Grouped fills fall
    back to the global fill for all-NA groups (reference behavior).
    """
    v = frame.vec(column)

    if v.is_categorical:
        if method != "mode":
            raise ValueError("categorical impute requires method='mode'")
        K = max(v.cardinality(), 1)
        if by:
            from h2o3_tpu.rapids.munge import frame_group_ids
            gid, ng, _ = frame_group_ids(frame, list(by))
            ok = (v.data >= 0) & frame.row_mask()
            comb = jnp.where(ok, gid * K + jnp.clip(v.data, 0, K - 1), ng * K)
            counts = jax.ops.segment_sum(ok.astype(jnp.float32), comb,
                                         ng * K + 1)[: ng * K].reshape(ng, K)
            mode_g = jnp.argmax(counts, axis=1).astype(jnp.int32)
            has = counts.sum(axis=1) > 0
            glob = jax.ops.segment_sum(
                ok.astype(jnp.float32),
                jnp.where(ok, jnp.clip(v.data, 0, K - 1), K), K + 1)[:K]
            gmode = jnp.argmax(glob).astype(jnp.int32)
            fill = jnp.where(has, mode_g, gmode)[jnp.clip(gid, 0, ng - 1)]
        else:
            counts = jax.ops.segment_sum(
                (v.data >= 0).astype(jnp.float32),
                jnp.clip(v.data, 0, K - 1), K)
            fill = jnp.argmax(counts).astype(jnp.int32)
        new = jnp.where(v.data < 0, fill, v.data).astype(jnp.int32)
        new = jnp.where(frame.row_mask(), new, -1)
        frame.replace_vec(column, Vec(new, VecType.CAT, v.nrows,
                                      domain=v.domain))
        return frame

    x = v.as_float()
    if by:
        if method not in ("mean", "median"):
            raise ValueError("grouped numeric impute supports mean|median")
        from h2o3_tpu.rapids.munge import _group_median, frame_group_ids
        gid, ng, _ = frame_group_ids(frame, list(by))
        ok = ~jnp.isnan(x) & frame.row_mask()
        c = jax.ops.segment_sum(ok.astype(jnp.float32), gid, ng + 1)
        if method == "mean":
            s = jax.ops.segment_sum(jnp.where(ok, x, 0.0), gid, ng + 1)
            per_group = s / jnp.maximum(c, 1.0)
        else:
            per_group = _group_median(frame, column, gid, ng + 1)
        glob = vmean(v) if method == "mean" else vmedian(v)
        fill = jnp.where(c > 0, per_group, glob)[gid]
    else:
        fill = {"mean": vmean, "median": vmedian, "min": vmin, "max": vmax}[method](v)
    out = jnp.where(jnp.isnan(x) & frame.row_mask(), fill, x)
    frame.replace_vec(column, Vec(out.astype(jnp.float32),
                                  VecType.NUM, v.nrows))
    return frame


def scale(frame: Frame, center: bool = True, scale_: bool = True) -> Frame:
    """Standardize numeric columns (reference: ``AstScale``)."""
    vecs = []
    for v in frame.vecs:
        if v.type.on_device and not v.is_categorical:
            x = v.as_float()
            if center:
                x = x - vmean(v)
            if scale_:
                x = x / max(vsd(v), 1e-30)
            vecs.append(Vec(x.astype(jnp.float32), VecType.NUM, v.nrows))
        else:
            vecs.append(v)
    return Frame(list(frame.names), vecs)
