"""String ops on STR (host object arrays) and CAT (domain transform) columns.

Reference: ``water/rapids/ast/prims/string/`` (16 files: ``AstToUpper``,
``AstStrSplit``, ``AstReplaceAll`` …). The reference optimizes CAT columns by
transforming the domain once instead of every row — same trick here; STR
columns are host-resident numpy object arrays (see ``Vec`` docstring), so the
ops run as one vectorized host pass and never touch the device.
"""

from __future__ import annotations

import re

import numpy as np

from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec


def _apply(vec: Vec, fn) -> Vec:
    """Apply a str→str fn: CAT → map the domain; STR → map the values."""
    if vec.is_categorical:
        new_dom = [fn(s) for s in vec.domain]
        if len(set(new_dom)) == len(new_dom):
            return Vec(vec.data, VecType.CAT, vec.nrows, domain=tuple(new_dom))
        # collapsed levels (e.g. tolower merging "A"/"a"): refactorize
        return Vec.from_numpy(np.array(
            [None if c < 0 else new_dom[c] for c in vec.to_numpy()], dtype=object))
    if vec.type is not VecType.STR:
        raise TypeError(f"string op on {vec.type} column")
    out = np.array([None if s is None else fn(s) for s in vec.host_values],
                   dtype=object)
    return Vec(None, VecType.STR, vec.nrows, host_values=out)


def _apply_num(vec: Vec, fn) -> Vec:
    """str→float fn; NA → NaN."""
    if vec.is_categorical:
        lut = np.array([fn(s) for s in vec.domain] + [np.nan], np.float64)
        codes = vec.to_numpy()
        vals = lut[np.where(codes >= 0, codes, len(lut) - 1)]
    else:
        vals = np.array([np.nan if s is None else fn(s) for s in vec.host_values])
    return Vec.from_numpy(vals.astype(np.float32), type=VecType.NUM)


def toupper(vec: Vec) -> Vec: return _apply(vec, str.upper)
def tolower(vec: Vec) -> Vec: return _apply(vec, str.lower)
def trim(vec: Vec) -> Vec: return _apply(vec, str.strip)
def lstrip(vec: Vec, chars: str | None = None) -> Vec: return _apply(vec, lambda s: s.lstrip(chars))
def rstrip(vec: Vec, chars: str | None = None) -> Vec: return _apply(vec, lambda s: s.rstrip(chars))
def nchar(vec: Vec) -> Vec: return _apply_num(vec, len)


def substring(vec: Vec, start: int, end: int | None = None) -> Vec:
    return _apply(vec, lambda s: s[start:end])


def sub(vec: Vec, pattern: str, replacement: str, ignore_case: bool = False) -> Vec:
    """Replace FIRST regex match (reference: ``AstReplaceFirst``)."""
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    return _apply(vec, lambda s: rx.sub(replacement, s, count=1))


def gsub(vec: Vec, pattern: str, replacement: str, ignore_case: bool = False) -> Vec:
    """Replace ALL regex matches (reference: ``AstReplaceAll``)."""
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    return _apply(vec, lambda s: rx.sub(replacement, s))


def grep(vec: Vec, pattern: str, ignore_case: bool = False, invert: bool = False) -> Vec:
    """1.0 where the regex matches (reference: ``AstGrep``)."""
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    hit = lambda s: float(bool(rx.search(s)) != invert)  # noqa: E731
    return _apply_num(vec, hit)


def startswith(vec: Vec, prefix: str) -> Vec:
    return _apply_num(vec, lambda s: float(s.startswith(prefix)))


def endswith(vec: Vec, suffix: str) -> Vec:
    return _apply_num(vec, lambda s: float(s.endswith(suffix)))


def strsplit(vec: Vec, pattern: str) -> list[Vec]:
    """Split into columns on a regex (reference: ``AstStrSplit`` → frame of
    string columns, ragged rows padded with NA)."""
    rx = re.compile(pattern)
    if vec.is_categorical:
        vals = [None if c < 0 else vec.domain[c] for c in vec.to_numpy()]
    else:
        vals = list(vec.host_values)
    parts = [None if s is None else rx.split(s) for s in vals]
    width = max((len(p) for p in parts if p is not None), default=0)
    out = []
    for i in range(width):
        col = np.array([None if p is None or i >= len(p) else p[i]
                        for p in parts], dtype=object)
        out.append(Vec(None, VecType.STR, vec.nrows, host_values=col))
    return out


def entropy(vec: Vec) -> Vec:
    """Per-string Shannon entropy (reference: ``AstEntropy``)."""
    def ent(s: str) -> float:
        if not s:
            return 0.0
        _, cnt = np.unique(list(s), return_counts=True)
        p = cnt / cnt.sum()
        return float(-(p * np.log2(p)).sum())
    return _apply_num(vec, ent)


def num_valid_substrings(vec: Vec, words: list[str]) -> Vec:
    """Count of dictionary words contained in each string (reference:
    ``AstCountSubstringsWords``)."""
    return _apply_num(vec, lambda s: float(sum(w in s for w in words)))
