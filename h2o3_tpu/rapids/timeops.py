"""Time ops on TIME columns (epoch-ms, exact f64 host payload).

Reference: ``water/rapids/ast/prims/time/`` (16 files: ``AstYear``,
``AstMonth``, ``AstDay``, ``AstDayOfWeek``, ``AstHour`` …, ``AstAsDate``,
``AstMktime``). TIME Vecs keep exact float64 epoch millis host-side (float32
device data is shifted/relative — see ``Vec``), so calendar decomposition runs
on the host payload via numpy datetime64 and returns device NUM columns.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec


def _ms(vec: Vec) -> np.ndarray:
    if vec.type is not VecType.TIME:
        raise TypeError(f"time op on {vec.type} column")
    return vec.to_numpy()   # float64 epoch ms, NaN for NA


def ms_to_datetime64(ms: np.ndarray) -> np.ndarray:
    """float64 epoch-ms (NaN = missing) → datetime64[ms] (NaT = missing);
    the one shared conversion for TIME round-trips."""
    out = np.full(len(ms), np.datetime64("NaT"), "datetime64[ms]")
    ok = ~np.isnan(ms)
    out[ok] = ms[ok].astype(np.int64).view("datetime64[ms]")
    return out


def _dt(vec: Vec) -> np.ndarray:
    return ms_to_datetime64(_ms(vec))


def _field(vec: Vec, values: np.ndarray) -> Vec:
    vals = values.astype(np.float32)
    return Vec.from_numpy(vals, type=VecType.NUM)


def _decompose(vec: Vec, unit_hi: str, unit_lo: str, offset: float = 0.0) -> Vec:
    dt = _dt(vec)
    hi = dt.astype(f"datetime64[{unit_hi}]")
    val = (dt - hi).astype(f"timedelta64[{unit_lo}]").astype(np.float64)
    val[np.isnat(dt)] = np.nan
    return _field(vec, val + offset)


def year(vec: Vec) -> Vec:
    dt = _dt(vec)
    y = dt.astype("datetime64[Y]").astype(np.float64) + 1970.0
    y[np.isnat(dt)] = np.nan
    return _field(vec, y)


def month(vec: Vec) -> Vec:
    return _decompose(vec, "Y", "M", offset=1.0)        # 1..12


def day(vec: Vec) -> Vec:
    return _decompose(vec, "M", "D", offset=1.0)        # 1..31


def hour(vec: Vec) -> Vec:
    return _decompose(vec, "D", "h")


def minute(vec: Vec) -> Vec:
    return _decompose(vec, "h", "m")


def second(vec: Vec) -> Vec:
    return _decompose(vec, "m", "s")


def millis(vec: Vec) -> Vec:
    return _decompose(vec, "s", "ms")


def day_of_week(vec: Vec) -> Vec:
    """0=Mon .. 6=Sun (reference ``AstDayOfWeek`` domain Mon-first)."""
    dt = _dt(vec)
    days = dt.astype("datetime64[D]").astype(np.float64)
    dow = np.mod(days + 3.0, 7.0)                        # 1970-01-01 = Thursday
    dow[np.isnat(dt)] = np.nan
    return _field(vec, dow)


def week(vec: Vec) -> Vec:
    dt = _dt(vec)
    doy = (dt.astype("datetime64[D]") - dt.astype("datetime64[Y]")
           ).astype(np.float64)
    val = np.floor(doy / 7.0) + 1.0
    val[np.isnat(dt)] = np.nan
    return _field(vec, val)


def as_date(vec: Vec, fmt: str) -> Vec:
    """Parse a STR/CAT column into a TIME Vec (reference: ``AstAsDate``;
    fmt uses Java-style yyyy/MM/dd/HH/mm/ss tokens like the reference)."""
    import datetime as _dt_mod
    py_fmt = (fmt.replace("yyyy", "%Y").replace("yy", "%y")
                 .replace("MM", "%m").replace("dd", "%d")
                 .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S"))
    if vec.is_categorical:
        vals = [None if c < 0 else vec.domain[c] for c in vec.to_numpy()]
    else:
        vals = list(vec.host_values)
    out = np.full(len(vals), np.datetime64("NaT"), "datetime64[ms]")
    for i, s in enumerate(vals):
        if s is not None:
            try:
                out[i] = np.datetime64(_dt_mod.datetime.strptime(s, py_fmt), "ms")
            except ValueError:
                pass
    return Vec.from_numpy(out, type=VecType.TIME)


def mktime(year_v, month_v=None, day_v=None, hour_v=None, minute_v=None,
           second_v=None) -> Vec:
    """Build a TIME column from numeric component columns (reference:
    ``AstMktime``; month/day are 1-based)."""
    n = year_v.nrows
    def arr(v, default):
        return v.to_numpy().astype(np.float64) if v is not None \
            else np.full(n, default, np.float64)
    y, mo, d = arr(year_v, 1970), arr(month_v, 1), arr(day_v, 1)
    h, mi, s = arr(hour_v, 0), arr(minute_v, 0), arr(second_v, 0)
    ok = ~(np.isnan(y) | np.isnan(mo) | np.isnan(d) | np.isnan(h)
           | np.isnan(mi) | np.isnan(s))
    out = np.full(n, np.datetime64("NaT"), "datetime64[ms]")
    yi = y[ok].astype(np.int64)
    base = (yi - 1970).astype("timedelta64[Y]") + np.zeros(ok.sum(), "datetime64[Y]")
    months = base.astype("datetime64[M]") + (mo[ok].astype(np.int64) - 1)
    days = months.astype("datetime64[D]") + (d[ok].astype(np.int64) - 1)
    ms = (days.astype("datetime64[ms]")
          + (h[ok] * 3600_000 + mi[ok] * 60_000 + s[ok] * 1000).astype("timedelta64[ms]"))
    out[ok] = ms
    return Vec.from_numpy(out, type=VecType.TIME)
