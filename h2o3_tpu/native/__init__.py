"""Native runtime components — C++ built on demand, bound via ctypes.

Reference: H2O-3's performance-critical native pieces ship as prebuilt shared
libraries loaded at runtime (``hex/tree/xgboost/XGBoostExtension.java:73-117``
``util/NativeLibrary.java`` loader chain). Same pattern: ``native/*.cpp``
compiles once into a cached ``.so`` next to this package (g++ is in the
image; pybind11 is not, hence the plain C ABI + ctypes). Every native path
has a pure-Python fallback — absence of a toolchain degrades, never breaks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_FAILED = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "..", "..", "native", "csv_parser.cpp")
_SO = os.path.join(_PKG_DIR, "_libh2o3native.so")


def _build() -> str | None:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except (subprocess.SubprocessError, FileNotFoundError):
        return None


def get_lib() -> ctypes.CDLL | None:
    """The native library, compiling on first use; None if unavailable."""
    global _LIB, _FAILED
    if _LIB is not None or _FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _FAILED:
            return _LIB
        so = _build()
        if so is None:
            _FAILED = True
            return None
        lib = ctypes.CDLL(so)
        lib.h2o3_parse_csv.restype = ctypes.c_void_p
        lib.h2o3_parse_csv.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_int, ctypes.c_char,
                                       ctypes.c_int]
        lib.h2o3_nrows.restype = ctypes.c_int64
        lib.h2o3_nrows.argtypes = [ctypes.c_void_p]
        lib.h2o3_ncols.restype = ctypes.c_int32
        lib.h2o3_ncols.argtypes = [ctypes.c_void_p]
        lib.h2o3_col_name.restype = ctypes.c_char_p
        lib.h2o3_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.h2o3_col_type.restype = ctypes.c_int32
        lib.h2o3_col_type.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.h2o3_col_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.h2o3_col_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.h2o3_col_card.restype = ctypes.c_int32
        lib.h2o3_col_card.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.h2o3_col_level.restype = ctypes.c_char_p
        lib.h2o3_col_level.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int]
        lib.h2o3_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def parse_csv_native(data: bytes, has_header: bool = True, sep: str = ",",
                     nthreads: int | None = None):
    """Parse CSV bytes with the native chunk-parallel parser.

    Returns ``(names, columns)`` where each column is
    ``("num", float64 array)`` or ``("cat", int32 codes, domain tuple)``;
    None when the native library is unavailable (caller falls back).
    """
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    if nthreads is None:
        nthreads = min(os.cpu_count() or 4, 16)
    h = lib.h2o3_parse_csv(data, len(data), int(has_header),
                           sep.encode()[0], int(nthreads))
    if not h:
        return None
    try:
        nrows = lib.h2o3_nrows(h)
        ncols = lib.h2o3_ncols(h)
        names, cols = [], []
        for c in range(ncols):
            names.append(lib.h2o3_col_name(h, c).decode())
            ptr = lib.h2o3_col_data(h, c)
            arr = np.ctypeslib.as_array(ptr, shape=(nrows,)).copy()
            if lib.h2o3_col_type(h, c) == 0:
                cols.append(("num", arr))
            else:
                dom = tuple(lib.h2o3_col_level(h, c, i).decode()
                            for i in range(lib.h2o3_col_card(h, c)))
                cols.append(("cat", arr.astype(np.int32), dom))
        return names, cols
    finally:
        lib.h2o3_free(h)
