"""LDAP simple-bind authenticator — the directory-backed login module.

Reference: ``water/H2O.java:242-266`` wires ``-ldap_login`` to a JAAS
``LdapLoginModule`` (and ``h2o-jaas-pam`` adds PAM); the server then gates
every request through that login. Here the same contract is a pure-Python
LDAPv3 simple bind (RFC 4511 BindRequest over a socket, BER-encoded by
hand — this image carries no ldap3/python-ldap) plugged into
``H2OServer(authenticator=...)``, the hook Basic/form auth already speak.

Usage (launch.py flags, mirroring the reference's ldap.conf essentials)::

    python -m h2o3_tpu.launch --serve \
        --ldap-login ldap://ldap.example.org:389 \
        --ldap-user-template "uid={},ou=people,dc=example,dc=org"

A login attempt binds as the templated DN with the presented password;
resultCode 0 authenticates, anything else (49 invalidCredentials, ...)
rejects. Failures — connection refused, malformed reply — reject closed.
"""

from __future__ import annotations

import socket
from urllib.parse import urlparse

__all__ = ["ldap_authenticator", "ldap_simple_bind"]


# -- minimal BER (the three forms a simple bind needs) -----------------------

def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _tlv(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def _read_tlv(buf: bytes, pos: int) -> tuple[int, bytes, int]:
    """(tag, value, next_pos); raises ValueError on truncation."""
    if pos + 2 > len(buf):
        raise ValueError("truncated BER element")
    tag = buf[pos]
    length = buf[pos + 1]
    pos += 2
    if length & 0x80:
        nb = length & 0x7F
        if nb == 0 or pos + nb > len(buf):
            raise ValueError("bad BER length")
        length = int.from_bytes(buf[pos:pos + nb], "big")
        pos += nb
    if pos + length > len(buf):
        raise ValueError("truncated BER value")
    return tag, buf[pos:pos + length], pos + length


def bind_request(msg_id: int, dn: str, password: str) -> bytes:
    """RFC 4511 §4.2: [APPLICATION 0] { version 3, name, simple pw }."""
    op = _tlv(0x60, _tlv(0x02, b"\x03")
              + _tlv(0x04, dn.encode())
              + _tlv(0x80, password.encode()))
    return _tlv(0x30, _tlv(0x02, bytes([msg_id])) + op)


def parse_bind_response(data: bytes) -> int:
    """resultCode of a BindResponse ([APPLICATION 1]); raises on junk."""
    tag, msg, _ = _read_tlv(data, 0)
    if tag != 0x30:
        raise ValueError("not an LDAPMessage")
    pos = 0
    tag, _mid, pos = _read_tlv(msg, pos)          # messageID
    tag, op, _ = _read_tlv(msg, pos)
    if tag != 0x61:
        raise ValueError(f"not a BindResponse (tag {tag:#x})")
    tag, code, _ = _read_tlv(op, 0)               # ENUMERATED resultCode
    if tag != 0x0A:
        raise ValueError("BindResponse without resultCode")
    return int.from_bytes(code, "big")


def ldap_simple_bind(url: str, dn: str, password: str,
                     timeout: float = 5.0) -> bool:
    """One LDAPv3 simple bind; True iff the directory says success (0).

    Empty passwords are rejected HERE: RFC 4513 §5.1.2 calls an empty
    simple password an *unauthenticated* bind that many servers accept
    with resultCode 0 — treating that as login would let anyone in as
    any user (the reference JAAS module guards the same way).
    """
    if not password:
        return False
    u = urlparse(url)
    if u.scheme not in ("ldap", "ldaps"):
        raise ValueError(f"unsupported LDAP url scheme {u.scheme!r}")
    host, port = u.hostname, u.port or (636 if u.scheme == "ldaps" else 389)
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            if u.scheme == "ldaps":
                import ssl
                s = ssl.create_default_context().wrap_socket(
                    s, server_hostname=host)
            s.settimeout(timeout)
            s.sendall(bind_request(1, dn, password))
            data = b""
            # read until the outer LDAPMessage TLV is complete (responses
            # with long diagnostics/referrals exceed any fixed byte cap)
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
                try:
                    _, msg, end = _read_tlv(data, 0)
                except ValueError:
                    continue        # header/body still partial
                return parse_bind_response(data[:end]) == 0
    except (OSError, ValueError):
        return False                # closed on any transport/format failure
    return False


def ldap_authenticator(url: str, user_template: str):
    """``(user, password) -> bool`` closure for ``H2OServer(authenticator=)``.

    ``user_template`` holds one ``{}`` that receives the login name, e.g.
    ``uid={},ou=people,dc=example,dc=org``. Login names with DN
    metacharacters are escaped per RFC 4514 before templating.
    """
    if "{}" not in user_template:
        raise ValueError("user template needs a {} placeholder, e.g. "
                         "'uid={},ou=people,dc=example,dc=org'")

    # short-TTL success cache: clients send Basic credentials on EVERY
    # request (h2o-py polls jobs sub-second), and a fresh TCP+bind per
    # call would hammer the directory. Key = (user, salted pw hash);
    # only successes cache, so revocation takes effect within the TTL.
    import hashlib
    import os as _os
    import threading as _th
    import time as _time
    cache: dict[tuple, float] = {}
    lock = _th.Lock()
    salt = _os.urandom(16)
    ttl = 300.0

    def _escape_dn(v: str) -> str:
        out = []
        for i, ch in enumerate(v):
            if ch in ',+"\\<>;=#':
                out.append("\\" + ch)
            elif ord(ch) < 0x20:
                out.append(f"\\{ord(ch):02x}")
            elif ch == " " and i in (0, len(v) - 1):
                # RFC 4514 §2.4: leading/trailing spaces must be escaped,
                # else the directory trims them and 'alice ' binds as alice
                out.append("\\ ")
            else:
                out.append(ch)
        return "".join(out)

    def authenticate(user: str, password: str) -> bool:
        if not user:
            return False
        key = (user, hashlib.sha256(salt + (password or "").encode())
               .hexdigest())
        now = _time.monotonic()
        with lock:
            exp = cache.get(key)
            if exp is not None and now < exp:
                return True
        ok = ldap_simple_bind(url, user_template.format(_escape_dn(user)),
                              password or "")
        if ok:
            with lock:
                cache[key] = now + ttl
                if len(cache) > 10000:      # bound memory under churn
                    cache.clear()
        return ok

    return authenticate
