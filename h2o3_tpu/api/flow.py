"""Flow — the built-in web console served from the node.

Reference: ``h2o-web/`` packages the Flow notebook (CoffeeScript app served
by the node at ``/``; ``h2o-web/README.md:1-8``): assist-driven cells for
importFiles/parse/buildModel/predict/inspect. The TPU build ships a
dependency-free single-page console over the same V3 REST surface with the
same workflow cells — import → frames (+per-column summaries) → build model
(algo/params form) → job polling → model inspection (metrics) → predict →
Rapids console — rendered client-side from ``/3/*`` JSON.
"""

FLOW_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1c2733}
 header{background:#1c2733;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:baseline}
 header h1{font-size:16px;margin:0}
 header span{color:#9db2c4;font-size:12px}
 main{padding:16px 20px;display:grid;grid-template-columns:1fr 1fr;gap:16px}
 section{background:#fff;border:1px solid #dde4ea;border-radius:6px;padding:12px}
 h2{font-size:13px;text-transform:uppercase;letter-spacing:.06em;color:#5a6b7b;margin:0 0 8px}
 table{width:100%;border-collapse:collapse;font-size:12px}
 td,th{text-align:left;padding:4px 6px;border-bottom:1px solid #eef2f5}
 th{color:#5a6b7b;font-weight:600}
 .wide{grid-column:1/3}
 input[type=text],select{padding:6px;border:1px solid #cfd8e0;border-radius:4px;font-size:13px}
 input[type=text]{width:60%}
 button{padding:6px 12px;border:0;border-radius:4px;background:#2f6fed;color:#fff;cursor:pointer;font-size:13px}
 button.small{padding:2px 8px;font-size:11px;background:#5a6b7b}
 pre{background:#f4f6f8;padding:8px;border-radius:4px;overflow:auto;max-height:240px;font-size:12px}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px;background:#e7f0e7;color:#2b6a2b}
 .err{color:#b32020}
 .row{display:flex;gap:8px;margin:4px 0;flex-wrap:wrap;align-items:center}
 label{font-size:12px;color:#5a6b7b}
</style></head><body>
<header><h1>h2o3-tpu Flow</h1><span id="cloud">connecting…</span>
 <span style="float:right">
  <input type="text" id="nbname" placeholder="notebook name" style="width:12em">
  <button class="small" onclick="saveFlow()">Save</button>
  <select id="nblist" onchange="loadFlow(this.value)"><option value="">Load…</option></select>
 </span>
</header>
<main>
<section class="wide"><h2>Import / Parse</h2>
 <div class="row">
  <input type="text" id="path" placeholder="/path/to/data.csv (csv, parquet, orc, arff, svmlight, avro, xlsx)">
  <input type="text" id="dest" placeholder="destination key (optional)" style="width:20%">
  <button onclick="importFile()">Import</button>
  <span id="importmsg"></span>
 </div>
</section>

<section><h2>Frames</h2><div id="frames"></div><div id="framedetail"></div></section>

<section><h2>Models</h2><div id="models"></div><div id="modeldetail"></div></section>

<section class="wide"><h2>Build Model</h2>
 <div class="row">
  <label>algo</label>
  <select id="algo"><option>gbm</option><option>drf</option><option>glm</option>
   <option>xgboost</option><option>deeplearning</option><option>kmeans</option>
   <option>naivebayes</option><option>isolationforest</option></select>
  <label>training frame</label><select id="trainframe"></select>
  <label>response</label><select id="ycol"></select>
  <label>params (k=v, comma sep)</label>
  <input type="text" id="params" placeholder="ntrees=20, max_depth=5" style="width:30%">
  <button onclick="buildModel()">Train</button>
  <span id="trainmsg"></span>
 </div>
 <div id="jobs"></div>
</section>

<section class="wide"><h2>Predict</h2>
 <div class="row">
  <label>model</label><select id="pmodel"></select>
  <label>frame</label><select id="pframe"></select>
  <button onclick="runPredict()">Predict</button>
  <span id="predmsg"></span>
 </div>
</section>

<section class="wide"><h2>Rapids console</h2>
 <div class="row">
  <input type="text" id="ast" placeholder="(mean (cols frame_key 'col'))" style="width:70%">
  <button onclick="runRapids()">Eval</button>
 </div>
 <pre id="rapidsout"></pre>
</section>
</main>
<script>
const J = (m, p, body) => fetch(p, body ? {method: m,
  headers: {"Content-Type": "application/json"}, body: JSON.stringify(body)}
  : {method: m}).then(r => r.json());

function esc(s){return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;').replace(/>/g,'&gt;').replace(/"/g,'&quot;').replace(/'/g,'&#39;')}

async function refreshCloud(){
  try{
    const c = await J("GET", "/3/Cloud");
    document.getElementById("cloud").innerHTML =
      `cloud <b>${esc(c.cloud_name)}</b> · ${c.cloud_size} device(s) · v${esc(c.version)} <span class="pill">healthy</span>`;
  }catch(e){document.getElementById("cloud").textContent = "unreachable";}
}

async function refreshFrames(){
  const out = await J("GET", "/3/Frames");
  const rows = out.frames.map(f =>
    `<tr><td><a href="#" onclick="frameDetail('${esc(f.frame_id.name)}');return false">${esc(f.frame_id.name)}</a></td>
     <td>${f.rows}</td><td>${f.column_count}</td>
     <td><button class="small" onclick="rmKey('${esc(f.frame_id.name)}')">rm</button></td></tr>`).join("");
  document.getElementById("frames").innerHTML =
    `<table><tr><th>key</th><th>rows</th><th>cols</th><th></th></tr>${rows}</table>`;
  const opts = out.frames.map(f => `<option>${esc(f.frame_id.name)}</option>`).join("");
  document.getElementById("trainframe").innerHTML = opts;
  document.getElementById("pframe").innerHTML = opts;
  refreshCols();
}

async function refreshCols(){
  const key = document.getElementById("trainframe").value;
  if(!key) return;
  try{
    const out = await J("GET", `/3/Frames/${key}/columns`);
    document.getElementById("ycol").innerHTML =
      out.columns.map(c => `<option>${esc(c.label)}</option>`).join("");
  }catch(e){}
}
document.getElementById("trainframe") && document.addEventListener("change",
  e => {if(e.target.id === "trainframe") refreshCols();});

async function frameDetail(key){
  const out = await J("GET", `/3/Frames/${key}`);
  const f = out.frames[0];
  const head = f.columns.map(c => `<th>${esc(c.label)}<br><span style="font-weight:400">${esc(c.type)}</span></th>`).join("");
  const n = Math.min(8, Math.max(...f.columns.map(c => (c.data||c.string_data||[]).length)));
  let body = "";
  for(let i = 0; i < n; i++){
    body += "<tr>" + f.columns.map(c => {
      let v = (c.string_data || c.data || [])[i];
      if(v !== null && c.domain && c.data) v = c.domain[c.data[i]] ?? v;
      return `<td>${v === null || v === undefined ? "·" : esc(typeof v === "number" ? +v.toFixed(4) : v)}</td>`;
    }).join("") + "</tr>";
  }
  const stats = f.columns.map(c =>
    `<tr><td>${esc(c.label)}</td><td>${c.mean==null?"·":(+c.mean).toFixed(4)}</td>
     <td>${c.sigma==null?"·":(+c.sigma).toFixed(4)}</td><td>${c.missing_count}</td>
     <td>${c.domain ? c.domain.length + " levels" : "·"}</td></tr>`).join("");
  document.getElementById("framedetail").innerHTML =
    `<h2 style="margin-top:10px">${esc(key)} — ${f.rows} rows</h2>
     <table><tr>${head}</tr>${body}</table>
     <h2 style="margin-top:10px">column summary</h2>
     <table><tr><th>col</th><th>mean</th><th>sigma</th><th>NAs</th><th>domain</th></tr>${stats}</table>`;
}

async function refreshModels(){
  const out = await J("GET", "/3/Models");
  const rows = out.models.map(m =>
    `<tr><td><a href="#" onclick="modelDetail('${esc(m.model_id.name)}');return false">${esc(m.model_id.name)}</a></td>
     <td>${esc(m.algo)}</td>
     <td><button class="small" onclick="rmKey('${esc(m.model_id.name)}')">rm</button></td></tr>`).join("");
  document.getElementById("models").innerHTML =
    `<table><tr><th>key</th><th>algo</th><th></th></tr>${rows}</table>`;
  document.getElementById("pmodel").innerHTML =
    out.models.map(m => `<option>${esc(m.model_id.name)}</option>`).join("");
}

async function modelDetail(key){
  const out = await J("GET", `/3/Models/${key}`);
  const m = out.models[0];
  const mm = m.output.training_metrics || {};
  const metrics = Object.entries(mm).filter(([k,v]) => typeof v === "number")
    .map(([k,v]) => `<tr><td>${esc(k)}</td><td>${(+v).toFixed(5)}</td></tr>`).join("");
  document.getElementById("modeldetail").innerHTML =
    `<h2 style="margin-top:10px">${esc(key)} (${esc(m.algo)}, ${esc(m.output.model_category||"")})</h2>
     <table><tr><th>training metric</th><th>value</th></tr>${metrics}</table>`;
}

async function rmKey(k){ await fetch(`/3/DKV/${k}`, {method: "DELETE"}); refreshAll(); }

async function importFile(){
  const path = document.getElementById("path").value.trim();
  const dest = document.getElementById("dest").value.trim();
  const msg = document.getElementById("importmsg");
  if(!path){ msg.innerHTML = '<span class="err">enter a path</span>'; return; }
  msg.textContent = "importing…";
  try{
    const body = {path}; if(dest) body.destination_frame = dest;
    const out = await J("POST", "/3/ImportFiles", body);
    if(out.msg) throw new Error(out.msg);
    msg.innerHTML = `<span class="pill">${esc(out.destination_frames[0])}</span>`;
    refreshAll();
  }catch(e){ msg.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}

async function pollJob(key, into){
  for(;;){
    const out = await J("GET", `/3/Jobs/${key}`);
    const j = out.jobs[0];
    into.textContent = `${j.status} ${(100*j.progress).toFixed(0)}% — ${j.progress_msg||""}`;
    if(["DONE","FAILED","CANCELLED"].includes(j.status)) return j;
    await new Promise(r => setTimeout(r, 500));
  }
}

async function buildModel(){
  const algo = document.getElementById("algo").value;
  const frame = document.getElementById("trainframe").value;
  const y = document.getElementById("ycol").value;
  const msg = document.getElementById("trainmsg");
  const body = {training_frame: frame, response_column: y};
  for(const kv of document.getElementById("params").value.split(",")){
    const [k, v] = kv.split("=").map(s => s && s.trim());
    if(k && v !== undefined) body[k] = v;
  }
  msg.textContent = "submitting…";
  try{
    const out = await J("POST", `/3/ModelBuilders/${algo}`, body);
    if(out.msg) throw new Error(out.msg);
    const j = await pollJob(out.job.key.name, msg);
    if(j.exception) msg.innerHTML = `<span class="err">${esc(j.exception)}</span>`;
    else { msg.innerHTML = `<span class="pill">${esc(j.dest.name)}</span>`; modelDetail(j.dest.name); }
    refreshModels();
  }catch(e){ msg.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}

async function runPredict(){
  const m = document.getElementById("pmodel").value;
  const f = document.getElementById("pframe").value;
  const msg = document.getElementById("predmsg");
  msg.textContent = "scoring…";
  try{
    const out = await J("POST", `/3/Predictions/models/${m}/frames/${f}`);
    if(out.msg) throw new Error(out.msg);
    const key = out.predictions_frame.name;
    msg.innerHTML = `<span class="pill">${esc(key)}</span>`;
    refreshFrames(); frameDetail(key);
  }catch(e){ msg.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}

async function runRapids(){
  const ast = document.getElementById("ast").value;
  const out = document.getElementById("rapidsout");
  try{
    const r = await J("POST", "/99/Rapids", {ast});
    out.textContent = JSON.stringify(r, null, 1);
    refreshFrames();
  }catch(e){ out.textContent = "error: " + e.message; }
}

// notebook persistence (reference: Flow save/load via NodePersistentStorage)
const FLOW_FIELDS = ["path","dest","algo","params","ast"];
async function saveFlow(){
  const name = document.getElementById("nbname").value || "flow";
  const doc = {version: 1, fields: {}};
  for (const f of FLOW_FIELDS) doc.fields[f] = document.getElementById(f).value;
  doc.rapids_log = document.getElementById("rapidsout").textContent;
  await fetch(`/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`,
              {method: "POST", body: JSON.stringify(doc)});
  refreshNotebooks();
}
async function loadFlow(name){
  if (!name) return;
  const r = await fetch(`/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`);
  const doc = JSON.parse(await r.text());
  for (const f of FLOW_FIELDS)
    if (doc.fields && f in doc.fields) document.getElementById(f).value = doc.fields[f];
  if (doc.rapids_log) document.getElementById("rapidsout").textContent = doc.rapids_log;
  document.getElementById("nbname").value = name;
}
async function refreshNotebooks(){
  const r = await J("GET", "/3/NodePersistentStorage/notebook");
  const sel = document.getElementById("nblist");
  sel.innerHTML = '<option value="">Load…</option>' +
    r.entries.map(e => `<option value="${esc(e.name)}">${esc(e.name)}</option>`).join("");
}
function refreshAll(){ refreshCloud(); refreshFrames(); refreshModels(); refreshNotebooks(); }
refreshAll();
setInterval(refreshCloud, 10000);
</script></body></html>
"""
