"""Flow — the built-in notebook web console served from the node.

Reference: ``h2o-web/`` packages the Flow notebook (CoffeeScript app served
by the node at ``/``; ``h2o-web/README.md:1-8``): an assist-driven CELL
notebook — each cell holds a command (importFiles/getFrames/buildModel/
predict/plot/…), runs against the V3 REST surface, and renders its output
inline; notebooks save/load; help is a first-class pane.

The TPU build ships the same product shape dependency-free: a single-page
cell notebook over ``/3/*``/``/99/*`` JSON with

- **assist**: one click inserts a template cell per workflow verb
  (reference ``assist`` cells);
- **commands**: ``importFiles``, ``getFrames``, ``getFrameSummary``
  (head + per-column stats + histogram sparklines from the server's ColV3
  rollup histograms), ``buildModel``, ``buildGrid``/``getGrid``,
  ``runAutoML``/``getLeaderboard``, ``getModels``, ``getModel``,
  ``predict``, ``getJobs``, ``rapids``, ``plot varimp|scoring|roc``,
  ``md`` (markdown-lite notes);
- **inline graphs**: dependency-free SVG — variable-importance bars,
  scoring-history lines, ROC curve from the thresholds table (reference
  Flow's vega plots);
- **help pane**: per-command usage + the live route list from the server;
- **notebooks**: cells persist via NodePersistentStorage (reference Flow
  save/load), with v1 console documents still loadable;
- **.flow import**: reference Flow notebooks (``{"cells": [{"type":
  "cs"|"md", "input": ...}]}`` JSON) load via the Import .flow button —
  known CoffeeScript commands (importFiles/buildModel/predict/getFrames/
  getModels) convert to native cells, the rest become annotated notes.
"""

FLOW_HTML = r"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1c2733}
 header{background:#1c2733;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:baseline}
 header h1{font-size:16px;margin:0}
 header span{color:#9db2c4;font-size:12px}
 #wrap{display:grid;grid-template-columns:minmax(0,1fr) 300px;gap:16px;padding:16px 20px}
 #nb{min-width:0}
 .cell{background:#fff;border:1px solid #dde4ea;border-left:4px solid #2f6fed;border-radius:6px;margin:0 0 10px;padding:8px}
 .cell.md{border-left-color:#8a63c9}
 .cell textarea{width:100%;border:0;resize:vertical;font:12px/1.5 ui-monospace,monospace;outline:none;background:#fbfcfd;min-height:2.2em;box-sizing:border-box}
 .cellbar{display:flex;gap:6px;align-items:center;margin-bottom:4px}
 .out{margin-top:6px;font-size:12px;overflow:auto}
 aside{font-size:12px}
 aside section{background:#fff;border:1px solid #dde4ea;border-radius:6px;padding:10px;margin-bottom:12px}
 h2{font-size:12px;text-transform:uppercase;letter-spacing:.06em;color:#5a6b7b;margin:0 0 8px}
 table{width:100%;border-collapse:collapse;font-size:12px}
 td,th{text-align:left;padding:3px 6px;border-bottom:1px solid #eef2f5}
 th{color:#5a6b7b;font-weight:600}
 button{padding:4px 10px;border:0;border-radius:4px;background:#2f6fed;color:#fff;cursor:pointer;font-size:12px}
 button.small{padding:2px 8px;font-size:11px;background:#5a6b7b}
 button.ghost{background:#e8eef7;color:#2f6fed}
 pre{background:#f4f6f8;padding:8px;border-radius:4px;overflow:auto;max-height:260px;font-size:11px;margin:4px 0}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px;background:#e7f0e7;color:#2b6a2b}
 .err{color:#b32020}
 .assist button{margin:2px}
 svg text{font:10px system-ui}
 .help dt{font-weight:600;margin-top:6px}.help dd{margin:0 0 2px 8px;color:#3f4f5e}
 a{color:#2f6fed;cursor:pointer}
</style></head><body>
<header><h1>h2o3-tpu Flow</h1><span id="cloud">connecting…</span>
 <span style="margin-left:auto">
  <input type="text" id="nbname" placeholder="notebook name" style="width:12em">
  <button class="small" onclick="saveFlow()">Save</button>
  <label class="small" style="cursor:pointer;background:#5a6b7b;color:#fff;padding:3px 8px;border-radius:4px;font-size:11px">Import .flow
   <input type="file" id="flowfile" accept=".flow,.json" style="display:none" onchange="importFlowFile(this.files[0])"></label>
  <select id="nblist" onchange="loadFlow(this.value)"><option value="">Load…</option></select>
 </span>
</header>
<div id="wrap">
 <div id="nb">
  <div class="assist" id="assist"></div>
  <div id="cells"></div>
  <button class="ghost" onclick="addCell('')">+ cell</button>
 </div>
 <aside>
  <section><h2>Frames</h2><div id="frames"></div></section>
  <section><h2>Models</h2><div id="models"></div></section>
  <section><h2>Help</h2><div class="help" id="help"></div></section>
 </aside>
</div>
<script>
const J = (m, p, body) => fetch(p, body ? {method: m,
  headers: {"Content-Type": "application/json"}, body: JSON.stringify(body)}
  : {method: m}).then(r => r.json());
function esc(s){return String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;')
  .replace(/>/g,'&gt;').replace(/"/g,'&quot;').replace(/'/g,'&#39;')}
function qk(k){return /[\s"']/.test(k) ? '"' + String(k).replace(/"/g, '') + '"' : k}
function cellLink(cmdline, label){
  return `<a data-cmd="${esc(cmdline)}" onclick="addCell(this.dataset.cmd,1)">${esc(label)}</a>`;
}

// ---------------------------------------------------------------- notebook
let CELLS = [];   // {id, input, output(html)}
let NEXT_CELL_ID = 1;
function renderCells(){
  const host = document.getElementById("cells");
  host.innerHTML = "";
  CELLS.forEach((c, i) => {
    const d = document.createElement("div");
    d.className = "cell" + (c.input.trim().startsWith("md ") ? " md" : "");
    d.innerHTML = `<div class="cellbar">
      <button onclick="runCell(${i})">Run</button>
      <button class="small" onclick="moveCell(${i},-1)">↑</button>
      <button class="small" onclick="moveCell(${i},1)">↓</button>
      <button class="small" onclick="delCell(${i})">✕</button>
      <span style="color:#8aa">cell ${i + 1} — shift+enter runs</span></div>`;
    const ta = document.createElement("textarea");
    ta.value = c.input;
    ta.rows = Math.max(1, c.input.split("\n").length);
    ta.oninput = () => { c.input = ta.value; };
    ta.onkeydown = e => { if (e.key === "Enter" && e.shiftKey){
      e.preventDefault(); c.input = ta.value; runCell(i); } };
    d.appendChild(ta);
    const out = document.createElement("div");
    out.className = "out";
    out.id = "cellout-" + c.id;
    out.innerHTML = c.output || "";
    d.appendChild(out);
    host.appendChild(d);
  });
}
function addCell(input, run){
  CELLS.push({id: NEXT_CELL_ID++, input: input || "", output: ""});
  renderCells();
  if (run) runCell(CELLS.length - 1);
}
function delCell(i){ CELLS.splice(i, 1); renderCells(); }
function moveCell(i, d){
  const j = i + d;
  if (j < 0 || j >= CELLS.length) return;
  [CELLS[i], CELLS[j]] = [CELLS[j], CELLS[i]];
  renderCells();
}

// ------------------------------------------------------------ assist + help
const ASSIST = [
  ["importFiles", "importFiles /path/to/data.csv"],
  ["getFrames", "getFrames"],
  ["frame summary", "getFrameSummary FRAME_KEY"],
  ["buildModel", "buildModel gbm {\"training_frame\": \"FRAME\", \"response_column\": \"Y\", \"ntrees\": 20}"],
  ["getModels", "getModels"],
  ["getModel", "getModel MODEL_KEY"],
  ["predict", "predict MODEL_KEY FRAME_KEY"],
  ["buildGrid", "buildGrid gbm {\"training_frame\": \"FRAME\", \"response_column\": \"Y\", \"hyper_parameters\": {\"max_depth\": [3, 5], \"ntrees\": [10, 20]}}"],
  ["getGrid", "getGrid GRID_KEY"],
  ["runAutoML", "runAutoML {\"training_frame\": \"FRAME\", \"response_column\": \"Y\", \"max_models\": 5, \"nfolds\": 0}"],
  ["leaderboard", "getLeaderboard PROJECT_KEY"],
  ["plot varimp", "plot varimp MODEL_KEY"],
  ["plot scoring", "plot scoring MODEL_KEY"],
  ["plot roc", "plot roc MODEL_KEY"],
  ["remove", "remove KEY"],
  ["getJobs", "getJobs"],
  ["rapids", "rapids (mean (cols FRAME 'col'))"],
  ["note", "md ## notes\nanything after 'md ' renders as a note"],
];
const HELP = {
  importFiles: "importFiles &lt;path&gt; [dest_key] — parse csv/parquet/orc/arff/svmlight/avro/xlsx into a frame",
  getFrames: "getFrames — list frames in the DKV",
  getFrameSummary: "getFrameSummary &lt;key&gt; — head rows + per-column mean/sigma/NAs/domain",
  buildModel: "buildModel &lt;algo&gt; &lt;json params&gt; — algos: gbm drf glm xgboost deeplearning kmeans naivebayes isolationforest …; polls the job to completion",
  buildGrid: "buildGrid &lt;algo&gt; &lt;json&gt; — cartesian/random grid over hyper_parameters; polls the job then lists the grid",
  getGrid: "getGrid &lt;key&gt; — models of a finished grid",
  runAutoML: "runAutoML &lt;json&gt; — leaderboard run (max_models/max_runtime_secs budgets)",
  getLeaderboard: "getLeaderboard &lt;project&gt; — ranked AutoML leaderboard",
  getModels: "getModels — list models",
  getModel: "getModel &lt;key&gt; — metrics + params",
  predict: "predict &lt;model&gt; &lt;frame&gt; — score a frame; result key in DKV",
  plot: "plot varimp|scoring|roc &lt;model&gt; — inline SVG charts from the model payload",
  remove: "remove &lt;key&gt; — delete a frame/model from the DKV",
  getJobs: "getJobs — job list with status/progress",
  rapids: "rapids &lt;ast&gt; — evaluate a Rapids s-expression server-side",
  md: "md &lt;text&gt; — a note cell (lines starting ## render as headings)",
};
function renderAssist(){
  document.getElementById("assist").innerHTML = "assist: " + ASSIST.map(
    ([label, tpl]) =>
      `<button class="ghost" data-cmd="${esc(tpl)}" onclick="addCell(this.dataset.cmd)">${esc(label)}</button>`
  ).join("");
  document.getElementById("help").innerHTML =
    "<dl>" + Object.entries(HELP).map(([k, v]) =>
      `<dt>${esc(k)}</dt><dd>${v}</dd>`).join("") + "</dl>" +
    `<a onclick="routeHelp()">server routes…</a><div id="routes"></div>`;
}
async function routeHelp(){
  try{
    const r = await fetch("/3/Metadata/endpoints").then(x => x.json());
    const list = (r.routes || []).map(x =>
      `<tr><td>${esc(x.http_method)}</td><td>${esc(x.url_pattern)}</td></tr>`).join("");
    document.getElementById("routes").innerHTML =
      `<pre style="max-height:200px"><table>${list}</table></pre>`;
  }catch(e){ document.getElementById("routes").textContent = "unavailable"; }
}

// -------------------------------------------------------------- SVG charts
function svgBar(pairs, title){
  const W = 560, H = 20 * pairs.length + 30, max = Math.max(...pairs.map(p => p[1]), 1e-12);
  let s = `<svg width="${W}" height="${H}"><text x="4" y="12" font-weight="600">${esc(title)}</text>`;
  pairs.forEach(([k, v], i) => {
    const y = 22 + i * 20, w = 360 * v / max;
    s += `<rect x="130" y="${y}" width="${w}" height="14" fill="#2f6fed" opacity="0.85"/>
          <text x="126" y="${y + 11}" text-anchor="end">${esc(String(k).slice(0, 18))}</text>
          <text x="${134 + w}" y="${y + 11}">${(+v).toPrecision(4)}</text>`;
  });
  return s + "</svg>";
}
function svgLine(series, title, xlab){
  // series: [{name, xs, ys, color}]
  const W = 560, H = 220, L = 46, B = 26;
  let xs = series.flatMap(s => s.xs), ys = series.flatMap(s => s.ys);
  ys = ys.filter(v => isFinite(v)); xs = xs.filter(v => isFinite(v));
  if (!xs.length || !ys.length) return "<i>no data</i>";
  const x0 = Math.min(...xs), x1 = Math.max(...xs), y0 = Math.min(...ys), y1 = Math.max(...ys);
  const px = x => L + (W - L - 10) * (x1 > x0 ? (x - x0) / (x1 - x0) : 0.5);
  const py = y => (H - B) - (H - B - 22) * (y1 > y0 ? (y - y0) / (y1 - y0) : 0.5);
  let s = `<svg width="${W}" height="${H}"><text x="4" y="12" font-weight="600">${esc(title)}</text>
    <line x1="${L}" y1="${H - B}" x2="${W - 8}" y2="${H - B}" stroke="#9db2c4"/>
    <line x1="${L}" y1="${H - B}" x2="${L}" y2="18" stroke="#9db2c4"/>
    <text x="${L}" y="${H - 8}">${(+x0).toPrecision(3)}</text>
    <text x="${W - 60}" y="${H - 8}">${(+x1).toPrecision(3)} ${esc(xlab || "")}</text>
    <text x="2" y="${py(y0) + 3}">${(+y0).toPrecision(3)}</text>
    <text x="2" y="${py(y1) + 3}">${(+y1).toPrecision(3)}</text>`;
  series.forEach((sr, k) => {
    const pts = sr.xs.map((x, i) => `${px(x)},${py(sr.ys[i])}`).join(" ");
    s += `<polyline fill="none" stroke="${sr.color}" stroke-width="1.6" points="${pts}"/>
          <text x="${L + 6 + 120 * k}" y="24" fill="${sr.color}">${esc(sr.name)}</text>`;
  });
  return s + "</svg>";
}
function sparkline(bins, w, h){
  if (!bins || !bins.length) return "·";
  const max = Math.max(...bins, 1);
  const bw = (w - 2) / bins.length;
  return `<svg width="${w}" height="${h}">` + bins.map((b, i) =>
    `<rect x="${1 + i * bw}" y="${h - 1 - (h - 3) * b / max}" width="${Math.max(bw - 0.6, 0.6)}" height="${(h - 3) * b / max + 1}" fill="#2f6fed" opacity="0.8"/>`
  ).join("") + "</svg>";
}
function tableCols(t){  // TwoDimTableV3 (column-major data) -> {name: values}
  const out = {};
  (t.columns || []).forEach((c, i) => { out[c.name] = t.data[i]; });
  return out;
}

// ---------------------------------------------------------------- commands
async function pollJob(jobKey, onTick, ms){
  for(;;){
    const jr = await J("GET", `/3/Jobs/${jobKey}`);
    const j = jr.jobs[0];
    onTick(j);
    if (["DONE", "FAILED", "CANCELLED"].includes(j.status)){
      if (j.exception) throw new Error(j.exception);
      return j;
    }
    await new Promise(r => setTimeout(r, ms || 500));
  }
}
async function runCell(i){
  const c = CELLS[i];
  const set = html => {
    c.output = html;
    const node = document.getElementById("cellout-" + c.id);
    if (node) node.innerHTML = html; else renderCells();
  };
  const line = c.input.trim();
  if (!line) return;
  // tokens honor double quotes so keys with spaces stay addressable:
  //   getFrameSummary "my frame"
  const toks = (line.match(/"([^"]*)"|\S+/g) || [])
    .map(t => t.startsWith('"') ? t.slice(1, -1) : t);
  const [cmd, ...rest] = toks;
  try{
    if (cmd === "md"){
      const txt = c.input.replace(/^md\s*/, "");
      set(txt.split("\n").map(l => l.startsWith("##")
        ? `<h3>${esc(l.replace(/^#+\s*/, ""))}</h3>` : `<p>${esc(l)}</p>`).join(""));
    } else if (cmd === "importFiles"){
      set("importing…");
      const body = {path: rest[0]};
      if (rest[1]) body.destination_frame = rest[1];
      const out = await J("POST", "/3/ImportFiles", body);
      if (out.msg) throw new Error(out.msg);
      set(`<span class="pill">${esc(out.destination_frames[0])}</span>`);
      refreshSide();
    } else if (cmd === "getFrames"){
      const out = await J("GET", "/3/Frames");
      set("<table><tr><th>key</th><th>rows</th><th>cols</th></tr>" +
        out.frames.map(f => `<tr><td>${cellLink("getFrameSummary " + qk(f.frame_id.name), f.frame_id.name)}</td><td>${f.rows}</td><td>${f.column_count}</td><td>${cellLink("remove " + qk(f.frame_id.name), "rm")}</td></tr>`).join("") + "</table>");
    } else if (cmd === "getFrameSummary"){
      const out = await J("GET", `/3/Frames/${encodeURIComponent(rest[0])}`);
      const f = out.frames[0];
      const head = f.columns.map(cc => `<th>${esc(cc.label)}<br><span style="font-weight:400">${esc(cc.type)}</span></th>`).join("");
      const n = Math.min(8, Math.max(...f.columns.map(cc => (cc.data || cc.string_data || []).length)));
      let body = "";
      for (let r = 0; r < n; r++)
        body += "<tr>" + f.columns.map(cc => {
          let v = (cc.string_data || cc.data || [])[r];
          if (v !== null && cc.domain && cc.data) v = cc.domain[cc.data[r]] ?? v;
          return `<td>${v == null ? "·" : esc(typeof v === "number" ? +v.toFixed(4) : v)}</td>`;
        }).join("") + "</tr>";
      const stats = f.columns.map(cc =>
        `<tr><td>${esc(cc.label)}</td><td>${cc.mean == null ? "·" : (+cc.mean).toFixed(4)}</td>
         <td>${cc.sigma == null ? "·" : (+cc.sigma).toFixed(4)}</td><td>${cc.missing_count}</td>
         <td>${cc.domain ? cc.domain.length + " levels" : "·"}</td>
         <td>${sparkline(cc.histogram_bins, 120, 22)}</td></tr>`).join("");
      set(`<b>${esc(rest[0])}</b> — ${f.rows} rows<table><tr>${head}</tr>${body}</table>
           <table><tr><th>col</th><th>mean</th><th>sigma</th><th>NAs</th><th>domain</th><th>distribution</th></tr>${stats}</table>`);
    } else if (cmd === "buildModel"){
      const algo = rest[0];
      const body = JSON.parse(line.slice(line.indexOf("{")));
      set("submitting…");
      const out = await J("POST", `/3/ModelBuilders/${algo}`, body);
      if (out.msg) throw new Error(out.msg);
      const j = await pollJob(out.job.key.name, j =>
        set(`${esc(j.status)} ${(100 * j.progress).toFixed(0)}% — ${esc(j.progress_msg || "")}`));
      set(`<span class="pill">${esc(j.dest.name)}</span> ` +
          cellLink("getModel " + qk(j.dest.name), "inspect") + " " +
          cellLink("plot varimp " + qk(j.dest.name), "varimp"));
      refreshSide();
    } else if (cmd === "getModels"){
      const out = await J("GET", "/3/Models");
      set("<table><tr><th>key</th><th>algo</th></tr>" + out.models.map(m =>
        `<tr><td>${cellLink("getModel " + qk(m.model_id.name), m.model_id.name)}</td><td>${esc(m.algo)}</td><td>${cellLink("remove " + qk(m.model_id.name), "rm")}</td></tr>`).join("") + "</table>");
    } else if (cmd === "getModel"){
      const out = await J("GET", `/3/Models/${encodeURIComponent(rest[0])}`);
      const m = out.models[0];
      const mm = m.output.training_metrics || {};
      const metrics = Object.entries(mm).filter(([k, v]) => typeof v === "number")
        .map(([k, v]) => `<tr><td>${esc(k)}</td><td>${(+v).toFixed(5)}</td></tr>`).join("");
      set(`<b>${esc(rest[0])}</b> (${esc(m.algo)}, ${esc(m.output.model_category || "")}) ` +
          cellLink("plot varimp " + qk(rest[0]), "varimp") + " " +
          cellLink("plot scoring " + qk(rest[0]), "scoring") + " " +
          cellLink("plot roc " + qk(rest[0]), "roc") +
          `<table><tr><th>training metric</th><th>value</th></tr>${metrics}</table>`);
    } else if (cmd === "predict"){
      set("scoring…");
      const out = await J("POST", `/3/Predictions/models/${encodeURIComponent(rest[0])}/frames/${encodeURIComponent(rest[1])}`);
      if (out.msg) throw new Error(out.msg);
      set(`<span class="pill">${esc(out.predictions_frame.name)}</span> ` +
          cellLink("getFrameSummary " + qk(out.predictions_frame.name), "inspect"));
      refreshSide();
    } else if (cmd === "buildGrid"){
      const algo = rest[0];
      const body = JSON.parse(line.slice(line.indexOf("{")));
      set("submitting grid…");
      const out = await J("POST", `/99/Grid/${algo}`, body);
      if (out.msg) throw new Error(out.msg);
      const j = await pollJob(out.job.key.name, j =>
        set(`${esc(j.status)} ${(100 * j.progress).toFixed(0)}%`));
      set(`<span class="pill">${esc(j.dest.name)}</span> ` +
          cellLink("getGrid " + qk(j.dest.name), "inspect grid"));
      refreshSide();
    } else if (cmd === "getGrid"){
      const out = await J("GET", `/99/Grids/${encodeURIComponent(rest[0])}`);
      if (out.msg) throw new Error(out.msg);
      set(`<b>${esc(rest[0])}</b><table><tr><th>model</th></tr>` +
        (out.model_ids || []).map(m =>
          `<tr><td>${cellLink("getModel " + qk(m.name), m.name)}</td></tr>`).join("") +
        "</table>" + ((out.failure_details || []).length
          ? `<pre class="err">${esc(out.failure_details.join("\n"))}</pre>` : ""));
    } else if (cmd === "runAutoML"){
      const body = JSON.parse(line.slice(line.indexOf("{")));
      set("starting AutoML…");
      const out = await J("POST", "/99/AutoMLBuilder", body);
      if (out.msg) throw new Error(out.msg);
      const j = await pollJob(out.job.key.name, j =>
        set(`${esc(j.status)} ${(100 * j.progress).toFixed(0)}% — ${esc(j.progress_msg || "training models")}`), 800);
      set(`<span class="pill">${esc(j.dest.name)}</span> ` +
          cellLink("getLeaderboard " + qk(j.dest.name), "leaderboard"));
      refreshSide();
    } else if (cmd === "getLeaderboard"){
      const out = await J("GET", `/99/Leaderboards/${encodeURIComponent(rest[0])}`);
      if (out.msg) throw new Error(out.msg);
      const t = out.table;
      const heads = t.columns.map(cc => `<th>${esc(cc.name)}</th>`).join("");
      const nrow = (t.data[0] || []).length;
      let rows = "";
      for (let r = 0; r < nrow; r++){
        rows += "<tr>" + t.columns.map((cc, ci) => {
          const v = t.data[ci][r];
          if (cc.name === "model_id")
            return `<td>${cellLink("getModel " + qk(v), v)}</td>`;
          return `<td>${typeof v === "number" ? (+v).toFixed(5) : esc(v == null ? "·" : v)}</td>`;
        }).join("") + "</tr>";
      }
      set(`<b>${esc(out.project_name)}</b> — sorted by ${esc(out.sort_metric)}
           <table><tr>${heads}</tr>${rows}</table>`);
    } else if (cmd === "plot"){
      const kind = rest[0], key = rest[1];
      const out = await J("GET", `/3/Models/${encodeURIComponent(key)}`);
      const mo = out.models[0].output;
      if (kind === "varimp"){
        const t = mo.variable_importances;
        if (!t) throw new Error("model has no variable importances");
        const cols = tableCols(t);
        const pairs = cols.variable.map((v, i) => [v, +cols.scaled_importance[i]]);
        pairs.sort((a, b) => b[1] - a[1]);
        set(svgBar(pairs.slice(0, 20), `variable importance — ${key}`));
      } else if (kind === "scoring"){
        const t = mo.scoring_history;
        if (!t) throw new Error("model has no scoring history");
        const cols = tableCols(t);
        const xkey = Object.keys(cols).find(k => /tree|iter|epoch/i.test(k)) || Object.keys(cols)[0];
        const palette = ["#2f6fed", "#d1342f", "#2b8a5c", "#8a63c9"];
        const series = Object.keys(cols)
          .filter(k => k !== xkey && cols[k].every(v => typeof v === "number"))
          .slice(0, 4).map((k, i) => ({name: k, xs: cols[xkey], ys: cols[k], color: palette[i]}));
        set(svgLine(series, `scoring history — ${key}`, xkey));
      } else if (kind === "roc"){
        const mm = mo.training_metrics || {};
        const t = mm.thresholds_and_metric_scores;
        if (!t) throw new Error("no thresholds table (binomial models only)");
        const cols = tableCols(t);
        set(svgLine([{name: `ROC (AUC ${(+mm.AUC).toFixed(4)})`, xs: cols.fpr, ys: cols.tpr, color: "#2f6fed"},
                     {name: "chance", xs: [0, 1], ys: [0, 1], color: "#9db2c4"}],
                    `ROC — ${key}`, "fpr"));
      } else throw new Error(`unknown plot kind ${kind}`);
    } else if (cmd === "remove"){
      await fetch(`/3/DKV/${encodeURIComponent(rest[0])}`, {method: "DELETE"});
      set(`<span class="pill">removed ${esc(rest[0])}</span>`);
      refreshSide();
    } else if (cmd === "getJobs"){
      const out = await J("GET", "/3/Jobs");
      set("<table><tr><th>job</th><th>status</th><th>progress</th></tr>" +
        out.jobs.map(j => `<tr><td>${esc(j.description || j.key.name)}</td><td>${esc(j.status)}</td><td>${(100 * j.progress).toFixed(0)}%</td></tr>`).join("") + "</table>");
    } else if (cmd === "rapids"){
      const r = await J("POST", "/99/Rapids", {ast: line.slice(7)});
      set(`<pre>${esc(JSON.stringify(r, null, 1))}</pre>`);
      refreshSide();
    } else {
      throw new Error(`unknown command ${cmd}; see help`);
    }
  }catch(e){ set(`<span class="err">${esc(e.message)}</span>`); }
}

// -------------------------------------------------------------- side panes
async function refreshCloud(){
  try{
    const c = await J("GET", "/3/Cloud");
    document.getElementById("cloud").innerHTML =
      `cloud <b>${esc(c.cloud_name)}</b> · ${c.cloud_size} device(s) · v${esc(c.version)} <span class="pill">healthy</span>`;
  }catch(e){ document.getElementById("cloud").textContent = "unreachable"; }
}
async function refreshSide(){
  try{
    const fo = await J("GET", "/3/Frames");
    document.getElementById("frames").innerHTML = "<table>" + fo.frames.map(f =>
      `<tr><td>${cellLink("getFrameSummary " + qk(f.frame_id.name), f.frame_id.name)}</td><td>${f.rows}×${f.column_count}</td><td>${cellLink("remove " + qk(f.frame_id.name), "rm")}</td></tr>`).join("") + "</table>";
    const mo = await J("GET", "/3/Models");
    document.getElementById("models").innerHTML = "<table>" + mo.models.map(m =>
      `<tr><td>${cellLink("getModel " + qk(m.model_id.name), m.model_id.name)}</td><td>${esc(m.algo)}</td><td>${cellLink("remove " + qk(m.model_id.name), "rm")}</td></tr>`).join("") + "</table>";
  }catch(e){}
}

// ---------------------------------------------------------------- persist
async function saveFlow(){
  const name = document.getElementById("nbname").value || "flow";
  const doc = {version: 2, cells: CELLS.map(c => ({input: c.input}))};
  await fetch(`/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`,
              {method: "POST", body: JSON.stringify(doc)});
  refreshNotebooks();
}
async function loadFlow(name){
  if (!name) return;
  const r = await fetch(`/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`);
  const doc = JSON.parse(await r.text());
  if (doc.version === 2 && doc.cells){
    CELLS = doc.cells.map(c => ({id: NEXT_CELL_ID++, input: c.input, output: ""}));
  } else if (doc.fields){      // v1 console documents: convert to cells
    CELLS = [];
    const push = input => CELLS.push({id: NEXT_CELL_ID++, input, output: ""});
    const f = doc.fields;
    if (f.path) push(`importFiles ${f.path}` + (f.dest ? ` ${qk(f.dest)}` : ""));
    if (f.algo){
      // v1 docs never persisted the response column (it lived in a
      // <select>): emit an md note + a template the user completes
      const body = {training_frame: f.dest || "EDIT_FRAME_KEY",
                    response_column: "EDIT_RESPONSE_COLUMN"};
      for (const kv of (f.params || "").split(",")){
        const [k, v] = kv.split("=").map(x => x && x.trim());
        if (k && v !== undefined) body[k] = v;
      }
      push("md converted from a v1 console document — fill in the " +
           "EDIT_* placeholders below before running");
      push(`buildModel ${f.algo} ${JSON.stringify(body)}`);
    }
    if (f.ast) push(`rapids ${f.ast}`);
  }
  document.getElementById("nbname").value = name;
  renderCells();
}
function convertRefFlowCell(cell){
  // reference Flow .flow cells: {type: "cs"|"md"|"raw", input: "..."}
  // (h2o-web Flow's CoffeeScript command language). Convert the common
  // verbs; anything else becomes an annotated note so nothing is lost.
  const inp = (cell.input || "").trim();
  if (cell.type === "md") return "md " + inp;
  let m;
  if ((m = inp.match(/^importFiles\s*\[\s*"([^"]+)"/)))
    return "importFiles " + m[1];
  if ((m = inp.match(/^buildModel\s+['"](\w+)['"]\s*,\s*(\{[\s\S]*\})/))){
    try{
      const params = JSON.parse(m[2].replace(/'/g, '"'));
      delete params.model_id;
      return `buildModel ${m[1]} ${JSON.stringify(params)}`;
    }catch(e){ /* fall through to note */ }
  }
  if ((m = inp.match(/^predict\s+model:\s*['"]([^'"]+)['"],?\s*frame:\s*['"]([^'"]+)['"]/)))
    return `predict ${qk(m[1])} ${qk(m[2])}`;
  if (/^getFrames/.test(inp)) return "getFrames";
  if (/^getModels/.test(inp)) return "getModels";
  if ((m = inp.match(/^getFrameSummary\s+['"]([^'"]+)['"]/)))
    return "getFrameSummary " + qk(m[1]);
  return "md [unconverted .flow cell] " + inp;
}
function importFlowFile(file){
  if (!file) return;
  const rd = new FileReader();
  rd.onload = () => {
    try{
      const doc = JSON.parse(rd.result);
      if (!doc.cells) throw new Error("not a .flow document");
      CELLS = doc.cells.map(c =>
        ({id: NEXT_CELL_ID++, input: convertRefFlowCell(c), output: ""}));
      document.getElementById("nbname").value =
        (file.name || "imported").replace(/\.flow$/, "");
      renderCells();
    }catch(e){ alert("import failed: " + e.message); }
  };
  rd.readAsText(file);
}
async function refreshNotebooks(){
  const r = await J("GET", "/3/NodePersistentStorage/notebook");
  const sel = document.getElementById("nblist");
  sel.innerHTML = '<option value="">Load…</option>' +
    r.entries.map(e => `<option value="${esc(e.name)}">${esc(e.name)}</option>`).join("");
}

renderAssist();
addCell("md ## welcome to Flow\nuse the assist buttons above to insert workflow cells; shift+enter runs a cell");
runCell(0);
refreshCloud(); refreshSide(); refreshNotebooks();
setInterval(refreshCloud, 10000);
</script></body></html>
"""
