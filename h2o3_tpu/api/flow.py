"""Flow — the built-in web console served from the node.

Reference: ``h2o-web/`` packages the Flow notebook (CoffeeScript app served
by the node at ``/``; ``h2o-web/README.md:1-8``). The TPU build ships a
dependency-free single-page console over the same V3 REST surface: cluster
status, frames, models, jobs, and a Rapids prompt — the day-to-day Flow
operations — rendered client-side from ``/3/*`` JSON.
"""

FLOW_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1c2733}
 header{background:#1c2733;color:#fff;padding:10px 20px;display:flex;gap:16px;align-items:baseline}
 header h1{font-size:16px;margin:0}
 header span{color:#9db2c4;font-size:12px}
 main{padding:16px 20px;display:grid;grid-template-columns:1fr 1fr;gap:16px}
 section{background:#fff;border:1px solid #dde4ea;border-radius:6px;padding:12px}
 h2{font-size:13px;text-transform:uppercase;letter-spacing:.06em;color:#5a6b7b;margin:0 0 8px}
 table{width:100%;border-collapse:collapse;font-size:13px}
 td,th{text-align:left;padding:4px 6px;border-bottom:1px solid #eef2f5}
 th{color:#5a6b7b;font-weight:600}
 #rapids{grid-column:1/3}
 input[type=text]{width:80%;padding:6px;border:1px solid #cfd8e0;border-radius:4px}
 button{padding:6px 12px;border:0;border-radius:4px;background:#2f6fed;color:#fff;cursor:pointer}
 pre{background:#f4f6f8;padding:8px;border-radius:4px;overflow:auto;max-height:200px}
 .pill{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px;background:#e7f0e7;color:#2b6a2b}
</style></head><body>
<header><h1>h2o3-tpu Flow</h1><span id="cloud">connecting…</span></header>
<main>
 <section><h2>Frames</h2><table id="frames"><tr><th>key</th><th>rows</th><th>cols</th></tr></table></section>
 <section><h2>Models</h2><table id="models"><tr><th>key</th><th>algo</th></tr></table></section>
 <section><h2>Jobs</h2><table id="jobs"><tr><th>key</th><th>status</th><th>progress</th></tr></table></section>
 <section><h2>Timeline (last events)</h2><table id="timeline"><tr><th>kind</th><th>what</th><th>ms</th></tr></table></section>
 <section id="rapids"><h2>Rapids</h2>
  <input type="text" id="expr" placeholder="(+ 1 2)"> <button onclick="runRapids()">Run</button>
  <pre id="result"></pre></section>
</main>
<script>
async function j(p, opt){const r = await fetch(p, opt); return r.json();}
function row(t, cells){const tr = document.createElement('tr');
 for(const c of cells){const td = document.createElement('td'); td.textContent = c; tr.appendChild(td);}
 t.appendChild(tr);}
function reset(t){while(t.rows.length > 1) t.deleteRow(1);}
async function refresh(){
 try{
  const c = await j('/3/Cloud');
  document.getElementById('cloud').textContent =
    `cloud ${c.cloud_name ?? ''} · ${c.cloud_size} node(s) · ` +
    (c.cloud_healthy ? 'healthy' : 'unhealthy') + ` · v${c.version ?? ''}`;
  const fr = await j('/3/Frames'); const ft = document.getElementById('frames'); reset(ft);
  for(const f of (fr.frames ?? [])) row(ft, [f.frame_id?.name ?? f.key, f.rows, f.column_count]);
  const mo = await j('/3/Models'); const mt = document.getElementById('models'); reset(mt);
  for(const m of (mo.models ?? [])) row(mt, [m.model_id?.name ?? m.key, m.algo]);
  const tl = await j('/3/Timeline'); const tt = document.getElementById('timeline'); reset(tt);
  for(const e of (tl.events ?? []).slice(-12).reverse())
    row(tt, [e.kind, e.what, (e.dur_ns/1e6).toFixed(2)]);
 }catch(e){document.getElementById('cloud').textContent = 'disconnected: '+e;}
}
async function runRapids(){
 const ast = document.getElementById('expr').value;
 const out = await j('/99/Rapids', {method:'POST',
   headers:{'Content-Type':'application/json'}, body: JSON.stringify({ast})});
 document.getElementById('result').textContent = JSON.stringify(out, null, 2);
 refresh();
}
refresh(); setInterval(refresh, 4000);
</script></body></html>
"""
