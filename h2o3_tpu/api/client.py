"""Thin REST client — the h2o-py connection surface over stdlib urllib.

Reference: ``h2o-py/h2o/backend/connection.py:249`` (``H2OConnection.request``
``:431-455``) — every client verb is one HTTP call to the V3 routes; training
polls ``/3/Jobs/{id}`` until DONE (``estimator_base.py:186``).
"""

from __future__ import annotations

import json
import time
import urllib.parse
import uuid
import urllib.request


class H2OClient:
    """``H2OClient(url)`` speaks to a running :class:`H2OServer`."""

    def __init__(self, url: str, tenant: str | None = None):
        self.url = url.rstrip("/")
        #: tenant id sent as ``X-H2O3-Tenant`` on every request (None =
        #: the server's default tenant) — the multi-tenant admission
        #: identity (docs/OPERATIONS.md "Tenancy")
        self.tenant = tenant
        # trace id of the most recent request (from the server's W3C
        # ``traceparent`` response header) — feed it to :meth:`trace`
        self.last_trace_id: str | None = None

    # -- transport -----------------------------------------------------------

    def request(self, method: str, path: str, data: dict | None = None) -> dict:
        url = self.url + path
        body = None
        headers = {}
        if self.tenant is not None:
            headers["X-H2O3-Tenant"] = str(self.tenant)
        if data is not None:
            body = urllib.parse.urlencode(
                {k: (json.dumps(v) if isinstance(v, (dict, list)) else v)
                 for k, v in data.items()}).encode()
            headers["Content-Type"] = "application/x-www-form-urlencoded"
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req) as resp:
                tp = resp.headers.get("traceparent", "")
                if tp.count("-") >= 2:
                    self.last_trace_id = tp.split("-")[1]
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            payload = e.read().decode()
            try:
                msg = json.loads(payload).get("msg", payload)
            except json.JSONDecodeError:
                msg = payload
            raise RuntimeError(f"{method} {path} → {e.code}: {msg}") from None

    # -- verbs (h2o-py equivalents) ------------------------------------------

    def cloud_status(self) -> dict:
        return self.request("GET", "/3/Cloud")

    def cloud(self) -> dict:
        """Alias of :meth:`cloud_status` (h2o-py ``h2o.cluster()`` shape);
        includes the ``mesh_slices`` utilization view."""
        return self.cloud_status()

    def mesh_slices(self) -> dict:
        """Mesh-slice scheduler utilization: slice layout + per-slice busy
        seconds / builds / queue wait (docs/ORCHESTRATION.md)."""
        return self.cloud_status().get("mesh_slices", {})

    def workers(self) -> list:
        """Elastic local-SGD membership: per-worker state / round /
        last-heartbeat rows of recent elastic groups, served inside
        ``GET /3/Cloud`` (docs/RELIABILITY.md "Elastic training")."""
        return self.cloud_status().get("workers", [])

    def import_file(self, path: str, destination_frame: str | None = None) -> str:
        """Server-side import+parse. A nonexistent/unreadable SERVER path
        surfaces as :class:`FileNotFoundError` carrying the structured 400
        the server replies (never a 500 traceback)."""
        d = {"path": path}
        if destination_frame:
            d["destination_frame"] = destination_frame
        try:
            out = self.request("POST", "/3/ImportFiles", d)
        except RuntimeError as e:
            # only PATH errors map to FileNotFoundError — a 400 can also be
            # a parse failure on a file that exists (ValueError server-side).
            # Anchor on the server's _check_readable message shape so a
            # parse error merely MENTIONING a path phrase never matches.
            msg = str(e)
            if "→ 400:" in msg and "import_file:" in msg \
                    and ("no such file" in msg or "not readable" in msg
                         or "is a directory" in msg):
                raise FileNotFoundError(msg) from None
            raise
        return out["destination_frames"][0]

    def upload_file(self, path: str, destination_frame: str | None = None) -> str:
        """Ship a CLIENT-LOCAL file to the server and parse it (h2o-py
        ``h2o.upload_file``: multipart POST /3/PostFile + POST /3/Parse)."""
        import os
        with open(path, "rb") as f:
            data = f.read()
        boundary = uuid.uuid4().hex
        fname = os.path.basename(path)
        body = (f"--{boundary}\r\nContent-Disposition: form-data; "
                f'name="file"; filename="{fname}"\r\n\r\n').encode() + data \
            + f"\r\n--{boundary}--\r\n".encode()
        req = urllib.request.Request(
            self.url + "/3/PostFile", data=body, method="POST",
            headers={"Content-Type":
                     f"multipart/form-data; boundary={boundary}"})
        with urllib.request.urlopen(req) as resp:
            raw_key = json.loads(resp.read())["destination_frame"]
        dest = destination_frame or raw_key
        self.request("POST", "/3/Parse",
                     {"source_frames": [raw_key], "destination_frame": dest})
        return dest

    def frame(self, key: str) -> dict:
        return self.request("GET", f"/3/Frames/{key}")["frames"][0]

    def frames(self) -> list[dict]:
        return self.request("GET", "/3/Frames")["frames"]

    def rm(self, key: str) -> None:
        try:
            self.request("DELETE", f"/3/Frames/{key}")
        except RuntimeError:
            self.request("DELETE", f"/3/Models/{key}")

    def train(self, algo: str, training_frame: str, y: str | None = None,
              poll_secs: float = 0.2, **params) -> dict:
        """POST /3/ModelBuilders/{algo}, poll the job, return the model JSON."""
        d = {"training_frame": training_frame, **params}
        if y is not None:
            d["response_column"] = y
        out = self.request("POST", f"/3/ModelBuilders/{algo}", d)
        job = self._poll(out["job"]["key"]["name"], poll_secs)
        return self.model(job["dest"]["name"])

    def _poll(self, job_key: str, poll_secs: float = 0.2) -> dict:
        while True:
            job = self.request("GET", f"/3/Jobs/{job_key}")["jobs"][0]
            if job["status"] in ("DONE", "FAILED", "CANCELLED"):
                if job["status"] == "FAILED":
                    raise RuntimeError(f"job failed: {job.get('exception')}")
                return job
            time.sleep(poll_secs)

    def model(self, key: str) -> dict:
        return self.request("GET", f"/3/Models/{key}")["models"][0]

    def models(self) -> list[dict]:
        return self.request("GET", "/3/Models")["models"]

    def predict(self, model_key: str, frame_key: str) -> str:
        out = self.request("POST",
                           f"/3/Predictions/models/{model_key}/frames/{frame_key}")
        return out["predictions_frame"]["name"]

    def score(self, model_key: str, rows: list, columns: list | None = None,
              priority: int | None = None,
              slo_ms: float | None = None) -> dict:
        """Request-sized scoring through the batched serving tier
        (``POST /3/Score/{model}``): ``rows`` is a list of dicts (column-
        keyed) or a list of lists ordered by ``columns``. ``priority``
        (0-9, default 5) orders shedding under overload — low priority is
        turned away first with 503+Retry-After; ``slo_ms`` overrides the
        model's latency target at admit. Returns the ScoreV3 payload —
        ``predictions`` column lists plus the batch shape this request
        rode in (docs/SERVING.md)."""
        d: dict = {"rows": rows}
        if columns:
            d["columns"] = list(columns)
        if priority is not None:
            d["priority"] = int(priority)
        if slo_ms is not None:
            d["slo_ms"] = float(slo_ms)
        return self.request("POST", f"/3/Score/{model_key}", d)

    def serving(self) -> dict:
        """Scoring-tier state (``GET /3/Score``): residency +
        compiled-scorer cache counters, per-model SLO controller state,
        shed accounting by reason/priority, and the replica-pool view."""
        return self.request("GET", "/3/Score")

    def serving_evict(self, model_key: str) -> bool:
        """Drop a model's scoring residency (``DELETE /3/Score/{model}``);
        its DKV copy stays — the next score re-admits it."""
        return bool(self.request("DELETE",
                                 f"/3/Score/{model_key}").get("evicted"))

    def rapids(self, ast: str, id: str | None = None) -> dict:
        d = {"ast": ast}
        if id:
            d["id"] = id
        return self.request("POST", "/99/Rapids", d)

    def grid(self, algo: str, training_frame: str, y: str,
             hyper_parameters: dict, search_criteria: dict | None = None,
             **params) -> dict:
        d = {"training_frame": training_frame, "response_column": y,
             "hyper_parameters": hyper_parameters, **params}
        if search_criteria:
            d["search_criteria"] = search_criteria
        out = self.request("POST", f"/99/Grid/{algo}", d)
        job = self._poll(out["job"]["key"]["name"])
        return self.request("GET", f"/99/Grids/{job['dest']['name']}")

    # -- round-2 parity surface ----------------------------------------------

    def parse_setup(self, source_frames: list[str]) -> dict:
        return self.request("POST", "/3/ParseSetup",
                            {"source_frames": source_frames})

    def split_frame(self, frame_key: str, ratios: list[float],
                    destination_frames: list[str] | None = None) -> list[str]:
        d = {"dataset": frame_key, "ratios": ratios}
        if destination_frames:
            d["destination_frames"] = destination_frames
        out = self.request("POST", "/3/SplitFrame", d)
        self._poll(out["key"]["name"])
        return [f["name"] for f in out["destination_frames"]]

    def model_metrics(self, model_key: str, frame_key: str) -> dict:
        out = self.request(
            "POST", f"/3/ModelMetrics/models/{model_key}/frames/{frame_key}")
        return out["model_metrics"][0]

    def partial_dependence(self, model_key: str, frame_key: str,
                           cols: list[str], nbins: int = 20) -> list[dict]:
        out = self.request("POST", "/3/PartialDependence/",
                           {"model_id": model_key, "frame_id": frame_key,
                            "cols": cols, "nbins": nbins})
        self._poll(out["key"]["name"])
        got = self.request("GET",
                           f"/3/PartialDependence/{out['destination_key']}")
        return got["partial_dependence_data"]

    def quantiles(self, frame_key: str, column: str,
                  probs: list[float] = (0.25, 0.5, 0.75)) -> list[float]:
        res = self.rapids(
            f"(quantile (cols {frame_key} \"{column}\") [{' '.join(map(str, probs))}])")
        fr = self.frame(res["key"]["name"])
        qcol = [c for c in fr["columns"] if c["label"] == column][0]
        return qcol["data"]

    def typeahead(self, src: str, limit: int = 100) -> list[str]:
        q = urllib.parse.urlencode({"src": src, "limit": limit})
        return self.request("GET", f"/3/Typeahead/files?{q}")["matches"]

    def save_model(self, model_key: str, directory: str) -> str:
        q = urllib.parse.urlencode({"dir": directory})
        return self.request("GET", f"/99/Models.bin/{model_key}?{q}")["dir"]

    def load_model(self, path: str) -> str:
        out = self.request("POST", "/99/Models.bin/", {"dir": path})
        return out["models"][0]["model_id"]["name"]

    def remove_all(self) -> None:
        self.request("DELETE", "/3/DKV")

    def jobs(self) -> list[dict]:
        return self.request("GET", "/3/Jobs")["jobs"]

    def job(self, job_key: str) -> dict:
        """One job's JobV3 — status/progress plus the reliability surface:
        ``retries`` (dispatch retries the build absorbed),
        ``max_runtime_secs``/``deadline_exceeded`` (deadline budget), and
        ``auto_recoverable``/``auto_recovery_dir`` (crash-resume snapshot
        state; docs/RELIABILITY.md)."""
        return self.request("GET", f"/3/Jobs/{job_key}")["jobs"][0]

    # -- observability (h2o-py: cluster().timeline / get_log; plus metrics) --

    def timeline(self) -> list[dict]:
        """Runtime event ring: dispatches, model fits, faults
        (``GET /3/Timeline``)."""
        return self.request("GET", "/3/Timeline")["events"]

    def logs(self, node: int = 0, name: str = "info") -> str:
        """Formatted server log lines from the LogRing
        (``GET /3/Logs/nodes/{n}/files/{name}``)."""
        return self.request("GET", f"/3/Logs/nodes/{node}/files/{name}")["log"]

    def metrics(self) -> list[dict]:
        """JSON metrics snapshot: flat {name, type, labels, value} rows
        (``GET /3/Metrics``)."""
        return self.request("GET", "/3/Metrics")["metrics"]

    def memory(self, top: int = 10) -> dict:
        """Device/host byte accounting: host RSS, per-device HBM stats,
        DKV bytes by kind + top-N keys (spilled stubs report their on-disk
        bytes under the ``spilled`` kind), watermarks, the leak report,
        and the Cleaner spill view — spill/fault-in/view-drop counters +
        ice_root contents (``GET /3/Memory``; docs/INGEST.md)."""
        return self.request("GET", f"/3/Memory?top={int(top)}")

    def jstack(self) -> list[dict]:
        """All server thread stacks (``GET /3/JStack``; h2o-py:
        ``h2o.cluster().get_status`` → JStack)."""
        return self.request("GET", "/3/JStack")["traces"]

    def profiler(self, depth: int = 5) -> dict:
        """Sampled stack profile: ``{"stacktraces": [...], "counts": [...]}``
        ordered hottest-first (``GET /3/Profiler?depth=N``)."""
        return self.request("GET", f"/3/Profiler?depth={int(depth)}")

    def compute(self) -> dict:
        """The compute observatory (``GET /3/Compute``): per-site compiled
        signatures, compile seconds, cost_analysis FLOPs/bytes, recompile
        events with signature diffs, and per-loop achieved FLOP/s +
        utilization-or-null (docs/OBSERVABILITY.md "Compute")."""
        return self.request("GET", "/3/Compute")

    def profiler_capture(self, duration_ms: int = 500) -> dict:
        """Open a bounded device-profiler window
        (``POST /3/Profiler/capture``) and return the capture record;
        fetch the Perfetto artifact with :meth:`profiler_download`. A
        concurrent capture raises (the server replies a structured 409)."""
        return self.request("POST",
                            f"/3/Profiler/capture?duration_ms="
                            f"{int(duration_ms)}")

    def profiler_captures(self) -> list[dict]:
        """Capture registry (``GET /3/Profiler/captures``)."""
        return self.request("GET", "/3/Profiler/captures")["captures"]

    def profiler_download(self, capture_id: str, path: str) -> str:
        """Save a capture's gzip Chrome-trace artifact to ``path`` and
        return it — gunzip and load at https://ui.perfetto.dev."""
        url = f"{self.url}/3/Profiler/captures/{capture_id}/download"
        with urllib.request.urlopen(url) as resp:
            data = resp.read()
        with open(path, "wb") as f:
            f.write(data)
        return path

    def timeseries(self, name: str | None = None,
                   labels: dict | None = None,
                   since: float | None = None) -> dict:
        """The flight recorder's retained series
        (``GET /3/TimeSeries``): per series the raw ``[t, value]`` tail
        and the min/max/mean/last rollup windows, plus recorder stats.
        ``name`` matches exactly or as a prefix; ``labels`` is a subset
        match; ``since`` is epoch seconds
        (docs/OBSERVABILITY.md "Flight recorder & post-mortems")."""
        q = []
        if name:
            q.append("name=" + urllib.parse.quote(str(name)))
        if labels:
            pairs = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            q.append("labels=" + urllib.parse.quote(pairs))
        if since is not None:
            q.append(f"since={float(since)}")
        path = "/3/TimeSeries" + (("?" + "&".join(q)) if q else "")
        return self.request("GET", path)

    def health(self) -> dict:
        """The ops-plane verdict (``GET /3/Health``): overall +
        per-subsystem healthy/degraded/unhealthy, each finding naming the
        tripping rule, observed value, and threshold
        (docs/OBSERVABILITY.md "Health & incidents")."""
        return self.request("GET", "/3/Health")

    def incidents(self, state: str | None = None) -> list[dict]:
        """Incident-ring summaries, newest first (``GET /3/Incidents``),
        optionally filtered to ``state="open"`` or ``"resolved"``; fetch
        one with :meth:`incident` for its trip-time context."""
        path = "/3/Incidents"
        if state is not None:
            path += f"?state={urllib.parse.quote(str(state))}"
        return self.request("GET", path)["incidents"]

    def ops(self) -> dict:
        """The ops plane in one view (``GET /3/Ops``): remediation policy
        (mode, rule→action map, bounds), the audited action log,
        per-tenant usage, and configured quotas (docs/OPERATIONS.md)."""
        return self.request("GET", "/3/Ops")

    def set_quota(self, tenant: str, qps=None, device_seconds=None,
                  bytes=None) -> dict:   # noqa: A002 — the REST param name
        """Install per-tenant budgets (``POST /3/Ops``): requests/second,
        device-seconds per rolling window, and DKV bytes. Omitted
        dimensions are unlimited; over-quota requests are shed with
        ``429 + Retry-After``."""
        data = {"tenant": tenant}
        if qps is not None:
            data["qps"] = qps
        if device_seconds is not None:
            data["device_seconds"] = device_seconds
        if bytes is not None:
            data["bytes"] = bytes
        return self.request("POST", "/3/Ops", data)["quota"]

    def remove_quota(self, tenant: str) -> bool:
        """Drop a tenant's budgets (``POST /3/Ops`` remove_quota)."""
        return bool(self.request("POST", "/3/Ops",
                                 {"remove_quota": tenant})["removed"])

    def rollback_action(self, action_id: str) -> bool:
        """Undo a recorded remediation action by its id
        (``POST /3/Ops`` rollback); the rollback is itself audited."""
        return bool(self.request("POST", "/3/Ops",
                                 {"rollback": action_id})["rolled_back"])

    def incident(self, incident_id: str) -> dict:
        """One incident with its correlated context — trace ids, log
        tail, memory top-keys, compute rows, observed-value series
        (``GET /3/Incidents/{id}``)."""
        return self.request("GET", f"/3/Incidents/{incident_id}")

    def diagnostics_bundle(self, path: str) -> str:
        """Download the one-call diagnostic bundle — a gzip tar of all
        four pillar snapshots + health verdict + incident ring + logs +
        hardware fingerprint + redacted config (``POST
        /3/Diagnostics/bundle``; the ``h2o logs download`` analog) — to
        ``path`` and return it."""
        req = urllib.request.Request(self.url + "/3/Diagnostics/bundle",
                                     method="POST")
        with urllib.request.urlopen(req) as resp:
            data = resp.read()
        with open(path, "wb") as f:
            f.write(data)
        return path

    def metrics_text(self) -> str:
        """Raw Prometheus/OpenMetrics exposition (``GET /metrics``)."""
        with urllib.request.urlopen(self.url + "/metrics") as resp:
            return resp.read().decode()

    def traces(self) -> list[dict]:
        """Completed-trace summaries, newest first (``GET /3/Traces``)."""
        return self.request("GET", "/3/Traces")["traces"]

    def trace(self, trace_id: str) -> dict:
        """Full span tree + critical path for one trace
        (``GET /3/Traces/{id}``)."""
        return self.request("GET", f"/3/Traces/{trace_id}")

    def trace_export(self, trace_id: str) -> dict:
        """Chrome trace-event JSON for Perfetto / chrome://tracing
        (``GET /3/Traces/{id}/export``); ``json.dump`` it to a file and
        load at https://ui.perfetto.dev."""
        return self.request("GET", f"/3/Traces/{trace_id}/export")

    def ping(self) -> bool:
        return bool(self.request("GET", "/3/Ping").get("healthy"))

    def shutdown(self) -> None:
        self.request("POST", "/3/Shutdown")
