"""V3 schema serialization — framework objects → REST JSON.

Reference: ``water/api/Schema.java`` (reflection-driven field copy via ``@API``
annotations) and ``water/api/schemas3/*.java`` (FrameV3, ModelSchemaV3,
JobV3, CloudV3 …). The wire format keys (``__meta.schema_type``, field names)
follow the reference so existing h2o-py response parsing recognizes them.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np


def _clean(x: Any) -> Any:
    """JSON-safe: numpy scalars → python, non-finite floats → None."""
    if isinstance(x, (np.floating, float)):
        f = float(x)
        return f if math.isfinite(f) else None
    if isinstance(x, (np.integer, int)):
        return int(x)
    if isinstance(x, np.ndarray):
        return [_clean(v) for v in x.tolist()]
    if isinstance(x, (list, tuple)):
        return [_clean(v) for v in x]
    if isinstance(x, dict):
        return {k: _clean(v) for k, v in x.items()}
    if isinstance(x, (str, bool)) or x is None:
        return x
    return str(x)


def _meta(schema_type: str) -> dict:
    return {"__meta": {"schema_version": 3, "schema_name": schema_type,
                       "schema_type": schema_type}}


def cloud_v3(version: str) -> dict:
    import os as _os

    import jax

    from h2o3_tpu.utils.memory import MEMORY, host_stats
    devs = jax.devices()
    # real memory accounting behind the reference's per-node heap fields
    # (water/api/schemas3/CloudV3.java semantics): max_mem = machine total,
    # free_mem = machine available, mem_value_size = bytes resident in the
    # DKV (the K/V store the reference's MemoryManager meters — HERE that
    # includes device HBM chunks; the per-device split lives in /3/Memory),
    # pojo_mem = process RSS not attributable to HOST-resident DKV bytes
    # (the "everything else" heap — HBM bytes are never subtracted from
    # RSS, they live in a different memory). One process serves the whole
    # device cloud, so the process numbers ride on every node row.
    host = host_stats()
    dkv_bytes, _by_kind, nkeys = MEMORY.dkv_totals()
    pojo = max(host["rss_bytes"] - MEMORY.dkv_host_bytes(), 0)
    pid = _os.getpid()
    # mesh-slice scheduler utilization (orchestration/scheduler.py): slice
    # layout + per-slice busy seconds / builds / queue wait — the
    # cluster-utilization view ROADMAP item 5 asks for, on the endpoint
    # every client already polls
    from h2o3_tpu.orchestration.scheduler import SLICE_STATS
    # elastic local-SGD membership (parallel/elastic.py): per-worker
    # state/round/last-heartbeat rows of recent elastic groups — the
    # reference's cloud-member heartbeat view, on the endpoint every
    # client already polls (docs/RELIABILITY.md "Elastic training")
    from h2o3_tpu.parallel.elastic import ELASTIC_STATS
    return {**_meta("CloudV3"), "version": version, "cloud_name": "h2o3_tpu",
            "mesh_slices": SLICE_STATS.snapshot(),
            "workers": _clean(ELASTIC_STATS.rows()),
            "cloud_size": len(devs), "cloud_healthy": True, "bad_nodes": 0,
            "consensus": True, "locked": True, "is_client": False,
            "cloud_uptime_millis": 0, "internal_security_enabled": False,
            "branch_name": "tpu", "build_number": "0", "build_age": "",
            "build_too_old": False, "node_idx": 0,
            "cloud_internal_timezone": "UTC",
            "datafile_parser_timezone": "UTC",
            "nodes": [{"h2o": str(d), "healthy": True, "num_cpus": 1,
                       "cpus_allowed": 1,
                       "free_mem": host["available_bytes"],
                       "max_mem": host["total_bytes"],
                       "mem_value_size": dkv_bytes, "pojo_mem": pojo,
                       "swap_mem": 0,
                       "free_disk": 0, "max_disk": 0, "num_keys": nkeys,
                       "tcps_active": 0, "open_fds": 0, "rpcs_active": 0,
                       "last_ping": 0, "sys_load": 0.0,
                       "my_cpu_pct": 0, "sys_cpu_pct": 0, "pid": pid}
                      for d in devs]}


def memory_v3(summary: dict) -> dict:
    """``GET /3/Memory`` — the three-level byte accounting: host RSS +
    machine totals, per-device HBM (``memory_stats`` or live-array
    fallback), DKV totals by kind with the top-N keys (spilled stubs keep
    their on-disk bytes under the ``spilled`` kind), monotonic watermarks,
    the leak-detector report (utils/memory.py), and the Cleaner's spill
    view — budget, spill/fault-in/view-drop counters, ice_root contents
    (utils/cleaner.py; docs/INGEST.md)."""
    return {**_meta("MemoryV3"), **_clean(summary)}


def compute_v3(snapshot: dict) -> dict:
    """``GET /3/Compute`` — the compute observatory (utils/costs.py): per
    logical compile site the compiled signatures (shapes/dtypes/statics),
    compile wall seconds, ``cost_analysis()`` FLOPs/bytes, and recompile
    events with signature diffs; per loop the achieved FLOP/s / bytes/s,
    arithmetic intensity, and utilization against the backend's peak row
    (utilization and roofline are null on backends outside the peak table
    — this CPU container included). ``docs/OBSERVABILITY.md`` "Compute"."""
    return {**_meta("ComputeV3"), **_clean(snapshot)}


def health_v3(verdict: dict) -> dict:
    """``GET /3/Health`` — the health evaluator's subsystem-scored verdict
    (utils/health.py): overall + per-subsystem ``healthy`` / ``degraded``
    / ``unhealthy``, every finding carrying the tripping rule, the
    observed value, and the threshold; plus the rule catalog with its env
    knobs and the currently-open incident rules
    (docs/OBSERVABILITY.md "Health & incidents")."""
    return {**_meta("HealthV3"), **_clean(verdict)}


def incidents_v3(summaries: list) -> dict:
    """``GET /3/Incidents`` — the bounded incident ring, newest first:
    rule / subsystem / severity / status / observed vs threshold /
    repeats / timestamps (contexts served per-incident by
    ``GET /3/Incidents/{id}``)."""
    return {**_meta("IncidentsV3"), "incidents": _clean(summaries)}


def timeseries_v3(payload: dict) -> dict:
    """``GET /3/TimeSeries`` — the flight recorder (utils/flight.py):
    matching retained series, each with its raw ``[t, value]`` tail and
    min/max/mean/last rollup windows, plus the recorder's stats
    (running / interval / retention / dropped-series counters)
    (docs/OBSERVABILITY.md "Flight recorder & post-mortems")."""
    return {**_meta("TimeSeriesV3"), **_clean(payload)}


def ops_v3(payload: dict) -> dict:
    """``GET/POST /3/Ops`` — the ops plane: remediation policy view
    (mode/map/bounds), the append-only action log, per-tenant usage, and
    the configured quotas (docs/OPERATIONS.md is the operator catalog)."""
    return {**_meta("OpsV3"), **_clean(payload)}


def incident_v3(record: dict) -> dict:
    """``GET /3/Incidents/{id}`` — one incident with its trip-time
    correlated context: recent trace ids, log-ring tail, memory top-keys,
    compute loop rows, the rule's observed-value series, and (for
    profiled compute incidents) the profiler capture id."""
    return {**_meta("IncidentV3"), **_clean(record)}


def _column_histogram(vec, r, nbins: int = 20) -> dict:
    """ColV3 histogram fields (reference ``FrameV3.ColV3``: Flow's frame
    inspector renders these as sparklines): fixed-stride bins over
    [min, max] counted in one device pass."""
    import jax
    import jax.numpy as jnp
    import math as _math
    # rows past nrows are padding; derived frames (predictions) can carry
    # FINITE pad values there, so mask by index like _numeric_rollups does
    in_range = jnp.arange(vec.data.shape[0]) < vec.nrows
    lo, hi = float(r.min), float(r.max)
    if not (_math.isfinite(lo) and _math.isfinite(hi)):
        # +/-inf rows are counted by rollups but must not set the range
        finite = jnp.isfinite(vec.data) & in_range
        big = jnp.float32(jnp.finfo(jnp.float32).max)
        lo = float(jnp.min(jnp.where(finite, vec.data, big)))
        hi = float(jnp.max(jnp.where(finite, vec.data, -big)))
    if not (hi > lo) or r.nrows == 0:
        return {"histogram_bins": [], "histogram_base": _clean(lo),
                "histogram_stride": 0}
    stride = (hi - lo) / nbins
    ids = jnp.clip(((vec.data - lo) / stride).astype(jnp.int32), 0, nbins - 1)
    ok = jnp.isfinite(vec.data) & in_range
    cnt = jax.ops.segment_sum(ok.astype(jnp.float32),
                              jnp.where(ok, ids, 0), num_segments=nbins)
    return {"histogram_bins": [int(x) for x in jax.device_get(cnt)],
            "histogram_base": _clean(lo), "histogram_stride": _clean(stride)}


def _histogram_cached(vec, r) -> dict:
    """Histograms are immutable like the rollups — compute once per vec
    (the reference caches them in RollupStats for the same reason; frame
    summaries are served repeatedly to Flow's side panel and h2o-py)."""
    cache = getattr(vec, "_hist_cache", None)
    if cache is None:
        if vec.is_numeric:
            cache = _column_histogram(vec, r)
        else:
            # categorical "histogram": per-level counts (reference ColV3
            # serves these for Flow's frame inspector bars)
            import jax
            import jax.numpy as jnp
            in_range = jnp.arange(vec.data.shape[0]) < vec.nrows
            codes = jnp.clip(vec.data, -1, len(vec.domain) - 1)
            cnt = jax.ops.segment_sum(
                ((vec.data >= 0) & in_range).astype(jnp.float32),
                jnp.maximum(codes, 0), num_segments=len(vec.domain))
            cache = {"histogram_bins": [int(x) for x in jax.device_get(cnt)],
                     "histogram_base": 0, "histogram_stride": 1}
        vec._hist_cache = cache
    return cache


def frame_v3(key: str, frame, rows: int = 10) -> dict:
    """FrameV3 with the exact per-column fields h2o-py's expr cache pops
    (``h2o-py/h2o/expr.py:_fill_data``): __meta, domain_cardinality,
    string_data, data; enum data = integer codes + domain (reference
    water/api/schemas3/FrameV3.java ColV3)."""
    cols = []
    for name, vec in zip(frame.names, frame.vecs):
        r = vec.rollups()     # handles host-resident (string/uuid) vecs too
        if rows <= 0:
            data, sdata = [], None
        elif vec.type.value == "string" or not vec.type.on_device:
            data, sdata = None, [None if v is None else str(v)
                                 for v in vec.to_numpy()[:rows]]
        else:
            data, sdata = _clean(vec.to_numpy()[:rows]), None
        col = {"__meta": {"schema_name": "ColV3", "schema_type": "ColV3"},
               "label": name, "type": vec.type.value,
               "missing_count": int(r.na_cnt),
               "domain": list(vec.domain) if vec.domain else None,
               "domain_cardinality": vec.cardinality(),
               "data": data, "string_data": sdata,
               "precision": 0, "zero_count": 0,
               "positive_infinity_count": 0, "negative_infinity_count": 0}
        if vec.is_numeric:
            col.update(mins=[_clean(r.min)], maxs=[_clean(r.max)],
                       mean=_clean(r.mean), sigma=_clean(r.sigma))
            col.update(_histogram_cached(vec, r))
        else:
            col.update(mins=[], maxs=[], mean=None, sigma=None)
            if vec.domain and vec.type.on_device:
                col.update(_histogram_cached(vec, r))
        cols.append(col)
    return {**_meta("FrameV3"), "frame_id": {"name": key},
            "rows": frame.nrows, "row_count": frame.nrows,
            "row_offset": 0, "column_offset": 0,
            "column_count": frame.ncols, "total_column_count": frame.ncols,
            "columns": cols}


def frames_list_v3(store) -> dict:
    from h2o3_tpu.frame.frame import Frame
    # raw_items: spilled frames list from their stubs (nrows/ncols carried)
    # instead of being re-inflated from disk just for a listing
    # mesh-slice views (Frame.on_mesh) are internal device-layout copies —
    # byte-accounted in /3/Memory, but not user frames for the listing
    frames = [{"frame_id": {"name": k}, "rows": v.nrows, "column_count": v.ncols}
              for k, v in store.raw_items()
              if (isinstance(v, Frame) or type(v).__name__ == "SwappedFrame")
              and not getattr(v, "_is_mesh_view", False)
              and "::mesh[" not in k]
    return {**_meta("FramesV3"), "frames": frames}


def metrics_v3(mm, domain=None) -> dict | None:
    if mm is None:
        return None
    out = {}
    if domain is not None and hasattr(mm, "auc"):
        # h2o-py's perf.confusion_matrix() reads the class labels here
        out["domain"] = list(domain)
    for f in ("mse", "rmse", "mae", "r2", "logloss", "auc", "pr_auc",
              "mean_per_class_error", "residual_deviance", "null_deviance",
              "accuracy", "mean_residual_deviance", "totss", "tot_withinss",
              "betweenss"):
        v = getattr(mm, f, None)
        if v is not None and not callable(v):
            out[f] = _clean(v)
    # h2o-py's metrics mixins read the reference's exact (capitalized) keys
    # and pick their class from __meta.schema_name (h2o/model/metrics/)
    schema = {"ModelMetricsBinomial": "ModelMetricsBinomialV3",
              "ModelMetricsMultinomial": "ModelMetricsMultinomialV3",
              "ModelMetricsRegression": "ModelMetricsRegressionV3",
              "ModelMetricsClustering": "ModelMetricsClusteringV3",
              }.get(type(mm).__name__, "ModelMetricsV3")
    for lower, upper in (("mse", "MSE"), ("rmse", "RMSE"), ("auc", "AUC"),
                         ("gini", "Gini"), ("r2", "r2")):
        v = getattr(mm, lower, None)
        if v is not None and not callable(v):
            out[upper] = _clean(v)
    out.setdefault("nobs", _clean(getattr(mm, "nobs", 0)))
    if hasattr(mm, "threshold_table"):
        # AUC2 criteria tables (reference: hex/AUC2.java; h2o-py's
        # perf.F1()/mcc()/find_threshold_by_max_metric read these)
        tcols, trows = mm.threshold_table()
        if trows:
            out["thresholds_and_metric_scores"] = twodim_table_v3(
                "Metrics for Thresholds", "Binomial metrics as a function of "
                "classification thresholds",
                [(c, "long" if c == "idx" else "double", "%f")
                 for c in tcols], trows)
            _, mrows = mm.max_criteria_and_metric_scores((tcols, trows))
            out["max_criteria_and_metric_scores"] = twodim_table_v3(
                "Maximum Metrics", "Maximum metrics at their respective "
                "thresholds",
                [("metric", "string", "%s"), ("threshold", "double", "%f"),
                 ("value", "double", "%f"), ("idx", "long", "%d")], mrows)
    out["description"] = None
    out["custom_metric_name"] = getattr(mm, "custom_metric_name", None)
    out["custom_metric_value"] = _clean(getattr(mm, "custom_metric_value", 0.0))
    out["scoring_time"] = 0
    return {**_meta(schema), **out}


def model_v3(model) -> dict:
    out = {**_meta("ModelSchemaV3"),
           "model_id": {"name": model.key}, "algo": model.algo,
           "algo_full_name": model.algo,
           "response_column_name": model.response_column,
           "parameters": [{"name": k, "actual_value": _clean(v)}
                          for k, v in dict(model.params).items()],
           "output": {
               "model_category": ("Binomial" if model.nclasses == 2 else
                                  "Multinomial" if model.nclasses > 2 else
                                  "Regression"),
               "training_metrics": metrics_v3(model.training_metrics,
                                              model.response_domain),
               "validation_metrics": metrics_v3(model.validation_metrics,
                                                model.response_domain),
               "cross_validation_metrics": metrics_v3(
                   model.cross_validation_metrics, model.response_domain),
               "cross_validation_metrics_summary":
                   _cv_summary_v3(getattr(model, "cv_metrics_summary",
                                          None)),
               # folds share one compiled program (CV by weight masking), so
               # no per-fold model keys exist; h2o-py reads this key
               # unconditionally when CV metrics are present
               "cross_validation_models": None,
               "run_time_ms": model.run_time_ms,
           }}
    if model.scoring_history is not None:
        cols, rows = model.scoring_history
        out["output"]["scoring_history"] = twodim_table_v3(
            "Scoring History", "", cols, rows)
    if hasattr(model, "varimp"):
        # h2o-py model.varimp() reads output.variable_importances
        # (reference: ModelOutputSchemaV3._variable_importances). Memoized:
        # recomputing walks every tree with per-tree device fetches (~43 ms
        # each over the tunnel) and Flow fetches the payload per plot.
        try:
            vi_rows = getattr(model, "_varimp_rows", None)
            if vi_rows is None:
                vi_rows = model.varimp()
                try:
                    model._varimp_rows = vi_rows
                except Exception:   # noqa: BLE001 — frozen model classes
                    pass
        except Exception:   # noqa: BLE001 — varimp optional on some families
            vi_rows = None
        if vi_rows:
            out["output"]["variable_importances"] = twodim_table_v3(
                "Variable Importances", "",
                [("variable", "string", "%s"),
                 ("relative_importance", "float", "%5f"),
                 ("scaled_importance", "float", "%5f"),
                 ("percentage", "float", "%5f")],
                [list(r) for r in vi_rows])
    meta_model = (model.output or {}).get("metalearner")
    if meta_model is not None:
        # h2o-py's H2OStackedEnsembleEstimator.metalearner() fetches this key
        out["output"]["metalearner"] = {"name": meta_model.key}
        out["output"]["stacking_strategy"] = "cross_validation"
    return out


def models_list_v3(store) -> dict:
    from h2o3_tpu.models.model_base import Model
    models = [{"model_id": {"name": k}, "algo": v.algo}
              for k, v in store.raw_items()
              if isinstance(v, Model)]
    return {**_meta("ModelsV3"), "models": models}


def raw_frame_v3(key: str, nbytes: int) -> dict:
    """FramesV3 body for a RAW upload key (reference exposes /3/PostFile
    results as 1-column ByteVec frames; h2o.upload_mojo's get_frame step
    reads this shape before handing the key to the generic builder)."""
    col = {"__meta": {"schema_version": 3, "schema_name": "ColV3",
                      "schema_type": "Vec"},
           "label": "C1", "type": "uuid", "data": [], "string_data": [],
           "missing_count": 0, "domain": None, "domain_cardinality": 0,
           "mean": 0, "sigma": 0, "zero_count": 0,
           "positive_infinity_count": 0, "negative_infinity_count": 0,
           "histogram_bins": [], "histogram_base": 0, "histogram_stride": 0,
           "percentiles": []}
    return {"__meta": {"schema_type": "FramesV3"},
            "frames": [{"frame_id": {"name": key},
                        "rows": nbytes, "row_count": nbytes,
                        "row_offset": 0, "column_offset": 0,
                        "column_count": 1, "total_column_count": 1,
                        "byte_size": nbytes, "is_text": False,
                        "columns": [col], "checksum": 0,
                        "default_percentiles": [], "compatible_models": [],
                        "chunk_summary": None,
                        "distribution_summary": None}]}


def _cv_summary_v3(summary) -> dict | None:
    """Per-fold CV metric table (reference ModelBuilder's
    cross_validation_metrics_summary: rows = metrics, columns = mean, sd,
    cv_{k}_valid; h2o-py renders it verbatim)."""
    if summary is None:
        return None
    _names, nfolds, rows = summary
    cols = [("", "string", "%s"), ("mean", "double", "%f"),
            ("sd", "double", "%f")] + [(f"cv_{k + 1}_valid", "double", "%f")
                                       for k in range(nfolds)]
    return twodim_table_v3("Cross-Validation Metrics Summary",
                           "per-fold holdout metrics", cols, rows)


def twodim_table_v3(name: str, description: str,
                    columns: list[tuple[str, str, str]],
                    rows: list[list], row_headers: bool = False) -> dict:
    """TwoDimTableV3 wire format (reference:
    ``water/api/schemas3/TwoDimTableV3.java:55`` ``fillFromImpl``); ``data``
    is column-major. With ``row_headers`` a leading row-index column (name
    ``""`` after pythonify("#"), type string) is embedded — the
    leaderboard/event-log convention, where h2o-py's ``_fetch_table`` drops
    it via ``fr[1:]``. Metric/scoring tables ship WITHOUT it (the reference
    passes a null colHeaderForRowHeaders; h2o-py indexes ``cell_values[0]``
    as the first real column)."""
    cols = ([{"name": "", "type": "string", "format": "%s", "description": "#"}]
            if row_headers else [])
    cols += [{"name": n, "type": t, "format": f, "description": n}
             for n, t, f in columns]
    data = [[str(i) for i in range(len(rows))]] if row_headers else []
    for c in range(len(columns)):
        data.append([_clean(r[c]) for r in rows])
    return {"__meta": {"schema_version": 3, "schema_name": "TwoDimTableV3",
                       "schema_type": "TwoDimTable"},
            "name": name, "description": description,
            "columns": cols, "rowcount": len(rows), "data": data}


def leaderboard_v99(aml, extensions: list[str] | None = None) -> dict:
    """LeaderboardV99 (reference:
    ``water/automl/api/schemas3/LeaderboardV99.java:11``)."""
    lb = aml.leaderboard
    cols, rows, sort_metric, sort_dec, sort_vals, model_ids = (
        lb.table(extensions) if lb is not None
        else ([("model_id", "string", "%s")], [], "auc", True, [], []))
    table = twodim_table_v3(
        f"Leaderboard for project {aml.project_name}",
        (f"models sorted in order of {sort_metric}, best first"
         if rows else "no models in this leaderboard"),
        cols, rows, row_headers=True)
    return {"__meta": {"schema_version": 99, "schema_name": "LeaderboardV99",
                       "schema_type": "Leaderboard"},
            "project_name": aml.project_name,
            "models": [{"name": k} for k in model_ids],
            "sort_metric": sort_metric,
            "sort_metrics": _clean(sort_vals),
            "sort_decreasing": sort_dec,
            "table": table}


def automl_v99(aml, job_key: str | None = None) -> dict:
    """AutoMLV99 state (reference:
    ``water/automl/api/schemas3/AutoMLV99.java:17``): the exact fields
    h2o-py's ``_fetch_state`` reads — project_name, leaderboard.models,
    leaderboard_table, event_log_table."""
    lbv = leaderboard_v99(aml)
    ev_cols = [("timestamp", "string", "%s"), ("level", "string", "%s"),
               ("stage", "string", "%s"), ("message", "string", "%s"),
               ("name", "string", "%s"), ("value", "string", "%s")]
    ev_rows = aml.event_log.table_rows()
    return {"__meta": {"schema_version": 99, "schema_name": "AutoMLV99",
                       "schema_type": "AutoML"},
            "automl_id": {"name": job_key or aml.project_name},
            "project_name": aml.project_name,
            "leaderboard": lbv,
            "leaderboard_table": lbv["table"],
            "event_log": {"name": f"{aml.project_name}_eventlog"},
            "event_log_table": twodim_table_v3(
                f"Event Log for:{aml.project_name}",
                "Actions taken and discoveries made by AutoML",
                ev_cols, ev_rows, row_headers=True),
            "sort_metric": lbv["sort_metric"],
            "modeling_steps": [
                {"name": name, "steps": [{"id": s, "weight": 10, "group": 1}
                                         for s in steps]}
                for name, steps in aml.modeling_steps()]}


def job_v3(job_id: str, job) -> dict:
    status = {"RUNNING": "RUNNING", "DONE": "DONE", "FAILED": "FAILED",
              "CANCELLED": "CANCELLED"}.get(job.status, job.status)
    d = {**_meta("JobV3"), "key": {"name": job_id}, "status": status,
         "progress": _clean(job.progress), "progress_msg": job.progress_msg,
         "msec": int(job.run_time * 1000),
         "description": getattr(job, "description", ""),
         # reliability surface (docs/RELIABILITY.md): True when the build
         # auto-checkpoints under auto_recovery_dir (hex/faulttolerance
         # semantics — a crashed job restarts from its snapshot); h2o-py's
         # H2OJob reads auto_recoverable/exception/warnings unconditionally
         "auto_recoverable": bool(getattr(job, "auto_recovery_dir", None)),
         "auto_recovery_dir": getattr(job, "auto_recovery_dir", None),
         # dispatch retries this job's build absorbed + its deadline budget
         "retries": int(getattr(job, "retries", 0) or 0),
         "max_runtime_secs": _clean(float(
             getattr(job, "max_runtime_secs", 0.0) or 0.0)),
         "deadline_exceeded": bool(getattr(job, "deadline_exceeded", False)),
         # elastic membership decay: workers ejected from this build's
         # local-SGD group (parallel/elastic.py; /3/Cloud serves the live
         # per-worker view)
         "workers_ejected": int(getattr(job, "workers_ejected", 0) or 0),
         "exception": None,
         "warnings": None,
         # the trace the job's execution reports into (None when it was
         # created outside any trace) — pollers correlate via /3/Traces/{id}
         "trace_id": getattr(job, "trace_id", None),
         "dest": {"name": getattr(job, "dest_key", None) or job_id}}
    if job.status == "FAILED" and job.exception is not None:
        d["exception"] = str(job.exception)
        d["stacktrace"] = ""
        if getattr(job, "retry_history", None):
            # what the retry budget tried before giving up (DispatchFailed)
            d["retry_history"] = job.retry_history
    return d


def score_v3(payload: dict) -> dict:
    """``POST /3/Score/{model}`` — batched request-sized predictions:
    ``predictions`` maps output columns (``predict``, ``p{level}``) to
    value lists; ``batch_rows``/``batch_requests`` report how the
    micro-batcher fused this request; ``priority`` echoes the request's
    shedding class and ``replica`` names the serving replica when a pool
    is routing (docs/SERVING.md)."""
    return {**_meta("ScoreV3"), **_clean(payload)}


def serving_v3(stats: dict) -> dict:
    """``GET /3/Score`` — scoring-tier state: resident models with
    artifact bytes + request counts + per-model ``slo`` controller state
    (target/window/p50/p99), residency budget, eviction count,
    compiled-signature cache hit/miss counters, ``shed`` accounting by
    reason/priority, the ``replicas`` pool view (slice leases, busy and
    queue-wait seconds, scale events), memory watermarks."""
    return {**_meta("ServingV3"), **_clean(stats)}


def trace_v3(trace: dict) -> dict:
    """One completed trace (``GET /3/Traces/{id}``): flat span list, the
    nested span tree, and the computed critical path — the chain of spans
    that determined the request's wall time."""
    from h2o3_tpu.utils import tracing
    return {**_meta("TraceV3"),
            "trace_id": trace["trace_id"], "name": trace["name"],
            "start_ns": trace["start_ns"], "dur_ns": trace["dur_ns"],
            "nspans": trace["nspans"], "dropped": trace.get("dropped", 0),
            "status": trace["status"],
            "in_progress": bool(trace.get("in_progress")),
            "spans": trace.get("spans", []),
            "tree": tracing.span_tree(trace),
            "critical_path": tracing.critical_path(trace)}
