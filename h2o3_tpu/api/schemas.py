"""V3 schema serialization — framework objects → REST JSON.

Reference: ``water/api/Schema.java`` (reflection-driven field copy via ``@API``
annotations) and ``water/api/schemas3/*.java`` (FrameV3, ModelSchemaV3,
JobV3, CloudV3 …). The wire format keys (``__meta.schema_type``, field names)
follow the reference so existing h2o-py response parsing recognizes them.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np


def _clean(x: Any) -> Any:
    """JSON-safe: numpy scalars → python, non-finite floats → None."""
    if isinstance(x, (np.floating, float)):
        f = float(x)
        return f if math.isfinite(f) else None
    if isinstance(x, (np.integer, int)):
        return int(x)
    if isinstance(x, np.ndarray):
        return [_clean(v) for v in x.tolist()]
    if isinstance(x, (list, tuple)):
        return [_clean(v) for v in x]
    if isinstance(x, dict):
        return {k: _clean(v) for k, v in x.items()}
    if isinstance(x, (str, bool)) or x is None:
        return x
    return str(x)


def _meta(schema_type: str) -> dict:
    return {"__meta": {"schema_version": 3, "schema_name": schema_type,
                       "schema_type": schema_type}}


def cloud_v3(version: str) -> dict:
    import jax
    devs = jax.devices()
    return {**_meta("CloudV3"), "version": version, "cloud_name": "h2o3_tpu",
            "cloud_size": len(devs), "cloud_healthy": True,
            "nodes": [{"h2o": str(d), "healthy": True, "num_cpus": 1}
                      for d in devs]}


def frame_v3(key: str, frame, rows: int = 10) -> dict:
    cols = []
    head = frame.to_pandas().head(rows)
    for name, vec in zip(frame.names, frame.vecs):
        r = vec.rollups()
        col = {"label": name, "type": str(vec.type).lower(),
               "missing_count": int(r.na_cnt),
               "domain": list(vec.domain) if vec.domain else None,
               "domain_cardinality": vec.cardinality(),
               "data": _clean(head[name].to_numpy() if name in head else [])}
        if vec.is_numeric:
            col.update(mins=[_clean(r.min)], maxs=[_clean(r.max)],
                       mean=_clean(r.mean), sigma=_clean(r.sigma))
        cols.append(col)
    return {**_meta("FrameV3"), "frame_id": {"name": key},
            "rows": frame.nrows, "row_count": frame.nrows,
            "column_count": frame.ncols, "columns": cols}


def frames_list_v3(store) -> dict:
    from h2o3_tpu.frame.frame import Frame
    frames = [{"frame_id": {"name": k}, "rows": v.nrows, "column_count": v.ncols}
              for k, v in ((k, store.get(k)) for k in store.keys())
              if isinstance(v, Frame)]
    return {**_meta("FramesV3"), "frames": frames}


def metrics_v3(mm) -> dict | None:
    if mm is None:
        return None
    out = {}
    for f in ("mse", "rmse", "mae", "r2", "logloss", "auc", "pr_auc",
              "mean_per_class_error", "residual_deviance", "null_deviance",
              "accuracy", "mean_residual_deviance", "totss", "tot_withinss",
              "betweenss"):
        v = getattr(mm, f, None)
        if v is not None and not callable(v):
            out[f] = _clean(v)
    return {**_meta("ModelMetricsV3"), **out}


def model_v3(model) -> dict:
    out = {**_meta("ModelSchemaV3"),
           "model_id": {"name": model.key}, "algo": model.algo,
           "algo_full_name": model.algo,
           "response_column_name": model.response_column,
           "parameters": [{"name": k, "actual_value": _clean(v)}
                          for k, v in dict(model.params).items()],
           "output": {
               "model_category": ("Binomial" if model.nclasses == 2 else
                                  "Multinomial" if model.nclasses > 2 else
                                  "Regression"),
               "training_metrics": metrics_v3(model.training_metrics),
               "validation_metrics": metrics_v3(model.validation_metrics),
               "cross_validation_metrics": metrics_v3(model.cross_validation_metrics),
               "run_time_ms": model.run_time_ms,
           }}
    return out


def models_list_v3(store) -> dict:
    from h2o3_tpu.models.model_base import Model
    models = [{"model_id": {"name": k}, "algo": v.algo}
              for k, v in ((k, store.get(k)) for k in store.keys())
              if isinstance(v, Model)]
    return {**_meta("ModelsV3"), "models": models}


def job_v3(job_id: str, job) -> dict:
    status = {"RUNNING": "RUNNING", "DONE": "DONE", "FAILED": "FAILED",
              "CANCELLED": "CANCELLED"}.get(job.status, job.status)
    d = {**_meta("JobV3"), "key": {"name": job_id}, "status": status,
         "progress": _clean(job.progress), "progress_msg": job.progress_msg,
         "msec": int(job.run_time * 1000)}
    if job.status == "FAILED" and job.exception is not None:
        d["exception"] = str(job.exception)
    if getattr(job, "dest_key", None):
        d["dest"] = {"name": job.dest_key}
    return d
