"""REST API (V3 schema surface) + thin Python client.

Reference: ``water/api/RequestServer.java`` (~150 routes), ``water/api/Schema``
+ ``schemas3/`` (126 classes), served by Jetty (``h2o-webserver-iface``).
Here: a stdlib threaded HTTP server (the REST plane is control-only — all data
compute stays on-device behind the estimator API) with the high-traffic V3
routes the h2o-py client actually uses.
"""

from h2o3_tpu.api.server import H2OServer, start_server
from h2o3_tpu.api.client import H2OClient

__all__ = ["H2OServer", "start_server", "H2OClient"]
