"""REST server — the V3 route surface.

Reference: ``water/api/RequestServer.java:24-80`` (route tree; core routes in
``RegisterV3Api.java``, algo routes via ``AlgoAbstractRegister``). Routes
implemented are the ones h2o-py traffics: Cloud, ImportFiles, Parse, Frames,
Models, ModelBuilders, Predictions, Jobs, Rapids, Grid, AutoML, Shutdown.

Training runs on a background thread through the same :class:`Job` the library
path uses (reference: ``Job.start`` → F/J pool), so clients poll ``/3/Jobs``
exactly like against the reference server.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from h2o3_tpu import __version__
from h2o3_tpu.api import schemas
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils import tracing as _tr
from h2o3_tpu.utils.registry import DKV, LOCKS

_LOG = logging.getLogger("h2o3_tpu")


def _route_label_of(pat: str) -> str:
    """Metric label for a route regex: regex classes become placeholders and
    escaped literals unescape, so ``/3/WaterMeterCpuTicks/\\d+`` labels as
    ``/3/WaterMeterCpuTicks/{n}`` (not the mangled ``.../d+``)."""
    return pat.replace(r"\d+", "{n}").replace("\\", "")

_ALGOS = None


def _algo_registry():
    global _ALGOS
    if _ALGOS is None:
        from h2o3_tpu.models import (ANOVAGLM, GAM, GBM, DRF, GLM, SVD,
                                     Aggregator, CoxPH, DecisionTree,
                                     DeepLearning, ExtendedIsolationForest,
                                     GLRM, Grep, IsolationForest,
                                     IsotonicRegression, KMeans,
                                     ModelSelection, NaiveBayes, PCA, RuleFit,
                                     Infogram, PSVM, TargetEncoder, UpliftDRF,
                                     Word2Vec, XGBoost)
        from h2o3_tpu.models.hglm import HGLM
        from h2o3_tpu.orchestration.stacked_ensemble import StackedEnsemble
        _ALGOS = {"stackedensemble": StackedEnsemble,
                  "gbm": GBM, "drf": DRF, "glm": GLM, "deeplearning": DeepLearning,
                  "xgboost": XGBoost, "kmeans": KMeans, "pca": PCA, "svd": SVD,
                  "glrm": GLRM, "naivebayes": NaiveBayes, "coxph": CoxPH,
                  "isolationforest": IsolationForest,
                  "extendedisolationforest": ExtendedIsolationForest,
                  "isotonicregression": IsotonicRegression,
                  "word2vec": Word2Vec, "targetencoder": TargetEncoder,
                  "rulefit": RuleFit, "decisiontree": DecisionTree,
                  "aggregator": Aggregator, "grep": Grep, "gam": GAM,
                  "modelselection": ModelSelection, "anovaglm": ANOVAGLM,
                  "upliftdrf": UpliftDRF, "psvm": PSVM, "infogram": Infogram,
                  "hglm": HGLM}
    return _ALGOS


def _name(x):
    """Unwrap h2o-py's KeyV3 payloads: {"name": k} → k."""
    return x.get("name") if isinstance(x, dict) else x


def _parse_list(v: str) -> list:
    """Bracketed list payload: JSON first, else h2o-py's unquoted
    ``stringify_list`` format ``[a,b,c]``."""
    try:
        out = json.loads(v)
        return out if isinstance(out, list) else [out]
    except (json.JSONDecodeError, ValueError):
        return [s.strip().strip('"') for s in v.strip("[]").split(",")
                if s.strip()]


class PayloadTooLarge(ValueError):
    """Raised for oversized request bodies; routed to HTTP 413."""


def _done_job(description: str, dest_key: str | None = None) -> dict:
    """A completed, DKV-registered job serialized as JobV3 — synchronous
    routes still hand h2o-py's H2OJob wrapper a pollable job payload."""
    job = Job(description, key=f"job_{uuid.uuid4().hex[:12]}")
    if dest_key:
        job.dest_key = dest_key
    job.run(lambda j: dest_key, background=False)
    return schemas.job_v3(job.key, job)


class _Handler(BaseHTTPRequestHandler):
    server_version = f"h2o3_tpu/{__version__}"

    # -- plumbing ------------------------------------------------------------

    def log_message(self, *a):   # route logs to our logger, not stderr
        pass

    def send_response(self, code, message=None):
        # status capture for the per-route request metrics (_route)
        self._last_status = code
        super().send_response(code, message)
        # W3C response propagation: every reply names its root span so the
        # caller can fetch the request's trace (client.trace(trace_id))
        span = getattr(self, "_trace_span", None)
        if span is not None:
            self.send_header("traceparent",
                             _tr.format_traceparent(span.context))

    def _reply(self, obj, code: int = 200):
        meta = obj.get("__meta") if isinstance(obj, dict) else None
        if isinstance(meta, dict) and "schema_name" not in meta:
            # h2o-py's response hook requires __meta.schema_name on every
            # payload (h2o-py/h2o/backend/connection.py H2OResponse)
            meta.setdefault("schema_name", meta.get("schema_type", "IcedV3"))
            meta.setdefault("schema_version", 3)
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (getattr(self, "_extra_headers", None) or {}).items():
            self.send_header(k, v)     # e.g. Retry-After on a 503
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str, headers: dict | None = None):
        import time as _t
        if code >= 500:   # server faults land in the log ring (/3/Logs)
            _LOG.warning("HTTP %d on %s: %s", code, self.path, msg)
        self._extra_headers = headers
        try:
            self._reply({"__meta": {"schema_type": "H2OErrorV3"},
                         "http_status": code, "msg": msg,
                         "exception_msg": msg,
                         "timestamp": int(_t.time() * 1000),
                         "error_url": self.path, "dev_msg": msg,
                         "exception_type": "java.lang.RuntimeException",
                         "values": {}, "stacktrace": []}, code)
        finally:
            self._extra_headers = None

    #: non-upload request bodies are parameter payloads; cap them (the
    #: reference relies on Jetty's request limits). File content goes
    #: through /3/PostFile, which has its own 1GiB cap.
    MAX_PARAM_BODY = 64 << 20

    def _drain_body(self, length: int) -> None:
        """Read and discard an oversized body: replying mid-upload breaks
        the pipe on the client side instead of delivering the error."""
        left = length
        while left > 0:
            chunk = self.rfile.read(min(left, 1 << 20))
            if not chunk:
                break
            left -= len(chunk)

    def _params(self) -> dict:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        length = int(self.headers.get("Content-Length") or 0)
        if length > self.MAX_PARAM_BODY:
            self._drain_body(length)
            raise PayloadTooLarge(
                f"request body of {length} bytes exceeds the "
                f"{self.MAX_PARAM_BODY >> 20}MiB parameter cap "
                "(use /3/PostFile for data uploads)")
        if length:
            body = self.rfile.read(length).decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(body))
            else:
                out.update({k: v[0] for k, v in urllib.parse.parse_qs(body).items()})
        return out

    # -- dispatch ------------------------------------------------------------

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def do_HEAD(self):
        # same auth gate as every other method (HEAD must not leak liveness
        # past the login check)
        if not self._check_auth():
            return
        self.send_response(200)
        self.end_headers()

    def _session_token(self) -> str | None:
        cookie = self.headers.get("Cookie") or ""
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "h2o3_session":
                return v
        return None

    def _check_auth(self) -> bool:
        """Credential gate (reference: ``water/H2O.java:242-266`` hash/LDAP
        login + ``water/webserver`` form auth): a valid form-login session
        cookie OR Basic credentials accepted by the server's pluggable
        authenticator. Replies 401 and returns False on failure."""
        authfn = getattr(self.server, "_authenticate", None)
        if authfn is None:
            return True
        tok = self._session_token()
        sessions = getattr(self.server, "_login_sessions", {})
        exp = sessions.get(tok) if tok else None
        if exp is not None:
            import time as _t
            if _t.time() < exp:
                return True
            sessions.pop(tok, None)    # expired (tolerant: handler threads race)
        hdr = self.headers.get("Authorization") or ""
        if hdr.startswith("Basic "):
            import base64
            try:
                user, _, pw = base64.b64decode(
                    hdr[6:]).decode("utf-8", "replace").partition(":")
            except Exception:
                user = pw = None
            if user is not None and authfn(user, pw):
                return True
        self.send_response(401)
        self.send_header("WWW-Authenticate", "Basic realm=h2o3_tpu")
        self.send_header("Content-Length", "0")
        self.end_headers()
        return False

    def r_login_page(self):
        """Minimal form-login page (reference: ``login.html`` served by the
        reference's Jetty when form auth is on)."""
        body = (b"<html><body><form method='POST' action='/login'>"
                b"<input name='username' placeholder='username'/>"
                b"<input name='password' type='password'/>"
                b"<button type='submit'>Log in</button></form></body></html>")
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_login(self):
        """Form login → session cookie (reference: j_security_check)."""
        p = self._params()
        authfn = getattr(self.server, "_authenticate", None)
        if authfn is not None and not authfn(str(p.get("username") or ""),
                                             str(p.get("password") or "")):
            self._error(401, "invalid credentials")
            return
        import time as _t
        sessions = self.server._login_sessions
        now = _t.time()
        # snapshot before sweeping: handler threads mutate concurrently
        for k in [k for k, exp in list(sessions.items()) if exp < now]:
            sessions.pop(k, None)
        if len(sessions) >= 10_000:    # cap: a login-per-request client
            sessions.clear()           # must fall back to re-auth, not OOM us
        tok = uuid.uuid4().hex
        sessions[tok] = now + self.server._session_ttl
        body = b'{"status": "ok"}'
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Set-Cookie",
                         f"h2o3_session={tok}; HttpOnly; Path=/")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_logout(self):
        tok = self._session_token()
        self.server._login_sessions.pop(tok, None)
        self._reply({"__meta": {"schema_type": "LogoutV3"}, "status": "ok"})

    #: paths reachable without credentials (the login flow itself)
    _AUTH_EXEMPT = {"/login", "/logout"}

    #: high-frequency read endpoints whose solo traces would churn the
    #: completed-trace ring (h2o-py polls /3/Jobs ~2×/s during builds,
    #: Prometheus scrapes /metrics, Flow refreshes /): their root spans
    #: still propagate context and return a traceparent, but the finished
    #: trace is discarded — unless the caller sent a traceparent, which is
    #: an explicit request to record the call in the caller's trace
    _TRACE_NOISE = re.compile(
        r"/(?:flow/.*|metrics|3/(?:Jobs(?:/[^/]+)?|Ping|Cloud|About|"
        r"Logs(?:/.*)?|Memory|Metrics|TimeSeries|Compute|Score|Timeline|"
        r"JStack|"
        r"WaterMeter[^/]*(?:/\d+)?|Health|Incidents(?:/[^/]+)?|Ops|"
        r"Traces(?:/.*)?)|99/(?:AutoML|Leaderboards)/[^/]+)?")

    #: endpoints that do real work — the ones tenant quotas meter
    #: (monitoring GETs and session plumbing are never shed: an operator
    #: must be able to LOOK at an over-quota tenant's usage)
    _METERED = re.compile(
        r"/3/(?:Score/[^/]+|Parse|PostFile(?:\.bin)?|"
        r"Predictions/models/[^/]+/frames/[^/]+)|"
        r"/4/Predictions/models/[^/]+/frames/[^/]+|"
        r"/(?:3|99)/ModelBuilders/[^/]+")

    def _route(self, method: str):
        path = urllib.parse.urlparse(self.path).path
        t0 = time.perf_counter()
        self._last_status = 0
        self._route_label = None
        # root span per request; an incoming W3C traceparent joins the
        # caller's trace (and its span becomes our root's parent)
        parent = _tr.parse_traceparent(self.headers.get("traceparent"))
        ephemeral = (parent is None and method == "GET"
                     and re.fullmatch(self._TRACE_NOISE, path) is not None)
        with _tr.TRACER.span(f"{method} {path}", kind="server", root=True,
                             parent=parent, ephemeral=ephemeral,
                             attrs={"method": method}) as span:
            self._trace_span = span
            try:
                self._dispatch(method, path)
            finally:
                # per-route request count/status/latency — labelled by ROUTE
                # PATTERN (bounded cardinality), never by the raw path
                route = self._route_label or "(unmatched)"
                if span is not None:
                    # rename to the matched pattern so trace listings stay
                    # readable; raw path survives as an attr for debugging
                    if self._route_label:
                        span.set_attrs(path=path)
                        span.name = f"{method} {self._route_label}"
                    span.set_attrs(http_status=self._last_status)
                    if self._last_status >= 500:
                        span.set_status("error")
                    if parent is None and route in ("(unmatched)",
                                                    "(unauthorized)"):
                        # only known-after-routing noise: a scanner hitting
                        # unknown paths (or failing auth) must not churn
                        # the completed-trace ring either
                        _tr.TRACER.make_ephemeral(span.trace_id)
                _tm.REQUESTS.labels(route=route, method=method,
                                    status=str(self._last_status)).inc()
                _tm.REQUEST_SECONDS.labels(
                    route=route, method=method).observe(
                    time.perf_counter() - t0)

    def _dispatch(self, method: str, path: str):
        if path not in self._AUTH_EXEMPT and not self._check_auth():
            self._route_label = "(unauthorized)"
            return
        try:
            import sys as _sys
            ten = _sys.modules.get("h2o3_tpu.ops_plane.tenancy")
            if ten is None:
                # multi-tenancy not loaded (embedded/library use): zero
                # overhead, exactly the pre-ops-plane dispatch
                self._run_routes(method, path)
                return
            raw = self.headers.get("X-H2O3-Tenant")
            if raw is None:
                q = urllib.parse.urlparse(self.path).query
                raw = {k: v[0] for k, v in
                       urllib.parse.parse_qs(q).items()}.get("tenant")
            try:
                tenant = ten.sanitize_tenant(raw)
            except ValueError as e:
                self._error(400, str(e))
                return
            with ten.tenant_scope(tenant):
                if method == "POST" \
                        and re.fullmatch(self._METERED, path) is not None:
                    try:
                        ten.QUOTAS.admit(tenant)
                    except ten.QuotaExceeded as e:
                        # over-quota is 429 + Retry-After — shed loudly,
                        # never dropped (reference: the 503 shed contract
                        # of r_score, but quota is the CALLER'S budget,
                        # not the server's capacity)
                        retry_s = max(int(e.retry_after_s + 0.999), 1)
                        self._error(429, str(e), headers={
                            "Retry-After": str(retry_s),
                            "X-Retry-After-Ms":
                                str(int(e.retry_after_s * 1000))})
                        return
                self._run_routes(method, path)
        except PayloadTooLarge as e:
            self._error(413, str(e))
        except KeyError as e:
            self._error(404, str(e))
        except Exception as e:   # one bad request must not kill the server
            self._error(500, f"{type(e).__name__}: {e}")

    def _run_routes(self, method: str, path: str):
        for pat, m, fn in _ROUTES:
            match = re.fullmatch(pat, path)
            if match and m == method:
                self._route_label = _route_label_of(pat)
                fn(self, *match.groups())
                return
        # extension-contributed routes (reference RestApiExtension SPI)
        from h2o3_tpu.utils import extensions as _ext
        for pat, m, fn in _ext.rest_routes():
            match = re.fullmatch(pat, path)
            if match and m == method:
                self._route_label = _route_label_of(pat)
                fn(self, *match.groups())
                return
        self._error(404, f"no route for {method} {path}")

    # -- routes (reference: RequestServer route registrations) ---------------

    def r_cloud(self):
        self._reply(schemas.cloud_v3(__version__))

    def r_about(self):
        self._reply({"__meta": {"schema_type": "AboutV3"},
                     "entries": [{"name": "Build version", "value": __version__}]})

    def r_import(self):
        p = self._params()
        from h2o3_tpu.frame.parse import import_file
        try:
            fr = import_file(p["path"], key=p.get("destination_frame"))
        except (FileNotFoundError, PermissionError, IsADirectoryError,
                ValueError) as e:
            # a bad path is CLIENT error, not a server fault: a structured
            # 400 whose msg carries the reason (the reference reports these
            # as ImportFiles `fails`, never a 500 traceback)
            self._error(400, str(e))
            return
        self._reply({"__meta": {"schema_type": "ImportFilesV3"},
                     "destination_frames": [fr.key], "fails": []})

    def r_import_multi(self):
        """Reference ImportFilesMulti: h2o-py sends paths as "[p1,p2]"."""
        p = self._params()
        paths = p.get("paths", "")
        if isinstance(paths, str):
            paths = _parse_list(paths)
        from h2o3_tpu.frame.parse import import_file
        keys, fails = [], []
        for path in paths:
            try:
                keys.append(import_file(path).key)
            except Exception as e:     # noqa: BLE001 — report per-file fails
                fails.append(f"{path}: {e}")
        self._reply({"__meta": {"schema_type": "ImportFilesV3"},
                     "destination_frames": keys, "fails": fails})

    def _read_upload(self) -> "tuple[bytes, str] | None":
        """Read a (possibly multipart) uploaded body; None = too large
        (the 413 is already sent). Returns (file bytes, filename)."""
        import os
        length = int(self.headers.get("Content-Length") or 0)
        if length > 1 << 30:
            self._drain_body(length)
            self._error(413, f"upload of {length} bytes exceeds the 1GiB cap")
            return None
        body = self.rfile.read(length)
        ctype = self.headers.get("Content-Type", "")
        data, fname = body, "upload.csv"
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if m:
            for part in body.split(b"--" + m.group(1).encode()):
                if b"\r\n\r\n" not in part:
                    continue
                hdrs, content = part.split(b"\r\n\r\n", 1)
                if b"filename=" not in hdrs:
                    continue
                fm = re.search(rb'filename="?([^";\r\n]+)"?', hdrs)
                if fm:
                    fname = os.path.basename(fm.group(1).decode("utf-8",
                                                                "replace"))
                data = content[:-2] if content.endswith(b"\r\n") else content
                break
        return data, fname

    def r_putkey(self):
        """Reference PutKeyHandler: store raw uploaded bytes under a DKV key
        (h2o-py ``_put_key`` — the transport for custom metric/distribution
        UDF zips, ``h2o.py:2073``)."""
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        dest = (q.get("destination_key") or [None])[0]
        overwrite = (q.get("overwrite") or ["True"])[0].lower() != "false"
        up = self._read_upload()
        if up is None:
            return
        data, fname = up
        key = dest or f"{fname.replace('.', '_')}_{uuid.uuid4().hex[:8]}"
        if not overwrite and DKV.get(key) is not None:
            self._error(400, f"key {key!r} already exists and overwrite=False")
            return
        from h2o3_tpu.frame.parse import RawFile
        DKV.put(key, RawFile(data, name=fname))
        self._reply({"__meta": {"schema_type": "PutKeyV3"},
                     "destination_key": key})

    def r_postfile(self):
        """Reference PostFileHandler (``water/api/PostFileHandler.java``,
        used by ``h2o.upload_file``): store the multipart body's file part as
        a raw key for ParseSetup/Parse. Uploads are size-capped (the
        reference relies on Jetty limits)."""
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        dest = (q.get("destination_frame") or [None])[0]
        up = self._read_upload()
        if up is None:
            return
        data, fname = up
        from h2o3_tpu.frame.parse import RawFile
        key = dest or f"{fname.replace('.', '_')}_{uuid.uuid4().hex[:8]}"
        DKV.put(key, RawFile(data, name=fname))
        self._reply({"__meta": {"schema_type": "PostFileV3"},
                     "destination_frame": key, "total_bytes": len(data)})

    def r_parse(self):
        # the reference splits guess (ParseSetup) and parse; import_file did
        # both, so Parse re-keys the already-parsed frame and hands back an
        # immediately-DONE job for the client's poll loop
        p = self._params()
        src = json.loads(p["source_frames"]) if isinstance(
            p.get("source_frames"), str) else p.get("source_frames", [])
        src_key = (src[0] if src else p.get("source_key", ""))
        src_key = _name(src_key)
        from h2o3_tpu.frame.parse import RawFile
        fr = DKV[src_key]
        if isinstance(fr, RawFile):
            fr = fr.frame()
        dest = _name(p.get("destination_frame")) or src_key
        if dest != src_key:
            DKV.remove(src_key)
        fr.key = dest
        DKV.put(dest, fr)
        job = Job("Parse", key=f"job_{uuid.uuid4().hex[:12]}")
        job.run(lambda j: setattr(j, "dest_key", dest) or dest,
                background=False)
        self._reply({"__meta": {"schema_type": "ParseV3"},
                     "destination_frame": {"name": dest},
                     "job": schemas.job_v3(job.key, job),
                     "rows": fr.nrows})

    def r_frames(self):
        self._reply(schemas.frames_list_v3(DKV))

    def r_frame(self, key):
        fr = DKV[key]
        from h2o3_tpu.frame.parse import RawFile
        if isinstance(fr, RawFile):
            # a /3/PostFile upload fetched as a frame (h2o.upload_mojo does
            # get_frame on the raw key before handing it to generic)
            self._reply(schemas.raw_frame_v3(key, len(fr.data)))
            return
        if not isinstance(fr, Frame):
            raise KeyError(f"{key} is not a frame")
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "frames": [schemas.frame_v3(key, fr)]})

    def r_frame_delete(self, key):
        with LOCKS.write(key):
            DKV.remove(key)
        self._reply({"__meta": {"schema_type": "FramesV3"}})

    def r_models(self):
        self._reply(schemas.models_list_v3(DKV))

    def r_model(self, key):
        m = DKV[key]
        if not isinstance(m, Model):
            raise KeyError(f"{key} is not a model")
        self._reply({"__meta": {"schema_type": "ModelsV3"},
                     "models": [schemas.model_v3(m)]})

    def r_model_delete(self, key):
        with LOCKS.write(key):
            DKV.remove(key)
        self._reply({"__meta": {"schema_type": "ModelsV3"}})

    def r_train(self, algo):
        p = self._params()
        if algo.lower() == "generic":
            # h2o.import_mojo / upload_mojo: no training_frame; the artifact
            # arrives as a server path or an uploaded RawFile key
            # (H2OGenericEstimator.from_file / h2o.upload_mojo)
            return self._train_generic(p)
        cls = _algo_registry().get(algo.lower())
        if cls is None:
            raise KeyError(f"unknown algorithm {algo!r}")
        train_key = p.pop("training_frame")
        frame = DKV[train_key]
        y = p.pop("response_column", None)
        x = p.pop("x", None)
        if isinstance(x, str):
            x = json.loads(x)
        valid = p.pop("validation_frame", None)
        vframe = DKV[valid] if valid else None
        kwargs = {}
        defaults = cls.defaults()
        for k, v in p.items():
            if k not in defaults:
                continue
            d = defaults[k]
            if isinstance(v, str):
                if isinstance(d, bool):
                    v = v.lower() in ("1", "true", "yes")
                elif isinstance(d, int) and not isinstance(d, bool):
                    v = int(float(v))
                elif isinstance(d, float):
                    v = float(v)
                elif isinstance(d, (list, tuple)) or v.startswith("["):
                    v = _parse_list(v)
                elif k == "metalearner_params" and v.startswith("{"):
                    v = json.loads(v)
            kwargs[k] = v
        if algo.lower() == "stackedensemble":
            # base_models arrive as ids (h2o-py _keyify; possibly _quoted or
            # KeyV3 dicts) — resolve to the DKV-registered Model objects
            kwargs["base_models"] = [
                DKV[str(_name(b)).strip('"')]
                for b in (kwargs.get("base_models") or [])]
        try:
            builder = cls(**kwargs)
            builder.validate_request()
        except ValueError as e:
            # a request the build could NEVER satisfy (unknown params, an
            # unsupported checkpoint combination) is a client error — a
            # structured 400 now, not a FAILED job the poller unwraps later
            return self._error(400, str(e))
        self._run_build_job(
            algo.lower(), builder, p.get("model_id"),
            lambda: builder.train(x=x, y=y, training_frame=frame,
                                  validation_frame=vframe),
            frame_keys=(train_key, valid))

    def _run_build_job(self, algo: str, builder, model_id, train_fn,
                       cleanup=None, frame_keys=()) -> None:
        """The shared train-job protocol every builder endpoint speaks:
        pre-assigned model key (h2o-py's H2OJob reads dest.name from the
        INITIAL response, before the background train finishes), background
        Job, ModelBuildersV3 reply.  Lockable protocol (water/Lockable.java):
        the build write-locks its destination model key and read-locks its
        input frames, so a concurrent DELETE waits instead of racing."""
        builder.model_id = model_id or f"{algo}_{uuid.uuid4().hex[:10]}"
        job = Job(f"{algo} via REST", key=f"job_{uuid.uuid4().hex[:12]}")
        job.dest_key = builder.model_id
        # mirror the builder's reliability contract onto the REST job so
        # /3/Jobs pollers see the deadline/recovery surface from the first
        # poll (the inner library Job enforces; this one reports)
        params = getattr(builder, "params", {})
        job.max_runtime_secs = float(params.get("max_runtime_secs") or 0.0)
        # only advertised when the builder actually writes snapshots
        # (supports_auto_recovery) — the inner job applies the same gate
        job.auto_recovery_dir = (
            params.get("auto_recovery_dir")
            if getattr(builder, "supports_auto_recovery", lambda: False)()
            else None)

        # forward /3/Jobs/{id}/cancel into the INNER library job the build
        # loops actually poll — without this a REST cancel only flips the
        # outer job's flag and the build runs to completion anyway
        _outer_cancel = job.cancel

        def _cancel_both():
            _outer_cancel()
            # flag FIRST, then try the inner job: train() re-checks the flag
            # right after creating its Job, so a cancel landing in the
            # window before builder.job exists is still honored (the
            # orderings make losing both impossible)
            builder._cancel_requested_early = True
            inner = getattr(builder, "job", None)
            if inner is not None:
                inner.cancel()
        job.cancel = _cancel_both

        def driver(j: Job):
            def mirror_inner_elastic():
                # elastic membership decay lives on the inner library job;
                # REST pollers read the outer one (live per-worker state is
                # on /3/Cloud's workers view throughout the build)
                inner = getattr(builder, "job", None)
                ejected = int(getattr(inner, "workers_ejected", 0) or 0)
                if ejected:
                    with j._lock:
                        j.workers_ejected = ejected

            def mirror_inner_cancel():
                # the build terminated on its deadline/cancel — the REST
                # job must read CANCELLED (not DONE) and carry the deadline
                # evidence, whether train() returned a partial model or
                # raised JobCancelled (no-partial builders like GLM)
                inner = getattr(builder, "job", None)
                if inner is None or inner.status != Job.CANCELLED:
                    return
                j.keep_partial()
                if inner.deadline_exceeded:
                    # one locked transition: a poller must never observe
                    # the flag without its progress_msg (Job invariant)
                    with j._lock:
                        j.deadline_exceeded = True
                        j.progress_msg = inner.progress_msg
                j.cancel()

            # one combined acquisition — two separate with-statements would
            # reintroduce the ABBA deadlock the global sort order prevents
            with LOCKS.locked(write=(builder.model_id,), read=frame_keys):
                # re-check under the lock: a delete may have won the race
                # between the handler's fetch and this acquisition
                for fk in frame_keys:
                    if fk and fk not in DKV:
                        raise KeyError(f"{fk} not found")
                try:
                    m = train_fn()
                except BaseException:
                    mirror_inner_cancel()
                    mirror_inner_elastic()
                    raise
                finally:
                    if cleanup is not None:
                        cleanup()
            mirror_inner_cancel()
            mirror_inner_elastic()
            j.dest_key = m.key
            return m

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "ModelBuildersV3"},
                     "job": schemas.job_v3(job.key, job),
                     "messages": [], "error_count": 0,
                     "parameters": [], "algo": algo})

    def _train_generic(self, p: dict):
        """POST /3/ModelBuilders/generic (reference hex/generic/Generic.java):
        wrap a MOJO artifact — ``path`` on the server filesystem, or
        ``model_key`` naming a /3/PostFile RawFile upload — as a model."""
        import os
        import tempfile

        from h2o3_tpu.genmodel.generic import Generic

        path = p.get("path")
        model_key = _name(p.get("model_key"))
        tmp = None
        if not path and model_key:
            raw = DKV[str(model_key).strip('"')]
            data = getattr(raw, "data", raw)
            if not isinstance(data, (bytes, bytearray)):
                raise TypeError(f"model_key {model_key!r} does not hold an "
                                "uploaded artifact")
            fd, tmp = tempfile.mkstemp(suffix=".zip")
            with os.fdopen(fd, "wb") as f:
                f.write(bytes(data))
            path = tmp
        if not path:
            raise ValueError("generic needs 'path' or 'model_key'")
        builder = Generic(path=path)
        self._run_build_job(
            "generic", builder, p.get("model_id"), builder.train,
            cleanup=(lambda: os.unlink(tmp)) if tmp is not None else None)

    def r_job(self, key):
        job = DKV[key]
        self._reply({"__meta": {"schema_type": "JobsV3"},
                     "jobs": [schemas.job_v3(key, job)]})

    def r_job_cancel(self, key):
        DKV[key].cancel()
        self._reply({"__meta": {"schema_type": "JobsV3"}})

    def r_predict(self, model_key, frame_key):
        # fetch under the read lock (a delete that already won must 404),
        # but SCORE outside it: scoring is read-only over refs this thread
        # now holds, and keeping the lock would serialize concurrent
        # predictions against the same model for no protection in return
        with LOCKS.read(model_key, frame_key):
            m, fr = DKV[model_key], DKV[frame_key]
        pred = m.predict(fr)
        dest = f"prediction_{uuid.uuid4().hex[:8]}"
        pred.key = dest
        DKV.put(dest, pred)
        self._reply({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
                     "predictions_frame": {"name": dest},
                     "model_metrics": []})

    def r_predict_v4(self, model_key, frame_key):
        """V4 surface: h2o-py model.predict POSTs here and polls the job."""
        if model_key not in DKV or frame_key not in DKV:
            raise KeyError(f"{model_key if model_key not in DKV else frame_key}"
                           " not found")
        dest = f"prediction_{uuid.uuid4().hex[:8]}"
        job = Job("Predict", key=f"job_{uuid.uuid4().hex[:12]}")
        job.dest_key = dest

        def driver(j: Job):
            # fetch INSIDE the lock: a delete that wins the race must 404
            # this job, not be resurrected by a stale reference. The predict
            # itself runs OUTSIDE — it is read-only over refs held here, and
            # concurrent predictions must not serialize on the key lock
            with LOCKS.read(model_key, frame_key):
                m, fr = DKV[model_key], DKV[frame_key]
            pred = m.predict(fr)
            pred.key = dest
            DKV.put(dest, pred)
            return pred

        job.run(driver, background=False)
        self._reply({"__meta": {"schema_type": "JobV4"},
                     "job": schemas.job_v3(job.key, job)})

    # -- scoring tier (serving/; docs/SERVING.md) ---------------------------

    def r_score(self, model_key):
        """``POST /3/Score/{model}`` — request-sized scoring: JSON rows in,
        predictions out, no DKV frame round-trip. Concurrent requests for
        one model are fused into one device dispatch by the micro-batcher;
        compiled executables are cached per (model, shape, batch-bucket).
        Over the residency budget the reply is 503 + Retry-After, never an
        OOM (docs/SERVING.md)."""
        from h2o3_tpu.serving import (SCORING, NotServable,
                                      ServiceUnavailable)
        p = self._params()
        try:
            rows = p.get("rows")
            if isinstance(rows, str):
                rows = json.loads(rows)
            columns = p.get("columns")
            if isinstance(columns, str):
                columns = _parse_list(columns)
        except (json.JSONDecodeError, ValueError) as e:
            self._error(400, f"rows is not valid JSON: {e}")
            return
        try:
            # SLO layer (docs/SERVING.md "SLO & replicas"): priority
            # orders shedding under overload; slo_ms overrides the
            # model's latency target at admit — their coercion errors
            # must name the FIELD, not blame the rows payload
            priority = p.get("priority")
            if priority is not None:
                priority = int(priority)
            slo_ms = p.get("slo_ms")
            if slo_ms is not None:
                slo_ms = float(slo_ms)
        except (ValueError, TypeError) as e:
            self._error(400, f"priority/slo_ms is not numeric: {e}")
            return
        try:
            out = SCORING.score(model_key, rows, columns,
                                priority=priority, slo_ms=slo_ms)
        except ServiceUnavailable as e:
            retry_s = max(1, int(round(e.retry_after_ms / 1000.0)))
            self._error(503, str(e), headers={
                "Retry-After": str(retry_s),
                "X-Retry-After-Ms": str(e.retry_after_ms)})
            return
        except (NotServable, ValueError) as e:
            self._error(400, str(e))
            return
        self._reply(schemas.score_v3(out))

    def r_score_stats(self):
        """``GET /3/Score`` — scoring-tier residency and cache counters:
        resident models (bytes/requests/idle + per-model SLO controller
        state), budget, evictions, compiled-signature hit/miss counts,
        shed accounting by reason/priority, the replica-pool view
        (slice leases, per-replica busy/queue-wait, scale events), and
        memory watermarks."""
        from h2o3_tpu.serving import SCORING
        self._reply(schemas.serving_v3(SCORING.stats()))

    def r_score_evict(self, model_key):
        """``DELETE /3/Score/{model}`` — drop a model's scoring residency
        (compiled signatures + batcher); its DKV copy is untouched."""
        from h2o3_tpu.serving import SCORING, ServiceUnavailable
        try:
            evicted = SCORING.evict(model_key)
        except ServiceUnavailable as e:
            self._error(503, str(e), headers={"Retry-After": "1"})
            return
        self._reply({"__meta": {"schema_type": "ScoreV3"},
                     "evicted": bool(evicted), "model": model_key})

    def r_rapids(self):
        p = self._params()
        from h2o3_tpu.rapids import rapids
        from h2o3_tpu.rapids.exec import Session
        # temp-frame scope persists across calls within one client session
        # (reference: water/rapids/Session.java keyed by session_id)
        sid = p.get("session_id") or self.server._session_id
        sess = self.server._rapids_sessions.setdefault(sid, Session())
        res = rapids(p["ast"], session=sess)
        if isinstance(res, Frame):
            key = p.get("id") or res.key or f"rapids_{uuid.uuid4().hex[:8]}"
            res.key = key
            DKV.put(key, res)
            self._reply({"__meta": {"schema_type": "RapidsFrameV3"},
                         "key": {"name": key},
                         "num_rows": res.nrows, "num_cols": res.ncols})
        elif isinstance(res, (int, float)):
            self._reply({"__meta": {"schema_type": "RapidsNumberV3"},
                         "scalar": schemas._clean(res)})
        else:
            self._reply({"__meta": {"schema_type": "RapidsStringV3"},
                         "string": str(res)})

    def r_grid(self, algo):
        p = self._params()
        cls = _algo_registry().get(algo.lower())
        if cls is None:
            raise KeyError(f"unknown algorithm {algo!r}")
        from h2o3_tpu.orchestration import GridSearch
        hyper = p.pop("hyper_parameters")
        if isinstance(hyper, str):
            hyper = json.loads(hyper)
        criteria = p.pop("search_criteria", None)
        if isinstance(criteria, str):
            criteria = json.loads(criteria)
        grid_frame_key = p.pop("training_frame")
        frame = DKV[grid_frame_key]
        y = p.pop("response_column", None)
        gs = GridSearch(cls, hyper, grid_id=p.pop("grid_id", None),
                        search_criteria=criteria)
        job = Job(f"grid {algo} via REST")

        def driver(j: Job):
            with LOCKS.read(grid_frame_key):
                g = gs.train(y=y, training_frame=frame)
            j.dest_key = g.grid_id
            return g

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "GridSearchV99"},
                     "job": schemas.job_v3(job.key, job)})

    def r_grid_get(self, key):
        g = DKV[key]
        self._reply({"__meta": {"schema_type": "GridSchemaV99"},
                     "grid_id": {"name": g.grid_id},
                     "model_ids": [{"name": k} for k in g.model_ids],
                     "failure_details": [d for _, d in g.failures]})

    def r_automl(self):
        """Reference AutoMLBuilderHandler (``water/automl/api/
        AutoMLBuilderHandler.java``): h2o-py POSTs a JSON body of
        build_control / build_models / input_spec; our own client may send
        the flat form. The run object registers in DKV under the job's
        dest key so ``GET /99/AutoML/{key}`` can serve state mid-run."""
        p = self._params()
        from h2o3_tpu.orchestration import AutoML
        for k in ("build_control", "build_models", "input_spec"):
            if isinstance(p.get(k), str):
                p[k] = json.loads(p[k])
        bc = dict(p.get("build_control") or {})
        bm = dict(p.get("build_models") or {})
        ispec = dict(p.get("input_spec") or {})
        # flat budget fields win when both are present
        crit = dict(bc.get("stopping_criteria") or {})
        crit.update({k: p[k] for k in ("max_models", "max_runtime_secs",
                                       "seed") if k in p})
        frame_key = _name(ispec.get("training_frame") or p["training_frame"])
        frame = DKV[frame_key]
        y = _name(ispec.get("response_column") or p.get("response_column"))
        drop = set(ispec.get("ignored_columns") or [])
        for c in (ispec.get("fold_column"), ispec.get("weights_column")):
            if _name(c):
                drop.add(_name(c))
        x = ([c for c in frame.names if c != y and c not in drop]
             if drop else None)
        sort_metric = ispec.get("sort_metric") or p.get("sort_metric")
        if sort_metric:
            # wire names are uppercase/alias forms; Leaderboard rows key on
            # the lowercase metric attrs (aucpr is stored as pr_auc)
            sort_metric = {"aucpr": "pr_auc", "auto": None}.get(
                sort_metric.lower(), sort_metric.lower())
        project = (bc.get("project_name") or p.get("project_name")
                   or f"AutoML_{uuid.uuid4().hex[:10]}")
        nf = p.get("nfolds", bc.get("nfolds"))
        nfolds = -1 if nf is None else int(nf)
        if nfolds < 0:          # reference AUTO: -1 → 5-fold CV; 0 disables
            nfolds = 5
        seed = crit.get("seed")
        aml = AutoML(max_models=int(crit.get("max_models", 0) or 0),
                     max_runtime_secs=float(crit.get("max_runtime_secs", 0) or 0),
                     nfolds=nfolds,
                     seed=-1 if seed is None else int(seed),
                     sort_metric=sort_metric,
                     exclude_algos=bm.get("exclude_algos") or (),
                     include_algos=bm.get("include_algos"),
                     project_name=project)
        DKV.put(project, aml)
        lb_key = _name(ispec.get("leaderboard_frame"))
        job = Job("AutoML via REST", key=f"job_{uuid.uuid4().hex[:12]}")
        job.dest_key = project

        def driver(j: Job):
            with LOCKS.read(frame_key, lb_key):
                aml.train(x=x, y=y, training_frame=frame,
                          leaderboard_frame=DKV[lb_key] if lb_key else None)
            j.dest_key = project
            return aml

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "AutoMLBuilderV99"},
                     "job": schemas.job_v3(job.key, job),
                     "build_control": {"project_name": project},
                     "build_models": bm, "input_spec": ispec})

    def r_automl_get(self, key):
        """Reference AutoMLHandler.fetch (``water/automl/api/
        AutoMLHandler.java``) — the state h2o-py's ``_fetch_state`` reads."""
        from h2o3_tpu.orchestration import AutoML
        aml = DKV[key]
        if not isinstance(aml, AutoML):
            raise KeyError(f"{key} is not an AutoML run")
        self._reply(schemas.automl_v99(aml, job_key=key))

    def r_leaderboards(self, project):
        """Reference LeaderboardsHandler.fetch (``water/automl/api/
        LeaderboardsHandler.java``)."""
        from h2o3_tpu.orchestration import AutoML
        p = self._params()
        aml = DKV[project]
        if not isinstance(aml, AutoML):
            raise KeyError(f"{project} is not an AutoML run")
        ext = p.get("extensions") or []
        if isinstance(ext, str):
            ext = _parse_list(ext)
        self._reply(schemas.leaderboard_v99(aml, ext))

    def r_shutdown(self):
        self._reply({"__meta": {"schema_type": "ShutdownV3"}})
        threading.Thread(target=self.server.shutdown, daemon=True).start()

    def r_gc(self):
        import gc
        gc.collect()
        self._reply({"__meta": {"schema_type": "GarbageCollectV3"}})

    # -- observability (reference: TimelineHandler, JStackHandler,
    #    ProfilerHandler, WaterMeter* behind /3/Timeline,/3/JStack,
    #    /3/Profiler,/3/WaterMeterCpuTicks,/3/WaterMeterIo) -----------------

    # -- NodePersistentStorage (reference: water/api/NodePersistentStorage
    #    Handler — Flow saves notebooks under category "notebook") ----------

    @staticmethod
    def _nps_name(x: str) -> str:
        """Path-component sanitizer: besides the charset filter, all-dot
        names ('.', '..') must not survive — they'd traverse out of the
        storage root."""
        safe = re.sub(r"[^\w.-]", "_", x).strip(".")
        return safe or "_"

    @classmethod
    def _nps_dir(cls, category: str) -> str:
        import os
        base = os.environ.get(
            "H2O3TPU_NPS_DIR",
            os.path.join(os.path.expanduser("~"), ".h2o3tpu", "nps"))
        d = os.path.join(base, cls._nps_name(category))
        os.makedirs(d, exist_ok=True)
        return d

    def r_nps_list(self, category):
        import os
        d = self._nps_dir(category)
        entries = []
        for name in sorted(os.listdir(d)):
            st = os.stat(os.path.join(d, name))
            entries.append({"name": name, "size": st.st_size,
                            "timestamp_millis": int(st.st_mtime * 1000)})
        self._reply({"__meta": {"schema_type": "NodePersistentStorageV3"},
                     "category": category, "entries": entries})

    def r_nps_get(self, category, name):
        import os
        path = os.path.join(self._nps_dir(category), self._nps_name(name))
        if not os.path.exists(path):
            raise KeyError(f"no {category}/{name} in persistent storage")
        with open(path, "rb") as f:
            body = f.read()
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_nps_put(self, category, name):
        import os
        length = int(self.headers.get("Content-Length") or 0)
        if length > 16 << 20:
            self._drain_body(length)
            self._error(413, "notebook exceeds the 16MiB cap")
            return
        data = self.rfile.read(length)
        # h2o-py/Flow POST the value as a multipart or urlencoded field
        ctype = self.headers.get("Content-Type", "")
        if "urlencoded" in ctype:
            vals = urllib.parse.parse_qs(data.decode("utf-8", "replace"))
            data = (vals.get("value") or [""])[0].encode()
        path = os.path.join(self._nps_dir(category), self._nps_name(name))
        with open(path, "wb") as f:
            f.write(data)
        self._reply({"__meta": {"schema_type": "NodePersistentStorageV3"},
                     "category": category, "name": name,
                     "total_bytes": len(data)})

    def r_nps_delete(self, category, name):
        import os
        path = os.path.join(self._nps_dir(category), self._nps_name(name))
        if os.path.exists(path):
            os.unlink(path)
        self._reply({"__meta": {"schema_type": "NodePersistentStorageV3"}})

    def r_timeline(self):
        from h2o3_tpu.utils.timeline import TIMELINE
        self._reply({"__meta": {"schema_type": "TimelineV3"},
                     "events": TIMELINE.snapshot()})

    def r_jstack(self):
        from h2o3_tpu.utils.timeline import jstack
        self._reply({"__meta": {"schema_type": "JStackV3"},
                     "traces": jstack()})

    def r_profiler(self):
        # reference: ProfileCollectorTask samples stacks `depth` times,
        # excluding the collector thread itself — a profile dominated by the
        # sampling loop would show no real work
        import time as _t
        from h2o3_tpu.utils.timeline import jstack
        p = self._params()
        samples = max(1, min(int(p.get("depth", 5)), 50))
        me = {threading.get_ident()}
        counts: dict[str, int] = {}
        for _ in range(samples):
            for tr in jstack(exclude=me):
                counts[tr["stack"]] = counts.get(tr["stack"], 0) + 1
            _t.sleep(0.01)
        entries = sorted(counts.items(), key=lambda kv: -kv[1])
        self._reply({"__meta": {"schema_type": "ProfilerV3"},
                     "stacktraces": [s for s, _ in entries],
                     "counts": [c for _, c in entries]})

    def r_cpu_ticks(self):
        from h2o3_tpu.utils.timeline import cpu_ticks
        self._reply({"__meta": {"schema_type": "WaterMeterCpuTicksV3"},
                     "cpu_ticks": cpu_ticks()})

    def r_io_meter(self):
        from h2o3_tpu.utils.timeline import io_stats
        self._reply({"__meta": {"schema_type": "WaterMeterIoV3"},
                     "persist_stats": io_stats()})

    def r_flow(self):
        # reference: h2o-web Flow notebook served from the node at /
        from h2o3_tpu.api.flow import FLOW_HTML
        body = FLOW_HTML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_logs(self):
        """``GET /3/Logs[?level=...]`` — the whole LogRing, optionally
        filtered by minimum severity. ``level`` accepts the reference's
        per-level file names (``water/util/Log.java`` writes one file per
        level: trace/debug/info/warn/error/fatal) or a numeric logging
        level; absent = unfiltered (every ring line)."""
        p = self._params()
        level = p.get("level")
        ring = _tm.install_log_ring()
        if level is None:
            self._reply({"__meta": {"schema_type": "LogsV3"},
                         "nodeidx": 0, "name": "unfiltered",
                         "log": "\n".join(ring.lines())})
            return
        min_level = _tm.LOG_FILES.get(str(level).lower())
        if min_level is None:
            try:
                min_level = int(level)
            except ValueError:
                raise KeyError(f"unknown log level {level!r}; one of "
                               f"{sorted(_tm.LOG_FILES)} or a numeric "
                               "logging level") from None
        self._reply({"__meta": {"schema_type": "LogsV3"},
                     "nodeidx": 0, "name": str(level),
                     "log": "\n".join(ring.lines(min_level))})

    def r_logs_file(self, node: str, name: str):
        """Reference: LogsHandler ``/3/Logs/nodes/{n}/files/{name}`` (the
        route h2o-py's ``h2o.cluster().get_log`` requests). Backed by the
        LogRing on the ``h2o3_tpu`` logger; the reference's per-level log
        *files* map to a minimum-level filter over the ring."""
        ring = _tm.install_log_ring()     # idempotent; survives cold fetches
        min_level = _tm.LOG_FILES.get(name.lower())
        if min_level is None:
            raise KeyError(f"unknown log file {name!r}; one of "
                           f"{sorted(_tm.LOG_FILES)}")
        self._reply({"__meta": {"schema_type": "LogsV3"},
                     "nodeidx": int(node),
                     "name": name,
                     "log": "\n".join(ring.lines(min_level))})

    def r_memory(self):
        """``GET /3/Memory[?top=N]`` — device/host byte accounting: host
        RSS + machine totals, per-device HBM stats, DKV bytes by kind with
        the top-N keys, monotonic watermarks, and the leak-detector report
        (docs/OBSERVABILITY.md "Memory")."""
        from h2o3_tpu.utils.memory import MEMORY
        p = self._params()
        try:
            top = max(1, min(int(p.get("top", 10)), 1000))
        except ValueError:
            raise KeyError(f"top must be an integer, got "
                           f"{p.get('top')!r}") from None
        self._reply(schemas.memory_v3(MEMORY.summary(top_n=top)))

    def r_compute(self):
        """``GET /3/Compute`` — the compute observatory: per-site compiled
        signatures / compile seconds / cost_analysis FLOPs + bytes,
        recompile events with signature diffs, and per-loop achieved
        throughput + utilization against the backend peak table (null on
        unknown backends; docs/OBSERVABILITY.md "Compute")."""
        from h2o3_tpu.utils.costs import COSTS
        self._reply(schemas.compute_v3(COSTS.snapshot()))

    def r_profiler_capture(self):
        """``POST /3/Profiler/capture[?duration_ms=N]`` — bounded
        ``jax.profiler.trace`` window with span-derived TraceAnnotations;
        returns the capture record (download the Perfetto artifact via
        ``/3/Profiler/captures/{id}/download``). A concurrent capture gets
        a structured 409 — the profiler runtime is process-global."""
        from h2o3_tpu.utils.profiling import PROFILER, CaptureBusy
        p = self._params()
        try:
            duration_ms = int(p.get("duration_ms", 500))
        except ValueError:
            raise KeyError(f"duration_ms must be an integer, got "
                           f"{p.get('duration_ms')!r}") from None
        try:
            rec = PROFILER.capture(duration_ms=duration_ms)
        except CaptureBusy as e:
            self._error(409, str(e), headers={"Retry-After": "1"})
            return
        self._reply({"__meta": {"schema_type": "ProfilerCaptureV3"}, **rec})

    def r_profiler_captures(self):
        """Capture registry: the last few capture records, oldest first."""
        from h2o3_tpu.utils.profiling import PROFILER
        self._reply({"__meta": {"schema_type": "ProfilerCapturesV3"},
                     "captures": PROFILER.list_captures()})

    def r_profiler_capture_download(self, capture_id):
        """The capture's Perfetto-loadable artifact (gzip Chrome trace
        JSON) — save and open at https://ui.perfetto.dev."""
        from h2o3_tpu.utils.profiling import PROFILER
        body, fname = PROFILER.artifact_bytes(capture_id)
        self.send_response(200)
        self.send_header("Content-Type", "application/gzip")
        self.send_header("Content-Disposition",
                         f'attachment; filename="{fname}"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- ops plane (utils/health.py + utils/incidents.py — the reference's
    #    cloud_healthy consensus + `h2o logs download` analog) --------------

    def r_health(self):
        """``GET /3/Health`` — the health evaluator's subsystem-scored
        verdict: healthy/degraded/unhealthy per subsystem (elastic,
        serving, memory, compute, dispatch) with the tripping rule,
        observed value, and threshold in every finding. Served from the
        background sweep when it runs, evaluated inline otherwise
        (docs/OBSERVABILITY.md "Health & incidents")."""
        from h2o3_tpu.utils.health import HEALTH
        self._reply(schemas.health_v3(HEALTH.verdict()))

    def r_incidents(self):
        """``GET /3/Incidents[?state=open|resolved]`` — the bounded
        incident ring, newest first (one open incident per rule; repeats
        fold in). Records carry ``resolved_at`` and, when the remediation
        engine acted, the ``action_id``. Contexts are served per-incident
        by ``GET /3/Incidents/{id}``."""
        from h2o3_tpu.utils.incidents import INCIDENTS
        state = self._params().get("state") or None
        try:
            rows = INCIDENTS.list(state=state)
        except ValueError as e:
            self._error(400, str(e))
            return
        self._reply(schemas.incidents_v3(rows))

    def r_ops(self):
        """``GET /3/Ops`` — the self-driving ops plane in one view: the
        remediation policy (mode, rule→action map, bounds, cooldown), the
        append-only action log, and per-tenant usage + configured quotas
        (docs/OPERATIONS.md is the operator catalog)."""
        from h2o3_tpu.ops_plane import ACTIONS, ENGINE, QUOTAS
        self._reply(schemas.ops_v3({
            "remediation": ENGINE.policy_view(),
            "actions": ACTIONS.list(),
            "tenants": QUOTAS.usage_all(),
            "quotas": QUOTAS.quotas()}))

    def r_ops_post(self):
        """``POST /3/Ops`` — quota CRUD + action rollback:

        - ``tenant`` (+ optional ``qps``/``device_seconds``/``bytes``)
          installs that tenant's budgets (omitted dimension = unlimited);
        - ``remove_quota=<tenant>`` drops a tenant's budgets;
        - ``rollback=<action_id>`` undoes a recorded action by token.
        """
        from h2o3_tpu.ops_plane import ACTIONS, QUOTAS
        p = self._params()
        if p.get("rollback"):
            ok = ACTIONS.rollback(str(p["rollback"]))
            self._reply(schemas.ops_v3(
                {"rolled_back": ok, "action_id": p["rollback"],
                 "actions": ACTIONS.list()}))
            return
        if p.get("remove_quota"):
            try:
                removed = QUOTAS.remove_quota(str(p["remove_quota"]))
            except ValueError as e:
                self._error(400, str(e))
                return
            self._reply(schemas.ops_v3({"removed": removed,
                                        "quotas": QUOTAS.quotas()}))
            return
        if not p.get("tenant"):
            self._error(400, "POST /3/Ops needs tenant (quota CRUD), "
                             "remove_quota, or rollback")
            return
        try:
            rec = QUOTAS.set_quota(
                p["tenant"],
                qps=float(p["qps"]) if p.get("qps") is not None else None,
                device_seconds=(float(p["device_seconds"])
                                if p.get("device_seconds") is not None
                                else None),
                bytes=(int(float(p["bytes"]))
                       if p.get("bytes") is not None else None))
        except ValueError as e:
            self._error(400, str(e))
            return
        self._reply(schemas.ops_v3({"quota": rec,
                                    "quotas": QUOTAS.quotas()}))

    def r_incident(self, incident_id):
        """``GET /3/Incidents/{id}`` — one incident with the correlated
        context captured at trip time: trace ids, log tail, memory
        top-keys, compute loop rows, and the rule's observed series."""
        from h2o3_tpu.utils.incidents import INCIDENTS
        self._reply(schemas.incident_v3(INCIDENTS.get(incident_id)))

    def r_diagnostics_bundle(self):
        """``POST /3/Diagnostics/bundle`` (GET also served for plain
        browser/curl downloads) — the ``h2o logs download`` analog: one
        gzip tar with all four pillar snapshots (metrics, traces, memory,
        compute), the health verdict, the incident ring, the log ring,
        the hardware fingerprint, and the secrets-redacted config dump."""
        from h2o3_tpu.utils.health import diagnostic_bundle
        body, fname = diagnostic_bundle()
        self.send_response(200)
        self.send_header("Content-Type", "application/gzip")
        self.send_header("Content-Disposition",
                         f'attachment; filename="{fname}"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_metrics_json(self):
        """JSON metrics snapshot — flat {name, type, labels, value} rows
        (TwoDimTable-friendly; the Python client's ``client.metrics()``)."""
        self._reply({"__meta": {"schema_type": "MetricsV3"},
                     "metrics": _tm.METRICS.snapshot()})

    def r_timeseries(self):
        """``GET /3/TimeSeries?name=&labels=&since=`` — the flight
        recorder's retained series (utils/flight.py): per series the raw
        ``(t, value)`` tail and the min/max/mean/last rollup windows.
        ``name`` matches exactly or as a prefix, ``labels`` is
        ``k=v,k2=v2`` (subset match), ``since`` is epoch seconds
        (docs/OBSERVABILITY.md "Flight recorder & post-mortems")."""
        from h2o3_tpu.utils.flight import FLIGHT
        p = self._params()
        labels = None
        if p.get("labels"):
            labels = {}
            for part in str(p["labels"]).split(","):
                if "=" not in part:
                    self._error(400, f"labels must be k=v,k2=v2 pairs, "
                                     f"got {part!r}")
                    return
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip()
        since = None
        if p.get("since") is not None:
            try:
                since = float(p["since"])
            except ValueError:
                self._error(400, f"since must be epoch seconds, "
                                 f"got {p['since']!r}")
                return
        series = FLIGHT.query(name=p.get("name") or None, labels=labels,
                              since=since)
        stats = FLIGHT.stats()
        # stats counts retained series under "series"; the payload key
        # of that name is the series list itself
        stats["series_retained"] = stats.pop("series")
        self._reply(schemas.timeseries_v3({"series": series, **stats}))

    def r_metrics_text(self):
        """Prometheus/OpenMetrics exposition at ``/metrics`` — point a
        Prometheus scrape job at this path (docs/OBSERVABILITY.md). The
        render itself is timed (``h2o3_metrics_scrape_seconds``) — the
        observers are observed too."""
        t0 = time.perf_counter()
        body = _tm.METRICS.to_openmetrics().encode()
        _tm.SCRAPE_SECONDS.observe(time.perf_counter() - t0)
        self.send_response(200)
        self.send_header("Content-Type",
                         "application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- distributed tracing (reference analog: water/api/TimelineHandler —
    #    the cluster-wide causally-ordered event snapshot; here per-request
    #    span trees, see docs/OBSERVABILITY.md "Tracing") --------------------

    def r_traces(self):
        """Completed traces, newest first (summaries; span lists via
        ``/3/Traces/{trace_id}``)."""
        self._reply({"__meta": {"schema_type": "TracesV3"},
                     "traces": _tr.TRACER.list_traces()})

    def r_trace(self, trace_id):
        """One trace: flat spans + nested tree + computed critical path."""
        try:
            trace = _tr.TRACER.get_trace(trace_id)
        except KeyError:
            raise KeyError(f"no trace {trace_id!r} (completed-trace ring "
                           f"holds the last {_tr.TRACE_RING_SIZE})")
        self._reply(schemas.trace_v3(trace))

    def r_trace_export(self, trace_id):
        """Chrome trace-event JSON — save and open in Perfetto
        (https://ui.perfetto.dev) or chrome://tracing."""
        trace = _tr.TRACER.get_trace(trace_id)
        self._reply(_tr.to_chrome_trace(trace))

    # -- round-2 parity sweep: the routes the real h2o-py client traffics
    #    (reference registrations: water/api/RegisterV3Api.java) -------------

    def r_ping(self):
        self._reply({"__meta": {"schema_type": "PingV3"}, "healthy": True})

    def r_jobs(self):
        jobs = [schemas.job_v3(k, DKV[k]) for k in DKV.keys()
                if isinstance(DKV.get(k), Job)]
        self._reply({"__meta": {"schema_type": "JobsV3"}, "jobs": jobs})

    def r_parse_setup(self):
        """Reference ParseSetupHandler: guess header/types from the source.
        Sources that are already parsed frames report their schema; raw
        paths get imported (our import does guess+parse in one pass)."""
        p = self._params()
        src = p.get("source_frames", [])
        if isinstance(src, str):
            src = json.loads(src)
        keys = [s.get("name") if isinstance(s, dict) else s for s in src]
        if not keys:
            raise KeyError("source_frames is required")
        from h2o3_tpu.frame.parse import RawFile, import_file
        frames = []
        for k in keys:
            obj = DKV.get(k)
            if isinstance(obj, RawFile):
                frames.append(obj.frame())
            elif isinstance(obj, Frame):
                frames.append(obj)
            else:
                frames.append(import_file(k))
        fr = frames[0]
        type_names = {"real": "Numeric", "int": "Numeric", "enum": "Enum",
                      "string": "String", "time": "Time", "uuid": "UUID"}
        self._reply({"__meta": {"schema_type": "ParseSetupV3"},
                     "source_frames": [{"name": k} for k in keys],
                     "destination_frame": (keys[0].rsplit("/", 1)[-1]
                                           .replace(".", "_") + ".hex"),
                     "number_columns": fr.ncols,
                     "column_names": list(fr.names),
                     "column_types": [type_names.get(v.type.value, "Numeric")
                                      for v in fr.vecs],
                     "separator": 44, "check_header": 1,
                     "parse_type": "CSV", "chunk_size": 4194304,
                     "na_strings": None, "single_quotes": False,
                     "escapechar": None, "skipped_columns": None,
                     "custom_non_data_line_markers": None,
                     "partition_by": None})

    def r_split_frame(self):
        """Reference SplitFrameHandler (hex/splitframe/SplitFrame.java):
        EXACT contiguous row split by ratios (unlike the client-side
        probabilistic H2OFrame.split_frame)."""
        p = self._params()
        fr = DKV[_name(p["dataset"])]
        ratios = p["ratios"]
        if isinstance(ratios, str):
            ratios = json.loads(ratios)
        dests = p.get("destination_frames")
        if isinstance(dests, str):
            dests = json.loads(dests)
        dests = [_name(d) for d in dests] if dests else [
            f"split_{uuid.uuid4().hex[:6]}_{i}" for i in range(len(ratios) + 1)]
        import numpy as np
        from h2o3_tpu.rapids.munge import gather_rows
        n = fr.nrows
        counts = [int(round(r * n)) for r in ratios]
        counts.append(n - sum(counts))
        job = Job("SplitFrame", key=f"job_{uuid.uuid4().hex[:12]}")

        def driver(j: Job):
            start = 0
            for dest, c in zip(dests, counts):
                part = gather_rows(fr, np.arange(start, start + c))
                part.key = dest
                DKV.put(dest, part)
                start += c
            j.dest_key = dests[0]
            return dests

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "SplitFrameV3"},
                     "key": {"name": job.key},
                     "destination_frames": [{"name": d} for d in dests]})

    def r_create_frame(self):
        p = self._params()
        from h2o3_tpu.frame.utils import create_frame
        kw = {k: (json.loads(v) if isinstance(v, str) and v[:1] in "[{tf"
                  else v) for k, v in p.items()}
        key = kw.pop("dest", None) or kw.pop("destination_frame",
                                             f"frame_{uuid.uuid4().hex[:8]}")
        numkw = {}
        import inspect
        sig = inspect.signature(create_frame)
        for k, v in kw.items():
            if k in sig.parameters:
                d = sig.parameters[k].default
                if isinstance(v, str) and isinstance(d, bool):
                    v = v.lower() in ("1", "true", "yes")
                elif isinstance(v, str):
                    try:             # None-defaulted params still need typing
                        v = int(v) if isinstance(d, int) or d is None else float(v)
                    except ValueError:
                        try:
                            v = float(v)
                        except ValueError:
                            pass
                numkw[k] = v
        fr = create_frame(**numkw)
        fr.key = key
        DKV.put(key, fr)
        self._reply({**_done_job("CreateFrame", key),
                     "key": {"name": key}, "rows": fr.nrows})

    def r_interaction(self):
        p = self._params()
        from h2o3_tpu.frame.utils import interaction
        factors = p.get("factor_columns") or p.get("factors") or []
        if isinstance(factors, str):
            factors = json.loads(factors)
        fr = interaction(DKV[_name(p["source_frame"])], factors,
                         pairwise=str(p.get("pairwise", "")).lower() == "true",
                         max_factors=int(p.get("max_factors", 100)),
                         min_occurrence=int(p.get("min_occurrence", 1)))
        key = _name(p.get("dest")) or f"interaction_{uuid.uuid4().hex[:6]}"
        fr.key = key
        DKV.put(key, fr)
        self._reply({**_done_job("Interaction", key), "key": {"name": key}})

    def r_missing_inserter(self):
        """Reference MissingInserterHandler: corrupt a fraction of cells to
        NA (pyunit fixture machinery)."""
        p = self._params()
        import numpy as np
        fr = DKV[_name(p["dataset"])]
        frac = float(p.get("fraction", 0.1))
        seed = int(p.get("seed", -1) or -1)
        rng = np.random.default_rng(None if seed < 0 else seed)
        from h2o3_tpu.frame.frame import Frame as _F
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.frame.types import VecType
        out = []
        for v in fr.vecs:
            if not v.type.on_device:
                out.append(v)
                continue
            vals = v.to_numpy().copy()
            hit = rng.random(len(vals)) < frac
            if v.is_categorical:
                vals = np.where(hit, -1, vals).astype(np.int32)
                out.append(Vec.from_numpy(vals, type=VecType.CAT,
                                          domain=v.domain))
            else:
                vals = vals.astype(np.float64)
                vals[hit] = np.nan
                out.append(Vec.from_numpy(vals.astype(np.float32),
                                          type=v.type))
        fr2 = _F(fr.names, out, key=fr.key)
        DKV.put(fr.key, fr2)
        self._reply({**_done_job("MissingInserter", fr.key),
                     "key": {"name": fr.key}})

    def r_typeahead(self):
        import glob
        import os
        p = self._params()
        src = p.get("src", "")
        limit = int(p.get("limit", 100))
        matches = sorted(glob.glob(src + "*"))[:limit] if src else []
        matches = [m + "/" if os.path.isdir(m) else m for m in matches]
        self._reply({"__meta": {"schema_type": "TypeaheadV3"},
                     "matches": matches})

    def r_find(self):
        """Reference FindHandler: first row index >= `row` whose `column`
        equals `match`."""
        p = self._params()
        import numpy as np
        fr = DKV[_name(p["key"])]
        col = p["column"]
        start = int(p.get("row", 0))
        target = p.get("match")
        v = fr.vec(col)
        vals = v.labels() if v.is_categorical else v.to_numpy()
        idx = -1
        for i in range(start, len(vals)):
            val = vals[i]
            if val is None or (isinstance(val, float) and np.isnan(val)):
                hit = target in (None, "", "NA")
            elif v.is_categorical:
                hit = str(val) == str(target)
            else:
                try:
                    hit = float(val) == float(target)
                except (TypeError, ValueError):
                    hit = False
            if hit:
                idx = i
                break
        self._reply({"__meta": {"schema_type": "FindV3"}, "prev": -1,
                     "next": idx})

    def r_frame_summary(self, key):
        # serves both /summary and /light: full column metadata, no data
        # page (h2o-py's H2OFrame._frame(light=True) builds its cache here)
        fr = DKV[key]
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "frames": [schemas.frame_v3(key, fr, rows=0)]})

    def r_frame_columns(self, key):
        fr = DKV[key]
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "columns": [{"label": n, "type": str(v.type).lower()}
                                 for n, v in zip(fr.names, fr.vecs)]})

    def r_frame_column(self, key, col):
        fr = DKV[key]
        sub = fr[[col]]
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "frames": [schemas.frame_v3(key, sub)]})

    def r_frame_col_summary(self, key, col):
        fr = DKV[key]
        v = fr.vec(col)
        r = v.rollups()
        out = {"label": col, "missing_count": int(r.na_cnt)}
        if v.is_numeric:
            out.update(mins=[schemas._clean(r.min)],
                       maxs=[schemas._clean(r.max)],
                       mean=schemas._clean(r.mean),
                       sigma=schemas._clean(r.sigma),
                       **schemas._histogram_cached(v, r),
                       percentiles=schemas._clean(
                           fr[[col]].quantile().vec(col).to_numpy()))
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "frames": [{"frame_id": {"name": key},
                                 "columns": [out]}]})

    def r_frame_col_domain(self, key, col):
        v = DKV[key].vec(col)
        self._reply({"__meta": {"schema_type": "FrameV3"},
                     "domain": [list(v.domain) if v.domain else None]})

    def r_frame_export(self, key):
        p = self._params()
        from h2o3_tpu.persist.frame_io import export_file
        path = export_file(DKV[key], p["path"])
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "job": _done_job("Export File", key), "path": path})

    def r_frame_save(self, key):
        import os
        p = self._params()
        from h2o3_tpu.persist.frame_io import save_frame
        dest = p["dir"]
        if os.path.isdir(dest):
            dest = os.path.join(dest, key)
        path = save_frame(DKV[key], dest)
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "job": _done_job("Save Frame", key), "path": path})

    def r_frame_load(self):
        p = self._params()
        from h2o3_tpu.persist.frame_io import load_frame
        fr = load_frame(p["dir"], key=p.get("frame_id"))
        DKV.put(fr.key, fr)
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "job": _done_job("Load Frame", fr.key),
                     "frame_id": {"name": fr.key}})

    def r_frames_delete_all(self):
        for k, v in DKV.raw_items():
            if isinstance(v, Frame) or type(v).__name__ == "SwappedFrame":
                DKV.remove(k)      # stub-aware: deletes spill files too
        self._reply({"__meta": {"schema_type": "FramesV3"}})

    def r_dkv_delete(self, key):
        DKV.remove(key)
        self._reply({"__meta": {"schema_type": "RemoveV3"}})

    def r_dkv_delete_all(self):
        DKV.clear()
        self._reply({"__meta": {"schema_type": "RemoveAllV3"}})

    def r_download_dataset(self):
        p = self._params()
        fr = DKV[_name(p["frame_id"])]
        csv = fr.to_pandas().to_csv(index=False)
        body = csv.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/csv")
        self.send_header("Content-Disposition",
                         f'attachment; filename="{fr.key or "frame"}.csv"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_import_sql(self):
        p = self._params()
        from h2o3_tpu.frame.sql import import_sql_table
        fr = import_sql_table(p["connection_url"], p["table"],
                              fetch_mode=p.get("fetch_mode", "SINGLE"))
        self._reply(_done_job("ImportSQLTable", fr.key))

    def r_model_builders(self):
        self._reply({"__meta": {"schema_type": "ModelBuildersV3"},
                     "model_builders": {
                         a: {"algo": a, "visibility": "Stable"}
                         for a in sorted(_algo_registry())}})

    def r_model_builder(self, algo):
        cls = _algo_registry().get(algo.lower())
        if cls is None:
            raise KeyError(f"unknown algorithm {algo!r}")
        params = [{"name": k,
                   "default_value": schemas._clean(v),
                   "type": type(v).__name__}
                  for k, v in cls.defaults().items()]
        self._reply({"__meta": {"schema_type": "ModelBuildersV3"},
                     "model_builders": {algo.lower(): {
                         "algo": algo.lower(),
                         "supervised": not getattr(cls, "unsupervised",
                                                   False),
                         "parameters": params}}})

    def r_model_metrics_compute(self, model_key, frame_key):
        m, fr = DKV[model_key], DKV[frame_key]
        mm = m.model_performance(fr)
        item = schemas.metrics_v3(mm, getattr(m, "response_domain", None))
        item["frame"] = {"name": frame_key}     # h2o-py filters on these
        item["model"] = {"name": model_key}
        self._reply({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
                     "model_metrics": [item]})

    def r_model_metrics_get(self, model_key):
        m = DKV[model_key]
        mms = [schemas.metrics_v3(mm, getattr(m, "response_domain", None))
               for mm in (m.training_metrics, m.validation_metrics,
                          m.cross_validation_metrics) if mm is not None]
        self._reply({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
                     "model_metrics": mms})

    def r_make_metrics(self, pred_key, actual_key):
        """Reference: h2o.make_metrics — metrics from a predictions frame
        vs an actuals column (no model needed)."""
        p = self._params()
        pred, act = DKV[pred_key], DKV[actual_key]
        from h2o3_tpu.models.data_info import response_as_float
        from h2o3_tpu.models.model_base import compute_metrics
        yvec = act.vec(p.get("response_column") or act.names[-1])
        y, valid = response_as_float(yvec)
        mask = act.row_mask() & valid
        prob_cols = [n for n in pred.names if n != "predict"]
        if yvec.is_categorical and prob_cols:
            raw = pred.matrix(prob_cols)
            ncl = len(prob_cols)
        else:
            raw = pred.vec("predict").data
            ncl = 0
        mm = compute_metrics(raw, y, mask, ncl)
        self._reply({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
                     "model_metrics": [schemas.metrics_v3(mm)]})

    def r_partial_dependence(self):
        p = self._params()
        from h2o3_tpu.explanation import partial_dependence
        m = DKV[_name(p["model_id"])]
        fr = DKV[_name(p["frame_id"])]
        cols = p.get("cols") or p.get("col_pairs_2dpdp") or []
        if isinstance(cols, str):
            cols = json.loads(cols)
        nbins = int(p.get("nbins", 20))
        name = p.get("destination_key") or f"pdp_{uuid.uuid4().hex[:8]}"
        job = Job("PartialDependence", key=f"job_{uuid.uuid4().hex[:12]}")

        def driver(j: Job):
            tables = partial_dependence(m, fr, cols, nbins=nbins)
            DKV.put(name, tables)
            j.dest_key = name
            return tables

        job.run(driver, background=True)
        job.dest_key = name
        self._reply({**schemas.job_v3(job.key, job),
                     "destination_key": name})

    def r_partial_dependence_get(self, name):
        tables = DKV[name]
        data = [{"columns": list(t.names),
                 "data": {n: schemas._clean(t.vec(n).to_numpy())
                          for n in t.names}} for t in tables]
        self._reply({"__meta": {"schema_type": "PartialDependenceV3"},
                     "partial_dependence_data": data})

    def r_pojo(self, model_key):
        import os
        import tempfile
        m = DKV[model_key.removesuffix(".java")]
        with tempfile.TemporaryDirectory() as d:
            path = m.download_pojo(os.path.join(d, f"{m.key}_pojo.py"))
            with open(path, "rb") as f:
                body = f.read()
            fname = os.path.basename(path)
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Disposition",
                         f'attachment; filename="{fname}"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_mojo(self, model_key):
        import os
        import tempfile
        m = DKV[model_key]
        with tempfile.TemporaryDirectory() as d:
            path = m.download_mojo(os.path.join(d, f"{m.key}.zip"))
            with open(path, "rb") as f:
                body = f.read()
            fname = os.path.basename(path)
        self.send_response(200)
        self.send_header("Content-Type", "application/zip")
        self.send_header("Content-Disposition",
                         f'attachment; filename="{fname}"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_model_save(self, model_key):
        import os
        p = self._params()
        from h2o3_tpu.persist.model_io import save_model
        dest = p["dir"]
        if os.path.isdir(dest):      # h2o-py passes a directory
            dest = os.path.join(dest, model_key)
        path = save_model(DKV[model_key], dest)
        self._reply({"__meta": {"schema_type": "ModelsV3"},
                     "dir": path, "models": [{"model_id": {"name": model_key}}]})

    def r_model_load(self, model_key):
        p = self._params()
        from h2o3_tpu.persist.model_io import load_model
        m = load_model(p["dir"])
        DKV.put(m.key, m)
        self._reply({"__meta": {"schema_type": "ModelsV3"},
                     "models": [{"model_id": {"name": m.key}}]})

    def r_model_json(self, model_key):
        self._reply({"__meta": {"schema_type": "ModelsV3"},
                     "models": [schemas.model_v3(DKV[model_key])]})

    def r_grids(self):
        from h2o3_tpu.orchestration.grid import Grid
        grids = [{"grid_id": {"name": k}} for k in DKV.keys()
                 if isinstance(DKV.get(k), Grid)]
        self._reply({"__meta": {"schema_type": "GridsV99"}, "grids": grids})

    def r_capabilities(self):
        from h2o3_tpu.utils import extensions as _ext
        self._reply({"__meta": {"schema_type": "CapabilitiesV3"},
                     "capabilities": [
                         {"name": a, "module": "core"}
                         for a in sorted(_algo_registry())] + [
                         {"name": e.name, "module": "extension"}
                         for e in _ext.extensions()]})

    def r_init_id(self):
        self._reply({"__meta": {"schema_type": "InitIDV3"},
                     "session_key": self.server._session_id})

    def r_sessions_v4(self):
        # h2o-py >=3.22 opens its Rapids session via the V4 endpoint; each
        # client gets a FRESH id so concurrent clients cannot collide on
        # temp-frame names (py_N_<sid>)
        from h2o3_tpu.rapids.exec import Session
        sid = f"_sid_{uuid.uuid4().hex[:10]}"
        self.server._rapids_sessions[sid] = Session()
        self._reply({"__meta": {"schema_type": "SessionIdV4"},
                     "session_key": sid})

    def r_init_id_delete(self, sid=None):
        # end the client's Rapids session: drop its temp frames (reference:
        # Session.end + temp-key cleanup)
        sess = self.server._rapids_sessions.pop(
            sid or self.server._session_id, None)
        if sess is not None:
            for name in list(sess._tmp):
                sess.remove(name)
            sess.end()
        self._reply({"__meta": {"schema_type": "InitIDV3"}})

    def r_session_properties(self):
        props = self.server._session_props
        p = self._params()
        if self.command == "POST":
            props[p["key"]] = p.get("value")
        self._reply({"__meta": {"schema_type": "SessionPropertyV3"},
                     "key": p.get("key"), "value": props.get(p.get("key"))})

    def r_log_and_echo(self):
        p = self._params()
        import logging
        logging.getLogger("h2o3_tpu").info(p.get("message", ""))
        self._reply({"__meta": {"schema_type": "LogAndEchoV3"},
                     "message": p.get("message", "")})

    def r_rapids_help(self):
        from h2o3_tpu.rapids.exec import known_prims
        self._reply({"__meta": {"schema_type": "RapidsHelpV3"},
                     "syntax": sorted(known_prims())})

    def r_metadata_endpoints(self):
        self._reply({"__meta": {"schema_type": "MetadataV3"},
                     "routes": [{"http_method": m, "url_pattern": pat}
                                for pat, m, _ in _ROUTES]})

    def r_metadata_endpoint(self, path):
        """Reference MetadataHandler.fetchRoute: one route's metadata, with
        its handler docstring as the help text."""
        import urllib.parse as _up
        want = _up.unquote(path)
        if want.isascii() and want.isdecimal() \
                and int(want) < len(_ROUTES):   # fetch by index
            pat, m, fn = _ROUTES[int(want)]
            self._reply({"__meta": {"schema_type": "MetadataV3"},
                         "routes": [{"http_method": m, "url_pattern": pat,
                                     "summary": (fn.__doc__ or "").strip()
                                     .split("\n")[0]}]})
            return
        for pat, m, fn in _ROUTES:
            if pat.replace("\\", "") == want or pat == want:
                self._reply({"__meta": {"schema_type": "MetadataV3"},
                             "routes": [{"http_method": m, "url_pattern": pat,
                                         "summary": (fn.__doc__ or "").strip()
                                         .split("\n")[0]}]})
                return
        raise KeyError(f"no route matching {want!r}")

    def r_kill3(self):
        """Reference KillMinus3Handler (kill -3 = SIGQUIT thread dump): log
        every thread's stack, but keep serving (the JVM analog dumps and
        continues too)."""
        import logging
        import sys
        import traceback
        dump = []
        for tid, frame in sys._current_frames().items():
            dump.append(f"Thread {tid}:\n"
                        + "".join(traceback.format_stack(frame)))
        logging.getLogger("h2o3_tpu").info("KillMinus3 thread dump:\n%s",
                                           "\n".join(dump))
        self._reply({"__meta": {"schema_type": "KillMinus3V3"}})

    # field inventories h2o-py's schema bootstrap fetches at connect time
    # (reference: water/api/schemas3/H2OErrorV3.java et al.)
    _SCHEMA_FIELDS = {
        "H2OErrorV3": ["timestamp", "error_url", "msg", "dev_msg",
                       "http_status", "values", "exception_type",
                       "exception_msg", "stacktrace"],
        "H2OModelBuilderErrorV3": [
            "timestamp", "error_url", "msg", "dev_msg", "http_status",
            "values", "exception_type", "exception_msg", "stacktrace",
            "parameters", "messages", "error_count"],
        "CloudV3": ["version", "cloud_name", "cloud_size", "cloud_healthy",
                    "nodes", "bad_nodes", "consensus", "locked", "is_client",
                    "cloud_uptime_millis", "internal_security_enabled",
                    "branch_name", "build_number", "build_age",
                    "build_too_old", "node_idx", "cloud_internal_timezone",
                    "datafile_parser_timezone", "mesh_slices", "workers"],
    }

    def r_metadata_schemas(self):
        """Reference MetadataHandler.listSchemas."""
        self._reply({"__meta": {"schema_type": "MetadataV3"},
                     "schemas": [{"name": n,
                                  "fields": [{"name": f, "is_schema": False,
                                              "help": f}
                                             for f in self._SCHEMA_FIELDS[n]]}
                                 for n in sorted(self._SCHEMA_FIELDS)]})

    def r_metadata_schema(self, name):
        fields = self._SCHEMA_FIELDS.get(name, [])
        self._reply({"__meta": {"schema_type": "MetadataV3"},
                     "schemas": [{"name": name,
                                  "fields": [{"name": f, "is_schema": False,
                                              "help": f} for f in fields]}]})

    def r_network_test(self):
        """Reference NetworkTestHandler: measure collective latency. Here:
        time one all-reduce over the mesh (the only 'network')."""
        import time as _t
        import jax
        import jax.numpy as jnp
        t0 = _t.time()
        # graftlint: ok(latency endpoint — the sync IS the measurement)
        jax.block_until_ready(jnp.sum(jnp.ones(1024)))
        dt = (_t.time() - t0) * 1e3
        self._reply({"__meta": {"schema_type": "NetworkTestV3"},
                     "microseconds_collective": dt * 1000,
                     "table": [{"collective_ms": dt}]})


_ROUTES = [
    (r"/3/Cloud", "GET", _Handler.r_cloud),
    (r"/3/About", "GET", _Handler.r_about),
    (r"/3/ImportFiles", "GET", _Handler.r_import),
    (r"/3/ImportFiles", "POST", _Handler.r_import),
    (r"/3/ImportFilesMulti", "POST", _Handler.r_import_multi),
    (r"/3/Parse", "POST", _Handler.r_parse),
    (r"/3/Frames", "GET", _Handler.r_frames),
    (r"/3/Frames/([^/]+)", "GET", _Handler.r_frame),
    (r"/3/Frames/([^/]+)", "DELETE", _Handler.r_frame_delete),
    (r"/3/Models", "GET", _Handler.r_models),
    (r"/3/Models/([^/]+)", "GET", _Handler.r_model),
    (r"/3/Models/([^/]+)", "DELETE", _Handler.r_model_delete),
    (r"/3/ModelBuilders/([^/]+)", "POST", _Handler.r_train),
    (r"/3/Jobs/([^/]+)", "GET", _Handler.r_job),
    (r"/3/Jobs/([^/]+)/cancel", "POST", _Handler.r_job_cancel),
    (r"/3/Predictions/models/([^/]+)/frames/([^/]+)", "POST", _Handler.r_predict),
    (r"/4/Predictions/models/([^/]+)/frames/([^/]+)", "POST", _Handler.r_predict_v4),
    (r"/3/Score/([^/]+)", "POST", _Handler.r_score),
    (r"/3/Score", "GET", _Handler.r_score_stats),
    (r"/3/Score/([^/]+)", "DELETE", _Handler.r_score_evict),
    (r"/99/Rapids", "POST", _Handler.r_rapids),
    (r"/99/Grid/([^/]+)", "POST", _Handler.r_grid),
    (r"/99/Grids/([^/]+)", "GET", _Handler.r_grid_get),
    (r"/99/AutoMLBuilder", "POST", _Handler.r_automl),
    (r"/99/AutoML/([^/]+)", "GET", _Handler.r_automl_get),
    (r"/99/Leaderboards/([^/]+)", "GET", _Handler.r_leaderboards),
    (r"/99/ModelBuilders/([^/]+)", "POST", _Handler.r_train),
    (r"/99/Models/([^/]+)", "GET", _Handler.r_model),
    (r"/3/PostFile", "POST", _Handler.r_postfile),
    (r"/3/PostFile\.bin", "POST", _Handler.r_postfile),
    (r"/3/PutKey", "POST", _Handler.r_putkey),
    (r"/3/Shutdown", "POST", _Handler.r_shutdown),
    (r"/3/GarbageCollect", "POST", _Handler.r_gc),
    (r"/3/Timeline", "GET", _Handler.r_timeline),
    (r"/3/JStack", "GET", _Handler.r_jstack),
    (r"/3/Profiler", "GET", _Handler.r_profiler),
    (r"/3/WaterMeterCpuTicks/\d+", "GET", _Handler.r_cpu_ticks),
    (r"/3/WaterMeterIo", "GET", _Handler.r_io_meter),
    (r"/3/Logs", "GET", _Handler.r_logs),
    (r"/3/Logs/nodes/(-?\d+)/files/([^/]+)", "GET", _Handler.r_logs_file),
    (r"/3/Memory", "GET", _Handler.r_memory),
    (r"/3/Compute", "GET", _Handler.r_compute),
    (r"/3/Health", "GET", _Handler.r_health),
    (r"/3/Incidents", "GET", _Handler.r_incidents),
    (r"/3/Incidents/([^/]+)", "GET", _Handler.r_incident),
    (r"/3/Ops", "GET", _Handler.r_ops),
    (r"/3/Ops", "POST", _Handler.r_ops_post),
    (r"/3/Diagnostics/bundle", "POST", _Handler.r_diagnostics_bundle),
    (r"/3/Diagnostics/bundle", "GET", _Handler.r_diagnostics_bundle),
    (r"/3/Profiler/capture", "POST", _Handler.r_profiler_capture),
    (r"/3/Profiler/captures", "GET", _Handler.r_profiler_captures),
    (r"/3/Profiler/captures/([^/]+)/download", "GET",
     _Handler.r_profiler_capture_download),
    (r"/3/Metrics", "GET", _Handler.r_metrics_json),
    (r"/3/TimeSeries", "GET", _Handler.r_timeseries),
    (r"/metrics", "GET", _Handler.r_metrics_text),
    (r"/3/Traces", "GET", _Handler.r_traces),
    (r"/3/Traces/([^/]+)", "GET", _Handler.r_trace),
    (r"/3/Traces/([^/]+)/export", "GET", _Handler.r_trace_export),
    (r"/", "GET", _Handler.r_flow),
    (r"/flow/index\.html", "GET", _Handler.r_flow),
    # round-2 parity sweep (reference: RegisterV3Api.java)
    (r"/3/Ping", "GET", _Handler.r_ping),
    (r"/3/Jobs", "GET", _Handler.r_jobs),
    (r"/3/ParseSetup", "POST", _Handler.r_parse_setup),
    (r"/3/SplitFrame", "POST", _Handler.r_split_frame),
    (r"/3/CreateFrame", "POST", _Handler.r_create_frame),
    (r"/3/Interaction", "POST", _Handler.r_interaction),
    (r"/3/MissingInserter", "POST", _Handler.r_missing_inserter),
    (r"/3/Typeahead/files", "GET", _Handler.r_typeahead),
    (r"/3/Find", "GET", _Handler.r_find),
    (r"/3/Frames/([^/]+)/summary", "GET", _Handler.r_frame_summary),
    (r"/3/Frames/([^/]+)/light", "GET", _Handler.r_frame_summary),
    (r"/3/Frames/([^/]+)/columns", "GET", _Handler.r_frame_columns),
    (r"/3/Frames/([^/]+)/columns/([^/]+)", "GET", _Handler.r_frame_column),
    (r"/3/Frames/([^/]+)/columns/([^/]+)/summary", "GET",
     _Handler.r_frame_col_summary),
    (r"/3/Frames/([^/]+)/columns/([^/]+)/domain", "GET",
     _Handler.r_frame_col_domain),
    (r"/3/Frames/([^/]+)/export", "POST", _Handler.r_frame_export),
    (r"/3/Frames/([^/]+)/save", "POST", _Handler.r_frame_save),
    (r"/3/Frames/load", "POST", _Handler.r_frame_load),
    (r"/3/Frames", "DELETE", _Handler.r_frames_delete_all),
    (r"/3/DKV/([^/]+)", "DELETE", _Handler.r_dkv_delete),
    (r"/3/DKV", "DELETE", _Handler.r_dkv_delete_all),
    (r"/3/DownloadDataset", "GET", _Handler.r_download_dataset),
    (r"/3/DownloadDataset\.bin", "GET", _Handler.r_download_dataset),
    (r"/99/ImportSQLTable", "POST", _Handler.r_import_sql),
    (r"/3/ModelBuilders", "GET", _Handler.r_model_builders),
    (r"/3/ModelBuilders/([^/]+)", "GET", _Handler.r_model_builder),
    (r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)", "POST",
     _Handler.r_model_metrics_compute),
    (r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)", "GET",
     _Handler.r_model_metrics_compute),
    (r"/3/ModelMetrics/models/([^/]+)", "GET", _Handler.r_model_metrics_get),
    (r"/3/ModelMetrics/predictions_frame/([^/]+)/actuals_frame/([^/]+)",
     "POST", _Handler.r_make_metrics),
    (r"/3/PartialDependence/", "POST", _Handler.r_partial_dependence),
    (r"/3/PartialDependence/([^/]+)", "GET",
     _Handler.r_partial_dependence_get),
    (r"/3/Models\.java/([^/]+)", "GET", _Handler.r_pojo),
    (r"/3/Models/([^/]+)/mojo", "GET", _Handler.r_mojo),
    (r"/99/Models\.mojo/([^/]+)", "GET", _Handler.r_mojo),
    (r"/99/Models\.bin/([^/]*)", "GET", _Handler.r_model_save),
    (r"/99/Models\.bin/([^/]*)", "POST", _Handler.r_model_load),
    (r"/99/Models/([^/]+)/json", "GET", _Handler.r_model_json),
    (r"/99/Grids", "GET", _Handler.r_grids),
    (r"/3/Capabilities", "GET", _Handler.r_capabilities),
    (r"/3/Capabilities/Core", "GET", _Handler.r_capabilities),
    (r"/3/Capabilities/API", "GET", _Handler.r_capabilities),
    (r"/3/InitID", "GET", _Handler.r_init_id),
    (r"/3/InitID", "DELETE", _Handler.r_init_id_delete),
    (r"/4/sessions", "POST", _Handler.r_sessions_v4),
    (r"/4/sessions/([^/]+)", "DELETE", _Handler.r_init_id_delete),
    (r"/3/SessionProperties", "GET", _Handler.r_session_properties),
    (r"/3/SessionProperties", "POST", _Handler.r_session_properties),
    (r"/3/LogAndEcho", "POST", _Handler.r_log_and_echo),
    (r"/99/Rapids/help", "GET", _Handler.r_rapids_help),
    (r"/3/Metadata/endpoints", "GET", _Handler.r_metadata_endpoints),
    (r"/3/Metadata/endpoints/(.+)", "GET", _Handler.r_metadata_endpoint),
    (r"/3/Metadata/schemaclasses/([^/]+)", "GET", _Handler.r_metadata_schema),
    (r"/3/Metadata/schemas", "GET", _Handler.r_metadata_schemas),
    (r"/3/KillMinus3", "GET", _Handler.r_kill3),
    (r"/3/Metadata/schemas/([^/]+)", "GET", _Handler.r_metadata_schema),
    (r"/3/NetworkTest", "GET", _Handler.r_network_test),
    (r"/3/NodePersistentStorage/([^/]+)", "GET", _Handler.r_nps_list),
    (r"/3/NodePersistentStorage/([^/]+)/([^/]+)", "GET", _Handler.r_nps_get),
    (r"/3/NodePersistentStorage/([^/]+)/([^/]+)", "POST", _Handler.r_nps_put),
    (r"/3/NodePersistentStorage/([^/]+)/([^/]+)", "DELETE",
     _Handler.r_nps_delete),
    (r"/login", "GET", _Handler.r_login_page),
    (r"/login", "POST", _Handler.r_login),
    (r"/logout", "POST", _Handler.r_logout),
]


class _H2OHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that proves its accept loop is alive:
    ``service_actions`` runs once per ``serve_forever`` poll (~0.5s), so
    it is exactly the seam where an accept-loop wedge shows as heartbeat
    silence — the black-box watchdog pages on it, and the chaos harness
    can stall it (``rest.accept``) to rehearse the wedge."""

    def service_actions(self):
        from h2o3_tpu.utils import blackbox as _bb
        from h2o3_tpu.utils import timeline as _tl
        if _tl.FAULTS is not None:
            _tl.FAULTS.maybe_fault("rest.accept")
        _bb.BLACKBOX.beat("rest_accept")


class H2OServer:
    """Embeddable REST server (reference: ``water.H2OApp`` + Jetty).

    Auth (reference ``water/H2O.java:242-266``): ``username``/``password``
    is the built-in hash login; ``authenticator`` is the pluggable hook —
    any ``(user, password) -> bool`` (an LDAP bind, a PAM check, a htpasswd
    file) slots in where the reference accepts a JAAS login module. Form
    login (POST /login → session cookie) works with either.

    TLS (reference ``h2o-internal-security``): pass ``ssl_certfile`` (+
    optional ``ssl_keyfile``) to serve https.
    """

    def __init__(self, port: int = 54321, host: str = "127.0.0.1",
                 username: str | None = None, password: str | None = None,
                 authenticator=None, ssl_certfile: str | None = None,
                 ssl_keyfile: str | None = None):
        self.httpd = _H2OHTTPServer((host, port), _Handler)
        self.httpd._session_id = f"_sid_{uuid.uuid4().hex[:10]}"
        self.httpd._session_props = {}
        self.httpd._rapids_sessions = {}
        self.httpd._login_sessions = {}    # token → expiry epoch
        self.httpd._session_ttl = 8 * 3600.0   # Jetty-like session TTL
        if authenticator is not None:
            self.httpd._authenticate = authenticator
        elif username is not None:
            import hmac
            stored = f"{username}:{password or ''}".encode()
            self.httpd._authenticate = (
                lambda u, p: hmac.compare_digest(f"{u}:{p}".encode(), stored))
        else:
            self.httpd._authenticate = None
        self.scheme = "http"
        if ssl_certfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_certfile, ssl_keyfile)
            # handshake on first read in the per-connection worker thread —
            # with do_handshake_on_connect=True a single idle client would
            # stall the accept loop mid-handshake and freeze the server
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                                server_side=True,
                                                do_handshake_on_connect=False)
            self.scheme = "https"
        self.host, self.port = host, self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    def start(self) -> "H2OServer":
        # log ring first (reference: Log.init runs before the API is up), so
        # startup lines are the first thing /3/Logs serves
        _tm.install_log_ring()
        # extension lifecycle (reference: ExtensionManager hooks run during
        # H2O.main before the REST API is declared up)
        from h2o3_tpu.utils import extensions as _ext
        _ext.load_env_extensions()
        _ext.init_all()
        # ops plane: the health evaluator sweeps the live registries on a
        # bounded interval (reference: the heartbeat-driven cloud_healthy
        # consensus). H2O3TPU_HEALTH_OFF=1 disables; /3/Health then
        # evaluates inline per request or reports "disabled".
        from h2o3_tpu.utils.health import HEALTH
        self._started_health = HEALTH.start()
        # flight recorder: retained metric time series in fixed-memory
        # rings (GET /3/TimeSeries; H2O3TPU_FLIGHT_OFF=1 disables) — the
        # history the trend rules and post-mortems read
        from h2o3_tpu.utils.flight import FLIGHT
        self._started_flight = FLIGHT.start()
        # black-box watchdog: wedge/crash post-mortems straight to
        # ice_root without REST. Watch the two loops that can wedge —
        # the accept loop (service_actions beats ~2×/s) and the health
        # sweep (beats once per interval). An orderly stop() disarms
        # BEFORE shutdown, so clean exits never dump.
        from h2o3_tpu.utils.blackbox import BLACKBOX
        self._armed_blackbox = BLACKBOX.arm()
        if self._armed_blackbox:
            BLACKBOX.watch("rest_accept", period_s=1.0)
            if self._started_health:
                BLACKBOX.watch("health_sweep", period_s=HEALTH.interval_s)
        # remediation engine: subscribe to incident rising edges (the
        # kill switch H2O3TPU_REMEDIATE — default `observe` — is resolved
        # per incident, so installing here commits to nothing). Importing
        # ops_plane also arms the tenancy hooks in dispatch/DKV/serving.
        from h2o3_tpu import ops_plane as _ops
        _ops.install()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        import os
        _LOG.info("REST server up at %s (pid %d)", self.url, os.getpid())
        _ext.report("cloud_up", url=self.url)
        return self

    def stop(self) -> None:
        if getattr(self, "_armed_blackbox", False):
            # disarm FIRST: this is the orderly-shutdown signal — once
            # disarmed, neither the watchdog nor the exit hooks dump
            from h2o3_tpu.utils.blackbox import BLACKBOX
            BLACKBOX.disarm()
            BLACKBOX.unwatch("rest_accept")
            BLACKBOX.unwatch("health_sweep")
            self._armed_blackbox = False
        if getattr(self, "_started_health", False):
            # only the server that actually started the sweep stops it —
            # a second embedded server must not kill the first one's
            from h2o3_tpu.utils.health import HEALTH
            HEALTH.stop()
            self._started_health = False
        if getattr(self, "_started_flight", False):
            from h2o3_tpu.utils.flight import FLIGHT
            FLIGHT.stop()
            self._started_flight = False
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(port: int = 54321, host: str = "127.0.0.1") -> H2OServer:
    """h2o-py surface: ``h2o.init()`` boots a node and its REST server."""
    return H2OServer(port=port, host=host).start()
