"""REST server — the V3 route surface.

Reference: ``water/api/RequestServer.java:24-80`` (route tree; core routes in
``RegisterV3Api.java``, algo routes via ``AlgoAbstractRegister``). Routes
implemented are the ones h2o-py traffics: Cloud, ImportFiles, Parse, Frames,
Models, ModelBuilders, Predictions, Jobs, Rapids, Grid, AutoML, Shutdown.

Training runs on a background thread through the same :class:`Job` the library
path uses (reference: ``Job.start`` → F/J pool), so clients poll ``/3/Jobs``
exactly like against the reference server.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from h2o3_tpu import __version__
from h2o3_tpu.api import schemas
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.utils.registry import DKV

_ALGOS = None


def _algo_registry():
    global _ALGOS
    if _ALGOS is None:
        from h2o3_tpu.models import (ANOVAGLM, GAM, GBM, DRF, GLM, SVD,
                                     Aggregator, CoxPH, DecisionTree,
                                     DeepLearning, ExtendedIsolationForest,
                                     GLRM, Grep, IsolationForest,
                                     IsotonicRegression, KMeans,
                                     ModelSelection, NaiveBayes, PCA, RuleFit,
                                     Infogram, PSVM, TargetEncoder, UpliftDRF,
                                     Word2Vec, XGBoost)
        _ALGOS = {"gbm": GBM, "drf": DRF, "glm": GLM, "deeplearning": DeepLearning,
                  "xgboost": XGBoost, "kmeans": KMeans, "pca": PCA, "svd": SVD,
                  "glrm": GLRM, "naivebayes": NaiveBayes, "coxph": CoxPH,
                  "isolationforest": IsolationForest,
                  "extendedisolationforest": ExtendedIsolationForest,
                  "isotonicregression": IsotonicRegression,
                  "word2vec": Word2Vec, "targetencoder": TargetEncoder,
                  "rulefit": RuleFit, "decisiontree": DecisionTree,
                  "aggregator": Aggregator, "grep": Grep, "gam": GAM,
                  "modelselection": ModelSelection, "anovaglm": ANOVAGLM,
                  "upliftdrf": UpliftDRF, "psvm": PSVM, "infogram": Infogram}
    return _ALGOS


class _Handler(BaseHTTPRequestHandler):
    server_version = f"h2o3_tpu/{__version__}"

    # -- plumbing ------------------------------------------------------------

    def log_message(self, *a):   # route logs to our logger, not stderr
        pass

    def _reply(self, obj, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str):
        self._reply({"__meta": {"schema_type": "H2OErrorV3"},
                     "http_status": code, "msg": msg, "exception_msg": msg}, code)

    def _params(self) -> dict:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            body = self.rfile.read(length).decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(body))
            else:
                out.update({k: v[0] for k, v in urllib.parse.parse_qs(body).items()})
        return out

    # -- dispatch ------------------------------------------------------------

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def _route(self, method: str):
        path = urllib.parse.urlparse(self.path).path
        try:
            for pat, m, fn in _ROUTES:
                match = re.fullmatch(pat, path)
                if match and m == method:
                    fn(self, *match.groups())
                    return
            self._error(404, f"no route for {method} {path}")
        except KeyError as e:
            self._error(404, str(e))
        except Exception as e:   # one bad request must not kill the server
            self._error(500, f"{type(e).__name__}: {e}")

    # -- routes (reference: RequestServer route registrations) ---------------

    def r_cloud(self):
        self._reply(schemas.cloud_v3(__version__))

    def r_about(self):
        self._reply({"__meta": {"schema_type": "AboutV3"},
                     "entries": [{"name": "Build version", "value": __version__}]})

    def r_import(self):
        p = self._params()
        from h2o3_tpu.frame.parse import import_file
        fr = import_file(p["path"], key=p.get("destination_frame"))
        self._reply({"__meta": {"schema_type": "ImportFilesV3"},
                     "destination_frames": [fr.key], "fails": []})

    def r_parse(self):
        # the reference splits guess (ParseSetup) and parse; import_file did
        # both, so Parse is an alias that can re-key the frame
        p = self._params()
        src = json.loads(p["source_frames"]) if isinstance(
            p.get("source_frames"), str) else p.get("source_frames", [])
        src_key = (src[0] if src else p.get("source_key", ""))
        src_key = src_key.get("name") if isinstance(src_key, dict) else src_key
        fr = DKV[src_key]
        dest = p.get("destination_frame") or src_key
        fr.key = dest
        DKV.put(dest, fr)
        self._reply({"__meta": {"schema_type": "ParseV3"},
                     "destination_frame": {"name": dest},
                     "rows": fr.nrows})

    def r_frames(self):
        self._reply(schemas.frames_list_v3(DKV))

    def r_frame(self, key):
        fr = DKV[key]
        if not isinstance(fr, Frame):
            raise KeyError(f"{key} is not a frame")
        self._reply({"__meta": {"schema_type": "FramesV3"},
                     "frames": [schemas.frame_v3(key, fr)]})

    def r_frame_delete(self, key):
        DKV.remove(key)
        self._reply({"__meta": {"schema_type": "FramesV3"}})

    def r_models(self):
        self._reply(schemas.models_list_v3(DKV))

    def r_model(self, key):
        m = DKV[key]
        if not isinstance(m, Model):
            raise KeyError(f"{key} is not a model")
        self._reply({"__meta": {"schema_type": "ModelsV3"},
                     "models": [schemas.model_v3(m)]})

    def r_model_delete(self, key):
        DKV.remove(key)
        self._reply({"__meta": {"schema_type": "ModelsV3"}})

    def r_train(self, algo):
        p = self._params()
        cls = _algo_registry().get(algo.lower())
        if cls is None:
            raise KeyError(f"unknown algorithm {algo!r}")
        frame = DKV[p.pop("training_frame")]
        y = p.pop("response_column", None)
        x = p.pop("x", None)
        if isinstance(x, str):
            x = json.loads(x)
        valid = p.pop("validation_frame", None)
        vframe = DKV[valid] if valid else None
        kwargs = {}
        defaults = cls.defaults()
        for k, v in p.items():
            if k not in defaults:
                continue
            d = defaults[k]
            if isinstance(v, str):
                if isinstance(d, bool):
                    v = v.lower() in ("1", "true", "yes")
                elif isinstance(d, int) and not isinstance(d, bool):
                    v = int(float(v))
                elif isinstance(d, float):
                    v = float(v)
                elif isinstance(d, (list, tuple)) or v.startswith("["):
                    v = json.loads(v)
            kwargs[k] = v
        builder = cls(**kwargs)

        job = Job(f"{algo} via REST", key=f"job_{uuid.uuid4().hex[:12]}")

        def driver(j: Job):
            m = builder.train(x=x, y=y, training_frame=frame,
                              validation_frame=vframe)
            j.dest_key = m.key
            return m

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "ModelBuildersV3"},
                     "job": schemas.job_v3(job.key, job)})

    def r_job(self, key):
        job = DKV[key]
        self._reply({"__meta": {"schema_type": "JobsV3"},
                     "jobs": [schemas.job_v3(key, job)]})

    def r_job_cancel(self, key):
        DKV[key].cancel()
        self._reply({"__meta": {"schema_type": "JobsV3"}})

    def r_predict(self, model_key, frame_key):
        m, fr = DKV[model_key], DKV[frame_key]
        pred = m.predict(fr)
        dest = f"prediction_{uuid.uuid4().hex[:8]}"
        pred.key = dest
        DKV.put(dest, pred)
        self._reply({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
                     "predictions_frame": {"name": dest},
                     "model_metrics": []})

    def r_rapids(self):
        p = self._params()
        from h2o3_tpu.rapids import rapids
        res = rapids(p["ast"])
        if isinstance(res, Frame):
            key = p.get("id") or f"rapids_{uuid.uuid4().hex[:8]}"
            res.key = key
            DKV.put(key, res)
            self._reply({"__meta": {"schema_type": "RapidsFrameV3"},
                         "key": {"name": key}})
        elif isinstance(res, (int, float)):
            self._reply({"__meta": {"schema_type": "RapidsNumberV3"},
                         "scalar": schemas._clean(res)})
        else:
            self._reply({"__meta": {"schema_type": "RapidsStringV3"},
                         "string": str(res)})

    def r_grid(self, algo):
        p = self._params()
        cls = _algo_registry().get(algo.lower())
        if cls is None:
            raise KeyError(f"unknown algorithm {algo!r}")
        from h2o3_tpu.orchestration import GridSearch
        hyper = p.pop("hyper_parameters")
        if isinstance(hyper, str):
            hyper = json.loads(hyper)
        criteria = p.pop("search_criteria", None)
        if isinstance(criteria, str):
            criteria = json.loads(criteria)
        frame = DKV[p.pop("training_frame")]
        y = p.pop("response_column", None)
        gs = GridSearch(cls, hyper, grid_id=p.pop("grid_id", None),
                        search_criteria=criteria)
        job = Job(f"grid {algo} via REST")

        def driver(j: Job):
            g = gs.train(y=y, training_frame=frame)
            j.dest_key = g.grid_id
            return g

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "GridSearchV99"},
                     "job": schemas.job_v3(job.key, job)})

    def r_grid_get(self, key):
        g = DKV[key]
        self._reply({"__meta": {"schema_type": "GridSchemaV99"},
                     "grid_id": {"name": g.grid_id},
                     "model_ids": [{"name": k} for k in g.model_ids],
                     "failure_details": [d for _, d in g.failures]})

    def r_automl(self):
        p = self._params()
        from h2o3_tpu.orchestration import AutoML
        spec = p.get("build_control", {})
        if isinstance(spec, str):
            spec = json.loads(spec)
        # h2o-py nests budgets under build_control.stopping_criteria; flat
        # fields win when both are present
        crit = dict(spec.get("stopping_criteria") or {})
        crit.update({k: p[k] for k in ("max_models", "max_runtime_secs",
                                       "seed") if k in p})
        frame = DKV[p.pop("training_frame")]
        y = p.pop("response_column", None)
        aml = AutoML(max_models=int(crit.get("max_models", 0) or 0),
                     max_runtime_secs=float(crit.get("max_runtime_secs", 0) or 0),
                     nfolds=int(p.get("nfolds", spec.get("nfolds", 5)) or 5),
                     seed=int(crit.get("seed", -1) or -1))
        job = Job("AutoML via REST")

        def driver(j: Job):
            leader = aml.train(y=y, training_frame=frame)
            j.dest_key = leader.key if leader else None
            return aml

        job.run(driver, background=True)
        self._reply({"__meta": {"schema_type": "AutoMLBuilderV99"},
                     "job": schemas.job_v3(job.key, job)})

    def r_shutdown(self):
        self._reply({"__meta": {"schema_type": "ShutdownV3"}})
        threading.Thread(target=self.server.shutdown, daemon=True).start()

    def r_gc(self):
        import gc
        gc.collect()
        self._reply({"__meta": {"schema_type": "GarbageCollectV3"}})

    # -- observability (reference: TimelineHandler, JStackHandler,
    #    ProfilerHandler, WaterMeter* behind /3/Timeline,/3/JStack,
    #    /3/Profiler,/3/WaterMeterCpuTicks,/3/WaterMeterIo) -----------------

    def r_timeline(self):
        from h2o3_tpu.utils.timeline import TIMELINE
        self._reply({"__meta": {"schema_type": "TimelineV3"},
                     "events": TIMELINE.snapshot()})

    def r_jstack(self):
        from h2o3_tpu.utils.timeline import jstack
        self._reply({"__meta": {"schema_type": "JStackV3"},
                     "traces": jstack()})

    def r_profiler(self):
        # reference: ProfileCollectorTask samples stacks `depth` times
        import time as _t
        from h2o3_tpu.utils.timeline import jstack
        p = self._params()
        samples = max(1, min(int(p.get("depth", 5)), 50))
        counts: dict[str, int] = {}
        for _ in range(samples):
            for tr in jstack():
                counts[tr["stack"]] = counts.get(tr["stack"], 0) + 1
            _t.sleep(0.01)
        entries = sorted(counts.items(), key=lambda kv: -kv[1])
        self._reply({"__meta": {"schema_type": "ProfilerV3"},
                     "stacktraces": [s for s, _ in entries],
                     "counts": [c for _, c in entries]})

    def r_cpu_ticks(self):
        from h2o3_tpu.utils.timeline import cpu_ticks
        self._reply({"__meta": {"schema_type": "WaterMeterCpuTicksV3"},
                     "cpu_ticks": cpu_ticks()})

    def r_io_meter(self):
        from h2o3_tpu.utils.timeline import io_stats
        self._reply({"__meta": {"schema_type": "WaterMeterIoV3"},
                     "persist_stats": io_stats()})

    def r_flow(self):
        # reference: h2o-web Flow notebook served from the node at /
        from h2o3_tpu.api.flow import FLOW_HTML
        body = FLOW_HTML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def r_logs(self):
        # reference: LogsHandler /3/Logs/nodes/{n}/files/{name}
        import logging
        self._reply({"__meta": {"schema_type": "LogsV3"},
                     "log": "\n".join(
                         h.format(r) if hasattr(h, "format") else str(r)
                         for h in logging.getLogger("h2o3_tpu").handlers
                         for r in getattr(h, "buffer", []))})


_ROUTES = [
    (r"/3/Cloud", "GET", _Handler.r_cloud),
    (r"/3/About", "GET", _Handler.r_about),
    (r"/3/ImportFiles", "GET", _Handler.r_import),
    (r"/3/ImportFiles", "POST", _Handler.r_import),
    (r"/3/Parse", "POST", _Handler.r_parse),
    (r"/3/Frames", "GET", _Handler.r_frames),
    (r"/3/Frames/([^/]+)", "GET", _Handler.r_frame),
    (r"/3/Frames/([^/]+)", "DELETE", _Handler.r_frame_delete),
    (r"/3/Models", "GET", _Handler.r_models),
    (r"/3/Models/([^/]+)", "GET", _Handler.r_model),
    (r"/3/Models/([^/]+)", "DELETE", _Handler.r_model_delete),
    (r"/3/ModelBuilders/([^/]+)", "POST", _Handler.r_train),
    (r"/3/Jobs/([^/]+)", "GET", _Handler.r_job),
    (r"/3/Jobs/([^/]+)/cancel", "POST", _Handler.r_job_cancel),
    (r"/3/Predictions/models/([^/]+)/frames/([^/]+)", "POST", _Handler.r_predict),
    (r"/99/Rapids", "POST", _Handler.r_rapids),
    (r"/99/Grid/([^/]+)", "POST", _Handler.r_grid),
    (r"/99/Grids/([^/]+)", "GET", _Handler.r_grid_get),
    (r"/99/AutoMLBuilder", "POST", _Handler.r_automl),
    (r"/3/Shutdown", "POST", _Handler.r_shutdown),
    (r"/3/GarbageCollect", "POST", _Handler.r_gc),
    (r"/3/Timeline", "GET", _Handler.r_timeline),
    (r"/3/JStack", "GET", _Handler.r_jstack),
    (r"/3/Profiler", "GET", _Handler.r_profiler),
    (r"/3/WaterMeterCpuTicks/\d+", "GET", _Handler.r_cpu_ticks),
    (r"/3/WaterMeterIo", "GET", _Handler.r_io_meter),
    (r"/3/Logs", "GET", _Handler.r_logs),
    (r"/", "GET", _Handler.r_flow),
    (r"/flow/index\.html", "GET", _Handler.r_flow),
]


class H2OServer:
    """Embeddable REST server (reference: ``water.H2OApp`` + Jetty)."""

    def __init__(self, port: int = 54321, host: str = "127.0.0.1"):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = host, self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "H2OServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(port: int = 54321, host: str = "127.0.0.1") -> H2OServer:
    """h2o-py surface: ``h2o.init()`` boots a node and its REST server."""
    return H2OServer(port=port, host=host).start()
