"""Persistence: binary model/frame save-load, exports, job recovery.

Reference: ``water/persist/PersistManager.java`` (URI-routed backends),
``water/fvec/persist/FramePersist.java`` (binary frame snapshots),
``water/api/ModelsHandler`` import/export, ``hex/faulttolerance/Recovery.java``
(auto-resume of long grid/AutoML jobs from a recovery dir).
"""

from h2o3_tpu.persist.frame_io import export_file, load_frame, save_frame
from h2o3_tpu.persist.model_io import load_model, save_model
from h2o3_tpu.persist.recovery import Recovery

__all__ = ["export_file", "load_frame", "save_frame",
           "load_model", "save_model", "Recovery"]
