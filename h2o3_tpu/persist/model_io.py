"""Binary model save/load.

Reference: ``hex/Model`` binary export via ``water/api/ModelsHandler``
import/export (Iced serialization of the whole model object). Here the model
object graph (params, DataInfo, output arrays, metrics) is pickled with every
``jax.Array`` converted to host numpy first — scoring code uses ``jnp`` ops
which accept numpy inputs, so a loaded model scores immediately and XLA
re-uploads constants on first use. One file, any mesh size.
"""

from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from h2o3_tpu.utils import telemetry as _tm

_MAGIC = b"h2o3_tpu-model-v1\n"


def _to_host(obj, _depth=0):
    if _depth > 12:
        return obj
    if isinstance(obj, jax.Array):
        return np.asarray(jax.device_get(obj))
    if isinstance(obj, dict):
        return {k: _to_host(v, _depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v, _depth + 1) for v in obj)
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        for k, v in vars(obj).items():
            setattr(obj, k, _to_host(v, _depth + 1))
        return obj
    return obj


def host_copy(model):
    """Deep copy with every jax.Array converted to host numpy — the ONE
    serializable form shared by binary saves and MOJO payloads."""
    import copy
    return _to_host(copy.deepcopy(model))


def save_model(model, path: str) -> str:
    """Write a binary model file; returns the path (h2o-py:
    ``h2o.save_model``)."""
    m = host_copy(model)
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        pickle.dump(m, fh)
    try:
        size = os.path.getsize(path)
        _tm.PERSIST_WRITE_BYTES.labels(what="model").inc(size)
        # the serialized size is the ground-truth artifact measure; stash
        # it on the live model so /3/Memory and ModelsV3 can report it
        model.artifact_file_bytes = size
    except OSError:
        pass
    return path


def load_model(path: str):
    """Load a saved model and re-register it in the DKV (h2o-py:
    ``h2o.load_model``)."""
    with open(path, "rb") as fh:
        if fh.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path} is not a saved model")
        m = pickle.load(fh)
    try:
        m.artifact_file_bytes = os.path.getsize(path)
        _tm.PERSIST_READ_BYTES.labels(what="model").inc(m.artifact_file_bytes)
    except OSError:
        pass
    from h2o3_tpu.utils.registry import DKV
    DKV.put(m.key, m)
    return m
