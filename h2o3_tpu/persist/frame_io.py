"""Binary frame snapshots + CSV export.

Reference: ``water/fvec/persist/FramePersist.java`` writes each Vec's chunks
plus a metadata record; ``h2o.export_file`` streams CSV. Here a frame snapshot
is one ``.npz`` (columns gathered to host) plus a small JSON header with
types/domains — the device relayout happens on load, so a snapshot taken on an
8-chip mesh restores onto any mesh size.
"""

from __future__ import annotations

import json
import os

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.utils import telemetry as _tm

_MAGIC = "h2o3_tpu-frame-v1"


def snapshot_bytes(path: str) -> int:
    """On-disk size of a frame snapshot — what the Cleaner registers under
    the ``spilled`` kind so `/3/Memory` reconciles across a sweep."""
    total = 0
    for name in ("columns.npz", "frame.json"):
        try:
            total += os.path.getsize(os.path.join(path, name))
        except OSError:
            pass
    return total


_snapshot_bytes = snapshot_bytes


def save_frame(frame: Frame, path: str) -> str:
    """Write a binary snapshot; returns the path (reference:
    ``FramePersist.saveTo``)."""
    os.makedirs(path, exist_ok=True)
    meta = {"magic": _MAGIC, "nrows": frame.nrows, "names": frame.names,
            "types": [v.type.name for v in frame.vecs],
            "domains": [list(v.domain) if v.domain else None for v in frame.vecs]}
    arrays = {}
    for i, v in enumerate(frame.vecs):
        if v.type is VecType.TIME:
            arrays[f"c{i}"] = v.to_numpy()                 # exact f64 ms
        elif v.type.on_device:
            arrays[f"c{i}"] = v.to_numpy()
        else:
            arrays[f"c{i}"] = np.asarray(["" if s is None else s
                                          for s in v.host_values])
            arrays[f"m{i}"] = np.array([s is None for s in v.host_values])
    np.savez_compressed(os.path.join(path, "columns.npz"), **arrays)
    with open(os.path.join(path, "frame.json"), "w") as fh:
        json.dump(meta, fh)
    _tm.PERSIST_WRITE_BYTES.labels(what="frame").inc(_snapshot_bytes(path))
    return path


def load_frame(path: str, key: str | None = None) -> Frame:
    """Restore a snapshot onto the current mesh (reference:
    ``FramePersist.loadFrom``)."""
    with open(os.path.join(path, "frame.json")) as fh:
        meta = json.load(fh)
    if meta.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not a frame snapshot")
    data = np.load(os.path.join(path, "columns.npz"), allow_pickle=False)
    vecs = []
    for i, (tname, dom) in enumerate(zip(meta["types"], meta["domains"])):
        t = VecType[tname]
        arr = data[f"c{i}"]
        if t is VecType.CAT:
            vecs.append(Vec.from_numpy(arr.astype(np.int32), type=t,
                                       domain=dom or []))
        elif t is VecType.TIME:
            from h2o3_tpu.rapids.timeops import ms_to_datetime64
            vecs.append(Vec.from_numpy(ms_to_datetime64(arr.astype(np.float64)),
                                       type=t))
        elif t.on_device:
            vecs.append(Vec.from_numpy(arr, type=t))
        else:
            na = data[f"m{i}"]
            vals = np.array([None if m else str(s) for s, m in zip(arr, na)],
                            dtype=object)
            vecs.append(Vec(None, t, meta["nrows"], host_values=vals))
    from h2o3_tpu.utils.registry import DKV
    fr = Frame(meta["names"], vecs, key=key)
    if key:
        DKV.put(key, fr)
    _tm.PERSIST_READ_BYTES.labels(what="frame").inc(_snapshot_bytes(path))
    return fr


def export_file(frame: Frame, path: str, header: bool = True, sep: str = ",") -> str:
    """CSV export (reference: ``h2o.export_file`` → ``Frame.export``);
    cloud URIs upload through the persist backends (PersistManager)."""
    df = frame.to_pandas()
    scheme = path.split("://", 1)[0].lower() if "://" in path else ""
    if scheme in ("s3", "s3a", "s3n", "gs", "gcs", "hdfs"):
        from h2o3_tpu.persist.cloud import MANAGER
        data = df.to_csv(index=False, header=header, sep=sep).encode()
        MANAGER.put(path, data)
        _tm.PERSIST_WRITE_BYTES.labels(what="csv").inc(len(data))
        return path
    df.to_csv(path, index=False, header=header, sep=sep)
    try:
        _tm.PERSIST_WRITE_BYTES.labels(what="csv").inc(os.path.getsize(path))
    except OSError:
        pass
    return path
