"""Job-level auto-recovery for long-running grid/AutoML searches.

Reference: ``hex/faulttolerance/Recovery.java:21-50`` — before a long job
starts, its params and training frame are written to ``-auto_recovery_dir``;
every model built is appended; on restart the job reloads the snapshot and
resumes where it stopped (already-built hyperparameter points are skipped).
"""

from __future__ import annotations

import json
import os

from h2o3_tpu.persist.frame_io import load_frame, save_frame
from h2o3_tpu.persist.model_io import load_model, save_model


def combo_key(combo: dict) -> str:
    """Canonical form of a hyperparameter point — the ONE spelling shared by
    recovery skip-detection and grid model-id tags (divergence would break
    resume)."""
    return json.dumps(combo, sort_keys=True, default=str)


class Recovery:
    """Checkpoint directory for a resumable search job.

    Usage (mirrors the reference's Recovery<Grid> lifecycle)::

        rec = Recovery(dir)
        rec.begin(params={...}, training_frame=f)  # no-op if resuming
        for combo in combos:
            if rec.is_done(combo): continue        # already built pre-crash
            model = build(combo)
            rec.model_built(combo, model)
        rec.done()
    """

    def __init__(self, recovery_dir: str):
        self.dir = recovery_dir
        os.makedirs(recovery_dir, exist_ok=True)
        self._state_path = os.path.join(recovery_dir, "recovery.json")
        self._state = self._load_state()

    def _load_state(self) -> dict:
        if os.path.exists(self._state_path):
            with open(self._state_path) as fh:
                return json.load(fh)
        return {"params": None, "built": [], "done": False}

    def _flush(self) -> None:
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh)
        os.replace(tmp, self._state_path)   # atomic: crash-safe snapshot

    # -- lifecycle -----------------------------------------------------------

    @property
    def resuming(self) -> bool:
        return self._state["params"] is not None and not self._state["done"]

    def begin(self, params: dict, training_frame=None) -> None:
        if self.resuming:
            return
        self._state = {"params": params, "built": [], "done": False}
        if training_frame is not None:
            save_frame(training_frame, os.path.join(self.dir, "training_frame"))
        self._flush()

    def training_frame(self):
        p = os.path.join(self.dir, "training_frame")
        return load_frame(p) if os.path.exists(p) else None

    @property
    def params(self) -> dict | None:
        return self._state["params"]

    def _key(self, combo: dict) -> str:
        return combo_key(combo)

    def _done_keys(self) -> set[str]:
        if getattr(self, "_done_cache", None) is None or \
                len(self._done_cache) != len(self._state["built"]):
            self._done_cache = {b["combo"] for b in self._state["built"]}
        return self._done_cache

    def is_done(self, combo: dict) -> bool:
        return self._key(combo) in self._done_keys()

    def model_built(self, combo: dict, model) -> None:
        fname = f"model_{len(self._state['built'])}.bin"
        save_model(model, os.path.join(self.dir, fname))
        self._state["built"].append({"combo": self._key(combo), "file": fname})
        self._done_cache = None
        self._flush()

    def built_models(self) -> list:
        return [load_model(os.path.join(self.dir, b["file"]))
                for b in self._state["built"]]

    def done(self) -> None:
        self._state["done"] = True
        self._flush()
