"""Job-level auto-recovery for long-running searches AND single builds.

Reference: ``hex/faulttolerance/Recovery.java:21-50`` — before a long job
starts, its params and training frame are written to ``-auto_recovery_dir``;
every model built is appended; on restart the job reloads the snapshot and
resumes where it stopped (already-built hyperparameter points are skipped).

Two granularities live here:

- :class:`Recovery` — grid/AutoML combo skipping (one file per built model).
- :class:`BuildRecovery` — ONE long iterative build (GBM/XGBoost/DL) under
  ``auto_recovery_dir``: the builder snapshots a partial model every K
  trees/epochs (``H2O3TPU_CHECKPOINT_EVERY``) through the SAME artifact
  format ``checkpoint=`` resume consumes, so a killed process restarts from
  the last snapshot instead of tree 0 — and, because tree PRNG keys are
  derived per-tree from the base seed, the resumed GBM's final trees are
  bit-identical to an uninterrupted run (docs/RELIABILITY.md).
"""

from __future__ import annotations

import json
import os

from h2o3_tpu.persist.frame_io import load_frame, save_frame
from h2o3_tpu.persist.model_io import load_model, save_model


def checkpoint_every(default: int = 10) -> int:
    """Snapshot cadence in trees/epochs (``H2O3TPU_CHECKPOINT_EVERY``)."""
    try:
        k = int(os.environ.get("H2O3TPU_CHECKPOINT_EVERY", "") or default)
    except ValueError:
        k = default
    return max(k, 1)


def _params_fingerprint(params: dict) -> str:
    """Canonical param identity for snapshot compatibility — transient keys
    (the recovery dir itself, a resolved checkpoint handle, model_id) are
    excluded so a resume with the same *training* configuration matches.
    Callables (custom_metric_func lambdas) fingerprint by NAME, not repr:
    ``str(fn)`` embeds a process-specific address, which would make every
    restarted process silently fail the match and rebuild from tree 0."""
    skip = {"auto_recovery_dir", "checkpoint", "model_id"}

    def _stable(v):
        if callable(v):
            return f"<callable {getattr(v, '__qualname__', type(v).__name__)}>"
        return v

    return json.dumps({k: _stable(v) for k, v in params.items()
                       if k not in skip}, sort_keys=True, default=str)


class BuildRecovery:
    """Auto-checkpoint directory for one resumable model build.

    Lifecycle (driven by ``ModelBuilder.train`` when ``auto_recovery_dir``
    is set)::

        rec = BuildRecovery(dir)
        snap = rec.load_snapshot(params)      # partial model or None
        # ... build resumes via the ordinary checkpoint= machinery ...
        rec.snapshot(partial_model, progress=K, target=ntrees)  # every K
        rec.complete()                        # success: snapshot removed
    """

    STATE = "build_recovery.json"
    MODEL = "model_snapshot.bin"

    def __init__(self, recovery_dir: str):
        self.dir = recovery_dir
        os.makedirs(recovery_dir, exist_ok=True)
        self._state_path = os.path.join(recovery_dir, self.STATE)
        self._model_path = os.path.join(recovery_dir, self.MODEL)

    def load_snapshot(self, params: dict):
        """The last partial-model snapshot, or None when there is nothing
        to resume: no snapshot, a finished build (progress >= target — a
        checkpoint that cannot legally seed ``ntrees must exceed``
        validation), or a snapshot taken under different training params
        (resuming it would silently train a different model)."""
        if not (os.path.exists(self._state_path)
                and os.path.exists(self._model_path)):
            return None
        try:
            with open(self._state_path) as fh:
                state = json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None
        if state.get("fingerprint") != _params_fingerprint(params):
            return None
        if int(state.get("progress", 0)) >= int(state.get("target", 1 << 62)):
            return None
        return load_model(self._model_path)

    def snapshot(self, model, progress: int, target: int) -> None:
        """Atomically persist a partial model + its progress marker: the
        model file lands via os.replace BEFORE the state file, so a crash
        mid-snapshot leaves either the previous consistent pair or the new
        model with the previous state (whose fingerprint still matches) —
        never a state pointing at a torn model file."""
        fingerprint = _params_fingerprint(model.params)
        # callable params (custom_metric_func lambdas/closures) don't pickle;
        # the snapshot drops them rather than failing a build that succeeds
        # without auto_recovery_dir — resume validates against the LIVE
        # builder's params, so the artifact never needs them
        clean = {k: v for k, v in model.params.items() if not callable(v)}
        orig_params = model.params
        if len(clean) != len(orig_params):
            model.params = clean
        try:
            tmp = self._model_path + ".tmp"
            save_model(model, tmp)
            os.replace(tmp, self._model_path)
        finally:
            model.params = orig_params
        doc = {"fingerprint": fingerprint,
               "progress": int(progress), "target": int(target),
               "model_key": model.key}
        tmp_s = self._state_path + ".tmp"
        with open(tmp_s, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp_s, self._state_path)

    def complete(self) -> None:
        """Successful build: retire the snapshot so a fresh run with the
        same dir trains from scratch instead of tripping resume checks."""
        for p in (self._state_path, self._model_path):
            try:
                os.remove(p)
            except OSError:
                pass


def combo_key(combo: dict) -> str:
    """Canonical form of a hyperparameter point — the ONE spelling shared by
    recovery skip-detection and grid model-id tags (divergence would break
    resume)."""
    return json.dumps(combo, sort_keys=True, default=str)


class Recovery:
    """Checkpoint directory for a resumable search job.

    Usage (mirrors the reference's Recovery<Grid> lifecycle)::

        rec = Recovery(dir)
        rec.begin(params={...}, training_frame=f)  # no-op if resuming
        for combo in combos:
            if rec.is_done(combo): continue        # already built pre-crash
            model = build(combo)
            rec.model_built(combo, model)
        rec.done()
    """

    def __init__(self, recovery_dir: str):
        self.dir = recovery_dir
        os.makedirs(recovery_dir, exist_ok=True)
        self._state_path = os.path.join(recovery_dir, "recovery.json")
        self._state = self._load_state()

    def _load_state(self) -> dict:
        if os.path.exists(self._state_path):
            with open(self._state_path) as fh:
                return json.load(fh)
        return {"params": None, "built": [], "done": False}

    def _flush(self) -> None:
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh)
        os.replace(tmp, self._state_path)   # atomic: crash-safe snapshot

    # -- lifecycle -----------------------------------------------------------

    @property
    def resuming(self) -> bool:
        return self._state["params"] is not None and not self._state["done"]

    def begin(self, params: dict, training_frame=None) -> None:
        if self.resuming:
            return
        self._state = {"params": params, "built": [], "done": False}
        if training_frame is not None:
            save_frame(training_frame, os.path.join(self.dir, "training_frame"))
        self._flush()

    def training_frame(self):
        p = os.path.join(self.dir, "training_frame")
        return load_frame(p) if os.path.exists(p) else None

    @property
    def params(self) -> dict | None:
        return self._state["params"]

    def _key(self, combo: dict) -> str:
        return combo_key(combo)

    def _done_keys(self) -> set[str]:
        if getattr(self, "_done_cache", None) is None or \
                len(self._done_cache) != len(self._state["built"]):
            self._done_cache = {b["combo"] for b in self._state["built"]}
        return self._done_cache

    def is_done(self, combo: dict) -> bool:
        return self._key(combo) in self._done_keys()

    def model_built(self, combo: dict, model) -> None:
        fname = f"model_{len(self._state['built'])}.bin"
        save_model(model, os.path.join(self.dir, fname))
        self._state["built"].append({"combo": self._key(combo), "file": fname})
        self._done_cache = None
        self._flush()

    def built_models(self) -> list:
        return [load_model(os.path.join(self.dir, b["file"]))
                for b in self._state["built"]]

    def done(self) -> None:
        self._state["done"] = True
        self._flush()
