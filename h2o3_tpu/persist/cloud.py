"""Cloud persist backends — S3 / GCS / HDFS-gateway over stdlib HTTP.

Reference: ``water/persist/PersistManager.java`` dispatches URI schemes to
``Persist`` implementations (``h2o-persist-s3``, ``h2o-persist-gcs``,
``h2o-persist-hdfs`` ship as optional modules on the AWS/GCS SDKs). This
build has no cloud SDKs and a zero-egress test image, so the backends speak
the services' plain HTTP protocols directly:

- **S3**: AWS Signature V4 (pure hashlib/hmac) against
  ``H2O3TPU_S3_ENDPOINT`` (default ``https://s3.<region>.amazonaws.com``),
  credentials from the standard ``AWS_ACCESS_KEY_ID``/
  ``AWS_SECRET_ACCESS_KEY`` env. Any S3-compatible store (minio, GCS
  interop, a test fake) works via the endpoint override — which is also how
  the offline tests drive a real signed round-trip without egress.
- **GCS**: JSON API upload/download with a bearer token from
  ``H2O3TPU_GCS_TOKEN``; ``H2O3TPU_GCS_ENDPOINT`` overrides the host.
- **HDFS**: WebHDFS REST (``H2O3TPU_WEBHDFS_ENDPOINT``), the httpfs
  gateway protocol.

``get(uri)``/``put(uri, data)`` are the whole SPI — frames parse through a
temp file; exports stream bytes up.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request


class PersistManager:
    """Scheme → backend registry (reference: PersistManager.I[] by scheme)."""

    def __init__(self):
        self._backends: dict[str, object] = {}

    def register(self, scheme: str, backend) -> None:
        self._backends[scheme.lower()] = backend

    def backend(self, uri: str):
        scheme = uri.split("://", 1)[0].lower()
        b = self._backends.get(scheme)
        if b is None:
            raise ValueError(f"no persist backend registered for "
                             f"{scheme}:// (have {sorted(self._backends)})")
        return b

    def get(self, uri: str) -> bytes:
        return self.backend(uri).get(uri)

    def put(self, uri: str, data: bytes) -> None:
        self.backend(uri).put(uri, data)

    def fetch_to_temp(self, uri: str) -> str:
        """Download to a temp file named like the object (parsers sniff the
        extension); caller unlinks."""
        import tempfile
        name = uri.rsplit("/", 1)[-1] or "object"
        suffix = os.path.splitext(name)[1] or ".csv"
        fd, tmp = tempfile.mkstemp(suffix=suffix)
        with os.fdopen(fd, "wb") as f:
            f.write(self.get(uri))
        return tmp


def _split_bucket_key(uri: str) -> tuple[str, str]:
    rest = uri.split("://", 1)[1]
    bucket, _, key = rest.partition("/")
    if not bucket or not key:
        raise ValueError(f"cloud URI needs bucket/key: {uri!r}")
    return bucket, key


class PersistS3:
    """SigV4-signed S3 REST (reference: ``water.persist.PersistS3``)."""

    def __init__(self, endpoint: str | None = None,
                 access_key: str | None = None,
                 secret_key: str | None = None, region: str | None = None):
        # overrides win; env is read PER CALL so configuration set after
        # import (tests, notebooks) takes effect
        self._endpoint, self._region = endpoint, region
        self._access_key, self._secret_key = access_key, secret_key

    @property
    def region(self) -> str:
        return self._region or os.environ.get("AWS_REGION", "us-east-1")

    @property
    def endpoint(self) -> str:
        return (self._endpoint or os.environ.get("H2O3TPU_S3_ENDPOINT")
                or f"https://s3.{self.region}.amazonaws.com")

    @property
    def access_key(self):
        return self._access_key or os.environ.get("AWS_ACCESS_KEY_ID")

    @property
    def secret_key(self):
        return self._secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY")

    # -- SigV4 (AWS General Reference, Signature Version 4) ------------------

    def _sign(self, method: str, path: str, payload: bytes) -> dict:
        if not self.access_key or not self.secret_key:
            raise ValueError(
                "S3 credentials missing: set AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY (and H2O3TPU_S3_ENDPOINT for "
                "S3-compatible stores)")
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(payload).hexdigest()
        canonical_headers = (f"host:{host}\n"
                             f"x-amz-content-sha256:{payload_hash}\n"
                             f"x-amz-date:{amz_date}\n")
        signed_headers = "host;x-amz-content-sha256;x-amz-date"
        canonical = "\n".join([method, urllib.parse.quote(path), "",
                               canonical_headers, signed_headers,
                               payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                             hashlib.sha256(canonical.encode()).hexdigest()])

        def hm(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(hm(hm(hm(b"AWS4" + self.secret_key.encode(), datestamp),
                     self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        auth = (f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={sig}")
        return {"Authorization": auth, "x-amz-date": amz_date,
                "x-amz-content-sha256": payload_hash}

    def _request(self, method: str, uri: str, data: bytes = b"") -> bytes:
        bucket, key = _split_bucket_key(uri)
        path = f"/{bucket}/{key}"
        headers = self._sign(method, path, data)
        # the request line must carry the SAME percent-encoding the
        # signature covered, or keys with spaces/non-ASCII get 403s
        req = urllib.request.Request(
            self.endpoint + urllib.parse.quote(path),
            data=data if method == "PUT" else None,
            method=method, headers=headers)
        with urllib.request.urlopen(req) as r:
            return r.read()

    def get(self, uri: str) -> bytes:
        return self._request("GET", uri)

    def put(self, uri: str, data: bytes) -> None:
        self._request("PUT", uri, data)


class PersistGCS:
    """GCS JSON-API backend (reference: ``h2o-persist-gcs``)."""

    def __init__(self, endpoint: str | None = None, token: str | None = None):
        self._endpoint, self._token = endpoint, token

    @property
    def endpoint(self) -> str:
        return (self._endpoint or os.environ.get("H2O3TPU_GCS_ENDPOINT")
                or "https://storage.googleapis.com")

    @property
    def token(self):
        return self._token or os.environ.get("H2O3TPU_GCS_TOKEN")

    def _headers(self) -> dict:
        if not self.token:
            raise ValueError("GCS token missing: set H2O3TPU_GCS_TOKEN (an "
                             "OAuth2 bearer token) and optionally "
                             "H2O3TPU_GCS_ENDPOINT")
        return {"Authorization": f"Bearer {self.token}"}

    def get(self, uri: str) -> bytes:
        bucket, key = _split_bucket_key(uri)
        url = (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        with urllib.request.urlopen(
                urllib.request.Request(url, headers=self._headers())) as r:
            return r.read()

    def put(self, uri: str, data: bytes) -> None:
        bucket, key = _split_bucket_key(uri)
        url = (f"{self.endpoint}/upload/storage/v1/b/{bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        req = urllib.request.Request(url, data=data, method="POST",
                                     headers=self._headers())
        urllib.request.urlopen(req).read()


class PersistWebHDFS:
    """WebHDFS/httpfs REST backend (reference: ``h2o-persist-hdfs`` — the
    SDK-free gateway protocol)."""

    def __init__(self, endpoint: str | None = None, user: str | None = None):
        self._endpoint, self._user = endpoint, user

    @property
    def endpoint(self):
        return (self._endpoint
                or os.environ.get("H2O3TPU_WEBHDFS_ENDPOINT"))

    @property
    def user(self) -> str:
        return self._user or os.environ.get("H2O3TPU_WEBHDFS_USER", "h2o")

    def _url(self, uri: str, op: str) -> str:
        if not self.endpoint:
            raise ValueError("set H2O3TPU_WEBHDFS_ENDPOINT "
                             "(http://namenode:9870) for hdfs:// access")
        path = uri.split("://", 1)[1]
        path = path.partition("/")[2] if "//" not in path else path
        return (f"{self.endpoint}/webhdfs/v1/{path}?op={op}"
                f"&user.name={self.user}")

    def get(self, uri: str) -> bytes:
        with urllib.request.urlopen(self._url(uri, "OPEN")) as r:
            return r.read()

    def put(self, uri: str, data: bytes) -> None:
        # WebHDFS CREATE is two-step: the namenode answers with a 307 to a
        # datanode, and urllib will not auto-redirect a PUT — ask for the
        # location explicitly and re-PUT there (httpfs gateways skip the
        # redirect and accept the first PUT)
        url = self._url(uri, "CREATE&overwrite=true&noredirect=true")
        req = urllib.request.Request(url, data=b"", method="PUT")
        try:
            with urllib.request.urlopen(req) as r:
                body = r.read()
                loc = r.headers.get("Location")
                if not loc and body:
                    import json as _json
                    try:
                        loc = _json.loads(body).get("Location")
                    except ValueError:
                        loc = None
        except urllib.error.HTTPError as e:
            if e.code != 307:
                raise
            loc = e.headers.get("Location")
        target = loc or url
        urllib.request.urlopen(urllib.request.Request(
            target, data=data, method="PUT")).read()


#: process-wide manager with the standard schemes (reference:
#: PersistManager's eager backend registration)
MANAGER = PersistManager()
for _scheme in ("s3", "s3a", "s3n"):
    MANAGER.register(_scheme, PersistS3())
for _scheme in ("gs", "gcs"):
    MANAGER.register(_scheme, PersistGCS())
MANAGER.register("hdfs", PersistWebHDFS())
