"""Serving schema — the request-sized input contract of a trained model.

Reference: ``hex.genmodel.GenModel`` exposes ``getNames``/``getDomainValues``
so external scorers (the EasyPredict wrapper, Steam, the REST scoring
servlets) can map a row of user values onto the model's training layout
without a Frame. Here the same contract is derived once per model and
reused by the scoring tier (:mod:`h2o3_tpu.serving.service`): ordered
feature columns, each numeric or categorical with its train-time domain.

Two derivation paths cover every servable family:

- models carrying a :class:`~h2o3_tpu.models.data_info.DataInfo` (GLM, DL,
  GAM, …) — ``cat_cols``/``cat_domains``/``num_cols``;
- tree ensembles (GBM/DRF/XGBoost/IF) — ``output["x_cols"]`` +
  ``output["feat_domains"]``.

Generic/MOJO wrappers unwrap to the decoded inner model. Models with
scoring-time preprocessors (TargetEncoder pipelines) are NOT servable here
— their transform is frame-shaped; ``/3/Predictions`` remains their path.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import CAT_NA, VecType
from h2o3_tpu.frame.vec import Vec


class NotServable(ValueError):
    """The model has no request-sized scoring contract (routes to HTTP 400)."""


def _unwrap(model):
    """Peel Generic → MojoModel → decoded inner model; the innermost object
    is the one whose feature metadata is real."""
    seen = 0
    while seen < 4:
        seen += 1
        mojo = (getattr(model, "output", None) or {}).get("mojo") \
            if hasattr(model, "output") else None
        if mojo is not None and hasattr(mojo, "_score_raw"):
            model = mojo
            continue
        inner = getattr(model, "_inner", None)
        if inner is not None and hasattr(inner, "_score_raw"):
            model = inner
            continue
        break
    return model


class ServingSchema:
    """Ordered (name, kind, domain) feature columns + row adaptation."""

    __slots__ = ("names", "cat_cols", "num_cols", "domains", "_level_maps")

    def __init__(self, names: list[str], cat_cols: list[str],
                 num_cols: list[str], domains: dict[str, tuple]):
        self.names = list(names)
        self.cat_cols = list(cat_cols)
        self.num_cols = list(num_cols)
        self.domains = dict(domains)
        # label -> code lookup per categorical column, built once: row
        # adaptation is on the request hot path
        self._level_maps = {c: {lvl: i for i, lvl in enumerate(dom)}
                            for c, dom in self.domains.items()}

    def to_dict(self) -> dict:
        return {"columns": [
            {"name": n, "type": "enum" if n in self._level_maps else "numeric",
             "domain": list(self.domains[n]) if n in self._level_maps else None}
            for n in self.names]}

    # -- request adaptation (host side) --------------------------------------

    def adapt_rows(self, rows, columns=None) -> tuple[np.ndarray, np.ndarray]:
        """JSON rows → ``(num [n, n_num] f32, cat [n, n_cat] i32)`` in schema
        order. ``rows`` is a list of dicts (column-keyed) or a list of lists
        ordered by ``columns`` (default: schema order). Missing values /
        unseen levels become NaN / -1 — exactly the NA codes training used."""
        if not isinstance(rows, (list, tuple)) or not rows:
            raise ValueError("rows must be a non-empty JSON array")
        n = len(rows)
        if isinstance(rows[0], dict):
            def cell(row, col):   # noqa: E306
                return row.get(col)
        else:
            order = list(columns) if columns else list(self.names)
            idx = {c: i for i, c in enumerate(order)}
            missing = [c for c in self.names if c not in idx]
            if missing:
                raise ValueError(f"rows lack model columns {missing}; "
                                 f"pass 'columns' naming the row order")
            def cell(row, col):   # noqa: E306
                i = idx[col]
                return row[i] if i < len(row) else None
        num = np.zeros((n, len(self.num_cols)), dtype=np.float32)
        cat = np.full((n, len(self.cat_cols)), CAT_NA, dtype=np.int32)
        for r, row in enumerate(rows):
            try:
                for j, c in enumerate(self.num_cols):
                    v = cell(row, c)
                    num[r, j] = np.nan if v is None or v == "" else float(v)
                for j, c in enumerate(self.cat_cols):
                    v = cell(row, c)
                    if v is None or v == "":
                        continue
                    code = self._level_maps[c].get(str(v))
                    if code is None and not isinstance(v, str):
                        # numeric payloads for enum columns are raw codes
                        # (the wire form genmodel's RowData also accepts);
                        # out-of-range codes are UNSEEN values → NA, same
                        # as an unknown label (silently clamping to the
                        # last level would fabricate a training category)
                        try:
                            code = int(v)
                        except (TypeError, ValueError):
                            code = None
                        if code is not None and not (
                                0 <= code < len(self.domains[c])):
                            code = None
                    cat[r, j] = CAT_NA if code is None else code
            except (TypeError, KeyError, IndexError, AttributeError) as e:
                # a bad cell (nested object, mixed list/dict rows) is a
                # CLIENT payload error — 400, never a 500/404 masquerade
                raise ValueError(
                    f"row {r} is malformed: {type(e).__name__}: {e}") \
                    from None
        return num, cat

    # -- frame reconstruction (traceable: called under jit) ------------------

    def build_frame(self, num, cat, nrows: int) -> Frame:
        """Columns → a Frame matching the training layout. ``num``/``cat``
        may be traced jax arrays — every constructor here is shape-only
        Python, so the compiled scorer re-runs this at trace time only."""
        names, vecs = [], []
        for j, c in enumerate(self.cat_cols):
            vecs.append(Vec(cat[:, j], VecType.CAT, nrows,
                            domain=self.domains[c]))
            names.append(c)
        for j, c in enumerate(self.num_cols):
            vecs.append(Vec(num[:, j], VecType.NUM, nrows))
            names.append(c)
        return Frame(names, vecs)


def serving_schema(model) -> ServingSchema:
    """Derive the model's request-sized input contract (raises
    :class:`NotServable` when none exists)."""
    target = _unwrap(model)
    if getattr(model, "preprocessors", None) or \
            getattr(target, "preprocessors", None):
        raise NotServable(
            "model has scoring-time preprocessors (frame-shaped transform); "
            "score it through /3/Predictions")
    out = getattr(target, "output", None) or {}
    di = getattr(target, "data_info", None)
    extra_num: list[str] = []
    oc = (getattr(target, "params", None) or {}).get("offset_column")
    if oc:
        extra_num.append(oc)
    if di is not None and getattr(di, "cat_cols", None) is not None:
        if out.get("sparse"):
            raise NotServable("sparse-trained GLM scores SparseFrame inputs; "
                              "no row-payload contract")
        cat_cols = list(di.cat_cols)
        num_cols = list(di.num_cols) + extra_num
        domains = dict(zip(di.cat_cols, di.cat_domains))
        names = cat_cols + num_cols
        return ServingSchema(names, cat_cols, num_cols, domains)
    if out.get("x_cols"):
        names = list(out["x_cols"]) + extra_num
        feat_domains = out.get("feat_domains") or {}
        cat_cols = [c for c in names if feat_domains.get(c)]
        num_cols = [c for c in names if not feat_domains.get(c)]
        domains = {c: tuple(feat_domains[c]) for c in cat_cols}
        return ServingSchema(names, cat_cols, num_cols, domains)
    raise NotServable(
        f"{type(target).__name__} carries neither a DataInfo nor x_cols "
        "feature metadata; no row-payload scoring contract")
