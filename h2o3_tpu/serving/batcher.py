"""Dynamic micro-batcher — coalesce concurrent score requests per model.

Reference (PAPERS.md, the TensorFlow-serving batching design): concurrent
small inference requests for one model enqueue; a short accumulation
window fuses them into ONE device dispatch and each caller gets back its
slice. Dispatch overhead (host→device transfer, executable launch, the
~40 ms tunneled round-trip on remote TPUs) is paid once per batch instead
of once per request — p50 moves by at most the window, throughput
multiplies under load.

The window: with no SLO configured it is the fixed
``H2O3TPU_SCORE_WINDOW_MS`` (default 1 ms) — resolved at batcher
CONSTRUCTION, not at module import, so late env changes and test
monkeypatching take effect (the graftlint ENV001 bug class). With an SLO
target set (``H2O3TPU_SCORE_SLO_MS`` / per-request ``slo_ms``) each
batch's window comes from the model's :class:`~h2o3_tpu.serving.slo.
SLOController` feedback loop instead — widened when queue depth grows,
narrowed when p99 headroom exists (docs/SERVING.md "SLO & replicas").
Either way the window closes EARLY when the queued rows fill the largest
batch bucket — a full bucket gains nothing by waiting. One daemon worker
thread per (model, replica) seat owns its queue; eviction stops the
thread.

Admission shedding rides here too: ``submit()`` asks the controller to
:meth:`~h2o3_tpu.serving.slo.SLOController.admit` BEFORE enqueueing, so
overload turns into an early 503 (``Shed``) instead of a timeout burned
inside the queue.

Tracing: the batch leader's request context is captured at enqueue, and
the worker adopts it — ``score:batch`` (rows/requests/bucket attrs) →
``score:dispatch`` (the compiled call) land in the leader's trace tree, so
``/3/Traces`` shows exactly how requests coalesced and where the batch
spent its time. Followers annotate their own request span with the batch
size they rode in.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from h2o3_tpu.serving.scorer import MAX_BUCKET
from h2o3_tpu.serving.slo import window_s_from_env
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils import tracing as _tr

#: a caller never blocks longer than this on its slice (seconds)
SCORE_TIMEOUT_S = float(os.environ.get("H2O3TPU_SCORE_TIMEOUT_S", "30"))  # graftlint: ok(ENV001 - tests monkeypatch this module attr; construction-time resolution would strand them)


class Evicted(RuntimeError):
    """The model lost residency between admission and dispatch (a racing
    eviction or key re-put). Transient by construction — the service layer
    re-admits and retries; it must never surface as a client 500."""


class _Pending:
    """One request's seat in the batch: inputs, completion event, slice."""

    __slots__ = ("num", "cat", "n", "event", "result", "error", "ctx",
                 "batch_rows", "batch_requests", "priority", "t_enq",
                 "queue_wait_s")

    def __init__(self, num: np.ndarray, cat: np.ndarray, n: int, ctx,
                 priority: int = 5):
        self.num = num
        self.cat = cat
        self.n = n
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.ctx = ctx               # leader's captured trace context (or None)
        self.batch_rows = 0
        self.batch_requests = 0
        self.priority = priority
        self.t_enq = time.monotonic()
        self.queue_wait_s: float | None = None


class ModelBatcher:
    """Per-(model, replica) request queue + dispatch worker.

    ``cache`` defaults to the entry's shared :class:`ScorerCache`; a
    replica seat passes its own so compiled executables live with the
    replica's slice lease. ``replica`` (a
    :class:`~h2o3_tpu.serving.replicas.ScoringReplica`) makes dispatches
    bind the replica's mesh and feeds its utilization accounting.
    """

    def __init__(self, entry, window_s: float | None = None, cache=None,
                 replica=None):
        self._entry = entry          # serving/service.py _Resident
        # resolved at CONSTRUCTION (not import): late env changes and
        # monkeypatch.setenv are honored — and the SLO controller derives
        # its base window through the same seam
        self._window = float(window_s) if window_s is not None \
            else window_s_from_env()
        self._cache = cache if cache is not None else entry.cache
        self._replica = replica
        label = f"score-{entry.key}" if replica is None \
            else f"score-{entry.key}@{replica.label}"
        self._cond = lockwitness.condition(
            "serving.batcher.ModelBatcher._cond")
        self._queue: list[_Pending] = []
        self._stopped = False
        self._dispatching = False    # a drained batch is on the device
        self._thread = threading.Thread(target=self._run, name=label,
                                        daemon=True)
        self._thread.start()

    # -- caller side ---------------------------------------------------------

    def submit(self, num: np.ndarray, cat: np.ndarray, n: int,
               priority: int = 5) -> _Pending:
        """Enqueue ``n`` rows; blocks until the batch containing them has
        dispatched and this request's slice is ready (or raises). With an
        SLO set, overload sheds HERE (:class:`~h2o3_tpu.serving.slo.Shed`)
        — before the rows ever enter the queue."""
        slo = getattr(self._entry, "slo", None)
        with self._cond:
            if self._stopped:
                raise Evicted(f"model {self._entry.key!r} was evicted")
            if slo is not None:
                # sheds by raising — the queue is untouched, the caller
                # gets a 503 + Retry-After in microseconds, not a timeout
                slo.admit(priority, sum(p.n for p in self._queue), n)
            # the request opening a fresh batch is its leader: capture the
            # REST root context so the batch/dispatch spans land in a trace
            ctx = _tr.TRACER.capture() if not self._queue else None
            p = _Pending(num, cat, n, ctx, priority=priority)
            self._queue.append(p)
            self._cond.notify_all()
        if not p.event.wait(SCORE_TIMEOUT_S):
            # withdraw from the queue so abandoned rows are not dispatched
            # to the device after the caller is gone — under overload that
            # would turn every timeout into wasted work plus a retry
            with self._cond:
                try:
                    self._queue.remove(p)
                    withdrawn = True
                except ValueError:
                    withdrawn = False   # already drained: the dispatch owns
                if withdrawn and p.ctx is not None:   # the ctx lifecycle
                    _tr.TRACER.release(p.ctx)
                    p.ctx = None
                self._cond.notify_all()    # let the worker re-arm now
            # an eviction may have raced the timeout: stop() already failed
            # this pending with Evicted — surface THAT (a retryable
            # residency loss), not a timeout blamed on the device
            if p.error is not None and isinstance(p.error, Evicted):
                raise p.error
            raise TimeoutError(
                f"scoring {self._entry.key!r} timed out after "
                f"{SCORE_TIMEOUT_S:.0f}s "
                + ("(batch never dispatched)" if withdrawn else
                   "(batch still on the device — likely a cold compile "
                   "or a wedged dispatch)"))
        if p.error is not None:
            raise p.error
        return p

    def busy(self) -> bool:
        """True while requests are queued or a batch is on the device —
        the residency layer must not evict a model mid-flight."""
        with self._cond:
            return bool(self._queue) or self._dispatching

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            victims = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        err = Evicted(f"model {self._entry.key!r} evicted mid-queue")
        for p in victims:
            self._fail(p, err)

    @staticmethod
    def _fail(p: _Pending, err: BaseException) -> None:
        if p.ctx is not None:
            _tr.TRACER.release(p.ctx)
            p.ctx = None
        p.error = err
        p.event.set()

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            finally:
                with self._cond:
                    self._dispatching = False

    def _collect_window_s(self, queued_rows: int) -> float:
        """This batch's accumulation window: the SLO controller's when a
        target is set (one control-law step per batch), else the fixed
        construction-time window — bit-identical PR 6 behavior."""
        slo = getattr(self._entry, "slo", None)
        if slo is not None and slo.active:
            return slo.window_s(queued_rows)
        return self._window

    def _collect(self) -> "list[_Pending] | None":
        """Block for the first request, then hold the accumulation window
        (early-out on a full max bucket), then drain the queue."""
        with self._cond:
            while True:
                # bounded wait + predicate recheck (graftlint WTX001): a
                # lost wakeup re-polls within a second instead of parking
                # the worker thread forever
                while not self._queue and not self._stopped:
                    self._cond.wait(timeout=1.0)
                if self._stopped:
                    return None
                window = self._collect_window_s(sum(p.n for p in self._queue))
                deadline = time.monotonic() + window
                while self._queue:
                    rows = sum(p.n for p in self._queue)
                    left = deadline - time.monotonic()
                    if left <= 0 or rows >= MAX_BUCKET or self._stopped:
                        break
                    self._cond.wait(left)
                if not self._queue:
                    continue     # every waiter withdrew (timeouts) — re-arm
                batch = self._queue[:]
                self._queue.clear()
                self._dispatching = True
                return batch

    def _dispatch(self, batch: list[_Pending]) -> None:
        entry = self._entry
        total = sum(p.n for p in batch)
        t_start = time.monotonic()
        for p in batch:
            p.queue_wait_s = max(t_start - p.t_enq, 0.0)
        leader_ctx = next((p.ctx for p in batch if p.ctx is not None), None)
        try:
            with _tr.TRACER.adopt(leader_ctx, "score:batch", kind="serving",
                                  attrs={"model": entry.key,
                                         "requests": len(batch),
                                         "rows": total}) as bspan:
                results = self._score_slices(batch, total, bspan)
        except Exception as e:   # noqa: BLE001 — every waiter must wake
            for p in batch:
                if p.ctx is leader_ctx:
                    p.ctx = None     # adopt() released the retention already
                self._fail(p, e)
            return
        wall = time.monotonic() - t_start
        slo = getattr(entry, "slo", None)
        if slo is not None:
            slo.record_dispatch(wall, total)
        if self._replica is not None:
            self._replica.record_dispatch(
                wall, total, max(p.queue_wait_s or 0.0 for p in batch))
        _tm.SCORE_BATCH_SIZE.observe(total)
        _tm.SCORE_BATCH_REQUESTS.observe(len(batch))
        for p, res in zip(batch, results):
            p.ctx = None             # retention released by adopt()
            p.result = res
            p.batch_rows = total
            p.batch_requests = len(batch)
            p.event.set()

    def _score_slices(self, batch: list[_Pending], total: int,
                      bspan) -> list[np.ndarray]:
        """Fuse the batch into bucket-padded arrays, dispatch (slicing into
        max-bucket chunks when oversized), hand each request its rows. A
        replica seat binds its slice mesh around compile + dispatch so the
        executables live (and rendezvous) on the replica's devices."""
        import contextlib

        entry = self._entry
        num = np.concatenate([p.num for p in batch], axis=0) \
            if len(batch) > 1 else batch[0].num
        cat = np.concatenate([p.cat for p in batch], axis=0) \
            if len(batch) > 1 else batch[0].cat
        if self._replica is not None and self._replica.mesh is not None:
            from h2o3_tpu.parallel.mesh import bind_mesh
            mesh_cm = bind_mesh(self._replica.mesh, rehome_models=False)
        else:
            mesh_cm = contextlib.nullcontext()
        outs: list[np.ndarray] = []
        start = 0
        with mesh_cm:
            while start < total:
                n = min(total - start, MAX_BUCKET)
                # cache-level selection so an ops-plane pin (recompile-storm
                # remediation) takes effect at the one serving call site
                bucket = self._cache.bucket_for(n)
                pnum = np.zeros((bucket, num.shape[1]), dtype=np.float32)
                pcat = np.full((bucket, cat.shape[1]), -1, dtype=np.int32)
                pnum[:n] = num[start:start + n]
                pcat[:n] = cat[start:start + n]
                scorer = self._cache.get(entry.model, entry.schema, bucket)
                if bspan is not None:
                    with _tr.TRACER.span("score:dispatch", kind="dispatch",
                                         attrs={"bucket": bucket, "rows": n,
                                                "mode": scorer.mode}):
                        raw = scorer.score(pnum, pcat)
                else:
                    raw = scorer.score(pnum, pcat)
                outs.append(raw[:n])
                start += n
        full = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        results, off = [], 0
        for p in batch:
            results.append(full[off:off + p.n])
            off += p.n
        return results
