"""SLO-adaptive batching controller + priority-based admission shedding.

ROADMAP item 2 ("latency-SLO-driven adaptive batching windows" +
"priority-based request shedding under overload"): the PR 6 micro-batcher
ran one fixed accumulation window (``H2O3TPU_SCORE_WINDOW_MS``) whatever
the load. This module replaces that constant with a per-model feedback
loop over a sliding latency ring, and turns hopeless requests away AT
ADMISSION instead of letting them burn their whole timeout inside the
batcher queue.

Controller algorithm (docs/SERVING.md "SLO & replicas"):

- The target is ``H2O3TPU_SCORE_SLO_MS`` (or a per-model override passed
  with the request — ``slo_ms`` on ``POST /3/Score``). **No target means
  no controller**: :meth:`SLOController.window_s` returns the fixed base
  window and :meth:`SLOController.admit` never sheds, so the tier degrades
  bit-identically to the PR 6 fixed-window path (pinned by test).
- Every completed request's end-to-end latency lands in a bounded ring;
  each batch collection reads the ring's p99 against the target:

  * ``p99 >= 0.9 x SLO`` — the window itself is now latency the budget
    cannot afford: **narrow hard** (x0.5).
  * queue depth grew past the last dispatch — demand outruns dispatch
    rate: **widen** (x1.25, capped at ``SLO/4``) so each dispatch
    amortizes over more rows.
  * ``p99 <= 0.5 x SLO`` — headroom: **narrow gently** (x0.9) back toward
    interactive latency; the floor is 1/16 of the base window.

- Shedding: the admission estimator multiplies the EMA dispatch wall by
  the dispatches queued ahead (queue depth over the max bucket) and
  compares it to the remaining SLO budget. A priority-``p`` request
  (0..9, default 5) is shed once the estimate exceeds ``(1 + p)`` SLO
  budgets — low-priority work is turned away first and earliest, with
  ``503 + Retry-After`` sized from the estimate, and the drop is
  accounted in ``h2o3_score_shed_total{reason,priority}`` instead of
  surfacing as an in-queue timeout minutes later.
"""

from __future__ import annotations

import math
import os

from h2o3_tpu.utils import lockwitness

#: priority scale: 0 (shed first) .. 9 (effectively never shed)
MIN_PRIORITY, MAX_PRIORITY, DEFAULT_PRIORITY = 0, 9, 5

#: latency samples the sliding ring keeps per model
RING_SIZE = 256


def window_s_from_env() -> float:
    """The base accumulation window, resolved AT CALL TIME (graftlint
    ENV001: a module-level read would freeze the env at import and
    silently ignore monkeypatch.setenv / late exports)."""
    try:
        return float(os.environ.get("H2O3TPU_SCORE_WINDOW_MS", "1.0")) / 1e3
    except ValueError:
        return 1e-3


def slo_ms_from_env() -> float | None:
    """Process-default latency target (``H2O3TPU_SCORE_SLO_MS``); None =
    no SLO = the PR 6 fixed-window behavior."""
    raw = os.environ.get("H2O3TPU_SCORE_SLO_MS", "").strip()
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        return None
    return ms if ms > 0 else None


def clamp_priority(priority) -> int:
    if priority is None:
        return DEFAULT_PRIORITY
    try:
        return max(MIN_PRIORITY, min(MAX_PRIORITY, int(priority)))
    except (TypeError, ValueError):
        return DEFAULT_PRIORITY


class Shed(RuntimeError):
    """Admission refused by the SLO estimator: the queue ahead cannot be
    served inside this priority's budget. Maps to ``503 + Retry-After``
    at the REST layer — early, cheap, and accounted — instead of a
    timeout burned inside the batcher."""

    def __init__(self, msg: str, priority: int, reason: str = "overload",
                 retry_after_ms: int = 1000):
        super().__init__(msg)
        self.priority = priority
        self.reason = reason
        self.retry_after_ms = retry_after_ms


class LatencyRing:
    """Bounded ring of recent end-to-end request latencies (seconds) with
    percentile reads — the controller's feedback signal."""

    __slots__ = ("_buf", "_size", "_next", "_count", "_lock")

    def __init__(self, size: int = RING_SIZE):
        self._size = max(int(size), 8)
        self._buf: list[float] = [0.0] * self._size
        self._next = 0
        self._count = 0
        self._lock = lockwitness.lock("serving.slo.LatencyRing._lock")

    def record(self, latency_s: float) -> None:
        v = float(latency_s)
        if not math.isfinite(v) or v < 0.0:
            # a NaN in the buffer poisons every percentile read (sorted()
            # with NaN is partial order — the controller would steer the
            # window off garbage); drop and account instead
            from h2o3_tpu.utils.telemetry import METRICS
            METRICS.reject("latency_ring")
            return
        with self._lock:
            self._buf[self._next] = v
            self._next = (self._next + 1) % self._size
            self._count += 1

    def percentile(self, p: float) -> float | None:
        """p in [0, 100]; None until at least 8 samples landed (a cold
        ring must not steer the window)."""
        with self._lock:
            n = min(self._count, self._size)
            if n < 8:
                return None
            vals = sorted(self._buf[:n])
        k = min(n - 1, max(0, int(math.ceil(p / 100.0 * n)) - 1))
        return vals[k]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


class SLOController:
    """Per-model feedback loop: latency ring -> collect window, plus the
    shedding admission estimator. Shared by every replica seat of one
    model so the ring sees the model's whole traffic."""

    def __init__(self, base_window_s: float | None = None,
                 slo_ms: float | None = None, max_bucket: int | None = None):
        if base_window_s is None:
            base_window_s = window_s_from_env()
        if slo_ms is None:
            slo_ms = slo_ms_from_env()
        if max_bucket is None:
            from h2o3_tpu.serving.scorer import MAX_BUCKET
            max_bucket = MAX_BUCKET
        self.base_window_s = float(base_window_s)
        self.max_bucket = int(max_bucket)
        self._lock = lockwitness.lock("serving.slo.SLOController._lock")
        self._slo_ms = float(slo_ms) if slo_ms else None
        self._window = self.base_window_s
        self._ring = LatencyRing()
        self._ema_dispatch_s: float | None = None
        self._last_queue_rows = 0
        self.shed_count = 0
        self.widened = 0
        self.narrowed = 0

    # -- target --------------------------------------------------------------

    @property
    def slo_ms(self) -> float | None:
        with self._lock:
            return self._slo_ms

    @property
    def active(self) -> bool:
        """True when a target is set — False IS the PR 6 fixed window."""
        return self.slo_ms is not None

    def set_target(self, slo_ms: float | None) -> None:
        """Per-model override at admit (request ``slo_ms`` beats the env
        default; ``None`` leaves the current target untouched)."""
        if slo_ms is None:
            return
        with self._lock:
            ms = float(slo_ms)
            self._slo_ms = ms if ms > 0 else None
            if self._slo_ms is None:
                self._window = self.base_window_s

    # -- feedback inputs -----------------------------------------------------

    def record_latency(self, latency_s: float) -> None:
        """End-to-end request latency (score() entry -> reply built)."""
        self._ring.record(latency_s)

    def record_dispatch(self, wall_s: float, rows: int) -> None:
        """One batch dispatch's device wall: feeds the shedding
        estimator's EMA (alpha 0.3 — a few batches of history, quick to
        follow a compile or a load shift)."""
        with self._lock:
            if self._ema_dispatch_s is None:
                self._ema_dispatch_s = float(wall_s)
            else:
                self._ema_dispatch_s += 0.3 * (wall_s - self._ema_dispatch_s)
            self._last_queue_rows = int(rows)

    @property
    def ema_dispatch_s(self) -> float | None:
        with self._lock:
            return self._ema_dispatch_s

    # -- the control law -----------------------------------------------------

    def window_s(self, queued_rows: int = 0) -> float:
        """The collect window for the batch being formed. Without a
        target this IS ``base_window_s``, every time — the fixed-window
        degrade the bit-identity test pins."""
        with self._lock:
            if self._slo_ms is None:
                return self.base_window_s
            slo_s = self._slo_ms / 1e3
            w = self._window
            p99 = self._ring.percentile(99)
            if p99 is not None:
                if p99 >= 0.9 * slo_s:
                    w *= 0.5
                    self.narrowed += 1
                elif queued_rows > self._last_queue_rows:
                    w *= 1.25
                    self.widened += 1
                elif p99 <= 0.5 * slo_s:
                    w *= 0.9
                    self.narrowed += 1
            w = max(self.base_window_s / 16.0, min(w, slo_s / 4.0))
            self._window = w
            return w

    def current_window_s(self) -> float:
        with self._lock:
            return self._window if self._slo_ms is not None \
                else self.base_window_s

    # -- shedding admission estimator ----------------------------------------

    def admit(self, priority: int, queued_rows: int, n_rows: int) -> None:
        """Raise :class:`Shed` when the estimated queue service time
        exceeds ``(1 + priority)`` SLO budgets. No target = no shedding."""
        with self._lock:
            if self._slo_ms is None or self._ema_dispatch_s is None:
                return      # cold tier (or no SLO): nothing to estimate yet
            slo_s = self._slo_ms / 1e3
            # dispatches queued ahead of this request's batch, plus its own
            ahead = math.ceil((queued_rows + n_rows) / self.max_bucket)
            est_s = self._ema_dispatch_s * max(ahead, 1) + self._window
            budget_s = slo_s * (1 + priority)
            if est_s <= budget_s:
                return
            self.shed_count += 1
            slo_ms = self._slo_ms
            retry_ms = max(100, int(math.ceil((est_s - slo_s) * 1e3)))
        raise Shed(
            f"estimated queue service time {est_s * 1e3:.1f}ms exceeds "
            f"priority-{priority} budget {budget_s * 1e3:.1f}ms "
            f"(SLO {slo_ms:.0f}ms); shed early, retry shortly",
            priority=priority, reason="overload", retry_after_ms=retry_ms)

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        """The per-model ``slo`` block inside ``GET /3/Score``."""
        p50 = self._ring.percentile(50)
        p99 = self._ring.percentile(99)
        with self._lock:
            return {
                "target_ms": self._slo_ms,
                "mode": "adaptive" if self._slo_ms is not None else "fixed",
                "window_ms": round((self._window if self._slo_ms is not None
                                    else self.base_window_s) * 1e3, 4),
                "base_window_ms": round(self.base_window_s * 1e3, 4),
                "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
                "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
                "samples": self._ring.count,
                "ema_dispatch_ms": (round(self._ema_dispatch_s * 1e3, 3)
                                    if self._ema_dispatch_s is not None
                                    else None),
                "widened": self.widened, "narrowed": self.narrowed,
                "shed": self.shed_count,
            }
